"""Segment-compacted effect phases (round-4 aggregation primitive).

Drop-in replacements for the fused effects megakernel phases of
ops/engine.py (_process_completions_fused / _acquire_effects_fused) that
contract ONE entry per batch *segment* instead of one per item.  A
segment is a maximal run of items sharing every scatter-relevant key
(resource, ctx/origin nodes, origin id), capped at 256 items
(ops/segment.py) — Zipf traffic at B=128K compacts ~11x, and the one-hot
digit-dot cost of every scatter kernel shrinks proportionally.

Dataflow per side (built for exactly two compaction passes):
  1. prepare_*: everything known at batch arrival (stat digit cumsums,
     row columns, the rowmin running minimum) rides the ONE build sort
     as payload operands — compaction costs nothing beyond the sort.
  2. values that exist only after rule checks (acquire pass/block masks,
     degrade event masks) pack into ONE [N, cols] matrix and take a
     single row gather at seg_end.

Correctness does NOT require the batch to be sorted: segments are runs of
EQUAL keys, and all landed quantities are order-independent (integer
digit-plane sums; f32 minima).  An unsorted batch merely produces more
segments; when the live segment count exceeds the static capacity
(cfg.seg_u), the engine either lax.cond-falls back to the per-item fused
path (seg_fallback=True, always exact) or drops overflow segments'
effects and reports TickOutput.seg_dropped (seg_fallback=False).

Hot-parameter scatters key on (rule, value-hash) — not segment-constant —
so they stay on the item axis in a second, small kernel call.

Reference map: same per-request semantics as StatisticSlot.java:54-164 /
DegradeSlot.exit:60-75 / ParamFlowSlot — this file only changes the
aggregation schedule, not what is counted.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from sentinel_tpu.core.config import EngineConfig
from sentinel_tpu.ops import fused as FU
from sentinel_tpu.ops import gsketch as GS
from sentinel_tpu.sketch import impl_for as _sketch
from sentinel_tpu.ops import param as P
from sentinel_tpu.ops import rowmin as RM
from sentinel_tpu.ops import rtq as RQ
from sentinel_tpu.ops import segment as SG
from sentinel_tpu.ops import segscan as SC
from sentinel_tpu.ops import tables as T
from sentinel_tpu.ops import window as W

#: rowmin sentinel (> any valid rt; replaced by drop row before scatter)
_RT_ABSENT = 3.0e38


def seg_capacity(cfg: EngineConfig, b: int) -> int:
    """Static compacted-axis capacity: explicit cfg.seg_u, else sized for
    Zipf-like traffic (distinct keys ~9-17% of B, measured) plus the
    256-block split overhead, with headroom."""
    if cfg.seg_u:
        return cfg.seg_u
    return min(b, b // 8 + b // SG.BLOCK + 64)


def dropped_items(ctx: SG.SegCtx, valid: Optional[jax.Array] = None) -> jax.Array:
    """Items whose effects a no-fallback compacted pass dropped: segments
    are item-contiguous in sid order, so everything past the last kept
    segment's end is dropped when capacity overflows.  ``valid`` (when
    given) excludes trash-row padding from the count — a short batch
    padded to shape would otherwise report dropped "items" whose effects
    were no-ops anyway."""
    n = ctx.head.shape[0]
    kept = ctx.seg_end[-1] + 1
    if valid is None:
        late = jnp.int32(n) - kept
    else:
        iota = jnp.arange(n, dtype=jnp.int32)
        late = jnp.sum((valid & (iota >= kept)).astype(jnp.int32))
    return jnp.where(ctx.ok, jnp.int32(0), late)


class CompCarry(NamedTuple):
    """Sort-carried compacted payloads of one completion batch."""

    ce: list  # cumsum-at-tail cols for (success, error, rt_q)
    split: list
    min_rt: jax.Array  # [U] per-segment min rt (or _RT_ABSENT)
    res: jax.Array  # [U]
    ctx_node: jax.Array
    origin_node: jax.Array


class AcqCarry(NamedTuple):
    res: jax.Array  # [U]
    ctx_node: jax.Array
    origin_node: jax.Array
    origin_id: jax.Array
    ctx_name: jax.Array
    res_sorted: jax.Array  # bool scalar — res nondecreasing over the batch


def prepare_completions(cfg: EngineConfig, comp, features: frozenset):
    """Build the completion-side SegCtx with every batch-known payload
    riding the compaction sort."""
    valid = comp.res != cfg.trash_row
    succ_w = jnp.where(valid, comp.success, 0)
    err_w = jnp.where(valid, comp.error, 0)
    rt1 = jnp.where(valid, comp.rt, 0.0)
    rt_q = jnp.round(
        jnp.minimum(rt1, float(cfg.statistic_max_rt)) * 8.0
    ).astype(jnp.int32)
    # the fused kernels' documented count envelope (cfg.max_batch_count,
    # cd=1 digit) applies to completion success/error exactly like the
    # per-item fused path; rt_q spans two digit planes
    cm = cfg.max_batch_count
    rtm = int(cfg.statistic_max_rt) * 8
    C_rows, split = SG.cum_cols([succ_w, err_w, rt_q], [cm, cm, rtm])
    head = SG.heads_from_keys(comp.res, comp.ctx_node, comp.origin_node)
    inc_min = SC.seg_incl_min_pl(
        head,
        jnp.where(valid & (rt1 > 0), rt1, jnp.float32(_RT_ABSENT)),
        _RT_ABSENT,
    )
    U = seg_capacity(cfg, comp.res.shape[0])
    ctx, carried = SG.build_from_head(
        head,
        U,
        payloads=list(C_rows)
        + [inc_min, comp.res, comp.ctx_node, comp.origin_node],
    )
    nC = len(C_rows)
    carry = CompCarry(
        ce=carried[:nC],
        split=split,
        min_rt=jnp.where(ctx.live, carried[nC], jnp.float32(_RT_ABSENT)),
        res=carried[nC + 1],
        ctx_node=carried[nC + 2],
        origin_node=carried[nC + 3],
    )
    return ctx, carry


def prepare_acquire(cfg: EngineConfig, acq):
    """Acquire-side SegCtx; only row sources are batch-known (values come
    after the checks via one packed gather)."""
    U = seg_capacity(cfg, acq.res.shape[0])
    ctx, carried = SG.build(
        [acq.res, acq.ctx_node, acq.origin_node, acq.origin_id, acq.ctx_name],
        U,
        payloads=[
            acq.res, acq.ctx_node, acq.origin_node, acq.origin_id, acq.ctx_name
        ],
    )
    return ctx, AcqCarry(
        res=carried[0],
        ctx_node=carried[1],
        origin_node=carried[2],
        origin_id=carried[3],
        ctx_name=carried[4],
        res_sorted=jnp.all(acq.res[1:] >= acq.res[:-1]),
    )


def _chunks_to_planes(chunk_lists):
    """sums_from_ce output -> (vals [P2, U], digits tuple, spec per plane)."""
    vals, digits, spec = [], [], []
    for chunks in chunk_lists:
        s = []
        for arr, w, dig in chunks:
            s.append((len(vals), w))
            vals.append(arr)
            digits.append(dig)
        spec.append(s)
    return jnp.stack(vals), tuple(digits), spec


def _recombine(out, spec):
    """Scatter output [n, P2] -> one exact int32 [n] column per plane."""
    o = jnp.round(out).astype(jnp.int32)
    return [sum(o[:, i] * w for i, w in s) for s in spec]


def _packed_seg_values(ctx: SG.SegCtx, planes, maxes, extra_rows=()):
    """Post-check compaction: ONE [N, cols] pack + ONE row gather at
    seg_end.  planes -> sums chunks (exact); extra_rows (segment-constant
    int32 row ids) -> compacted [U] columns appended verbatim."""
    C_rows, split = SG.cum_cols(planes, maxes)
    cols = list(C_rows) + [r.astype(jnp.int32) for r in extra_rows]
    M = jnp.stack(cols, axis=1)  # [N, X]
    G = M[ctx.seg_end]  # [U, X]
    nC = len(C_rows)
    chunks = SG.sums_from_ce(ctx, [G[:, i] for i in range(nC)], split)
    rows = [
        jnp.where(ctx.live, G[:, nC + i], -1) for i in range(len(extra_rows))
    ]
    return chunks, rows


def _clean_rows_u(cfg: EngineConfig, x, live):
    return jnp.where(
        live & (x != cfg.trash_row) & (x >= 0), x, jnp.int32(2**30)
    )


def _stat_rows_u(cfg, ctx, carry, with_nodes: bool):
    res_u = _clean_rows_u(cfg, carry.res, ctx.live)
    if not with_nodes:
        return res_u[None, :]
    c_u = _clean_rows_u(cfg, carry.ctx_node, ctx.live)
    o_u = _clean_rows_u(cfg, carry.origin_node, ctx.live)
    return jnp.stack([res_u, c_u, o_u])


def _bits(x):
    return jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)


def _unbits(x):
    return jax.lax.bitcast_convert_type(x, jnp.float32)


class _Expander:
    """Collects per-segment int32 columns, then performs ONE [B]-row
    gather by sid plus ONE transpose so every per-item column reads as a
    contiguous row.  Separate per-check expansions cost 0.3-1.3 ms EACH
    at B=128K (bool gathers and strided column slices are the worst); the
    shared pack amortizes all of it into ~0.5 ms."""

    def __init__(self, ctx: SG.SegCtx):
        self.ctx = ctx
        self.cols = []
        self.R = None

    def add(self, col) -> int:
        assert self.R is None, "expander already ran"
        self.cols.append(col.astype(jnp.int32))
        return len(self.cols) - 1

    def add_f(self, col) -> int:
        return self.add(_bits(col))

    def run(self):
        if not self.cols:  # feature sets with no segment-level columns
            self.R = jnp.zeros((0, self.ctx.sid.shape[0]), jnp.int32)
            return
        G = jnp.stack(self.cols, axis=1)[self.ctx.sid]  # [B, C]
        self.R = G.T  # [C, B] — row reads are free views

    def get(self, i):
        return self.R[i]

    def get_f(self, i):
        return _unbits(self.R[i])


def run_checks_seg(
    cfg: EngineConfig,
    state,
    rules,
    acq,
    now_ms,
    sys_load,
    sys_cpu,
    valid,
    forced,
    ctx: SG.SegCtx,
    carry: AcqCarry,
    features: frozenset,
):
    """The whole acquire check phase with every per-item table read hoisted
    to the segment level: rule slots, packed fields, window/concurrency/
    pool reads, CB state, authority lists and tail thresholds happen once
    per SEGMENT, and all per-item context expands back through ONE shared
    monotone gather (_Expander).  Item-level logic (ranks, comparisons,
    verdict masks) is bit-identical to engine's per-stage checks —
    AuthoritySlot -> SystemSlot -> ParamFlowSlot -> FlowSlot(+tail) ->
    DegradeSlot, first-fail order preserved.

    Ranks switch at runtime between head-run segmented integer scans
    (valid when the batch is res-sorted and, for flow, all enabled rules
    are DIRECT + limitApp ANY so equal rank keys are contiguous) and the
    batch-order rank kernels.  Requires *_rules_per_resource == 1 for the
    active features (engine checks statically).

    Exactness note: comparisons use the margin rearrangement
    (rank + cnt > thr - wp instead of wp + rank + cnt > thr), identical
    to the per-item forms whenever the operands are f32-exact integers
    (< 2^24 — the same envelope as the window counters themselves).  At
    magnitudes beyond that, the two lax.cond branches may round verdicts
    differently by one ulp.

    Returns the same tuple engine._run_checks_plain produces.
    """
    from sentinel_tpu.ops import engine as E
    from sentinel_tpu.ops import degrade as D
    from sentinel_tpu.core import rule_tensors as RT
    from sentinel_tpu.core.rules import (
        CONTROL_DEFAULT,
        CONTROL_RATE_LIMITER,
        CONTROL_WARM_UP,
        CONTROL_WARM_UP_RATE_LIMITER,
        GRADE_QPS,
        GRADE_THREAD,
        STRATEGY_DIRECT,
        STRATEGY_RELATE,
    )
    from sentinel_tpu.ops.rank import grouped_exclusive_cumsum

    b = acq.res.shape[0]
    now_f = now_ms.astype(jnp.float32)
    cnt = acq.count.astype(jnp.float32)
    zero_block = jnp.zeros((b,), bool)
    live = ctx.live
    res_u = jnp.where(live & (carry.res >= 0), carry.res, cfg.max_resources)
    res_l = jnp.minimum(res_u, cfg.max_resources)
    exp = _Expander(ctx)

    # ================= segment-level phase =================
    with_auth = "authority" in features
    with_param = "param" in features
    with_flow = "flow" in features
    with_degrade = "degrade" in features

    # all four per-resource slot tables are read at the SAME index — one
    # shared 8-lane row gather serves them (tables.lane_gather_multi; a
    # separate lane gather each cost ~0.1 ms apiece at U~16K).  Keyed by
    # NAME so the gather list and the consumers can never fall out of
    # order.
    n_res1 = cfg.max_resources + 1
    slot_tabs = []
    if with_auth:
        slot_tabs.append(("auth", jnp.asarray(rules.auth.mode)))
    if with_param:
        slot_tabs.append(("param", jnp.asarray(rules.param.res_params)[:, 0]))
    if with_flow:
        slot_tabs.append(("flow", jnp.asarray(rules.flow.res_rules)[:, 0]))
    if with_degrade:
        slot_tabs.append(("degrade", jnp.asarray(rules.degrade.res_cbs)[:, 0]))
    slot_vals = {
        name: g.astype(jnp.int32)
        for (name, _t), g in zip(
            slot_tabs,
            T.lane_gather_multi(cfg, [t for _n, t in slot_tabs], res_l, n_res1)
            if slot_tabs
            else [],
        )
    }

    if with_auth:
        n = n_res1
        mode = slot_vals["auth"]
        origins = T.big_gather(cfg, rules.auth.origins, res_l, n)
        listed = (
            (origins == carry.origin_id[:, None]) & (origins != RT.AUTH_EMPTY)
        ).any(axis=1)
        auth_u = ((mode == 1) & ~listed) | ((mode == 2) & listed)

    if with_param:
        # KP == 1 statically (the seg_checks gate) -> shared slot gather
        pslot_u = slot_vals["param"]
        pcms, pcms_epochs, pcms_idx = P.refresh(
            state.pcms, state.pcms_epochs, now_ms, cfg
        )
        pgu = T.small_gather_fields(
            cfg,
            T.pack_fields(
                [
                    rules.param.enabled,
                    rules.param.threshold,
                    rules.param.grade,
                    rules.param.cls,
                    rules.param.lane,
                ]
            ),
            pslot_u,
        )
        ih_u = T.small_gather_int(cfg, rules.param.item_hash, pslot_u)  # [U, KI]
        it_u = T.small_gather_fields(
            cfg, jnp.asarray(rules.param.item_threshold, jnp.float32), pslot_u
        )
        KI = ih_u.shape[1]
        p_en_u = (pgu[:, 0] > 0) & live
        p_thread_u = pgu[:, 2].astype(jnp.int32) == GRADE_THREAD
        i_pflags = exp.add(
            p_en_u.astype(jnp.int32) | (p_thread_u.astype(jnp.int32) << 1)
        )
        i_plane = exp.add(jnp.clip(pgu[:, 4].astype(jnp.int32), -1, cfg.param_dims - 1))
        i_pslot = exp.add(jnp.where(live, pslot_u, cfg.max_param_rules))
        i_pcls = exp.add(
            jnp.clip(pgu[:, 3].astype(jnp.int32), 0, max(cfg.param_classes - 1, 0))
        )
        i_pthr = exp.add_f(pgu[:, 1])
        i_ih = [exp.add(ih_u[:, k]) for k in range(KI)]
        i_it = [exp.add_f(it_u[:, k]) for k in range(KI)]

    if with_flow:
        f = rules.flow
        sec_cfg = W.WindowConfig(cfg.second_sample_count, cfg.second_window_ms)
        slot_u = slot_vals["flow"]
        fg = T.small_gather_fields(
            cfg,
            T.pack_fields(
                [
                    f.enabled, f.limit_app, f.strategy, f.ref_node, f.ref_ctx,
                    f.grade, f.count, f.behavior, f.max_queue_ms,
                    f.warning_token, f.slope, state.warmup_tokens,
                ]
            ),
            slot_u,
        )
        latest_u = T.small_gather_int(
            cfg, jnp.round(state.latest_passed_ms).astype(jnp.int32), slot_u
        ).astype(jnp.float32)
        enabled = fg[:, 0] > 0
        la = fg[:, 1].astype(jnp.int32)
        named = (la >= 0) & (la == carry.origin_id)
        match = (
            (la == RT.LIMIT_ANY)
            | ((la >= 0) & (la == carry.origin_id))
            | ((la == RT.LIMIT_OTHER) & (carry.origin_id >= 0) & ~named)
        )
        applicable_u = enabled & match & live
        strategy = fg[:, 2].astype(jnp.int32)
        ref_node = fg[:, 3].astype(jnp.int32)
        ref_ctx = fg[:, 4].astype(jnp.int32)
        direct_node = jnp.where(la == RT.LIMIT_ANY, carry.res, carry.origin_node)
        chain_ok = (ref_ctx >= 0) & (ref_ctx == carry.ctx_name)
        node = jnp.where(
            strategy == STRATEGY_DIRECT,
            direct_node,
            jnp.where(
                strategy == STRATEGY_RELATE,
                ref_node,
                jnp.where(chain_ok, carry.ctx_node, -1),
            ),
        )
        node_ok = (node >= 0) & (node != cfg.trash_row)
        applicable_u = applicable_u & node_ok
        node_safe_u = jnp.where(node_ok & (node < cfg.node_rows), node, cfg.trash_row)
        grade = fg[:, 5].astype(jnp.int32)
        rcount = fg[:, 6]
        behavior = jnp.where(
            grade == GRADE_QPS, fg[:, 7].astype(jnp.int32), CONTROL_DEFAULT
        )
        rest = fg[:, 11]
        warning = fg[:, 9]
        above = jnp.maximum(rest - warning, 0.0)
        warm_qps = jnp.floor(
            1.0 / (above * fg[:, 10] + 1.0 / jnp.maximum(rcount, 1e-9)) + 0.5
        )
        warm_qps = jnp.where(rest >= warning, warm_qps, rcount)
        is_warm = (behavior == CONTROL_WARM_UP) | (
            behavior == CONTROL_WARM_UP_RATE_LIMITER
        )
        is_rl = (behavior == CONTROL_RATE_LIMITER) | (
            behavior == CONTROL_WARM_UP_RATE_LIMITER
        )
        pace_qps = jnp.where(
            behavior == CONTROL_WARM_UP_RATE_LIMITER,
            warm_qps,
            jnp.maximum(rcount, 1e-9),
        )
        thr_eff = jnp.where(is_warm, warm_qps, rcount)
        cur_wid = W.wid_of(now_ms, cfg.second_window_ms)
        pool_dense = jnp.where(
            state.occ_epoch == cur_wid + 1, state.occ_tokens, 0.0
        )
        # running sums are exact here: completions refreshed this now_ms
        # before checks (ops/window.py Option-B read contract)
        wsum = W.window_event_run(state.win_sec, W.EV_PASS)
        tab = jnp.stack(
            [wsum, state.concurrency, jnp.round(pool_dense).astype(jnp.int32)],
            axis=1,
        )
        g = tab[node_safe_u]
        wp = g[:, 0].astype(jnp.float32)
        conc = g[:, 1].astype(jnp.float32)
        pool = g[:, 2].astype(jnp.float32)
        i_fflags = exp.add(
            applicable_u.astype(jnp.int32)
            | (is_rl.astype(jnp.int32) << 1)
            | ((behavior == CONTROL_WARM_UP_RATE_LIMITER).astype(jnp.int32) << 2)
            | ((grade == GRADE_QPS).astype(jnp.int32) << 3)
            | ((behavior == CONTROL_DEFAULT).astype(jnp.int32) << 4)
        )
        i_node = exp.add(node_safe_u)
        i_fslot = exp.add(jnp.where(live, slot_u, cfg.max_flow_rules))
        i_mq = exp.add_f(thr_eff - wp)
        i_mt = exp.add_f(rcount - conc)
        i_mrl = exp.add_f(latest_u - now_f)
        i_maxq = exp.add_f(fg[:, 8])
        i_pace = exp.add_f(pace_qps)
        i_mo = exp.add_f(rcount - pool)

    with_tail = "tail_flow" in features and cfg.sketch_stats
    if with_tail:
        # UNCONDITIONAL under the feature: "tail_flow" is only compiled in
        # when sketch-id flow rules exist (client._select_features), so a
        # lax.cond on any_tail_rules would buy nothing on real workloads
        # while its boundary copies cost ~0.3-1.4 ms at B=128K (STATUS
        # cond-boundary measurements).  With no rules loaded the gathers
        # read UNRULED thresholds and nothing blocks — semantics identical.
        thr_tab = jnp.asarray(rules.tail.thr)
        tres_u = jnp.where(live, carry.res, -1)
        tail_u = live & (tres_u >= cfg.node_rows)
        tcols = P.cms_cell(tres_u, cfg.sketch_depth, cfg.sketch_width)
        # ONE flat gather across all depths (tables.depth_gather_1col)
        t = T.depth_gather_1col(cfg, thr_tab, tcols, cfg.sketch_width)
        thr_u = jnp.max(
            jnp.where(tail_u[None, :], t, RT.TAIL_UNRULED), axis=0
        )
        est_u = _sketch(cfg).estimate_plane_mxu(
            cfg, state.gs, now_ms, tres_u, W.EV_PASS, E.sketch_config(cfg)
        )
        i_tthr = exp.add_f(thr_u)
        i_test = exp.add_f(est_u)

    if with_degrade:
        dslot_u = slot_vals["degrade"]
        dgu = T.small_gather_fields(
            cfg, T.pack_fields([rules.degrade.enabled, state.cb_state]), dslot_u
        )
        d_en = (dgu[:, 0] > 0) & live
        st_u = dgu[:, 1].astype(jnp.int32)
        retry_due = now_ms >= T.small_gather_int(cfg, state.cb_retry_ms, dslot_u)
        open_wait = (st_u == D.CB_OPEN) & ~retry_due
        open_due = (st_u == D.CB_OPEN) & retry_due
        half = st_u == D.CB_HALF_OPEN
        i_dflags = exp.add(
            d_en.astype(jnp.int32)
            | (open_wait.astype(jnp.int32) << 1)
            | (open_due.astype(jnp.int32) << 2)
            | (half.astype(jnp.int32) << 3)
        )
        i_dslot = exp.add(
            jnp.minimum(
                jnp.where(live, dslot_u, cfg.max_degrade_rules),
                cfg.max_degrade_rules,
            )
        )

    if with_auth:
        i_auth = exp.add(auth_u.astype(jnp.int32))

    exp.run()

    # ================= item-level phase (slot order) =================
    # Items in segments past the compacted capacity have no segment-level
    # data (their expansions clamp to slot U-1 — garbage): FAIL CLOSED.
    # Empty whenever ctx.ok (sid < U for every item), so this is a no-op
    # on the seg_fallback=True path, where the lax.cond guards capacity;
    # with seg_fallback=False these items are counted by dropped_items and
    # block as system rejections rather than pass unchecked.
    overflow = valid & (ctx.sid >= ctx.U)

    if with_auth:
        # ~overflow: garbage expansions must not mislabel the fail-closed
        # block as BLOCK_AUTHORITY (it lands as a system rejection below)
        auth_block = (exp.get(i_auth) > 0) & valid & ~forced & ~overflow
    else:
        auth_block = zero_block
    eligible = valid & ~auth_block & ~forced & ~overflow

    if "system" in features:
        sys_block = E._check_system(
            cfg, state, rules, acq, now_ms, sys_load, sys_cpu, eligible
        )
        sys_block = sys_block | overflow
    else:
        sys_block = zero_block | overflow
    eligible = eligible & ~sys_block

    if with_param:
        fl = exp.get(i_pflags)
        p_en_i = (fl & 1) > 0
        p_thread_i = (fl & 2) > 0
        lane_i = exp.get(i_plane)
        pslot_i = exp.get(i_pslot)
        cls_i = exp.get(i_pcls)
        pthr_i = exp.get_f(i_pthr)
        lane_oh = jnp.clip(lane_i, 0, cfg.param_dims - 1)[
            :, None
        ] == jax.lax.broadcasted_iota(jnp.int32, (1, cfg.param_dims), 1)
        ph = jnp.sum(jnp.where(lane_oh, acq.param_hash, 0), axis=1)
        ph = jnp.where(lane_i >= 0, ph, 0)
        p_app = p_en_i & (ph != 0)
        prows = P.pair_rows(pslot_i, ph, cfg.param_depth, cfg.param_width)
        wtab = P.class_tables(
            pcms, pcms_epochs, jnp.asarray(rules.param.class_k), now_ms, cfg
        )
        est = P.estimate_fused(cfg, wtab, prows, cls_i)
        any_thread = jnp.any(
            jnp.asarray(rules.param.enabled)
            & (jnp.asarray(rules.param.grade) == GRADE_THREAD)
        )
        conc_est = jax.lax.cond(
            any_thread,
            lambda: P.conc_estimate(cfg, state.pconc, prows),
            lambda: jnp.zeros((prows.shape[0],), jnp.float32),
        )
        is_item = jnp.zeros((b,), bool)
        item_thr = jnp.zeros((b,), jnp.float32)
        for k in range(KI):
            ihk = exp.get(i_ih[k])
            itk = exp.get_f(i_it[k])
            hit = (ihk == ph) & (ihk != 0)
            item_thr = jnp.where(hit, jnp.maximum(item_thr, itk), item_thr)
            is_item = is_item | hit
        pthr = jnp.where(is_item, item_thr, pthr_i)
        elig_p = eligible & p_app
        key = ph * jnp.int32(2) + pslot_i  # KP == 1
        (p_rank,) = grouped_exclusive_cumsum(key, [cnt], elig_p)
        over = jnp.where(p_thread_i, conc_est, est) + p_rank + cnt > pthr
        param_block = p_app & over & elig_p & eligible
        param_state = (
            pcms, pcms_epochs, pcms_idx, prows,
            p_app & ~p_thread_i, p_app & p_thread_i,
        )
    else:
        param_block = zero_block
        param_state = None
    eligible = eligible & ~param_block

    occupy = "occupy" in features
    if with_flow:
        fl = exp.get(i_fflags)
        app_i = (fl & 1) > 0
        rl_i = (fl & 2) > 0
        wurl_i = (fl & 4) > 0
        qps_i = (fl & 8) > 0
        def_i = (fl & 16) > 0
        node_i = exp.get(i_node)
        slot_i = exp.get(i_fslot)
        margin_q = exp.get_f(i_mq)
        margin_t = exp.get_f(i_mt)
        m_rl = exp.get_f(i_mrl)
        mq_i = exp.get_f(i_maxq)
        pace_i = exp.get_f(i_pace)
        margin_o = exp.get_f(i_mo)
        # same 3-digit pacing-cost clamp as _check_flow (int32 rank safety)
        cost = jnp.where(
            rl_i,
            jnp.minimum(
                jnp.floor(1000.0 * cnt / pace_i + 0.5), float((1 << 24) - 1)
            ),
            0.0,
        )
        elig_f = eligible & app_i
        rank_key = jnp.where(rl_i, jnp.int32(cfg.node_rows) + slot_i, node_i)
        direct_any = ~jnp.any(
            jnp.asarray(f.enabled)
            & (
                (jnp.asarray(f.strategy) != STRATEGY_DIRECT)
                | (jnp.asarray(f.limit_app) != RT.LIMIT_ANY)
            )
        )
        seg_rank_ok = carry.res_sorted & direct_any

        def _ranks_seg():
            head_k = jnp.concatenate(
                [jnp.ones((1,), bool), rank_key[1:] != rank_key[:-1]]
            )
            r = SC.seg_excl_cumsum_pl(
                head_k,
                jnp.stack(
                    [jnp.where(elig_f, acq.count, 0), elig_f.astype(jnp.int32)]
                ),
            )
            rc = SC.seg_excl_cumsum_wide_pl(
                head_k, jnp.where(elig_f, cost, 0.0).astype(jnp.int32)
            )
            return r[0].astype(jnp.float32), r[1].astype(jnp.float32), rc

        def _ranks_sort():
            return E._rank(
                cfg,
                rank_key,
                [cnt, jnp.ones_like(cnt), cost],
                elig_f,
                cfg.node_rows + cfg.max_flow_rules + 1,
            )

        if cfg.seg_static_ranks:
            # scans only (cfg contract: sorted + DIRECT/ANY rules); if the
            # contract breaks at runtime, ranks are garbage — fail closed
            # below by blocking every applicable item rather than
            # misranking silently
            rank_tok, rank_thr, rank_cost = _ranks_seg()
            rank_guard = ~seg_rank_ok
        else:
            rank_tok, rank_thr, rank_cost = jax.lax.cond(
                seg_rank_ok, _ranks_seg, _ranks_sort
            )
            rank_guard = jnp.zeros((), bool)
        qps_block = rank_tok + cnt > margin_q
        thread_block = rank_thr + cnt > margin_t
        basic_block = jnp.where(qps_i, qps_block, thread_block)
        csum_incl = rank_cost + cost
        rl_wait = jnp.maximum(m_rl + csum_incl, csum_incl - cost)
        rl_block = rl_wait > mq_i
        entry_block = jnp.where(rl_i, rl_block, basic_block) & app_i
        entry_block = entry_block | (wurl_i & app_i & qps_block)
        entry_block = entry_block | (rank_guard & app_i)
        flow_block = entry_block & elig_f

        occupying = jnp.zeros((b,), bool)
        occ_wait = jnp.zeros((b,), jnp.float32)
        occ_grant = None
        if occupy:
            cand = (acq.prio > 0) & def_i & qps_i & app_i & elig_f & qps_block
            if cfg.seg_static_ranks:
                # under a broken static-rank contract nothing may occupy
                # ahead (a garbage grant would bypass the fail-closed
                # entry_block above)
                cand = cand & ~rank_guard

            def _occ_rank(cand):
                def _seg():
                    head_n = jnp.concatenate(
                        [jnp.ones((1,), bool), node_i[1:] != node_i[:-1]]
                    )
                    (r,) = SC.seg_excl_cumsum_pl(
                        head_n, jnp.where(cand, acq.count, 0)[None, :]
                    )
                    return r.astype(jnp.float32)

                def _sort():
                    (r,) = E._rank(cfg, node_i, [cnt], cand, cfg.node_rows)
                    return r

                if cfg.seg_static_ranks:
                    # contract break -> rank_guard already blocks the
                    # entry, so a garbage occupy rank cannot grant
                    rank_occ = _seg()
                else:
                    rank_occ = jax.lax.cond(seg_rank_ok, _seg, _sort)
                return cand & (rank_occ + cnt <= margin_o)

            granted = jax.lax.cond(
                jnp.any(cand), _occ_rank, lambda c: jnp.zeros_like(c), cand
            )
            still_blocked = entry_block & ~granted & elig_f
            occupying = granted & elig_f & ~still_blocked
            flow_block = still_blocked
            occ_wait_v = (
                cfg.second_window_ms - (now_ms % cfg.second_window_ms)
            ).astype(jnp.float32)
            occ_wait = jnp.where(occupying, occ_wait_v, 0.0)
            occ_grant = (granted & elig_f, node_i, cnt)

        rl_ok = rl_i & app_i & ~entry_block & elig_f & ~flow_block
        wait_ms_entry = jnp.where(rl_ok, jnp.maximum(rl_wait, 0.0), 0.0)
        wait_ms = jnp.maximum(wait_ms_entry, occ_wait).astype(jnp.int32)
        fslots = slot_i
        rl_info = (rl_ok, cost)
    else:
        flow_block = zero_block
        occupying = zero_block
        occ_grant = None
        fslots = None
        rl_info = None
        wait_ms = jnp.zeros((b,), jnp.int32)

    if with_tail:
        # unconditional (see the segment-level tail phase above): the rank
        # scan + compare interior is cheap next to the cond boundary it
        # replaced, and with no ruled tail items `ruled` is all-False
        thr = jnp.where(
            eligible & (acq.res >= cfg.node_rows),
            exp.get_f(i_tthr),
            RT.TAIL_UNRULED,
        )
        est_t = exp.get_f(i_test)
        ruled = thr < RT.TAIL_UNRULED / 2

        def _tail_seg():
            head_r = jnp.concatenate(
                [jnp.ones((1,), bool), acq.res[1:] != acq.res[:-1]]
            )
            (r,) = SC.seg_excl_cumsum_pl(
                head_r, jnp.where(ruled, acq.count, 0)[None, :]
            )
            return r.astype(jnp.float32)

        def _tail_sort():
            (r,) = grouped_exclusive_cumsum(acq.res, [cnt], ruled)
            return r

        if cfg.seg_static_ranks:
            # unsorted batch under the static contract: block ruled
            # tail items outright (fail closed, loud) — t_rank would
            # be garbage
            t_rank = _tail_seg()
            tail_block = ruled & (
                (est_t + t_rank + cnt > thr) | ~carry.res_sorted
            )
        else:
            t_rank = jax.lax.cond(carry.res_sorted, _tail_seg, _tail_sort)
            tail_block = ruled & (est_t + t_rank + cnt > thr)
        flow_block = flow_block | (tail_block & eligible)
    eligible = eligible & ~flow_block

    if with_degrade:
        fl = exp.get(i_dflags)
        en_i = (fl & 1) > 0
        ow_i = (fl & 2) > 0
        od_i = (fl & 4) > 0
        hf_i = (fl & 8) > 0
        dslot_i = exp.get(i_dslot)
        probe_cand = od_i & en_i & eligible

        def _probe_rank(cand):
            def _seg():
                head_s = jnp.concatenate(
                    [jnp.ones((1,), bool), dslot_i[1:] != dslot_i[:-1]]
                )
                (r,) = SC.seg_excl_cumsum_pl(head_s, cand.astype(jnp.int32)[None, :])
                return r.astype(jnp.float32)

            def _sort():
                (r,) = E._rank(
                    cfg,
                    dslot_i,
                    [jnp.ones_like(dslot_i, dtype=jnp.float32)],
                    cand,
                    cfg.max_degrade_rules + 1,
                )
                return r

            if cfg.seg_static_ranks:
                # unsorted under the static contract: elect NO probes
                # (conservative — the breaker simply stays open a tick)
                p_rank = _seg()
                return cand & (p_rank < 0.5) & carry.res_sorted
            p_rank = jax.lax.cond(carry.res_sorted, _seg, _sort)
            return cand & (p_rank < 0.5)

        probe = jax.lax.cond(
            jnp.any(probe_cand),
            _probe_rank,
            lambda c: jnp.zeros_like(c),
            probe_cand,
        )
        entry_blk_d = en_i & (ow_i | (od_i & ~probe) | hf_i)
        degrade_block = entry_blk_d & eligible
        probe_ok = probe & ~degrade_block
        Dn1 = cfg.max_degrade_rules + 1
        flip = jax.lax.cond(
            jnp.any(probe_ok),
            lambda: T.small_scatter_or(
                cfg, jnp.zeros((Dn1,), jnp.int32), dslot_i, probe_ok
            ),
            lambda: jnp.zeros((Dn1,), jnp.int32),
        )
        cb_state = jnp.where(
            (flip > 0) & (state.cb_state == D.CB_OPEN),
            D.CB_HALF_OPEN,
            state.cb_state,
        )
    else:
        degrade_block = zero_block
        cb_state = state.cb_state

    return (
        auth_block,
        sys_block,
        param_block,
        param_state,
        flow_block,
        wait_ms,
        occupying,
        occ_grant,
        fslots,
        rl_info,
        degrade_block,
        cb_state,
        None,  # latest_passed: the fused paths land it via the effects kernel
    )


def process_completions_seg(
    cfg: EngineConfig,
    state,
    rules,
    comp,
    now_ms,
    features: frozenset,
    ctx: SG.SegCtx,
    carry: CompCarry,
):
    """_process_completions_fused with segment-compacted scatters.

    Bit-identical state updates (ints sum order-free; minima order-free);
    see engine._process_completions_fused for the per-plane semantics and
    reference citations."""
    from sentinel_tpu.ops import engine as E

    b = comp.res.shape[0]
    U = ctx.U
    valid = comp.res != cfg.trash_row
    with_nodes = "nodes" in features
    sec_cfg = W.WindowConfig(cfg.second_sample_count, cfg.second_window_ms)
    min_cfg = W.WindowConfig(cfg.minute_sample_count, cfg.minute_window_ms)
    erow = cfg.entry_node_row
    inb, entry_deltas, entry_rt, entry_rt_min = E._completion_entry_stats(
        cfg, comp, valid
    )

    vals3_u, digits3, spec3 = _chunks_to_planes(
        SG.sums_from_ce(ctx, carry.ce, carry.split)
    )
    stat_rows = _stat_rows_u(cfg, ctx, carry, with_nodes)
    jobs = [FU.Job("stat", cfg.max_nodes, stat_rows, vals3_u, digits3)]

    # --- exact per-row windowed minRt over compacted per-segment minima --
    RMIN = stat_rows.shape[0]
    seg_min = jnp.where(carry.min_rt < 1.0e38, carry.min_rt, -1.0)
    mh_rows, mh_vals = RM.min_heads(
        jnp.where(stat_rows < cfg.max_nodes, stat_rows, -1).reshape(-1),
        jnp.tile(seg_min, (RMIN,)),
        jnp.ones((RMIN * U,), bool),
        cfg.max_nodes,
    )
    jobs.append(
        FU.Job(
            "rowmin",
            cfg.max_nodes,
            mh_rows.reshape(RMIN, U),
            mh_vals.T.reshape(3, RMIN, U).transpose(1, 0, 2),
            (2, 2, 1),
        )
    )

    if cfg.sketch_stats:
        res_u = jnp.where(ctx.live, carry.res, -1)
        cols_u = P.cms_cell(res_u, cfg.sketch_depth, cfg.sketch_width)
        valid_u = ctx.live & (res_u != cfg.trash_row) & (res_u >= 0)
        for d in range(cfg.sketch_depth):
            jobs.append(
                FU.Job(
                    f"sketch{d}",
                    cfg.sketch_width,
                    jnp.where(valid_u, cols_u[:, d], -1)[None, :],
                    vals3_u,
                    digits3,
                )
            )

    # --- circuit-breaker columns + probe flags ---------------------------
    with_degrade = "degrade" in features
    if with_degrade:
        KD = cfg.degrade_rules_per_resource
        slots_f, cb_counts, cb_epochs, active, is_err, is_slow, g_idx, half_open = (
            E._degrade_completion_masks(cfg, state, rules, comp, valid, now_ms)
        )
        nbd = cfg.cb_sample_count
        Dn = cfg.max_degrade_rules
        probe_done = active & half_open
        probe_fail = probe_done & (is_err | is_slow)
        planes = []
        rows_src = []
        for d in range(KD):
            sl = lambda x: x.reshape(b, KD)[:, d]
            planes += [
                sl(jnp.where(active, 1, 0)),
                sl(jnp.where(is_err, 1, 0)),
                sl(jnp.where(is_slow, 1, 0)),
                sl(probe_done.astype(jnp.int32)),
                sl(probe_fail.astype(jnp.int32)),
            ]
            flat = jnp.where(slots_f < Dn, slots_f * nbd + g_idx, -1)
            rows_src += [sl(flat), sl(jnp.where(slots_f < Dn, slots_f, -1))]
        # per-ITEM plane bound is 1 (event flags); seg sums stay <= BLOCK
        # and ride single 2-digit chunks
        chunks, crows = _packed_seg_values(
            ctx, planes, [1] * len(planes), extra_rows=rows_src
        )
        cbp_vals, cbp_digits, cbp_spec = _chunks_to_planes(
            [chunks[5 * d + k] for d in range(KD) for k in range(3)]
        )
        prp_vals, prp_digits, prp_spec = _chunks_to_planes(
            [chunks[5 * d + k] for d in range(KD) for k in range(3, 5)]
        )
        P2c = cbp_vals.shape[0] // KD
        P2p = prp_vals.shape[0] // KD
        jobs.append(
            FU.Job(
                "cb",
                Dn * nbd,
                jnp.stack([crows[2 * d] for d in range(KD)]),
                cbp_vals.reshape(KD, P2c, U),
                cbp_digits[:P2c],
            )
        )
        jobs.append(
            FU.Job(
                "probe",
                Dn,
                jnp.stack([crows[2 * d + 1] for d in range(KD)]),
                prp_vals.reshape(KD, P2p, U),
                prp_digits[:P2p],
            )
        )

    outs = FU.scatter_many(jobs)
    oi = 0
    stat_out = outs[oi]
    oi += 1
    min_out = outs[oi]
    oi += 1
    sk_out = None
    if cfg.sketch_stats:
        sk_out = jnp.stack(outs[oi : oi + cfg.sketch_depth])
        oi += cfg.sketch_depth
    if with_degrade:
        cb_out = outs[oi]
        probe_out = outs[oi + 1]

    # --- THREAD-grade param release: item-axis kernel, skipped when no
    # lane releases (the common QPS-only workload pays nothing) -----------
    with_param = "param" in features
    if with_param:
        cd = cfg.count_digits
        KPp = cfg.param_rules_per_resource
        rel, prows_c, rel_cnt_f = E._param_release_ctx(cfg, rules, comp, valid)
        pr = jnp.where(rel[:, None], prows_c, -1).reshape(b, KPp, cfg.param_depth)
        rel_cnt = rel_cnt_f.reshape(b, KPp).T[:, None, :]

        def _rel_scatter():
            pjobs = [
                FU.Job(f"prel{d}", cfg.param_width, pr[:, :, d].T, rel_cnt, (cd,))
                for d in range(cfg.param_depth)
            ]
            return jnp.stack([o[:, 0] for o in FU.scatter_many(pjobs)])

        prel_out = jax.lax.cond(
            jnp.any(rel),
            _rel_scatter,
            lambda: jnp.zeros((cfg.param_depth, cfg.param_width), jnp.float32),
        )

    # --- land (same tail as the per-item fused path) ---------------------
    succ_h, err_h, rtq_h = _recombine(stat_out, spec3)
    pad_tail = cfg.node_rows - cfg.max_nodes
    hist = jnp.zeros((cfg.node_rows, W.NUM_EVENTS), jnp.int32)
    hist = hist.at[: cfg.max_nodes, W.EV_SUCCESS].set(succ_h)
    hist = hist.at[: cfg.max_nodes, W.EV_EXCEPTION].set(err_h)
    hist = hist.at[erow].add(entry_deltas)
    rt_hist = jnp.concatenate(
        [rtq_h.astype(jnp.float32) / 8.0, jnp.zeros((pad_tail,), jnp.float32)]
    )
    rt_hist = rt_hist.at[erow].add(entry_rt)
    mins_m, present_m = RM.combine(min_out)
    row_min = (
        jnp.concatenate([mins_m, jnp.full((pad_tail,), W.RT_MIN_INIT, jnp.float32)]),
        jnp.concatenate([present_m, jnp.zeros((pad_tail,), bool)]),
    )
    win_sec = W.add_dense(
        state.win_sec, now_ms, hist, rt_hist, sec_cfg, row_min=row_min
    )
    win_sec = W.min_into_row(win_sec, now_ms, erow, entry_rt_min, sec_cfg)
    win_min = state.win_min
    if cfg.enable_minute_window:
        win_min = W.add_dense(
            state.win_min, now_ms, hist, rt_hist, min_cfg, row_min=row_min
        )
    state = state._replace(win_sec=win_sec, win_min=win_min)

    state = state._replace(
        rtq=RQ.add(state.rtq, now_ms, comp.rt, inb & (comp.rt > 0), E.rtq_config(cfg))
    )
    if sk_out is not None:
        upd = jnp.stack(
            [
                jnp.stack(_recombine(sk_out[d], spec3), axis=1)
                for d in range(cfg.sketch_depth)
            ]
        )  # [depth, width, 3]
        state = state._replace(
            gs=_sketch(cfg).add_dense(
                state.gs,
                now_ms,
                upd,
                (W.EV_SUCCESS, W.EV_EXCEPTION, GS.RT_PLANE),
                E.sketch_config(cfg),
            )
        )

    concurrency = jnp.maximum(state.concurrency - hist[:, W.EV_SUCCESS], 0)

    if with_param:
        dec = jnp.round(prel_out).astype(jnp.int32)
        state = state._replace(pconc=jnp.maximum(state.pconc - dec, 0))

    if not with_degrade:
        return state._replace(concurrency=concurrency)

    cb_cols = _recombine(cb_out, cbp_spec[:3])
    cb_upd = jnp.stack(cb_cols, axis=1).reshape(Dn, nbd, 3)
    cb_counts = cb_counts.at[:Dn].add(cb_upd)
    pr_cols = _recombine(probe_out, prp_spec[:2])
    sf = jnp.concatenate(
        [jnp.stack(pr_cols, axis=1), jnp.zeros((1, 2), jnp.int32)]
    )
    cb_counts, cb_state, cb_retry = E._cb_transitions(
        cfg, state, rules, cb_counts, cb_epochs, sf[:, 0], sf[:, 1], now_ms
    )
    return state._replace(
        concurrency=concurrency,
        cb_counts=cb_counts,
        cb_epochs=cb_epochs,
        cb_state=cb_state,
        cb_retry_ms=cb_retry,
    )


def acquire_effects_seg(
    cfg: EngineConfig,
    state,
    rules,
    acq,
    now_ms,
    features: frozenset,
    passed,
    occupying,
    valid,
    fslots,
    occ_grant,
    rl_info,
    param_ctx,
    ctx: SG.SegCtx,
    carry: AcqCarry,
):
    """_acquire_effects_fused with segment-compacted scatters (same
    semantics; see that function for the reference map).  All post-check
    value planes and per-lane rows compact through ONE packed gather."""
    from sentinel_tpu.ops import engine as E

    b = acq.res.shape[0]
    U = ctx.U
    with_nodes = "nodes" in features
    sec_cfg = W.WindowConfig(cfg.second_sample_count, cfg.second_window_ms)
    min_cfg = W.WindowConfig(cfg.minute_sample_count, cfg.minute_window_ms)
    erow = cfg.entry_node_row
    cd = cfg.count_digits
    K = cfg.flow_rules_per_resource
    CMAX = cfg.max_batch_count  # fused path clamps per-item counts

    pass_c, block_c, occ_c, entry_deltas = E._acquire_entry_stats(
        cfg, acq, valid, passed, occupying
    )

    # --- assemble the one packed post-check compaction -------------------
    planes = [pass_c, block_c, occ_c]
    maxes = [CMAX, CMAX, CMAX]
    rows_src = []
    if cfg.sketch_stats:
        planes.append(jnp.where(passed, acq.count, 0))
        maxes.append(CMAX)
    slot_planes = []
    if fslots is not None:
        F = cfg.max_flow_rules
        cnt_f = E._fan(acq.count, K)
        w = c = n1 = None
        if "warmup" in features:
            adm = E._fan(passed, K)
            w = jnp.where(adm, cnt_f, 0).reshape(b, K)
            slot_planes.append("warm")
        if rl_info is not None:
            rl_ok, cost = rl_info
            c = jnp.where(rl_ok, jnp.round(cost).astype(jnp.int32), 0).reshape(b, K)
            n1 = jnp.where(rl_ok, 1, 0).reshape(b, K)
            slot_planes.append("latest")
        # LANE-MAJOR: the chunk slicing below walks chunks per lane
        for d in range(K):
            if w is not None:
                planes.append(w[:, d])
                maxes.append(CMAX)
            if c is not None:
                planes += [c[:, d], n1[:, d]]
                maxes += [(1 << 24) - 1, 255]
        fs = jnp.where(fslots < F, fslots, -1).reshape(b, K)
        rows_src += [fs[:, d] for d in range(K)]
    if occ_grant is not None:
        grant_lane, onodes, ocnt = occ_grant
        commit = grant_lane & E._fan(occupying, K)
        cm = jnp.where(commit, jnp.round(ocnt).astype(jnp.int32), 0).reshape(b, K)
        on = jnp.where(onodes < cfg.max_nodes, onodes, -1).reshape(b, K)
        for d in range(K):
            planes.append(cm[:, d])
            maxes.append(CMAX)
            rows_src.append(on[:, d])

    chunks, crows = _packed_seg_values(ctx, planes, maxes, extra_rows=rows_src)
    pi = 0
    ri = 0
    vals3_u, digits3, spec3 = _chunks_to_planes(chunks[pi : pi + 3])
    pi += 3
    stat_rows = _stat_rows_u(cfg, ctx, carry, with_nodes)
    jobs = [FU.Job("stat", cfg.max_nodes, stat_rows, vals3_u, digits3)]

    if cfg.sketch_stats:
        sk_vals, sk_digits, sk_spec = _chunks_to_planes(
            [chunks[pi], chunks[1]]  # (admitted count, block)
        )
        pi += 1
        res_u = jnp.where(ctx.live, carry.res, -1)
        cols_u = P.cms_cell(res_u, cfg.sketch_depth, cfg.sketch_width)
        valid_u = ctx.live & (res_u != cfg.trash_row) & (res_u >= 0)
        for d in range(cfg.sketch_depth):
            jobs.append(
                FU.Job(
                    f"sketch{d}",
                    cfg.sketch_width,
                    jnp.where(valid_u, cols_u[:, d], -1)[None, :],
                    sk_vals,
                    sk_digits,
                )
            )

    n_flow_jobs = 0
    if fslots is not None and slot_planes:
        per_lane = (1 if "warm" in slot_planes else 0) + (
            2 if "latest" in slot_planes else 0
        )
        lane_chunks = []
        for d in range(K):
            lane_chunks.extend(chunks[pi + d * per_lane : pi + (d + 1) * per_lane])
        f_vals, f_digits, f_spec = _chunks_to_planes(lane_chunks)
        pi += K * per_lane
        P2f = f_vals.shape[0] // K
        jobs.append(
            FU.Job(
                "fslots",
                cfg.max_flow_rules,
                jnp.stack(crows[ri : ri + K]),
                f_vals.reshape(K, P2f, U),
                f_digits[:P2f],
            )
        )
        ri += K
        n_flow_jobs = 1
    elif fslots is not None:
        ri += K

    n_occ_jobs = 0
    if occ_grant is not None:
        o_vals, o_digits, o_spec = _chunks_to_planes(chunks[pi : pi + K])
        pi += K
        P2o = o_vals.shape[0] // K
        jobs.append(
            FU.Job(
                "occ",
                cfg.max_nodes,
                jnp.stack(crows[ri : ri + K]),
                o_vals.reshape(K, P2o, U),
                o_digits[:P2o],
            )
        )
        ri += K
        n_occ_jobs = 1

    outs = FU.scatter_many(jobs)
    oi = 0
    stat_out = outs[oi]
    oi += 1
    sk_out = None
    if cfg.sketch_stats:
        sk_out = jnp.stack(outs[oi : oi + cfg.sketch_depth])
        oi += cfg.sketch_depth
    f_out = None
    if n_flow_jobs:
        f_out = outs[oi]
        oi += 1
    occ_out = None
    if n_occ_jobs:
        occ_out = outs[oi]
        oi += 1

    # --- param pass + THREAD concurrency: item-axis kernel ---------------
    p_out = None
    if param_ctx is not None:
        pcms, pcms_epochs, pcms_idx, prows, q_add, thread_add = param_ctx
        KP = cfg.param_rules_per_resource
        adm = E._fan(passed, KP)
        cnt_p = E._fan(acq.count, KP)
        p_vals = jnp.stack(
            [
                jnp.where(q_add & adm, cnt_p, 0),
                jnp.where(thread_add & adm, cnt_p, 0),
            ]
        )
        p_vals_r = p_vals.reshape(2, b, KP).transpose(2, 0, 1)
        pjobs = [
            FU.Job(
                f"param{d}",
                cfg.param_width,
                prows[:, d].reshape(b, KP).T,
                p_vals_r,
                (cd, cd),
            )
            for d in range(cfg.param_depth)
        ]
        p_out = jnp.stack(FU.scatter_many(pjobs))  # [depth, Q, 2]

    # --- land (same tail as the per-item fused path) ---------------------
    pass_h, block_h, occ_h = _recombine(stat_out, spec3)
    hist = jnp.zeros((cfg.node_rows, W.NUM_EVENTS), jnp.int32)
    hist = hist.at[: cfg.max_nodes, W.EV_PASS].set(pass_h)
    hist = hist.at[: cfg.max_nodes, W.EV_BLOCK].set(block_h)
    hist = hist.at[: cfg.max_nodes, W.EV_OCCUPIED].set(occ_h)
    hist = hist.at[erow].add(entry_deltas)
    win_sec = W.add_dense(state.win_sec, now_ms, hist, None, sec_cfg)
    win_min = state.win_min
    if cfg.enable_minute_window:
        win_min = W.add_dense(state.win_min, now_ms, hist, None, min_cfg)
    concurrency = state.concurrency + hist[:, W.EV_PASS] + hist[:, W.EV_OCCUPIED]
    state = state._replace(
        win_sec=win_sec, win_min=win_min, concurrency=concurrency
    )

    if sk_out is not None:
        upd = jnp.stack(
            [
                jnp.stack(_recombine(sk_out[d], sk_spec), axis=1)
                for d in range(cfg.sketch_depth)
            ]
        )
        # the completion phase already refreshed this now_ms's sketch
        # bucket (its write is unconditional under sketch_stats)
        state = state._replace(
            gs=_sketch(cfg).add_dense(
                state.gs,
                now_ms,
                upd,
                (W.EV_PASS, W.EV_BLOCK),
                E.sketch_config(cfg),
                pre_refreshed=True,
            )
        )

    if f_out is not None:
        # lanes are row-vectors of one job, so f_out [F, P2] is already
        # summed over lanes; recombine with lane 0's spec (lanes share it)
        cols = _recombine(f_out, f_spec[: len(f_spec) // K])
        fi = 0
        pad1 = jnp.zeros((1,), jnp.float32)
        if "warm" in slot_planes:
            acc_add = jnp.concatenate([cols[fi].astype(jnp.float32), pad1])
            state = state._replace(warm_acc=state.warm_acc + acc_add)
            fi += 1
        if "latest" in slot_planes:
            T_s = jnp.concatenate([cols[fi].astype(jnp.float32), pad1])
            n_s = jnp.concatenate([cols[fi + 1].astype(jnp.float32), pad1])
            state = state._replace(
                latest_passed_ms=E._apply_latest(
                    state.latest_passed_ms, T_s, n_s, now_ms
                )
            )

    if occ_out is not None:
        add = jnp.concatenate(
            [
                _recombine(occ_out, o_spec[: len(o_spec) // K])[0].astype(
                    jnp.float32
                ),
                jnp.zeros((cfg.node_rows - cfg.max_nodes,), jnp.float32),
            ]
        )
        cur_wid = W.wid_of(now_ms, cfg.second_window_ms)
        pool_vec = jnp.where(state.occ_epoch == cur_wid + 1, state.occ_tokens, 0.0)
        state = state._replace(
            occ_tokens=pool_vec + add,
            occ_epoch=jnp.where(add > 0, cur_wid + 1, state.occ_epoch),
        )

    if p_out is not None:
        upd = jnp.round(p_out).astype(jnp.int32)
        pcms = pcms.at[:, :, pcms_idx].add(upd[:, :, 0])
        pconc = jnp.maximum(state.pconc + upd[:, :, 1], 0)
        state = state._replace(pcms=pcms, pcms_epochs=pcms_epochs, pconc=pconc)

    return state
