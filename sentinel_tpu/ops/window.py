"""Vectorized sliding-window statistics — the TPU-native LeapArray.

The reference keeps one lock-free ring of buckets *per resource*
(sentinel-core/.../slots/statistic/base/LeapArray.java:41): bucket index is
``(timeMs / windowLengthInMs) % sampleCount`` (LeapArray.java:112-124), and a
deprecated bucket is lazily reset when next written (LeapArray.java:149-248).
Per-bucket counters are LongAdders over the event enum
(MetricBucket.java:28, MetricEvent.java:21).

Here ALL resources share one ring-buffer tensor:

    counts : int32  [rows, nb, NE]   (PASS, BLOCK, EXCEPTION, SUCCESS, OCCUPIED)
    rt_sum : float32[rows, nb]
    rt_min : float32[rows, nb]
    epochs : int32  [nb]             window-id currently held by each column

and the per-resource CAS dance collapses into two vectorized rules:

  * WRITE  (add_batch): all events in a micro-batch share one ``now_ms``,
    so only column ``wid % nb`` is touched; if its epoch != wid the whole
    column (all rows at once) is zeroed first — the batched form of
    "reset deprecated bucket on wrap".
  * READ: a column is valid iff ``epochs[b] > wid - nb`` — the batched form
    of ``!isWindowDeprecated`` (LeapArray.java:241-245 clock-drift branch
    included: columns from the future simply never exist because time is a
    single host-stamped scalar).

Everything is a pure function of (state, now_ms); nothing reads a clock.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

# Event enum — mirrors MetricEvent.java:21 (RT and minRt live in the float
# planes; OCCUPIED_PASS is kept for future-occupancy parity).
EV_PASS = 0
EV_BLOCK = 1
EV_EXCEPTION = 2
EV_SUCCESS = 3
EV_OCCUPIED = 4
NUM_EVENTS = 5

# rt_min initial value — requests never exceed statistic_max_rt (5000 ms,
# SentinelConfig.java:63); this also matches StatisticNode minRt semantics.
RT_MIN_INIT = 5000.0


class WindowConfig(NamedTuple):
    sample_count: int  # number of buckets (nb)
    window_ms: int  # bucket length

    @property
    def interval_ms(self) -> int:
        return self.sample_count * self.window_ms


class WindowState(NamedTuple):
    counts: jax.Array  # int32 [rows, nb, NUM_EVENTS]
    rt_sum: jax.Array  # float32 [rows, nb]
    rt_min: jax.Array  # float32 [rows, nb]
    epochs: jax.Array  # int32 [nb]


def init_window(rows: int, cfg: WindowConfig) -> WindowState:
    nb = cfg.sample_count
    return WindowState(
        counts=jnp.zeros((rows, nb, NUM_EVENTS), dtype=jnp.int32),
        rt_sum=jnp.zeros((rows, nb), dtype=jnp.float32),
        rt_min=jnp.full((rows, nb), RT_MIN_INIT, dtype=jnp.float32),
        # any epoch older than (0 - nb) is invalid from t=0
        epochs=jnp.full((nb,), -(cfg.sample_count + 1), dtype=jnp.int32),
    )


def _wid(now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    return (now_ms // cfg.window_ms).astype(jnp.int32)


def current_index(now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    return _wid(now_ms, cfg) % cfg.sample_count


def refresh(state: WindowState, now_ms: jax.Array, cfg: WindowConfig) -> WindowState:
    """Lazily reset the current column if it holds an old window.

    Batched analog of LeapArray.java:149-248 (CAS-create / reuse /
    tryLock-reset), applied to all rows of the column at once.

    Masked single-column update instead of lax.cond: an XLA cond's
    identity branch materializes a copy of every carried buffer (~20 MB
    for the minute window — a measured ~0.1 ms/tick fixed cost each),
    while the masked form touches one column in place under donation.
    """
    wid = _wid(now_ms, cfg)
    idx = wid % cfg.sample_count
    fresh = state.epochs[idx] == wid
    keep_i = fresh.astype(state.counts.dtype)
    keep_f = fresh.astype(jnp.float32)
    return WindowState(
        counts=state.counts.at[:, idx, :].multiply(keep_i),
        rt_sum=state.rt_sum.at[:, idx].multiply(keep_f),
        rt_min=state.rt_min.at[:, idx].set(
            jnp.where(fresh, state.rt_min[:, idx], RT_MIN_INIT)
        ),
        # reuse keeps epoch == wid, reset stamps it — identical either way
        epochs=state.epochs.at[idx].set(wid),
    )


def add_batch(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,  # int32 [B] — row per event (trash row for padding)
    deltas: jax.Array,  # int32 [B, NUM_EVENTS]
    rt: Optional[jax.Array] = None,  # float32 [B] — RT contribution (0 if none)
    cfg: WindowConfig = None,
) -> WindowState:
    """Scatter a micro-batch of events into the current bucket column.

    Duplicate rows accumulate (scatter-add), which is the batched form of
    the reference's LongAdder.add on the current WindowWrap.
    """
    state = refresh(state, now_ms, cfg)
    idx = current_index(now_ms, cfg)
    counts = state.counts.at[rows, idx, :].add(deltas, mode="drop")
    if rt is not None:
        rt_sum = state.rt_sum.at[rows, idx].add(rt, mode="drop")
        # min only among events that actually carry an RT (rt > 0 marks them;
        # use a large fill for non-carriers so they don't clobber the min)
        rt_for_min = jnp.where(rt > 0, rt, jnp.float32(RT_MIN_INIT))
        rt_min = state.rt_min.at[rows, idx].min(rt_for_min, mode="drop")
    else:
        rt_sum, rt_min = state.rt_sum, state.rt_min
    return WindowState(counts=counts, rt_sum=rt_sum, rt_min=rt_min, epochs=state.epochs)


def add_dense(
    state: WindowState,
    now_ms: jax.Array,
    count_hist: jax.Array,  # int32 [rows, NUM_EVENTS] — dense per-row deltas
    rt_hist: Optional[jax.Array],  # float32 [rows] or None
    cfg: WindowConfig,
    row_min=None,  # optional (mins f32 [rows], present bool [rows])
) -> WindowState:
    """Apply a precomputed dense per-row delta to the current bucket column.

    The MXU-path companion of add_batch: the batch is first reduced to a
    dense histogram (ops/tables.histogram — one-hot matmuls), then landing
    it in the window is a plain elementwise add on the current column.
    Per-row rt_min lands from ``row_min`` — the exact dense minimum built
    by ops/rowmin.py (sort + segmented scan + head sum-scatter)."""
    state = refresh(state, now_ms, cfg)
    idx = current_index(now_ms, cfg)
    counts = state.counts.at[:, idx, :].add(count_hist.astype(state.counts.dtype))
    rt_sum = state.rt_sum if rt_hist is None else state.rt_sum.at[:, idx].add(rt_hist)
    rt_min = state.rt_min
    if row_min is not None:
        mins, present = row_min
        rt_min = rt_min.at[:, idx].min(
            jnp.where(present, mins, jnp.float32(RT_MIN_INIT))
        )
    return WindowState(
        counts=counts, rt_sum=rt_sum, rt_min=rt_min, epochs=state.epochs
    )


def min_into_row(
    state: WindowState, now_ms: jax.Array, row: int, value: jax.Array, cfg: WindowConfig
) -> WindowState:
    """Scatter-min a scalar into ONE fixed row's current bucket (static
    index — cheap): keeps ENTRY-node minRt exact for the BBR system check
    while the dense path skips per-row minimums."""
    idx = current_index(now_ms, cfg)
    rt_min = state.rt_min.at[row, idx].min(value)
    return state._replace(rt_min=rt_min)


def valid_mask(state: WindowState, now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    """bool [nb] — which columns fall inside [now - interval, now]."""
    wid = _wid(now_ms, cfg)
    return (state.epochs > wid - cfg.sample_count) & (state.epochs <= wid)


def window_counts(state: WindowState, now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    """int32 [rows, NUM_EVENTS] — sum over valid buckets (ArrayMetric reads)."""
    mask = valid_mask(state, now_ms, cfg)  # [nb]
    return jnp.sum(state.counts * mask[None, :, None], axis=1)


def window_event(
    state: WindowState, now_ms: jax.Array, cfg: WindowConfig, event: int
) -> jax.Array:
    """int32 [rows] — windowed total of one event across all rows."""
    mask = valid_mask(state, now_ms, cfg)
    return jnp.sum(state.counts[:, :, event] * mask[None, :], axis=1)


def window_rt(state: WindowState, now_ms: jax.Array, cfg: WindowConfig):
    """(rt_total f32 [rows], rt_min f32 [rows]) over valid buckets."""
    mask = valid_mask(state, now_ms, cfg)
    rt_total = jnp.sum(state.rt_sum * mask[None, :], axis=1)
    rt_min = jnp.min(
        jnp.where(mask[None, :], state.rt_min, jnp.float32(RT_MIN_INIT)), axis=1
    )
    return rt_total, rt_min


def gather_window_event(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,  # int32 [B]
    cfg: WindowConfig,
    event: int,
) -> jax.Array:
    """int32 [B] — windowed event total for selected rows only.

    The decision path reads only the rows referenced by the batch, so this
    is a [B, nb] gather instead of a full [rows, nb] reduction.
    """
    mask = valid_mask(state, now_ms, cfg)  # [nb]
    vals = state.counts[rows, :, event]  # [B, nb] gather
    return jnp.sum(vals * mask[None, :], axis=1)


def gather_window_counts(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,
    cfg: WindowConfig,
) -> jax.Array:
    """int32 [B, NUM_EVENTS] for selected rows."""
    mask = valid_mask(state, now_ms, cfg)
    vals = state.counts[rows, :, :]  # [B, nb, NE]
    return jnp.sum(vals * mask[None, :, None], axis=1)


def gather_window_rt(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,
    cfg: WindowConfig,
):
    """(rt_total f32 [B], rt_min f32 [B]) for selected rows."""
    mask = valid_mask(state, now_ms, cfg)
    rt_total = jnp.sum(state.rt_sum[rows, :] * mask[None, :], axis=1)
    rt_min = jnp.min(
        jnp.where(mask[None, :], state.rt_min[rows, :], jnp.float32(RT_MIN_INIT)),
        axis=1,
    )
    return rt_total, rt_min
