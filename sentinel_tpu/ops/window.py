"""Vectorized sliding-window statistics — the TPU-native LeapArray.

The reference keeps one lock-free ring of buckets *per resource*
(sentinel-core/.../slots/statistic/base/LeapArray.java:41): bucket index is
``(timeMs / windowLengthInMs) % sampleCount`` (LeapArray.java:112-124), and a
deprecated bucket is lazily reset when next written (LeapArray.java:149-248).
Per-bucket counters are LongAdders over the event enum
(MetricBucket.java:28, MetricEvent.java:21).

Here ALL resources share one ring-buffer tensor:

    counts : int32  [rows, nbp, NE]  (PASS, BLOCK, EXCEPTION, SUCCESS, OCCUPIED)
    rt_sum : float32[rows, nbp]
    rt_min : float32[rows, nbp]
    epochs : int32  [nbp]            window-id currently held by each column

plus O(1) RUNNING window sums (arXiv 1604.02450 — subtract-expired /
add-new), maintained at write time and corrected at bucket rotation:

    run        : int32  [rows, NE]  windowed event totals
    run_rt     : float32[rows]      windowed RT sum
    run_rt_min : float32[rows]      windowed RT minimum
    rot_wid    : int32  []          wid of the last batched expiry

and the per-resource CAS dance collapses into three vectorized rules:

  * WRITE  (add_batch / add_dense): all events in a micro-batch share one
    ``now_ms``, so only column ``wid % nbp`` is touched; if its epoch !=
    wid the whole column (all rows at once) is zeroed first — the batched
    form of "reset deprecated bucket on wrap".  Every write also lands in
    the running sums.
  * ROTATE (refresh): when the bucket id advances past the last expiry
    (every ``slack_buckets`` buckets — 1 by default), ALL expired columns
    leave the running sums in one vectorized masked reduction (the
    2305.16513 batched rotation kernel) under a lax.cond whose outputs are
    only the O(rows) running-sum arrays — the big bucket tensors stay out
    of the cond, so its identity branch copies O(rows) bytes, not the
    window.  Expired columns are stamped ``PURGED`` (never re-subtracted)
    and their storage is zeroed lazily when the cursor next reaches them.
  * READ: exact masked reads stay available — a column is valid iff its
    AGE ``wid - epochs[b]`` lies in [0, nb) (wraparound-safe modular
    arithmetic; columns from the future simply never exist because time is
    a single host-stamped scalar).  The ``*_run`` read family instead
    returns the running sums directly — single O(rows)/O(B) gathers with
    no per-read reduction over the bucket axis.  They are EXACT whenever a
    refresh ran in the same bucket as the read (the engine-tick contract:
    completions refresh before any check reads); between refreshes they
    only ever OVERESTIMATE (lazy expiry — the fail-closed direction).

Slack windows (arXiv 1703.01166): ``WindowConfig.slack_frac > 0`` batches
rotation/expiry to every ``ceil(slack_frac * nb)`` buckets.  The ring
carries ``slack_buckets - 1`` extra physical columns so the write cursor
only ever lands on columns the last batched expiry already purged — no
live/stale mixing.  Expired-but-unpurged columns remain counted for at
most ``slack_buckets - 1`` bucket lengths: a bounded OVERESTIMATE (the
documented error direction), zero when slack is off (the default for the
exact second-scale window).

Everything is a pure function of (state, now_ms); nothing reads a clock.
``now_ms`` is interpreted as UNSIGNED 32-bit engine-ms: the window id
stays continuous when the host's int32 engine clock wraps past 2^31
(~24.8 days at 1 ms buckets) and only resets at the full 2^32 horizon.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

# Event enum — mirrors MetricEvent.java:21 (RT and minRt live in the float
# planes; OCCUPIED_PASS is kept for future-occupancy parity).
EV_PASS = 0
EV_BLOCK = 1
EV_EXCEPTION = 2
EV_SUCCESS = 3
EV_OCCUPIED = 4
NUM_EVENTS = 5

# rt_min initial value — requests never exceed statistic_max_rt (5000 ms,
# SentinelConfig.java:63); this also matches StatisticNode minRt semantics.
RT_MIN_INIT = 5000.0

#: epoch sentinel for a column whose contents already left the running sums
#: (batched expiry) but whose storage has not been zeroed yet — far outside
#: any reachable window id so the age test can never read it as live
PURGED = -(1 << 30)


class WindowConfig(NamedTuple):
    sample_count: int  # number of logical buckets (nb)
    window_ms: int  # bucket length
    # slack fraction (arXiv 1703.01166): batch rotation/expiry to every
    # ceil(slack_frac * nb) buckets, accepting a bounded overestimate-only
    # window slack.  0.0 (default) = exact rotation every bucket.
    slack_frac: float = 0.0

    @property
    def interval_ms(self) -> int:
        return self.sample_count * self.window_ms

    @property
    def slack_buckets(self) -> int:
        """Buckets between batched expiries (g) — 1 means no slack."""
        import math

        if self.slack_frac <= 0.0:
            return 1
        return max(1, math.ceil(self.slack_frac * self.sample_count))

    @property
    def phys_buckets(self) -> int:
        """Physical ring columns (nbp = nb + g - 1): the extra ``g - 1``
        columns guarantee the write cursor only reaches columns the last
        batched expiry already purged."""
        return self.sample_count + self.slack_buckets - 1


class WindowState(NamedTuple):
    counts: jax.Array  # int32 [rows, nbp, NUM_EVENTS]
    rt_sum: jax.Array  # float32 [rows, nbp]
    rt_min: jax.Array  # float32 [rows, nbp]
    epochs: jax.Array  # int32 [nbp]
    run: jax.Array  # int32 [rows, NUM_EVENTS] — O(1) windowed totals
    run_rt: jax.Array  # float32 [rows] — O(1) windowed RT sum
    run_rt_min: jax.Array  # float32 [rows] — windowed RT minimum
    rot_wid: jax.Array  # int32 [] — wid of the last batched expiry


def init_window(rows: int, cfg: WindowConfig) -> WindowState:
    nbp = cfg.phys_buckets
    return WindowState(
        counts=jnp.zeros((rows, nbp, NUM_EVENTS), dtype=jnp.int32),
        rt_sum=jnp.zeros((rows, nbp), dtype=jnp.float32),
        rt_min=jnp.full((rows, nbp), RT_MIN_INIT, dtype=jnp.float32),
        # any epoch older than (0 - nb) is invalid from t=0
        epochs=jnp.full((nbp,), -(cfg.sample_count + 1), dtype=jnp.int32),
        run=jnp.zeros((rows, NUM_EVENTS), dtype=jnp.int32),
        run_rt=jnp.zeros((rows,), dtype=jnp.float32),
        run_rt_min=jnp.full((rows,), RT_MIN_INIT, dtype=jnp.float32),
        rot_wid=jnp.int32(-(cfg.sample_count + 1)),
    )


def wid_of(now_ms: jax.Array, window_ms: int) -> jax.Array:
    """Window id of an engine-ms timestamp, continuous across the int32
    clock wrap.

    ``now_ms`` bits are reinterpreted as UNSIGNED 32-bit before the
    division: the old signed form snapped to a discontinuous negative wid
    at 2^31 (~24.8 days of engine-ms at 1 ms buckets) and silently reset
    every window; unsigned division keeps ids marching to the full 2^32
    horizon (~49.7 days), and all epoch comparisons downstream use modular
    AGE differences, which stay exact for spans < 2^31 windows."""
    u = jnp.asarray(now_ms).astype(jnp.uint32)
    return (u // jnp.uint32(window_ms)).astype(jnp.int32)


def _wid(now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    return wid_of(now_ms, cfg.window_ms)


def current_index(now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    u = jnp.asarray(now_ms).astype(jnp.uint32)
    return ((u // jnp.uint32(cfg.window_ms)) % jnp.uint32(cfg.phys_buckets)).astype(
        jnp.int32
    )


def _age(wid: jax.Array, epochs: jax.Array) -> jax.Array:
    """Buckets-ago of each column, in wraparound-safe modular int32."""
    return wid - epochs


def refresh(state: WindowState, now_ms: jax.Array, cfg: WindowConfig) -> WindowState:
    """Rotate: batched expiry of the running sums + lazy reset of the
    current column.

    Batched analog of LeapArray.java:149-248 (CAS-create / reuse /
    tryLock-reset), applied to all rows of the column at once.

    The expiry reductions (one masked pass over [rows, nbp] — the
    2305.16513 rotation kernel) run under lax.cond gated on the bucket id
    actually advancing past the last expiry, so steady-state ticks inside
    one bucket pay O(rows) for the cond pass-through, not O(rows * nb).
    The big bucket tensors are NOT cond outputs (an identity branch would
    copy them — ~20 MB for the minute window, a measured ~0.1 ms/tick
    fixed cost each); the current column is zeroed with a masked
    single-column update in place under donation, exactly as before.
    """
    nb = cfg.sample_count
    nbp = cfg.phys_buckets
    g = cfg.slack_buckets
    wid = _wid(now_ms, cfg)
    idx = current_index(now_ms, cfg)

    cur_epoch = state.epochs[idx]
    fresh = cur_epoch == wid
    cur_unpurged = ~fresh & (cur_epoch != PURGED)
    # rotation due: the bucket id advanced g past the last batched expiry,
    # or the write cursor reached a column whose contents are still in the
    # running sums (safety net: slack invariant violations can only come
    # from the 2^32 engine-clock horizon — never let run leak permanently)
    due = (_age(wid, state.rot_wid) >= g) | cur_unpurged

    cur_onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (nbp,), 0) == idx
    )

    def _expire(run, run_rt, run_rt_min, epochs):
        age = _age(wid, epochs)
        live = (age >= 0) & (age < nb) & (epochs != PURGED)
        # everything outside the window — plus the cursor's own column if
        # it is about to be recycled — leaves the running sums at once
        doomed = (~live | (cur_onehot & ~fresh)) & (epochs != PURGED)
        dm_i = doomed.astype(jnp.int32)[None, :, None]
        dm_f = doomed.astype(jnp.float32)[None, :]
        gone = jnp.sum(state.counts * dm_i, axis=1)
        gone_rt = jnp.sum(state.rt_sum * dm_f, axis=1)
        survivors = live & ~doomed
        new_min = jnp.min(
            jnp.where(survivors[None, :], state.rt_min, jnp.float32(RT_MIN_INIT)),
            axis=1,
        )
        return (
            run - gone,
            run_rt - gone_rt,
            new_min,
            jnp.where(doomed, PURGED, epochs),
            wid,
        )

    def _skip(run, run_rt, run_rt_min, epochs):
        return run, run_rt, run_rt_min, epochs, state.rot_wid

    run, run_rt, run_rt_min, epochs, rot_wid = jax.lax.cond(
        due,
        _expire,
        _skip,
        state.run,
        state.run_rt,
        state.run_rt_min,
        state.epochs,
    )

    keep_i = fresh.astype(state.counts.dtype)
    keep_f = fresh.astype(jnp.float32)
    return WindowState(
        counts=state.counts.at[:, idx, :].multiply(keep_i),
        rt_sum=state.rt_sum.at[:, idx].multiply(keep_f),
        rt_min=state.rt_min.at[:, idx].set(
            jnp.where(fresh, state.rt_min[:, idx], RT_MIN_INIT)
        ),
        # reuse keeps epoch == wid, reset stamps it — identical either way
        epochs=epochs.at[idx].set(wid),
        run=run,
        run_rt=run_rt,
        run_rt_min=run_rt_min,
        rot_wid=jnp.asarray(rot_wid, jnp.int32),
    )


def add_batch(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,  # int32 [B] — row per event (trash row for padding)
    deltas: jax.Array,  # int32 [B, NUM_EVENTS]
    rt: Optional[jax.Array] = None,  # float32 [B] — RT contribution (0 if none)
    cfg: WindowConfig = None,
) -> WindowState:
    """Scatter a micro-batch of events into the current bucket column.

    Duplicate rows accumulate (scatter-add), which is the batched form of
    the reference's LongAdder.add on the current WindowWrap.  Every delta
    also lands in the running sums (the 1604.02450 add-new half)."""
    state = refresh(state, now_ms, cfg)
    idx = current_index(now_ms, cfg)
    counts = state.counts.at[rows, idx, :].add(deltas, mode="drop")
    run = state.run.at[rows, :].add(deltas, mode="drop")
    run_rt_min = state.run_rt_min
    if rt is not None:
        rt_sum = state.rt_sum.at[rows, idx].add(rt, mode="drop")
        run_rt = state.run_rt.at[rows].add(rt, mode="drop")
        # min only among events that actually carry an RT (rt > 0 marks them;
        # use a large fill for non-carriers so they don't clobber the min)
        rt_for_min = jnp.where(rt > 0, rt, jnp.float32(RT_MIN_INIT))
        rt_min = state.rt_min.at[rows, idx].min(rt_for_min, mode="drop")
        run_rt_min = run_rt_min.at[rows].min(rt_for_min, mode="drop")
    else:
        rt_sum, rt_min, run_rt = state.rt_sum, state.rt_min, state.run_rt
    return state._replace(
        counts=counts,
        rt_sum=rt_sum,
        rt_min=rt_min,
        run=run,
        run_rt=run_rt,
        run_rt_min=run_rt_min,
    )


def add_dense(
    state: WindowState,
    now_ms: jax.Array,
    count_hist: jax.Array,  # int32 [rows, NUM_EVENTS] — dense per-row deltas
    rt_hist: Optional[jax.Array],  # float32 [rows] or None
    cfg: WindowConfig,
    row_min=None,  # optional (mins f32 [rows], present bool [rows])
) -> WindowState:
    """Apply a precomputed dense per-row delta to the current bucket column.

    The MXU-path companion of add_batch: the batch is first reduced to a
    dense histogram (ops/tables.histogram — one-hot matmuls), then landing
    it in the window is a plain elementwise add on the current column AND
    on the running sums.  Per-row rt_min lands from ``row_min`` — the
    exact dense minimum built by ops/rowmin.py (sort + segmented scan +
    head sum-scatter)."""
    state = refresh(state, now_ms, cfg)
    idx = current_index(now_ms, cfg)
    ch = count_hist.astype(state.counts.dtype)
    counts = state.counts.at[:, idx, :].add(ch)
    run = state.run + ch
    if rt_hist is None:
        rt_sum, run_rt = state.rt_sum, state.run_rt
    else:
        rt_sum = state.rt_sum.at[:, idx].add(rt_hist)
        run_rt = state.run_rt + rt_hist
    rt_min = state.rt_min
    run_rt_min = state.run_rt_min
    if row_min is not None:
        mins, present = row_min
        filled = jnp.where(present, mins, jnp.float32(RT_MIN_INIT))
        rt_min = rt_min.at[:, idx].min(filled)
        run_rt_min = jnp.minimum(run_rt_min, filled)
    return state._replace(
        counts=counts,
        rt_sum=rt_sum,
        rt_min=rt_min,
        run=run,
        run_rt=run_rt,
        run_rt_min=run_rt_min,
    )


def add_row_delta(
    state: WindowState,
    now_ms: jax.Array,
    row: int,
    deltas: jax.Array,  # int32 [NUM_EVENTS]
    rt: Optional[jax.Array],  # float32 scalar or None
    cfg: WindowConfig,
) -> WindowState:
    """Add a single fixed row's delta vector (static row index — cheap).

    The ENTRY-node reduction path: the caller already summed the batch, so
    this is one .at[row] update on the bucket column and the running sums
    (keeping both in lockstep — direct field writes would silently leave
    the running sums behind).  The caller must have refreshed this
    ``now_ms`` already (it always lands right after add_batch/add_dense)."""
    idx = current_index(now_ms, cfg)
    counts = state.counts.at[row, idx, :].add(deltas)
    run = state.run.at[row, :].add(deltas)
    if rt is None:
        return state._replace(counts=counts, run=run)
    return state._replace(
        counts=counts,
        run=run,
        rt_sum=state.rt_sum.at[row, idx].add(rt),
        run_rt=state.run_rt.at[row].add(rt),
    )


def min_into_row(
    state: WindowState, now_ms: jax.Array, row: int, value: jax.Array, cfg: WindowConfig
) -> WindowState:
    """Scatter-min a scalar into ONE fixed row's current bucket (static
    index — cheap): keeps ENTRY-node minRt exact for the BBR system check
    while the dense path skips per-row minimums."""
    idx = current_index(now_ms, cfg)
    rt_min = state.rt_min.at[row, idx].min(value)
    run_rt_min = state.run_rt_min.at[row].min(value)
    return state._replace(rt_min=rt_min, run_rt_min=run_rt_min)


def valid_mask(state: WindowState, now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    """bool [nbp] — which columns fall inside [now - interval, now]."""
    age = _age(_wid(now_ms, cfg), state.epochs)
    return (age >= 0) & (age < cfg.sample_count) & (state.epochs != PURGED)


# -- exact masked reads (host observability, migration, oracles) -------------


def window_counts(state: WindowState, now_ms: jax.Array, cfg: WindowConfig) -> jax.Array:
    """int32 [rows, NUM_EVENTS] — sum over valid buckets (ArrayMetric reads).

    Exact at any ``now_ms`` — pays a [rows, nbp] reduction per call; the
    tick hot path reads the running sums instead (window_counts_run)."""
    mask = valid_mask(state, now_ms, cfg)  # [nbp]
    return jnp.sum(state.counts * mask[None, :, None], axis=1)


def window_event(
    state: WindowState, now_ms: jax.Array, cfg: WindowConfig, event: int
) -> jax.Array:
    """int32 [rows] — windowed total of one event across all rows."""
    mask = valid_mask(state, now_ms, cfg)
    return jnp.sum(state.counts[:, :, event] * mask[None, :], axis=1)


def window_rt(state: WindowState, now_ms: jax.Array, cfg: WindowConfig):
    """(rt_total f32 [rows], rt_min f32 [rows]) over valid buckets."""
    mask = valid_mask(state, now_ms, cfg)
    rt_total = jnp.sum(state.rt_sum * mask[None, :], axis=1)
    rt_min = jnp.min(
        jnp.where(mask[None, :], state.rt_min, jnp.float32(RT_MIN_INIT)), axis=1
    )
    return rt_total, rt_min


def gather_window_event(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,  # int32 [B]
    cfg: WindowConfig,
    event: int,
) -> jax.Array:
    """int32 [B] — windowed event total for selected rows only (exact
    masked form — a [B, nbp] gather + reduction)."""
    mask = valid_mask(state, now_ms, cfg)  # [nbp]
    vals = state.counts[rows, :, event]  # [B, nbp] gather
    return jnp.sum(vals * mask[None, :], axis=1)


def gather_window_counts(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,
    cfg: WindowConfig,
) -> jax.Array:
    """int32 [B, NUM_EVENTS] for selected rows."""
    mask = valid_mask(state, now_ms, cfg)
    vals = state.counts[rows, :, :]  # [B, nbp, NE]
    return jnp.sum(vals * mask[None, :, None], axis=1)


def gather_window_rt(
    state: WindowState,
    now_ms: jax.Array,
    rows: jax.Array,
    cfg: WindowConfig,
):
    """(rt_total f32 [B], rt_min f32 [B]) for selected rows."""
    mask = valid_mask(state, now_ms, cfg)
    rt_total = jnp.sum(state.rt_sum[rows, :] * mask[None, :], axis=1)
    rt_min = jnp.min(
        jnp.where(mask[None, :], state.rt_min[rows, :], jnp.float32(RT_MIN_INIT)),
        axis=1,
    )
    return rt_total, rt_min


# -- O(1) running-sum reads (the tick hot path) ------------------------------
#
# Single gathers from the running sums: no bucket-axis reduction, cost
# O(rows) / O(B) regardless of the window shape.  EXACT whenever refresh
# ran in the read's bucket (the engine tick refreshes on the completion
# write before any check reads, all at one now_ms); otherwise they lag
# expiry and only ever OVERESTIMATE (lazy expiry — fail-closed).  Under
# slack they additionally carry the configured bounded slack overestimate.


def window_counts_run(state: WindowState) -> jax.Array:
    """int32 [rows, NUM_EVENTS] — windowed totals, zero reduction."""
    return state.run


def window_event_run(state: WindowState, event: int) -> jax.Array:
    """int32 [rows] — one event's windowed totals, zero reduction."""
    return state.run[:, event]


def gather_window_event_run(
    state: WindowState, rows: jax.Array, event: int
) -> jax.Array:
    """int32 [B] — single gather from the running sums."""
    return state.run[rows, event]


def gather_window_counts_run(state: WindowState, rows: jax.Array) -> jax.Array:
    """int32 [B, NUM_EVENTS] — single gather from the running sums."""
    return state.run[rows, :]


def gather_window_rt_run(state: WindowState, rows: jax.Array):
    """(rt_total f32 [B], rt_min f32 [B]) — single gathers."""
    return state.run_rt[rows], state.run_rt_min[rows]
