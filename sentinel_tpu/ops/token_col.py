"""Cluster token decision column — the shard's device-answered batch.

Protocol v2 coalesces BATCH frames from many connections into one
decision batch (cluster/token_service.TokenColumnBatcher).  This module
is the jitted kernel that answers it: every cluster flow owns one row
("slot") of a shared sliding-window tensor (ops/window.py — the same
epoch-validated O(1) running-sum shape as the engine tier, arXiv
1604.02450), and one call decides B entries against their per-flow
global thresholds in a single gather + prefix-sum + scatter-add.

Within-batch ordering: entries arrive PRESORTED by slot (host presort,
native batch_sort3), and ``heads[i]`` is the index of the first entry of
entry *i*'s slot run.  An exclusive prefix sum of requested units,
rebased at each head, charges every entry with the units requested by
SAME-slot entries ahead of it in the batch — so one coalesced batch
admits exactly what sequential requests would have.  The prefix charges
*requested* (not granted) units: a denied all-or-nothing entry still
reserves its ask against later same-slot entries of the SAME batch.
That slack is bounded by one batch and errs toward under-admission —
the fail-closed direction.

Decision semantics per entry (matching the engine's GlobalRequestLimiter
``used + units <= threshold``):

  all-or-nothing (partial=False): granted = units if avail >= units else 0
  partial-grant  (partial=True):  granted = clip(floor(avail), 0, units)
  forced         (forced=True):   granted = units unconditionally — the
      occupy-ahead emulation: a prioritized over-limit ask charges its
      units anyway (against the CURRENT bucket, one bucket earlier than
      the engine's tryOccupyNext — the conservative direction) and the
      host answers SHOULD_WAIT with the time to the next bucket.

Granted units land in the window as EV_PASS, denied as EV_BLOCK, so the
window IS the budget ledger — replenishment is bucket expiry, identical
to the engine tier.  Everything is a pure function of (state, now_ms);
nothing reads a clock.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.ops import window as W

#: shard decision window: DEFAULT_SAMPLE_COUNT buckets over one
#: DEFAULT_INTERVAL_MS accounting interval (cluster/constants.py values,
#: restated literally to keep ops/ free of cluster imports)
DEFAULT_CFG = W.WindowConfig(sample_count=10, window_ms=100)


class TokenColState(NamedTuple):
    win: W.WindowState  # per-slot pass/block ledger
    limits: jax.Array  # float32 [slots] — global threshold per flow slot


def init_state(slots: int, cfg: W.WindowConfig = DEFAULT_CFG) -> TokenColState:
    return TokenColState(
        win=W.init_window(slots, cfg),
        limits=jnp.zeros((slots,), dtype=jnp.float32),
    )


def decide_batch(
    state: TokenColState,
    now_ms: jax.Array,  # int32/int64 scalar — host-stamped batch time
    slots: jax.Array,  # int32 [B] — flow slot per entry (slot-sorted)
    units: jax.Array,  # int32 [B] — requested units (0 = padding)
    heads: jax.Array,  # int32 [B] — index of entry's slot-run head
    partial: jax.Array,  # bool [B] — partial-grant vs all-or-nothing
    forced: jax.Array,  # bool [B] — unconditional charge (occupy-ahead)
    cfg: W.WindowConfig = DEFAULT_CFG,
) -> Tuple[jax.Array, jax.Array, TokenColState]:
    """(granted int32 [B], observed float32 [B], updated ledger state).

    ``observed`` is the window usage each entry was decided against
    (used + same-batch prefix) — the deny-provenance value the protocol
    v3 _T_PROV block ships back to clients, so a remote block can report
    "observed N of limit M" like a local one (obs/explain.py)."""
    # rotate once up front so the O(1) running sums are exact at this
    # now_ms, then the ledger read is a single [B] gather instead of the
    # old masked [B, nb] reduction per batch
    win = W.refresh(state.win, now_ms, cfg)
    used = W.gather_window_event_run(win, slots, W.EV_PASS)
    # per-entry ask clipped so an int32 cumsum over MAX_BATCH_ENTRIES
    # cannot overflow (2048 × 2^20 < 2^31); a single ask beyond 1M units
    # is already past every sane threshold and the lease ceiling
    units = jnp.minimum(units, jnp.int32(1 << 20))
    # exclusive prefix of requested units, rebased per slot run
    ex = jnp.cumsum(units) - units
    prefix = ex - ex[heads]
    observed = used.astype(jnp.float32) + prefix.astype(jnp.float32)
    avail = state.limits[slots] - observed
    units_f = units.astype(jnp.float32)
    grant_partial = jnp.clip(jnp.floor(avail), 0.0, units_f)
    grant_strict = jnp.where(avail >= units_f, units_f, 0.0)
    granted = jnp.where(partial, grant_partial, grant_strict).astype(jnp.int32)
    granted = jnp.where(forced, units, granted)
    deltas = jnp.zeros((slots.shape[0], W.NUM_EVENTS), dtype=jnp.int32)
    deltas = deltas.at[:, W.EV_PASS].set(granted)
    deltas = deltas.at[:, W.EV_BLOCK].set(units - granted)
    win = W.add_batch(win, now_ms, slots, deltas, cfg=cfg)
    return granted, observed, TokenColState(win=win, limits=state.limits)


def ms_to_next_bucket(now_ms: int, cfg: W.WindowConfig = DEFAULT_CFG) -> int:
    """Host helper: ms until the next bucket boundary — the SHOULD_WAIT
    horizon for the occupy-ahead emulation.  Always in [1, window_ms]."""
    return int(cfg.window_ms - (now_ms % cfg.window_ms))


def set_limits(state: TokenColState, limits: jax.Array) -> TokenColState:
    """Replace the per-slot thresholds (rule push / census reprojection)
    without disturbing the standing window ledger."""
    return TokenColState(win=state.win, limits=limits.astype(jnp.float32))


@functools.lru_cache(maxsize=None)
def jitted_decide(cfg: W.WindowConfig = DEFAULT_CFG):
    """Process-shared jitted decide_batch for one window config — every
    TokenColumnBatcher instance reuses the same compiled executables
    (keyed by shape), so a test suite constructing many services pays
    XLA compilation once per (slots, batch) shape, not per service."""
    return jax.jit(functools.partial(decide_batch, cfg=cfg))
