"""Pallas segmented exclusive prefix sums — the rank-scan hot path.

`segment.seg_excl_cumsum` computed segmented sums as int32 cumsum +
two-level running-max + subtract: exact, but ~1.2 ms of XLA scan ops at
B=128K (profiled: associative_scan slices + reduce-windows dominate the
check phase's rank costs).  This kernel computes the segmented sums
directly in one sequential-grid pass:

  - the exclusive segmented sum equals the INCLUSIVE segmented scan of
    the right-shifted values (sv[i] = head[i] ? 0 : v[i-1]) with the
    heads as reset flags;
  - per 256-item tile, that scan is 8 log-steps of the classic segmented
    combine — s[i] += f[i] ? 0 : s[i-d]; f[i] |= f[i-d] — pure int32
    VPU rolls/selects/adds, bit-exact by construction.  (An earlier
    masked-matmul formulation spent ~0.3 ms/call building [256,256]
    masks on the VPU and LOST to the XLA scans it replaced — measured.)
  - a carry per value row rides VMEM scratch across tiles (sequential
    "arbitrary" grid).  After the within-tile scan, the open segment's
    sum is simply s[TB-1] + v[TB-1], and items before the tile's first
    head add the incoming carry.  int32 wraparound cannot occur within
    the caller contract (per-segment totals < 2^31).

Interpret mode runs the identical kernel on CPU for tests; the public
entries fall back to segment.seg_excl_cumsum when Pallas is unavailable
(SENTINEL_NO_PALLAS).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sentinel_tpu.ops import fused as FU

#: tile length: the grid is SEQUENTIAL (carry), so per-tile overhead is
#: the dominant cost — 2048-item tiles keep the step count low (64 tiles
#: at B=128K; 256-item tiles cost ~0.4 ms/call in pure grid overhead,
#: measured) while the log-step count only grows to 11
TB = 2048


def _kernel(head_ref, vals_ref, out_ref, carry):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    V = vals_ref.shape[0]

    @pl.when(t == 0)
    def _():
        carry[...] = jnp.zeros_like(carry)

    h = head_ref[:, :]  # int32 [1, TB] 0/1
    v = vals_ref[:, :]  # int32 [V, TB]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, TB), 1)

    def shift(x, d, fill):
        r = jnp.roll(x, d, axis=-1)
        return jnp.where(iota >= d, r, fill)

    # sv[i] = head[i] ? 0 : v[i-1]  (out-of-tile v treated as 0: the
    # cross-tile contribution rides the carry instead)
    s = jnp.where(h > 0, 0, shift(v, 1, 0))
    f = h
    d = 1
    while d < TB:
        s = s + jnp.where(f > 0, 0, shift(s, d, 0))
        f = jnp.maximum(f, shift(f, d, 0))
        d *= 2
    # s: within-tile EXCLUSIVE segmented sums; f[i]: any head at <= i

    c = carry[0:V, 0:1]  # [V, 1]
    out_ref[:, :] = s + jnp.where(f > 0, 0, c)

    # open segment's within-tile sum = s[last] + v[last]; a head-free tile
    # extends the previous carry
    open_sum = s[:, TB - 1 : TB] + v[:, TB - 1 : TB]  # [V, 1]
    any_head = f[0:1, TB - 1 : TB]  # [1, 1]
    carry[0:V, 0:1] = open_sum + jnp.where(any_head > 0, 0, c)


def seg_excl_cumsum_pl(head: jax.Array, values: jax.Array) -> jax.Array:
    """Drop-in for segment.seg_excl_cumsum: head [N] bool (head[0] True),
    values [V, N] or [N] nonnegative int32 with per-row segment totals
    < 2^31.  Exact; Pallas on TPU, XLA-scan fallback otherwise."""
    from sentinel_tpu.ops import segment as SG

    if not FU.available():
        return SG.seg_excl_cumsum(head, values)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    squeeze = values.ndim == 1
    v = values[None, :] if squeeze else values
    V, n = v.shape
    v = v.astype(jnp.int32)
    pad = (-n) % TB
    if pad:
        v = jnp.concatenate([v, jnp.zeros((V, pad), jnp.int32)], axis=1)
        head = jnp.concatenate([head, jnp.ones((pad,), bool)])
    Np = v.shape[1]
    nT = Np // TB

    out = FU.run_pallas(pl.pallas_call(
        _kernel,
        grid=(nT,),
        in_specs=[
            pl.BlockSpec((1, TB), lambda t: (0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((V, TB), lambda t: (0, t), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (V, TB), lambda t: (0, t), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((V, Np), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, 128), jnp.int32)],
        compiler_params=FU.tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=FU.interpret_mode(),
    ), head.astype(jnp.int32)[None, :], v,
        key=("seg_excl_cumsum", V, Np))

    res = out[:, :n]
    return res[0] if squeeze else res


def _kernel_min(head_ref, vals_ref, out_ref, carry):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    V = vals_ref.shape[0]

    @pl.when(t == 0)
    def _():
        carry[...] = jnp.full_like(carry, jnp.float32(3.0e38))

    h = head_ref[:, :]
    v = vals_ref[:, :]  # f32 [V, TB]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, TB), 1)
    BIG = jnp.float32(3.0e38)

    def shift(x, d):
        r = jnp.roll(x, d, axis=-1)
        return jnp.where(iota >= d, r, BIG)

    def shift_f(x, d):
        r = jnp.roll(x, d, axis=-1)
        return jnp.where(iota >= d, r, 0)

    # inclusive segmented running MIN of v (resets at heads)
    m = v
    f = h
    d = 1
    while d < TB:
        m = jnp.minimum(m, jnp.where(f > 0, BIG, shift(m, d)))
        f = jnp.maximum(f, shift_f(f, d))
        d *= 2

    c = carry[0:V, 0:1]
    res = jnp.minimum(m, jnp.where(f > 0, BIG, c))
    out_ref[:, :] = res
    carry[0:V, 0:1] = res[:, TB - 1 : TB]


def seg_incl_min_pl(head: jax.Array, values: jax.Array, fill: float) -> jax.Array:
    """Within-segment inclusive running minimum — the pallas form of
    segment.block_min_inclusive.  f32 min is order-free → bit-exact vs
    the associative-scan path.

    CALLER CONTRACT: heads must include segment.BLOCK-aligned synthetic
    boundaries (heads_from_keys produces them).  The pallas kernel is a
    true segmented min (cross-tile carry) and would ALSO handle longer
    runs, but the SENTINEL_NO_PALLAS fallback is block_min_inclusive,
    which resets at every BLOCK boundary regardless of heads — the two
    paths agree only under the block-capped contract."""
    from sentinel_tpu.ops import segment as SG

    if not FU.available():
        return SG.block_min_inclusive(head, values, fill)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = values.shape[0]
    v = values.astype(jnp.float32)[None, :]
    pad = (-n) % TB
    if pad:
        v = jnp.concatenate([v, jnp.full((1, pad), fill, jnp.float32)], axis=1)
        head = jnp.concatenate([head, jnp.ones((pad,), bool)])
    Np = v.shape[1]

    out = FU.run_pallas(pl.pallas_call(
        _kernel_min,
        grid=(Np // TB,),
        in_specs=[
            pl.BlockSpec((1, TB), lambda t: (0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TB), lambda t: (0, t), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, TB), lambda t: (0, t), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, Np), jnp.float32),
        scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
        compiler_params=FU.tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=FU.interpret_mode(),
    ), head.astype(jnp.int32)[None, :], v,
        key=("seg_incl_min", Np))
    # sentinel BIG never leaks: every segment has >= 1 item, and heads
    # reset the min to that item's value; fill only pads
    return out[0, :n]


def seg_excl_cumsum_wide_pl(head: jax.Array, values: jax.Array) -> jax.Array:
    """segment.seg_excl_cumsum_wide on the Pallas path: values <= 2^24
    (pacing costs) whose batch TOTAL may overflow int32.

    Exactly the original's scheme — two 12-bit digit lanes through the
    integer scan (per-lane totals <= 4095 * 2^23 < 2^31, int32-safe),
    recombined in f32 AFTER the exact integer differences — so results
    are bit-identical to segment.seg_excl_cumsum_wide.  (A first cut cast
    one int32 scan to f32 and WRAPPED once a segment's total crossed
    2^31 — caught on hardware by review; the rate-limiter rank path
    feeds exactly such totals on slow-pace rules over large batches.)"""
    from sentinel_tpu.ops import segment as SG

    if not FU.available():
        return SG.seg_excl_cumsum_wide(head, values)
    v = values.astype(jnp.int32)
    r = seg_excl_cumsum_pl(head, jnp.stack([v & 0xFFF, v >> 12]))
    return r[1].astype(jnp.float32) * 4096.0 + r[0].astype(jnp.float32)
