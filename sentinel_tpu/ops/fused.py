"""Fused Pallas effects-phase megakernels.

Why this exists (measured on v5e, see benchmarks/probe_fused_hist.py and
BENCH_r02): the XLA one-hot-matmul table path (ops/mxu_table.py) pays
~0.3-0.9 ms PER OP at B=128K regardless of FLOPs — every scatter/gather
materializes [B, n_lo] one-hot tensors in HBM and takes its own fusion,
and the tick makes ~25 such calls (19 ms total).  The fused formulation
runs ONE Pallas kernel per tick phase: each grid step loads a tile of
items into VMEM, builds the one-hot factors there, and contracts them
into EVERY destination table's accumulator (stat windows, circuit-breaker
columns, CMS sketch, per-rule scatters) without ever writing a one-hot to
HBM.  Measured: the 3B-item stat landing drops 5.0 ms -> ~1.3 ms; the
full set of effect scatters collapses from ~11 ms of serial fusions to
~2-3 ms of mostly-MXU work.

Exactness matches ops/mxu_table.py bit for bit: integer payloads are
decomposed into base-256 digit planes (bf16 represents 0..255 exactly, so
a DEFAULT-precision one-pass bf16 dot with a 0/1 one-hot side is exact),
accumulated in f32, and recombined with integer arithmetic outside the
kernel.  The same value bounds apply (counts <= 65535 via 2 digits,
rt_q <= 2^16, cells < 2^24 before f32 accumulation loses integers).

Reference map: this is the batched replacement for the reference's
per-request LongAdder writes in StatisticSlot.java:54-164 and the
LeapArray bucket adds (slots/statistic/base/LeapArray.java:41) — one
kernel landing a whole micro-batch of slot-chain side effects at once.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

#: default items per grid step.  Multi-job kernels unroll one [tb, N_LO]
#: LoV temporary per digit-dot; ~25 dots x tb=2048 x 128 x 2B ~= 13 MB
#: stays inside Mosaic's 16 MB scoped-vmem stack (tb=4096 overflows on
#: some job mixes) and measures within noise of 4096 at bench shapes.
TILE = 2048
#: gather kernels hold [tb, N_LO] f32 select products per unrolled digit
TILE_GATHER = 2048

#: one-hot minor-axis width — 128 lanes exactly, so Lo is a single vreg
#: column and the dot's N dim never pads
N_LO = 128


def tpu_compiler_params(**kw):
    """Mosaic compiler params across jax versions: the class was renamed
    TPUCompilerParams -> CompilerParams (jax 0.5); accept either."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)


@functools.cache
def _patch_eager_interpret_impl() -> bool:
    """jax<0.5 only: make EAGER interpret-mode pallas calls work.

    That jax's ``_pallas_call_impl`` re-binds the primitive inside a
    FRESH ``jax.jit`` closure per invocation, which (a) infinitely
    recurses under ``jax.disable_jit()`` (the test suite's eager-heavy
    fixture) and (b) even with jit enabled re-traces and re-compiles the
    kernel on EVERY eager call (the closure is new each time, so the jit
    cache never hits).  Interpret mode needs neither: its evaluator is
    plain jnp ops (a scan over the grid), exactly what eager execution
    wants.  Route the eager impl straight there; jitted lowering and the
    Mosaic TPU path are untouched.  jax>=0.5 fixed both and keeps the
    CompilerParams name, which is the version gate."""
    from jax.experimental.pallas import tpu as pltpu

    if hasattr(pltpu, "CompilerParams"):
        return False  # jax>=0.5: eager pallas is healthy
    try:
        from jax._src.pallas import pallas_call as _pc

        orig = _pc._pallas_call_impl
        interp = _pc._pallas_call_impl_interpret

        def impl(*args, **params):
            if params.get("interpret"):
                p = {k: v for k, v in params.items() if k not in ("interpret", "backend")}
                return interp(*args, **p)
            return orig(*args, **params)

        _pc.pallas_call_p.def_impl(impl)
        return True
    except (ImportError, AttributeError):  # pragma: no cover - future jax layouts
        return False


#: jitted pallas wrappers for EAGER callers, keyed by the call site's
#: static plan (kernel structure + shapes).  Eager pallas on this jax
#: either recurses (disable_jit) or re-compiles per call (fresh impl
#: closure defeats the jit cache); wrapping the built pallas_call in a
#: key-cached jit pays one small compile per distinct kernel and runs
#: compiled thereafter — the behavior the suite's eager-heavy fixture
#: (tests/conftest.py) was measured against.
_EAGER_PALLAS_CACHE: dict = {}
_EAGER_PALLAS_LOCK = threading.Lock()


def run_pallas(call, *args, key=None):
    """Invoke a built pallas_call so it works EAGERLY on every jax this
    repo meets; inside a jit trace this is a plain call (the lowering
    path is healthy everywhere).

    ``key``: hashable static plan of the call site (kernel structure,
    shapes, tiling).  Two calls with equal keys MUST be equivalent
    pallas programs up to traced inputs — the first caller's kernel is
    the one that stays cached."""
    _patch_eager_interpret_impl()
    if key is None or not jax.config.jax_disable_jit:
        return call(*args)
    with _EAGER_PALLAS_LOCK:
        fn = _EAGER_PALLAS_CACHE.get(key)
        if fn is None:
            fn = jax.jit(call)
            _EAGER_PALLAS_CACHE[key] = fn
    with jax.disable_jit(False):
        return fn(*args)


def interpret_mode() -> bool:
    """True when running without a Mosaic backend (tests on CPU)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # stlint: disable=fail-open — backend probe failure selects INTERPRET mode (exact, slow); verdicts unaffected
        return True


@functools.cache
def available() -> bool:
    """Fused kernels compile on TPU (Mosaic); interpret elsewhere."""
    if os.environ.get("SENTINEL_NO_PALLAS"):
        return False
    return True


class Job(NamedTuple):
    """One scatter destination processed by a fused kernel.

    rows:   int32 [R, N] — R row-vectors per item (e.g. the res/ctx/origin
            stat fan of StatisticSlot.java:54-123 is R=3); ids outside
            [0, n) are dropped (the trash-row / drop-mode analog).
    values: int32 [P, N] value planes shared by every row-vector, or
            [R, P, N] for per-row-vector values.
    digits: per-plane base-256 digit counts; plane p must satisfy
            0 <= value < 256**digits[p] (matching mxu_table max_int).
    n:      logical table rows.
    """

    name: str
    n: int
    rows: jax.Array
    values: jax.Array
    digits: tuple


def _pad_axis(x: jax.Array, axis: int, to: int, fill) -> jax.Array:
    pad = to - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


#: max digit-dot units per pallas call — Mosaic's 16 MB scoped-vmem stack
#: holds ~25-30 unrolled [tb, N_LO] temporaries at tb=2048; larger job
#: mixes (e.g. rules_per_resource > 1 configs) split across calls
_MAX_UNITS_PER_CALL = 28


def _job_units(j: "Job") -> int:
    return j.rows.shape[0] * sum(j.digits)


def scatter_many(jobs: Sequence[Job], tb: int = TILE, interpret: Optional[bool] = None):
    """Run every job's scatter in ONE Pallas kernel over a shared item axis.

    All jobs must share the item-axis length N (pad shorter vectors with
    row id -1 upstream).  Returns one f32 [n_j, P_j] histogram per job —
    digit planes already recombined; integer-exact within the documented
    bounds.  The caller lands these into window/sketch state with plain
    elementwise adds (ops/window.add_dense etc.).

    Job lists whose total digit-dot count exceeds the scoped-vmem budget
    are transparently split across several pallas calls (per-call overhead
    is small against the per-dot cost).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = interpret_mode()

    total_units = sum(_job_units(j) for j in jobs)
    if total_units > _MAX_UNITS_PER_CALL and len(jobs) > 1:
        chunks: list = [[]]
        acc = 0
        for j in jobs:
            u = _job_units(j)
            if chunks[-1] and acc + u > _MAX_UNITS_PER_CALL:
                chunks.append([])
                acc = 0
            chunks[-1].append(j)
            acc += u
        out: list = []
        for ch in chunks:
            out.extend(scatter_many(ch, tb=tb, interpret=interpret))
        return out

    N = jobs[0].rows.shape[-1]
    for j in jobs:
        assert j.rows.shape[-1] == N, f"job {j.name}: item axis mismatch"
        assert j.values.shape[-1] == N, f"job {j.name}: values item axis mismatch"

    nT = max((N + tb - 1) // tb, 1)
    Np = nT * tb

    # --- static plan per job ------------------------------------------------
    # ALL jobs' row-vectors and value planes pack into TWO stacked inputs
    # (one pad+reshape+transpose each) instead of two per job — at small
    # batches the ~3 XLA prep ops per job were a measurable fixed cost
    plans = []  # (R, P, per_row_vals, n_hi, pd_total, digits, n, roff, voff)
    row_stack = []
    val_stack = []
    roff = voff = 0
    out_shapes = []
    out_specs = []
    for j in jobs:
        rows = j.rows
        assert rows.ndim == 2, f"job {j.name}: rows must be [R, N]"
        R = rows.shape[0]
        per_row = j.values.ndim == 3
        P = j.values.shape[-2]
        assert len(j.digits) == P, f"job {j.name}: digits/planes mismatch"
        n_hi = (j.n + N_LO - 1) // N_LO
        pd = sum(j.digits)
        plans.append(
            (R, P, per_row, n_hi, pd, tuple(j.digits), j.n, roff, voff)
        )
        roff += R
        voff += R * P if per_row else P
        row_stack.append(rows.astype(jnp.int32))
        vals = j.values.astype(jnp.int32)
        val_stack.append(vals.reshape(-1, N))
        out_shapes.append(jax.ShapeDtypeStruct((pd, n_hi, N_LO), jnp.float32))
        out_specs.append(
            pl.BlockSpec((pd, n_hi, N_LO), lambda t: (0, 0, 0), memory_space=pltpu.VMEM)
        )

    rows_all = _pad_axis(jnp.concatenate(row_stack, axis=0), 1, Np, -1)
    vals_all = _pad_axis(jnp.concatenate(val_stack, axis=0), 1, Np, 0)
    SR = rows_all.shape[0]
    SV = vals_all.shape[0]
    # 2-D blocks over the natural [S, Np] stacks: the tile axis is sliced
    # by the index map, so kernel inputs need no layout transpose — the
    # old [nT, S, tb] form cost a ~0.1 ms HBM copy per stacked input at
    # B=128K (profiled)
    ins = [rows_all, vals_all]
    in_specs = [
        pl.BlockSpec((SR, tb), lambda t: (0, t), memory_space=pltpu.VMEM),
        pl.BlockSpec((SV, tb), lambda t: (0, t), memory_space=pltpu.VMEM),
    ]

    def kernel(*refs):
        rows_ref, vals_ref = refs[0], refs[1]
        orefs = refs[2:]
        t = pl.program_id(0)

        for o in orefs:

            @pl.when(t == 0)
            def _(o=o):
                o[...] = jnp.zeros_like(o)

        iota_l = jax.lax.broadcasted_iota(jnp.int32, (tb, N_LO), 1)
        for ji, (R, P, per_row, n_hi, pd, digits, n, roff, voff) in enumerate(plans):
            iota_h = jax.lax.broadcasted_iota(jnp.int32, (n_hi, tb), 0)
            for r in range(R):
                k = rows_ref[roff + r, :]
                ok = (k >= 0) & (k < n)
                safe = jnp.where(ok, k, 0)
                hi = safe // N_LO
                lo = safe - hi * N_LO
                oki = ok.astype(jnp.int32)
                HiT = ((hi[None, :] == iota_h) & (oki[None, :] > 0)).astype(
                    jnp.bfloat16
                )
                Lo = (lo[:, None] == iota_l).astype(jnp.bfloat16)
                # ONE wide dot per row: every digit plane rides as N_LO
                # extra N-columns — [n_hi, tb] x [tb, pd*N_LO] keeps the
                # MXU fed, where the old per-digit [.,tb]x[tb,N_LO] dots
                # were too narrow to utilize it (the digit loop was ~10x
                # off the roofline, measured).  Same products, same
                # per-column f32 accumulation order — bit-identical.
                cols = []
                for p in range(P):
                    v = vals_ref[voff + (r * P + p if per_row else p), :]
                    for d in range(digits[p]):
                        dig = ((v >> (8 * d)) & 0xFF)[:, None].astype(jnp.bfloat16)
                        cols.append(Lo * dig)
                wide = jnp.concatenate(cols, axis=1)  # [tb, pd*N_LO]
                res = jax.lax.dot(
                    HiT, wide, preferred_element_type=jnp.float32
                )  # [n_hi, pd*N_LO]
                for k2 in range(pd):
                    orefs[ji][k2, :, :] += res[:, k2 * N_LO : (k2 + 1) * N_LO]

    grid = (nT,)
    outs = run_pallas(pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
        # Mosaic's default 16 MB scoped-vmem stack is marginal for the
        # ~28-unit job mixes (observed 16.24 MB on a 27-val-row mix at
        # B=4096 after the 2-D block-spec change); v5e has 128 MB VMEM
        # per core, so double the scope rather than split finer
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=32 * 1024 * 1024
        ),
    ), *ins,
        key=("scatter_many", tuple(plans), SR, SV, nT, tb, bool(interpret)))

    # --- digit recombination (XLA elementwise; exact integer weights) ------
    results = []
    for out, (R, P, per_row, n_hi, pd, digits, n, _roff, _voff) in zip(outs, plans):
        flat = out.reshape(pd, n_hi * N_LO)[:, :n]  # [pd, n]
        cols = []
        off = 0
        for p in range(P):
            acc = flat[off]
            for d in range(1, digits[p]):
                acc = acc + flat[off + d] * float(1 << (8 * d))
            cols.append(acc)
            off += digits[p]
        results.append(jnp.stack(cols, axis=1))  # [n, P]
    return results


# ---------------------------------------------------------------------------
# fused gather suite: chained per-item reads sharing one item axis
# ---------------------------------------------------------------------------


class GatherJob(NamedTuple):
    """One gather source read by a fused gather kernel.

    ids:    int32 [N] — row per item; out-of-range ids read 0.
    table:  int32 [n, P] — NONNEGATIVE integer table; each plane p bounded
            by 256**digits[p] (digit-plane exactness, like mxu_table
            gather with max_int).
    digits: per-plane digit counts.
    """

    name: str
    ids: jax.Array
    table: jax.Array
    digits: tuple


def gather_many(
    jobs: Sequence[GatherJob], tb: int = TILE_GATHER, interpret: Optional[bool] = None
):
    """Per-item gathers from several tables in ONE kernel.

    Returns one f32 [N, P] per job.  The table rides in VMEM as bf16 digit
    planes ([digits_total, n_hi, N_LO], built XLA-side — cheap elementwise)
    and each tile contracts Hi @ plane then selects with Lo — the gather
    formulation of ops/mxu_table.py:137-184 without HBM one-hots.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = interpret_mode()

    N = jobs[0].ids.shape[0]
    for j in jobs:
        assert j.ids.shape[0] == N, f"gather job {j.name}: item axis mismatch"
    nT = max((N + tb - 1) // tb, 1)
    Np = nT * tb

    plans = []
    ins = []
    in_specs = []
    out_shapes = []
    out_specs = []
    for j in jobs:
        n, P = j.table.shape
        assert len(j.digits) == P
        n_hi = (n + N_LO - 1) // N_LO
        pd = sum(j.digits)
        plans.append((P, n_hi, pd, tuple(j.digits), n))

        ids_p = _pad_axis(j.ids.astype(jnp.int32)[None, :], 1, Np, -1)
        ins.append(ids_p)
        in_specs.append(
            pl.BlockSpec((1, tb), lambda t: (0, t), memory_space=pltpu.VMEM)
        )
        # digit planes of the table: [pd, n_hi, N_LO] bf16
        t32 = j.table.astype(jnp.int32)
        pad_rows = n_hi * N_LO - n
        if pad_rows:
            t32 = jnp.concatenate([t32, jnp.zeros((pad_rows, P), jnp.int32)])
        planes = []
        for p in range(P):
            for d in range(j.digits[p]):
                planes.append((t32[:, p] >> (8 * d)) & 0xFF)
        tabd = jnp.stack(planes, 0).astype(jnp.bfloat16).reshape(pd, n_hi, N_LO)
        ins.append(tabd)
        in_specs.append(
            pl.BlockSpec((pd, n_hi, N_LO), lambda t: (0, 0, 0), memory_space=pltpu.VMEM)
        )
        out_shapes.append(jax.ShapeDtypeStruct((nT, P, tb), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, P, tb), lambda t: (t, 0, 0), memory_space=pltpu.VMEM)
        )

    def kernel(*refs):
        nrefs = refs[: len(ins)]
        orefs = refs[len(ins) :]
        iota_l = jax.lax.broadcasted_iota(jnp.int32, (tb, N_LO), 1)
        ri = 0
        for ji, (P, n_hi, pd, digits, n) in enumerate(plans):
            ids_ref = nrefs[ri]
            tab_ref = nrefs[ri + 1]
            ri += 2
            k = ids_ref[0, :]
            ok = (k >= 0) & (k < n)
            safe = jnp.where(ok, k, 0)
            hi = safe // N_LO
            lo = safe - hi * N_LO
            oki = ok.astype(jnp.int32)
            iota_h = jax.lax.broadcasted_iota(jnp.int32, (tb, n_hi), 1)
            Hi = ((hi[:, None] == iota_h) & (oki[:, None] > 0)).astype(jnp.bfloat16)
            Lo = (lo[:, None] == iota_l).astype(jnp.bfloat16)
            off = 0
            for p in range(P):
                acc = None
                for d in range(digits[p]):
                    sel = jax.lax.dot(
                        Hi, tab_ref[off], preferred_element_type=jnp.float32
                    )  # [tb, N_LO]
                    part = jnp.sum(sel * Lo.astype(jnp.float32), axis=1)
                    acc = part * float(1 << (8 * d)) if acc is None else acc + part * float(1 << (8 * d))
                    off += 1
                orefs[ji][0, p, :] = acc

    outs = run_pallas(pl.pallas_call(
        kernel,
        grid=(nT,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
        # same scoped-vmem headroom as scatter_many (see comment there)
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=32 * 1024 * 1024
        ),
    ), *ins,
        key=("gather_many", tuple(plans), Np, tb, bool(interpret)))

    results = []
    for out, (P, n_hi, pd, digits, n) in zip(outs, plans):
        results.append(out.transpose(1, 0, 2).reshape(P, Np)[:, :N].T)  # [N, P]
    return results
