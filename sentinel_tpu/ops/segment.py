"""Sorted-batch segment machinery: the round-4 aggregation primitive.

The fused one-hot digit-dot kernels (ops/fused.py) stream the whole item
axis through the MXU for every destination table — cost LINEAR in batch
size with no amortization (the round-3 cost model).  Real traffic is
Zipf-skewed: a 128K-item tick touches ~12K distinct resources (9%), so
almost all of that streaming is redundant.

This module exploits a batch that arrives SORTED by a composite key
(resource id first): equal-key items form contiguous *segments*, and

  - per-table scatters contract SEGMENT SUMS over a short compacted axis
    (U entries) instead of per-item payloads over the full batch,
  - per-item table reads (rule fields, window totals) happen once per
    segment and expand back with ONE monotone gather,
  - within-tick FCFS ranks (ops/rank.py) become segmented prefix sums on
    the already-sorted order — no per-rank sort networks.

Sorting stably by key preserves arrival order within each segment, so
every rank/verdict is bit-identical to the unsorted engine (integer
digit-plane sums are order-independent; see tests/test_segment.py and
the engine equivalence suite).

Exactness scheme: segments are capped at BLOCK=256 items by synthetic
breaks at block boundaries, so a segment never spans two 256-item blocks.
Per-item payloads are split into base-256 digit planes (<= 255 each),
prefix-summed in int32 (exact: 255 * 2^23 < 2^31), and differenced at
segment ends; a digit-plane segment sum is <= 255*256 = 65280 and two
adjacent digit sums recombine to < 2^24 — inside the bf16 digit-dot
exactness envelope of ops/fused.py.

Reference map: this replaces the per-request LongAdder adds of
StatisticSlot.java:54-164 and the CAS ranking of
RateLimiterController.java:50-105 with sort + segmented scans — the
batched form of "group requests by resource, then admit in arrival
order".
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp

#: segments never span a BLOCK-item boundary (synthetic heads), capping
#: segment length so digit-plane sums stay exact (see module docstring)
BLOCK = 256

_INT_MIN = np.int32(-(2**31) + 1)  # numpy scalar, NOT jnp: a module-level device array becomes a hoisted jaxpr const (extra executable parameter) and this jaxlib's dispatch fastpath drops consts when sibling cfg-variant executables coexist.  Enforced structurally by the jaxpr analyzer's const-hoist pass (sentinel_tpu/analysis/jaxpr)
_INT_MAX = np.int32(2**31 - 1)  # numpy scalar, NOT jnp: same hazard class; see _INT_MIN above and analysis/jaxpr/passes/const_hoist.py


class SegCtx(NamedTuple):
    """Segment structure of one sorted batch (item axis N, capacity U)."""

    head: jax.Array  # bool [N] — first item of its segment
    sid: jax.Array  # int32 [N] — segment id, 0-based, nondecreasing
    n_seg: jax.Array  # int32 scalar — live segment count
    ok: jax.Array  # bool scalar — n_seg <= U (compacted outputs valid)
    seg_end: jax.Array  # int32 [U] — last item position per live segment
    live: jax.Array  # bool [U] — segment slot holds a live segment

    @property
    def U(self) -> int:
        return self.seg_end.shape[0]


def heads_from_keys(*cols: jax.Array) -> jax.Array:
    """Segment-start marks from sorted key columns + BLOCK boundaries."""
    n = cols[0].shape[0]
    change = jnp.zeros((n,), bool)
    for c in cols:
        change = change | jnp.concatenate(
            [jnp.ones((1,), bool), c[1:] != c[:-1]]
        )
    pos = jax.lax.broadcasted_iota(jnp.int32, (n,), 0)
    return change | (pos % BLOCK == 0)


def build(key_cols: Sequence[jax.Array], U: int, payloads: Sequence[jax.Array] = ()):
    """Segment structure for a batch sorted by ``key_cols`` (stably).

    One sort compacts segment-end positions into [U]; when the live
    segment count exceeds U, ``ok`` is False and the caller must take its
    uncompacted fallback (compacted outputs would drop segments).

    ``payloads``: per-item columns to compact THROUGH the sort — the
    returned [U] arrays hold each segment's value at its last item
    (exactly ``compact(ctx, p)`` but without the extra per-column [U]
    gathers, which cost ~0.11 ms each at B=128K).  Dead slots carry junk;
    mask with ctx.live.  Returns (ctx, compacted_payloads).
    """
    head = heads_from_keys(*key_cols)
    return build_from_head(head, U, payloads)


def build_from_head(head: jax.Array, U: int, payloads: Sequence[jax.Array] = ()):
    """build() for a precomputed head vector (see heads_from_keys)."""
    n = head.shape[0]
    sid = jnp.cumsum(head.astype(jnp.int32)) - 1
    n_seg = sid[-1] + 1
    ok = n_seg <= U
    tail = jnp.concatenate([head[1:], jnp.ones((1,), bool)])
    pos = jax.lax.broadcasted_iota(jnp.int32, (n,), 0)
    skey = jnp.where(tail & (sid < U), sid, _INT_MAX)
    out = jax.lax.sort(
        [skey, pos] + [p for p in payloads], num_keys=1, is_stable=False
    )
    skeys, spos = out[0], out[1]
    comp = list(out[2:])
    if U > n:  # short batches still produce [U]-shaped compacted outputs
        skeys = jnp.concatenate([skeys, jnp.full((U - n,), _INT_MAX, jnp.int32)])
        spos = jnp.concatenate([spos, jnp.zeros((U - n,), jnp.int32)])
        comp = [
            jnp.concatenate([c, jnp.zeros((U - n,), c.dtype)]) for c in comp
        ]
    seg_end = spos[:U]
    live = skeys[:U] != _INT_MAX
    ctx = SegCtx(
        head=head, sid=sid, n_seg=n_seg, ok=ok, seg_end=seg_end, live=live
    )
    return ctx, [c[:U] for c in comp]


def compact(ctx: SegCtx, arr: jax.Array, fill=0) -> jax.Array:
    """Per-segment value (constant within each segment): [N(,P)] -> [U(,P)].

    Reads each segment's LAST item; dead slots get ``fill``.
    """
    g = arr[ctx.seg_end]
    mask = ctx.live if g.ndim == 1 else ctx.live[:, None]
    return jnp.where(mask, g, fill)


def cum_cols(planes: Sequence[jax.Array], maxes: Sequence[int]):
    """Digit-split payload planes + exact int32 prefix sums.

    Returns (C_rows: list of [N] int32 inclusive cumsums, split: list of
    (plane_idx, weight)).  Planes wider than 255 are digit-split BEFORE
    the prefix sum so the int32 cumsum stays exact (item axis <= 2^23).
    Feed the C_rows through build()'s payload sort (or gather them at
    seg_end) and hand the per-segment values to sums_from_ce."""
    n = planes[0].shape[0]
    assert n <= (1 << 23), "item axis too long for exact int32 digit cumsum"
    split: list = []  # (plane_idx, weight)
    cols = []
    for p, (v, m) in enumerate(zip(planes, maxes)):
        v = v.astype(jnp.int32)
        if m <= 255:
            cols.append(v)
            split.append((p, 1))
        else:
            d = max(1, (int(m).bit_length() + 7) // 8)
            for k in range(d):
                cols.append((v >> (8 * k)) & 0xFF)
                split.append((p, 1 << (8 * k)))
    C = jnp.cumsum(jnp.stack(cols, axis=0), axis=1)  # [Pd, N]
    return [C[i] for i in range(C.shape[0])], split


def sums_from_ce(ctx: SegCtx, ce_cols: Sequence[jax.Array], split) -> list:
    """Per-segment sums from compacted cumsum columns (each [U] int32,
    the cumsum value at each segment's last item).

    Returns, per input plane, a list of (sums [U] int32, weight, digits):
    the plane's segment sum is sum(weight_k * sums_k), each sums_k < 2^24
    and scatter-able with ``digits`` base-256 digit planes (ops/fused.Job).
    """
    Ce = jnp.stack(ce_cols, axis=1)  # [U, Pd]
    prev = jnp.concatenate([jnp.zeros((1, Ce.shape[1]), jnp.int32), Ce[:-1]])
    sums_d = jnp.where(ctx.live[:, None], Ce - prev, 0)  # each <= 255*BLOCK

    n_planes = max(p for p, _ in split) + 1
    out: list = [[] for _ in range(n_planes)]
    j = 0
    while j < len(split):
        p, w = split[j]
        if (
            j + 1 < len(split)
            and split[j + 1][0] == p
            and split[j + 1][1] == w * 256
        ):
            s = sums_d[:, j] + sums_d[:, j + 1] * 256
            out[p].append((s, w, 3))
            j += 2
        else:
            out[p].append((sums_d[:, j], w, 2))
            j += 1
    return out


def seg_sums(
    ctx: SegCtx,
    planes: Sequence[jax.Array],  # each int32 [N], values in [0, maxes[p]]
    maxes: Sequence[int],
) -> list:
    """Exact per-segment sums of int32 payload planes (cum_cols +
    ONE packed row gather at seg_end + sums_from_ce).  Callers that know
    their planes before build() should carry the cum_cols through the
    build sort instead (cheaper)."""
    C_rows, split = cum_cols(planes, maxes)
    CT = jnp.stack(C_rows, axis=1)  # [N, Pd] — one packed row gather
    Ce = CT[ctx.seg_end]
    return sums_from_ce(ctx, [Ce[:, i] for i in range(Ce.shape[1])], split)


def _two_level_max(x: jax.Array) -> jax.Array:
    """Inclusive running max along the last axis via block scan + cross-
    block offsets (both lane-parallel associative scans)."""
    *lead, n = x.shape
    pad = (-n) % BLOCK
    if pad:
        x = jnp.concatenate(
            [x, jnp.full((*lead, pad), _INT_MIN, x.dtype)], axis=-1
        )
    nb = x.shape[-1] // BLOCK
    r = x.reshape(*lead, nb, BLOCK)
    within = jax.lax.associative_scan(jnp.maximum, r, axis=len(lead) + 1)
    blast = within[..., -1]
    cross = jax.lax.associative_scan(jnp.maximum, blast, axis=len(lead))
    cross_excl = jnp.concatenate(
        [jnp.full((*lead, 1), _INT_MIN, x.dtype), cross[..., :-1]], axis=-1
    )
    out = jnp.maximum(within, cross_excl[..., None]).reshape(*lead, -1)
    return out[..., :n]


def seg_excl_cumsum(head: jax.Array, values: jax.Array) -> jax.Array:
    """Segmented EXCLUSIVE prefix sums over sorted items, int32-exact.

    ``head`` marks segment starts (head[0] must be True); ``values`` is
    [V, N] (or [N]) nonnegative int32 with sum(values) < 2^31 per row.
    Item i receives the sum of earlier same-segment items — the batched
    arrival-order rank of ops/rank.py, without the sort (the batch IS the
    sorted order here).  Segments may span BLOCK boundaries (two-level
    scan); use this for node-run ranks where runs aren't block-capped.
    """
    squeeze = values.ndim == 1
    v = values[None, :] if squeeze else values
    v = v.astype(jnp.int32)
    C = jnp.cumsum(v, axis=1)
    E = C - v
    base = _two_level_max(jnp.where(head[None, :], E, _INT_MIN))
    out = E - base
    return out[0] if squeeze else out


def seg_excl_cumsum_wide(head: jax.Array, values: jax.Array) -> jax.Array:
    """seg_excl_cumsum for values whose batch total may overflow int32
    (e.g. rate-limiter pacing costs, <= 2^24 each): two 12-bit digit
    lanes, recombined as f32 AFTER the exact integer differences — one
    rounding instead of the accumulated rounding of an f32 prefix sum."""
    v = values.astype(jnp.int32)
    lo = v & 0xFFF
    hi = v >> 12
    r = seg_excl_cumsum(head, jnp.stack([lo, hi]))
    return r[1].astype(jnp.float32) * 4096.0 + r[0].astype(jnp.float32)


class _MinCarry(NamedTuple):
    m: jax.Array
    flag: jax.Array


def block_min_inclusive(head: jax.Array, v: jax.Array, fill: float) -> jax.Array:
    """Within-segment inclusive running minimum, [N] -> [N].

    Requires segments that never span BLOCK boundaries (build() inserts
    synthetic heads), so one within-block composite scan suffices: the
    carry resets at each head.  f32 min is order-free, so this is
    bit-exact.  The value at each segment's LAST item is the segment min
    — carry this through build()'s payload sort or read it at seg_end."""
    n = v.shape[0]
    pad = (-n) % BLOCK
    vp = jnp.concatenate([v, jnp.full((pad,), fill, v.dtype)]) if pad else v
    hp = jnp.concatenate([head, jnp.ones((pad,), bool)]) if pad else head
    nb = vp.shape[0] // BLOCK
    m = vp.reshape(nb, BLOCK)
    f = hp.reshape(nb, BLOCK)

    def op(a: _MinCarry, b: _MinCarry) -> _MinCarry:
        return _MinCarry(
            m=jnp.where(b.flag, b.m, jnp.minimum(a.m, b.m)),
            flag=a.flag | b.flag,
        )

    scanned = jax.lax.associative_scan(op, _MinCarry(m=m, flag=f), axis=1)
    return scanned.m.reshape(-1)[:n]


def seg_min_f32(ctx: SegCtx, v: jax.Array, fill: float) -> jax.Array:
    """Per-segment minimum of a float32 plane, compacted to [U]."""
    inc = block_min_inclusive(ctx.head, v, fill)
    return jnp.where(ctx.live, inc[ctx.seg_end], fill)


def expand(ctx: SegCtx, seg_vals: jax.Array) -> jax.Array:
    """Broadcast per-segment values back to items: [U(,P)] -> [N(,P)].

    One monotone gather (sid is sorted) — pack every per-segment quantity
    into seg_vals' columns so the whole tick pays this once per side.
    """
    return seg_vals[ctx.sid]


def sort_batch(key_cols: Sequence[jax.Array], payloads: Sequence[jax.Array]):
    """Device-side stable sort fallback for callers without a presorted
    batch: returns (perm, sorted_payloads).  The runtime client presorts
    on the host instead (np.lexsort over the segment keys in
    runtime/client._run_tick, verdicts mapped back through the inverse
    permutation) and never calls this."""
    n = key_cols[0].shape[0]
    pos = jax.lax.broadcasted_iota(jnp.int32, (n,), 0)
    ops = list(key_cols) + [pos] + [p for p in payloads]
    out = jax.lax.sort(ops, num_keys=len(key_cols), is_stable=True)
    perm = out[len(key_cols)]
    return perm, list(out[len(key_cols) + 1 :])


def unsort(perm: jax.Array, cols: Sequence[jax.Array]):
    """Restore batch order for output planes (device-side fallback)."""
    out = jax.lax.sort(
        [perm] + [c for c in cols], num_keys=1, is_stable=False
    )
    return list(out[1:])
