"""Packed host↔device wire format for the client tick path.

ROADMAP item 1: the engine decides at ~19 M dps pipelined but the client
path ships ~5 MB of full-width columns per tick and reads verdicts,
telemetry, timeline and hot-set rows back in FOUR separate transfers.
This module is the wire half of the fix (runtime/client.py owns the
dirty-column upload half):

Readback — ONE flat uint32 buffer per tick (``TickOutput.wire``),
packed on-device so only packed bytes ever cross the transport::

    word 0            WIRE_MAGIC (layout/version tag)
    word 1            n_wait   — count of PASS_WAIT rows with wait > 0
    word 2            seg_dropped — fail-closed seg-overflow item count
    word 3            checksum — uint32 sum of words {0,1,2} ∪ payload
    [bitmap]          ceil(B / 10) words; 10 verdicts per word, 3 bits
                      each (verdict codes are 0..6 — core/errors.py)
    [sidecar]         EXC_K row indices then EXC_K wait values (uint32):
                      the top-EXC_K rows of wait_ms.  Covers every
                      PASS_WAIT row whenever n_wait <= EXC_K; a rarer
                      overflow tick falls back to reading the full
                      TickOutput.wait_ms column (the one escape hatch).
    [stats]           N_STATS words — float32 telemetry row, bitcast
    [timeline]        timeline_k * TL_COLS words — float32, bitcast
    [hot]             hotset_k * 2 words — float32, bitcast
    [explain]         2 + explain_k * EXPLAIN_WORDS words — verdict
                      provenance records for up to explain_k BLOCKED
                      rows (obs/explain.py owns the record encoding):
                      ``[n_blocked, sec_sum, records...]`` with its OWN
                      additive checksum ``sec_sum`` seeded with
                      EXPLAIN_MAGIC.  The section sits OUTSIDE the main
                      checksum: a corrupt explain section drops the
                      tick's explanations only (fail-OPEN for the
                      provenance), while main-section corruption still
                      fails every verdict CLOSED.

Optional blocks appear iff the config emits them, so the layout is a
pure function of (EngineConfig, batch shape) — the host unpacks by a
static offset table, no per-tick negotiation.  The additive checksum
detects any single-flipped-byte corruption (the chaos ``corrupt``
action's exact fault model) plus truncation/drop via the length check;
``unpack`` raises :class:`WireDecodeError` and the client fails the tick
CLOSED (runtime/client._resolve_tick).  ``unpack`` validates the main
section ONLY and hands the explain words back raw — decode + sec_sum
validation live in obs/explain.py behind their own chaos failpoint.

Upload — batch columns whose value range is statically bounded travel
narrow and widen on-device at tick entry (``widen_acquire`` /
``widen_complete``): prio/inbound are 0/1 flags, pre_verdict is a
verdict code, and count/success/error are clamped to
``cfg.max_batch_count`` at the client's batch-build choke point whenever
the fused path is active.  Dtypes are STATIC per config (a
value-dependent encoding would change the jitted tick's signature and
recompile mid-serving); the dirty-column skip lives in the client.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from sentinel_tpu.core.config import EngineConfig

#: layout/version tag — bump when the word layout changes
WIRE_MAGIC = 0x53_E1_71_12
#: verdict codes are 0..6 (core/errors.py) — 3 bits, 10 per uint32 word
VERDICT_BITS = 3
VERDICTS_PER_WORD = 10
_VMASK = (1 << VERDICT_BITS) - 1
#: header words: magic, n_wait, seg_dropped, checksum
HDR_WORDS = 4
#: PASS_WAIT sidecar capacity — pacing verdicts are rare by design
#: (flow rules with RATE_LIMITER behavior); 64 rows = 512 B covers the
#: normal tick, and an overflow tick reads the full wait column instead
EXC_K = 64
#: seed of the explain section's own checksum — distinct from the main
#: checksum so a flip in either section is attributed to that section
EXPLAIN_MAGIC = 0x0B_5E_CF_A1
#: uint32 words per explain record (obs/explain.py packs/unpacks them)
EXPLAIN_WORDS = 4


class WireDecodeError(Exception):
    """The fused readback failed validation (bad magic, wrong length, or
    checksum mismatch).  The client turns this into a fail-CLOSED tick."""


class WireLayout(NamedTuple):
    """Static offset table for one (config, batch shape) pair."""

    b: int  # batch rows the bitmap covers
    exc_k: int  # sidecar rows (min(EXC_K, b))
    n_stats: int  # telemetry words (0 = block absent)
    tl_rows: int  # timeline rows (0 = block absent)
    tl_cols: int
    hot_rows: int  # hot-candidate rows (0 = block absent)
    expl_k: int  # explain record rows (0 = block absent)
    off_bitmap: int
    n_bitmap: int
    off_exc: int
    off_stats: int
    off_tl: int
    off_hot: int
    off_expl: int  # == total when the explain block is absent
    total: int  # whole-buffer length in words


def layout_for(cfg: EngineConfig, b: int) -> WireLayout:
    """The wire layout this config emits at batch shape ``b`` — must
    mirror the engine's emission conditions exactly (ops/engine.tick)."""
    from sentinel_tpu.ops import engine as E

    n_stats = E.N_STATS if cfg.device_telemetry else 0
    tl_rows = E.timeline_k(cfg) if cfg.device_telemetry else 0
    # hot candidates clamp to the batch shape (engine._device_hot_candidates)
    hot_rows = min(E.hotset_k(cfg), b)
    expl_k = min(E.explain_k(cfg), b)
    exc_k = min(EXC_K, b)
    n_bitmap = -(-b // VERDICTS_PER_WORD)
    off_bitmap = HDR_WORDS
    off_exc = off_bitmap + n_bitmap
    off_stats = off_exc + 2 * exc_k
    off_tl = off_stats + n_stats
    off_hot = off_tl + tl_rows * E.TL_COLS
    off_expl = off_hot + hot_rows * 2
    total = off_expl + (2 + expl_k * EXPLAIN_WORDS if expl_k else 0)
    return WireLayout(
        b=b,
        exc_k=exc_k,
        n_stats=n_stats,
        tl_rows=tl_rows,
        tl_cols=E.TL_COLS,
        hot_rows=hot_rows,
        expl_k=expl_k,
        off_bitmap=off_bitmap,
        n_bitmap=n_bitmap,
        off_exc=off_exc,
        off_stats=off_stats,
        off_tl=off_tl,
        off_hot=off_hot,
        off_expl=off_expl,
        total=total,
    )


# -- device side (inside the jitted tick) -----------------------------------


def pack_tick_output(
    cfg: EngineConfig,
    verdict,  # int8 [B]
    wait_ms,  # int32 [B]
    seg_dropped,  # int32 scalar or plain 0
    stats,  # float32 [N_STATS] or None
    res_stats,  # float32 [K, TL_COLS] or None
    hot,  # float32 [K, 2] or None
    expl=None,  # (n_blocked uint32 scalar, records uint32 [K, 4]) or None
):
    """Pack one tick's outputs into the flat uint32 wire buffer.

    Pure jnp (element-wise shifts + one top_k + concatenates) — cheap on
    any backend against a tick that already streamed the full batch, and
    it keeps the single-readback property on CPU tests and TPU alike."""
    b = verdict.shape[0]
    lo = layout_for(cfg, b)
    v = verdict.astype(jnp.uint32)
    v = jnp.pad(v, (0, lo.n_bitmap * VERDICTS_PER_WORD - b))
    shifts = (jnp.arange(VERDICTS_PER_WORD, dtype=jnp.uint32) * VERDICT_BITS)
    # lanes occupy disjoint bit ranges, so the OR-fold is a plain sum
    bitmap = jnp.sum(
        v.reshape(lo.n_bitmap, VERDICTS_PER_WORD) << shifts[None, :],
        axis=1,
        dtype=jnp.uint32,
    )
    n_wait = jnp.sum(wait_ms > 0).astype(jnp.uint32)
    # top-K by wait value: whenever n_wait <= exc_k this captures EVERY
    # wait row (the rest read 0 and the host filters them out)
    wv, wi = jax.lax.top_k(wait_ms, lo.exc_k)
    parts = [bitmap, wi.astype(jnp.uint32), wv.astype(jnp.uint32)]
    if lo.n_stats:
        parts.append(jax.lax.bitcast_convert_type(stats, jnp.uint32))
    if lo.tl_rows:
        parts.append(
            jax.lax.bitcast_convert_type(res_stats, jnp.uint32).reshape(-1)
        )
    if lo.hot_rows:
        parts.append(jax.lax.bitcast_convert_type(hot, jnp.uint32).reshape(-1))
    payload = jnp.concatenate(parts)
    magic = jnp.uint32(WIRE_MAGIC)
    dropped = jnp.asarray(seg_dropped).astype(jnp.uint32).reshape(())
    # the MAIN checksum stops at off_expl: the explain section carries
    # its own sec_sum so its corruption fails OPEN (provenance dropped)
    # without poisoning the verdict path's fail-CLOSED check
    cksum = (
        magic
        + n_wait
        + dropped
        + jnp.sum(payload, dtype=jnp.uint32)
    )
    out = [jnp.stack([magic, n_wait, dropped, cksum]), payload]
    if lo.expl_k:
        n_blocked, records = expl
        n_blocked = jnp.asarray(n_blocked).astype(jnp.uint32).reshape(())
        flat = records.astype(jnp.uint32).reshape(-1)
        sec_sum = (
            jnp.uint32(EXPLAIN_MAGIC)
            + n_blocked
            + jnp.sum(flat, dtype=jnp.uint32)
        )
        out.append(jnp.stack([n_blocked, sec_sum]))
        out.append(flat)
    return jnp.concatenate(out)


# -- host side (resolver thread) --------------------------------------------


class WireFrame(NamedTuple):
    """One decoded tick readback (host numpy)."""

    verdict: np.ndarray  # int8 [B]
    wait: Optional[np.ndarray]  # int32 [B]; None = sidecar overflowed
    n_wait: int
    seg_dropped: int
    stats: Optional[np.ndarray]  # float32 [N_STATS]
    res_stats: Optional[np.ndarray]  # float32 [K, TL_COLS]
    hot: Optional[np.ndarray]  # float32 [K, 2]
    expl: Optional[np.ndarray]  # RAW uint32 explain words (unvalidated)


def unpack(data: bytes, lo: WireLayout) -> WireFrame:
    """Validate and unpack one fused readback.

    Raises :class:`WireDecodeError` on any integrity failure — length
    (drop/short_read), magic, or checksum (any single-byte corruption);
    the caller fails the tick CLOSED rather than fanning out garbage
    verdicts."""
    if len(data) != lo.total * 4:
        raise WireDecodeError(
            f"wire length {len(data)} B != layout {lo.total * 4} B"
        )
    buf = np.frombuffer(data, dtype=np.uint32)
    if int(buf[0]) != WIRE_MAGIC:
        raise WireDecodeError(f"bad wire magic {int(buf[0]):#x}")
    # main checksum stops at off_expl — the explain section fails open
    # on its own sec_sum (obs/explain.decode_records), never the tick
    expect = (
        int(buf[0]) + int(buf[1]) + int(buf[2])
        + int(np.sum(buf[HDR_WORDS : lo.off_expl], dtype=np.uint64))
    ) & 0xFFFFFFFF
    if int(buf[3]) != expect:
        raise WireDecodeError(
            f"wire checksum mismatch ({int(buf[3]):#x} != {expect:#x})"
        )
    n_wait = int(buf[1])
    seg_dropped = int(buf[2])
    words = buf[lo.off_bitmap : lo.off_bitmap + lo.n_bitmap]
    shifts = np.arange(VERDICTS_PER_WORD, dtype=np.uint32) * VERDICT_BITS
    verdict = (
        ((words[:, None] >> shifts[None, :]) & _VMASK)
        .reshape(-1)[: lo.b]
        .astype(np.int8)
    )
    wait: Optional[np.ndarray]
    if n_wait == 0:
        wait = np.zeros(lo.b, np.int32)
    elif n_wait <= lo.exc_k:
        idx = buf[lo.off_exc : lo.off_exc + lo.exc_k].astype(np.int64)
        vals = buf[lo.off_exc + lo.exc_k : lo.off_stats].astype(np.int32)
        live = vals > 0
        if int(idx[live].max(initial=0)) >= lo.b:
            raise WireDecodeError("wait sidecar row index out of range")
        wait = np.zeros(lo.b, np.int32)
        wait[idx[live]] = vals[live]
    else:
        wait = None  # overflow: caller reads the full wait_ms column
    stats = res_stats = hot = None
    if lo.n_stats:
        stats = buf[lo.off_stats : lo.off_tl].view(np.float32)
    if lo.tl_rows:
        res_stats = (
            buf[lo.off_tl : lo.off_hot].view(np.float32)
            .reshape(lo.tl_rows, lo.tl_cols)
        )
    if lo.hot_rows:
        hot = (
            buf[lo.off_hot : lo.off_expl].view(np.float32)
            .reshape(lo.hot_rows, 2)
        )
    expl = buf[lo.off_expl : lo.total].copy() if lo.expl_k else None
    return WireFrame(
        verdict=verdict,
        wait=wait,
        n_wait=n_wait,
        seg_dropped=seg_dropped,
        stats=stats,
        res_stats=res_stats,
        hot=hot,
        expl=expl,
    )


# -- narrow upload dtypes ----------------------------------------------------


def _count_dtype(cfg: EngineConfig):
    """Narrowest dtype that carries count-valued columns exactly.  The
    client clamps counts to cfg.max_batch_count at batch build ONLY when
    the fused path is active (engine._use_fused — static per process),
    so narrowing is sound exactly then; the unfused paths stay exact to
    65535 and keep int32."""
    from sentinel_tpu.ops.engine import _use_fused

    if not _use_fused(cfg):
        return np.int32
    if cfg.max_batch_count <= 0xFF:
        return np.uint8
    if cfg.max_batch_count <= 0x7FFF:
        return np.int16
    return np.int32


def acquire_wire_dtypes(cfg: EngineConfig) -> dict:
    """field -> numpy dtype for AcquireBatch columns narrower than int32
    under packed_wire.  prio/inbound are 0/1 flags and pre_verdict is a
    verdict code (0..6) — always int8-safe; count follows the clamp."""
    if not cfg.packed_wire:
        return {}
    out = {
        "prio": np.int8,
        "inbound": np.int8,
        "pre_verdict": np.int8,
    }
    cd = _count_dtype(cfg)
    if cd is not np.int32:
        out["count"] = cd
    return out


def complete_wire_dtypes(cfg: EngineConfig) -> dict:
    """field -> numpy dtype for CompleteBatch columns narrower than int32
    under packed_wire (same envelope as the acquire side)."""
    if not cfg.packed_wire:
        return {}
    out = {"inbound": np.int8}
    cd = _count_dtype(cfg)
    if cd is not np.int32:
        out["success"] = cd
        out["error"] = cd
    return out


def _widen(batch, fields):
    reps = {}
    for f in fields:
        x = getattr(batch, f)
        if x.dtype != jnp.int32:
            reps[f] = x.astype(jnp.int32)
    return batch._replace(**reps) if reps else batch


def widen_acquire(acq):
    """Restore int32 for narrow-uploaded acquire columns at tick entry —
    everything downstream of tick() sees the classic dtypes."""
    return _widen(acq, ("count", "prio", "inbound", "pre_verdict"))


def widen_complete(comp):
    return _widen(comp, ("inbound", "success", "error"))
