"""Pallas TPU kernels for the engine's table primitives.

The matmul formulation in ops/mxu_table.py is MXU-correct but pays HBM for
every intermediate: each scatter/gather materializes [B, n_lo] one-hot and
one-hot*value tensors (~134 MB each at B=128K), and a tick makes ~15 such
calls — the measured round-1 tick was memory-bound on exactly this traffic
plus per-op XLA overhead (benchmarks/profile_prims.py: every table op
~0.5-0.9 ms regardless of FLOPs).

These kernels keep the same math — two-level one-hot contraction,
    row id r = hi * n_lo + lo
    scatter:  out[hi, lo] += sum_b Hi[b,hi] * Lo[b,lo] * v[b]
    gather:   out[b] = rowsum((Hi @ table[hi]) * Lo)
— but build Hi/Lo tiles in VMEM per block and never write them to HBM.

Precision scheme (measured on v5e): Mosaic lowers a DEFAULT-precision f32
dot to ONE bf16 pass — exact only for integer operands <= 256.  So integer
payloads are decomposed into base-256 digit planes (each exact at the full
bf16 MXU rate, same trick as ops/mxu_table.py) and recombined after the
contraction, while genuinely-float payloads use Precision.HIGHEST (6-pass
bf16, exact for f32 products with a 0/1 one-hot side).

STATUS: experimental — NOT wired into the engine.  Measured on v5e
(benchmarks/check_pallas.py, benchmarks/profile_prims.py), the per-call
Mosaic overhead and 6-pass HIGHEST dots make these LOSE to the XLA matmul
path (ops/mxu_table.py) at the engine's shapes; they are kept as the
starting point for a future fused multi-op megakernel, which is the only
formulation where pallas wins.  Only benchmarks import this module.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

_TB = 2048  # items per grid step for scatter/gather
#: rank kernel chunk: the [C, C] same-key mask is C^2 f32 in VMEM
_TB_RANK = 1024

_DEFAULT = jax.lax.Precision.DEFAULT  # one bf16 pass on Mosaic
_HIGHEST = jax.lax.Precision.HIGHEST  # six bf16 passes — f32-exact


@functools.cache
def available() -> bool:
    """Pallas TPU kernels need a real TPU backend (Mosaic)."""
    import os

    if os.environ.get("SENTINEL_NO_PALLAS"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _plan(n: int, n_lo: int = 512):
    n_lo = min(n_lo, max(128, n))
    n_lo = max(n_lo, 128)
    n_hi = max((n + n_lo - 1) // n_lo, 1)
    return n_hi, n_lo


def _ndigits(max_int: int) -> int:
    return max(1, (int(max_int).bit_length() + 7) // 8)


def _pad_to(x, m, fill):
    pad = (-x.shape[0]) % m
    if pad:
        fill_arr = jnp.full((pad,) + x.shape[1:], fill, x.dtype)
        x = jnp.concatenate([x, fill_arr])
    return x


def _onehots_f32(ids, ok, n_hi, n_lo):
    # NOTE: Mosaic can't reshape 1-bit vectors to 2D, so the valid mask is
    # widened to int32 before gaining an axis
    safe = jnp.where(ok, ids, 0)
    hi = safe // n_lo
    lo = safe - hi * n_lo
    tb = ids.shape[0]
    oki = ok.astype(jnp.int32)[:, None]
    iota_h = jax.lax.broadcasted_iota(jnp.int32, (tb, n_hi), 1)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (tb, n_lo), 1)
    Hi = ((hi[:, None] == iota_h) & (oki > 0)).astype(jnp.float32)
    Lo = (lo[:, None] == iota_l).astype(jnp.float32)
    return Hi, Lo


_C00 = (((0,), (0,)), ((), ()))  # [TB,A] x [TB,B] -> [A,B]
_C10 = (((1,), (0,)), ((), ()))  # [A,TB] x [TB,B] -> [A,B]


# ---------------------------------------------------------------------------
# scatter-add / histogram
# ---------------------------------------------------------------------------


def scatter_add(
    ids: jax.Array, values: jax.Array, n: int, max_int: int = 65535
) -> jax.Array:
    """Dense [n, P] histogram: out[r, p] = sum over items with id r of
    values[item, p]; ids outside [0, n) are dropped.

    Integer values ride base-256 digit planes (one DEFAULT-precision dot
    per digit, exact); float values use one HIGHEST dot per plane.
    Returns f32 (integer-valued when inputs are ints)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    squeeze = values.ndim == 1
    if squeeze:
        values = values[:, None]
    P = values.shape[1]
    is_int = jnp.issubdtype(values.dtype, jnp.integer) or values.dtype == jnp.bool_
    nd = _ndigits(max_int) if is_int else 1
    n_hi, n_lo = _plan(n)
    ids_p = _pad_to(ids.astype(jnp.int32), _TB, -1)
    nT = ids_p.shape[0] // _TB
    vals_p = _pad_to(values.astype(jnp.int32 if is_int else jnp.float32), _TB, 0)
    ids3 = ids_p.reshape(nT, 1, _TB)
    vals3 = vals_p.reshape(nT, _TB, P).transpose(0, 2, 1)  # [nT, P, TB]

    def kernel(ids_ref, vals_ref, out_ref):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _():
            out_ref[...] = jnp.zeros_like(out_ref)

        k = ids_ref[0, 0, :]
        ok = (k >= 0) & (k < n)
        Hi, Lo = _onehots_f32(k, ok, n_hi, n_lo)
        for p in range(P):
            if is_int:
                v_int = vals_ref[0, p, :]
                for d in range(nd):
                    dig = ((v_int >> (8 * d)) & 0xFF).astype(jnp.float32)
                    LoV = Lo * dig[:, None]
                    out_ref[p * nd + d, :, :] += jax.lax.dot_general(
                        Hi,
                        LoV,
                        _C00,
                        preferred_element_type=jnp.float32,
                        precision=_DEFAULT,
                    )
            else:
                LoV = Lo * vals_ref[0, p, :][:, None]
                out_ref[p, :, :] += jax.lax.dot_general(
                    Hi,
                    LoV,
                    _C00,
                    preferred_element_type=jnp.float32,
                    precision=_HIGHEST,
                )

    PD = P * nd if is_int else P
    out = pl.pallas_call(
        kernel,
        grid=(nT,),
        in_specs=[
            pl.BlockSpec((1, 1, _TB), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, P, _TB), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (PD, n_hi, n_lo), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((PD, n_hi, n_lo), jnp.float32),
    )(ids3, vals3)
    out = out.reshape(PD, n_hi * n_lo)[:, :n]
    if is_int and nd > 1:
        out = out.reshape(P, nd, n)
        scale = jnp.asarray([float(1 << (8 * d)) for d in range(nd)], jnp.float32)
        out = jnp.einsum("pdn,d->pn", out, scale)
    out = out.T  # [n, P]
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# gather
# ---------------------------------------------------------------------------


def gather(
    ids: jax.Array, table: jax.Array, n: int, max_int: Optional[int] = None
) -> jax.Array:
    """out [B, P] = table[ids] with zeros for ids outside [0, n).

    With ``max_int`` (nonnegative int tables; pass (1<<32)-1 to ride raw
    bits) the table is split into base-256 digit planes outside the kernel
    and contracted at DEFAULT precision; otherwise one HIGHEST dot per
    plane.  Returns f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    squeeze = table.ndim == 1
    if squeeze:
        table = table[:, None]
    P = table.shape[1]
    is_int = jnp.issubdtype(table.dtype, jnp.integer)
    use_digits = is_int and max_int is not None
    nd = _ndigits(max_int) if use_digits else 1
    n_hi, n_lo = _plan(n)
    pad_rows = n_hi * n_lo - n

    if use_digits:
        t = table.astype(jnp.int32)
        if pad_rows:
            t = jnp.concatenate([t, jnp.zeros((pad_rows, P), jnp.int32)])
        u = t.astype(jnp.uint32)
        # digit planes [n_pad, P*nd] in order d*P + p (XLA-side, fused)
        td = jnp.concatenate(
            [((u >> (8 * d)) & 0xFF).astype(jnp.float32) for d in range(nd)], axis=1
        )
        tab3 = td.T.reshape(nd * P, n_hi, n_lo)
    else:
        t32 = table.astype(jnp.float32)
        if pad_rows:
            t32 = jnp.concatenate([t32, jnp.zeros((pad_rows, P), jnp.float32)])
        tab3 = t32.T.reshape(P, n_hi, n_lo)
    PD = tab3.shape[0]

    ids_p = _pad_to(ids.astype(jnp.int32), _TB, -1)
    nT = ids_p.shape[0] // _TB
    ids3 = ids_p.reshape(nT, 1, _TB)

    def kernel(ids_ref, tab_ref, out_ref):
        k = ids_ref[0, 0, :]
        ok = (k >= 0) & (k < n)
        Hi, Lo = _onehots_f32(k, ok, n_hi, n_lo)
        for p in range(P):
            if use_digits:
                sel = jnp.zeros((_TB, n_lo), jnp.float32)
                for d in range(nd):
                    sel_d = jax.lax.dot_general(
                        Hi,
                        tab_ref[d * P + p],
                        _C10,
                        preferred_element_type=jnp.float32,
                        precision=_DEFAULT,
                    )
                    sel = sel + sel_d * float(1 << (8 * d))
            else:
                sel = jax.lax.dot_general(
                    Hi,
                    tab_ref[p],
                    _C10,
                    preferred_element_type=jnp.float32,
                    precision=_HIGHEST,
                )
            out_ref[0, p, :] = jnp.sum(sel * Lo, axis=1)

    out = pl.pallas_call(
        kernel,
        grid=(nT,),
        in_specs=[
            pl.BlockSpec((1, 1, _TB), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (PD, n_hi, n_lo), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, P, _TB), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nT, P, _TB), jnp.float32),
    )(ids3, tab3)
    out = out.transpose(1, 0, 2).reshape(P, nT * _TB)[:, : ids.shape[0]].T  # [B, P]
    return out[:, 0] if squeeze else out


def gather_int(ids: jax.Array, table: jax.Array, n: int) -> jax.Array:
    """Bit-exact int32 gather (signed payloads — hashes, absolute
    engine-ms): the raw 32 bits split into two unsigned 16-bit half planes
    (each f32-exact through the digit path) and recombine with integer
    ops — a single f32 can't carry 32 bits of mantissa."""
    shape = table.shape
    flat = table.reshape(n, -1).astype(jnp.uint32)
    P = flat.shape[1]
    halves = jnp.concatenate(
        [(flat >> 16).astype(jnp.int32), (flat & 0xFFFF).astype(jnp.int32)], axis=1
    )  # [n, 2P]
    g = gather(ids, halves, n, max_int=65535)
    hi_i = jnp.round(g[:, :P]).astype(jnp.uint32)
    lo_i = jnp.round(g[:, P:]).astype(jnp.uint32)
    out = ((hi_i << 16) | lo_i).astype(jnp.int32)
    return out.reshape((ids.shape[0],) + shape[1:])


# ---------------------------------------------------------------------------
# grouped exclusive rank (three phases, no cross-chunk serialization)
# ---------------------------------------------------------------------------


def grouped_rank(
    keys: jax.Array,
    values: Sequence[jax.Array],
    eligible: jax.Array,
    key_space: int,
) -> tuple:
    """Grouped exclusive cumsum over a dense small key space.

    For each item: sum of values of ELIGIBLE items earlier in the batch
    with the same key.  Three phases so chunks never serialize on a shared
    accumulator:
      A) per-chunk per-key totals (pallas histogram, independent chunks)
      B) exclusive prefix over the chunk axis (one triangular matmul)
      C) per-chunk: own-offset gather + strictly-lower-triangular same-key
         matmul (pallas, independent chunks)
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C = _TB_RANK
    nv = len(values)
    n_hi, n_lo = _plan(key_space)
    keys_p = _pad_to(keys.astype(jnp.int32), C, -1)
    b = keys.shape[0]
    nT = keys_p.shape[0] // C
    vals = jnp.stack(
        [jnp.where(eligible, v.astype(jnp.float32), 0.0) for v in values], axis=1
    )  # [B, nv]
    vals = _pad_to(vals, C, 0.0)
    keys3 = keys_p.reshape(nT, 1, C)
    vals3 = vals.reshape(nT, C, nv).transpose(0, 2, 1)  # [nT, nv, C]

    # --- phase A: per-chunk histograms -------------------------------------
    def hist_kernel(keys_ref, vals_ref, out_ref):
        k = keys_ref[0, 0, :]
        ok = (k >= 0) & (k < key_space)
        Hi, Lo = _onehots_f32(k, ok, n_hi, n_lo)
        for p in range(nv):
            LoV = Lo * vals_ref[0, p, :][:, None]
            out_ref[0, p, :, :] = jax.lax.dot_general(
                Hi,
                LoV,
                _C00,
                preferred_element_type=jnp.float32,
                precision=_HIGHEST,
            )

    hists = pl.pallas_call(
        hist_kernel,
        grid=(nT,),
        in_specs=[
            pl.BlockSpec((1, 1, C), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nv, C), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, nv, n_hi, n_lo), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nT, nv, n_hi, n_lo), jnp.float32),
    )(keys3, vals3)

    # --- phase B: exclusive prefix over chunks (strict lower triangular) ---
    tril = jnp.tril(jnp.ones((nT, nT), jnp.float32), k=-1)
    offs = jnp.matmul(tril, hists.reshape(nT, -1), precision=_HIGHEST).reshape(
        nT, nv, n_hi, n_lo
    )

    # --- phase C: offset gather + within-chunk triangular -------------------
    def rank_kernel(keys_ref, vals_ref, offs_ref, out_ref):
        k = keys_ref[0, 0, :]
        ok = (k >= 0) & (k < key_space)
        Hi, Lo = _onehots_f32(k, ok, n_hi, n_lo)
        iota_r = jax.lax.broadcasted_iota(jnp.int32, (C, C), 0)
        iota_c = jax.lax.broadcasted_iota(jnp.int32, (C, C), 1)
        oki = ok.astype(jnp.int32)
        same = (
            (k[:, None] == k[None, :])
            & (iota_c < iota_r)
            & (oki[:, None] > 0)
            & (oki[None, :] > 0)
        ).astype(jnp.float32)
        v_cols = vals_ref[0].T  # [C, nv]
        within = jax.lax.dot_general(
            same,
            v_cols,
            _C10,
            preferred_element_type=jnp.float32,
            precision=_HIGHEST,
        )  # [C, nv]
        for p in range(nv):
            sel = jax.lax.dot_general(
                Hi,
                offs_ref[0, p],
                _C10,
                preferred_element_type=jnp.float32,
                precision=_HIGHEST,
            )
            out_ref[0, p, :] = jnp.sum(sel * Lo, axis=1) + within[:, p]

    out = pl.pallas_call(
        rank_kernel,
        grid=(nT,),
        in_specs=[
            pl.BlockSpec((1, 1, C), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nv, C), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, nv, n_hi, n_lo), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec((1, nv, C), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nT, nv, C), jnp.float32),
    )(keys3, vals3, offs)
    out = out.transpose(1, 0, 2).reshape(nv, nT * C)[:, :b]
    return tuple(out[p] for p in range(nv))
