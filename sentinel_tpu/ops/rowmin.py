"""Exact per-row minimum on the MXU path — the windowed minRt plane.

Problem: the reference keeps per-bucket minRt per resource
(MetricBucket.java:28 min plane; StatisticNode minRt feeds snapshots and
the dashboard).  A scatter-MIN cannot ride the one-hot matmul path (dots
only sum), and XLA's native scatter-min serializes (~65 ns/element) — the
round-1/2 builds therefore skipped per-row minRt on the MXU path
(documented divergence; VERDICT r2 #6).

TPU-native solution: reduce duplicates BEFORE scattering, so the scatter
becomes a plain sum —

  1. ``lax.sort([row, value_bits], num_keys=2)``: positive-float bit
     patterns are order-preserving, so after the two-key sort each row's
     FIRST item already holds that row's minimum (~0.4 ms at 3x128K),
  2. segment heads (row != previous row) are unique per row, so a
     sum-scatter of the head values IS the per-row min — and it rides the
     exact one-hot digit path (f32 bits split into 16-bit halves).

Exactness: bit-exact with the XLA scatter path's `.at[rows].min(rt)` for
positive rts (absent/invalid rts drop; rows with no item report absent).
"""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np
import jax.numpy as jnp

from sentinel_tpu.core.config import EngineConfig
from sentinel_tpu.ops import tables as T

#: int32 bit pattern above any valid positive float's bits
_ABSENT = np.int32(0x7F000000)  # numpy scalar, NOT jnp: a module-level device array becomes a hoisted jaxpr const (extra executable parameter) and this jaxlib's dispatch fastpath drops consts when sibling cfg-variant executables coexist.  Enforced structurally by the jaxpr analyzer's const-hoist pass (sentinel_tpu/analysis/jaxpr)


def min_heads(
    rows: jax.Array,  # int32 [N] — target row per item (out-of-range drops)
    values: jax.Array,  # float32 [N] — POSITIVE values (rt ms)
    valid: jax.Array,  # bool [N]
    n_rows: int,
) -> Tuple[jax.Array, jax.Array]:
    """(head_rows int32 [N], head_vals int32 [N, 3]) — per row at most ONE
    item survives (its min), as (bits>>16, bits&0xFFFF, 1) halves ready for
    an exact digit-plane sum-scatter; all other items carry row -1."""
    ok = valid & (rows >= 0) & (rows < n_rows) & (values > 0)
    key = jnp.where(ok, rows, jnp.int32(n_rows))  # invalid to a pad segment
    bits = jnp.where(ok, jax.lax.bitcast_convert_type(values, jnp.int32), _ABSENT)
    sk, sv = jax.lax.sort([key, bits], num_keys=2)
    head = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]]) & (sk < n_rows)
    u = jnp.where(head, sv, 0).astype(jnp.uint32)
    hvals = jnp.stack(
        [
            (u >> 16).astype(jnp.int32),
            (u & 0xFFFF).astype(jnp.int32),
            head.astype(jnp.int32),
        ],
        axis=1,
    )
    return jnp.where(head, sk, -1), hvals


def combine(hist: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(mins f32 [n], present bool [n]) from the landed [n, 3] head sums."""
    hist = jnp.round(hist).astype(jnp.int32)
    present = hist[:, 2] > 0
    bits = ((hist[:, 0].astype(jnp.uint32) << 16) | hist[:, 1].astype(jnp.uint32)).astype(
        jnp.int32
    )
    return jax.lax.bitcast_convert_type(bits, jnp.float32), present


def per_row_min(
    cfg: EngineConfig,
    rows: jax.Array,
    values: jax.Array,
    valid: jax.Array,
    n_rows: int,
):
    """(min_vals f32 [n_rows], present bool [n_rows]) — exact min of
    values per row via min_heads + a digit-plane sum-scatter.  The fused
    engine path lands the heads through its scatter_many kernel instead
    (one extra job); this standalone form serves the unfused MXU path."""
    hrows, hvals = min_heads(rows, values, valid, n_rows)
    hist = T.big_scatter_add(
        cfg,
        jnp.zeros((n_rows, 3), jnp.int32),
        hrows,
        hvals,
        n_rows,
        max_int=65535,
    )
    return combine(hist.astype(jnp.float32))
