"""Circuit breakers as a vectorized state machine.

The reference's AbstractCircuitBreaker.java:33-136 is a CAS state machine
(CLOSED/OPEN/HALF_OPEN) per DegradeRule, with its own LeapArray of
slow/error counts (ResponseTimeCircuitBreaker.java:162,
ExceptionCircuitBreaker.java:37).  Here every degrade rule is a row in:

    cb_state    : int32 [D+1]          (0 CLOSED, 1 OPEN, 2 HALF_OPEN)
    cb_retry_ms : int32 [D+1]          next-probe deadline for OPEN rules
    cb_counts   : int32 [D+1, nb, 3]   (TOTAL, ERROR, SLOW) ring buckets
    cb_epochs   : int32 [D+1, nb]      per-rule epochs (rules have their own
                                       statIntervalMs, so bucket lengths vary
                                       per row — window_ms[D+1])

Transitions per tick:
  - completions scatter TOTAL/ERROR/SLOW into each rule's current bucket;
  - a completion observed while HALF_OPEN resolves the probe:
    error-or-slow → OPEN (regression, AbstractCircuitBreaker.java:136),
    otherwise → CLOSED with stats reset;
  - CLOSED rules re-evaluate their trip condition on windowed sums;
  - the acquire path (in engine.py) elects one probe per OPEN rule whose
    retry deadline passed, moving it to HALF_OPEN.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

CB_CLOSED = 0
CB_OPEN = 1
CB_HALF_OPEN = 2

CBE_TOTAL = 0
CBE_ERROR = 1
CBE_SLOW = 2

# DegradeRule grades (RuleConstant)
GRADE_SLOW_RATIO = 0
GRADE_ERROR_RATIO = 1
GRADE_ERROR_COUNT = 2


def refresh_columns(
    counts: jax.Array,  # int32 [D+1, nb, 3]
    epochs: jax.Array,  # int32 [D+1, nb]
    window_ms: jax.Array,  # int32 [D+1]
    now_ms: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Zero each rule's current bucket if stale. Returns (counts, epochs, cur_idx)."""
    nb = counts.shape[1]
    wid = (now_ms // jnp.maximum(window_ms, 1)).astype(jnp.int32)
    idx = wid % nb
    onehot = jax.nn.one_hot(idx, nb, dtype=jnp.int32)
    # one-hot contraction, not take_along_axis (serialized row gather)
    cur_epoch = jnp.sum(epochs * onehot, axis=1)
    stale = (cur_epoch != wid).astype(jnp.int32)
    keep = 1 - onehot * stale[:, None]
    counts = counts * keep[:, :, None]
    epochs = jnp.where((onehot == 1) & (stale[:, None] == 1), wid[:, None], epochs)
    return counts, epochs, idx


def window_sums(
    counts: jax.Array, epochs: jax.Array, window_ms: jax.Array, now_ms: jax.Array
) -> jax.Array:
    """int32 [D+1, 3] — windowed totals per rule."""
    nb = counts.shape[1]
    wid = (now_ms // jnp.maximum(window_ms, 1)).astype(jnp.int32)
    valid = (epochs > (wid[:, None] - nb)) & (epochs <= wid[:, None])
    return jnp.sum(counts * valid[:, :, None], axis=1)


def trip_condition(
    sums: jax.Array,  # int32 [D+1, 3]
    grade: jax.Array,  # int32 [D+1]
    count: jax.Array,  # float32 [D+1] (maxRT / ratio / abs count)
    slow_ratio: jax.Array,  # float32 [D+1]
    min_request: jax.Array,  # int32 [D+1]
) -> jax.Array:
    """bool [D+1] — should a CLOSED breaker trip OPEN now?

    Mirrors ResponseTimeCircuitBreaker.onRequestComplete:65-90 and
    ExceptionCircuitBreaker threshold checks.
    """
    total = sums[:, CBE_TOTAL].astype(jnp.float32)
    err = sums[:, CBE_ERROR].astype(jnp.float32)
    slow = sums[:, CBE_SLOW].astype(jnp.float32)
    enough = total >= min_request.astype(jnp.float32)
    safe_total = jnp.maximum(total, 1.0)
    trip_slow = (grade == GRADE_SLOW_RATIO) & enough & (slow / safe_total > slow_ratio)
    trip_eratio = (grade == GRADE_ERROR_RATIO) & enough & (err / safe_total > count)
    trip_ecount = (grade == GRADE_ERROR_COUNT) & enough & (err >= count)
    return trip_slow | trip_eratio | trip_ecount
