"""Metric log reader — time-range queries over the writer's files.

The analog of MetricSearcher (node/metric/MetricSearcher.java:34,84-113):
used by the ``metric`` command handler (SendMetricCommandHandler.java:41-43)
to serve the dashboard's catch-up pull.  The ``.idx`` companion file maps
second-timestamps to byte offsets so queries seek, not scan.
"""

from __future__ import annotations

import os
from typing import List, Optional

from sentinel_tpu.metrics.node import MetricNode
from sentinel_tpu.metrics.writer import list_metric_files


def _read_idx(path: str):
    """[(second_ms, offset)] for one metric file, or [] if no idx."""
    idx_path = path + ".idx"
    out = []
    if not os.path.exists(idx_path):
        return out
    with open(idx_path, "r", encoding="utf-8") as f:
        for line in f:
            try:
                sec, off = line.split()
                out.append((int(sec), int(off)))
            except ValueError:
                continue
    return out


class MetricSearcher:
    def __init__(self, base_dir: str, app_name: str):
        self.base_dir = base_dir
        self.app_name = app_name

    def find(self, begin_ms: int, recommended_count: int = 6000) -> List[MetricNode]:
        """Nodes with timestamp >= begin_ms, up to recommended_count —
        but never truncating mid-second (MetricSearcher.find contract:
        all lines of the last included second are returned)."""
        out: List[MetricNode] = []
        for path in list_metric_files(self.base_dir, self.app_name):
            idx = _read_idx(path)
            if idx and idx[-1][0] < begin_ms:
                continue  # whole file before range
            offset = _seek_offset(idx, begin_ms)
            for node in _iter_file(path, offset):
                if node.timestamp < begin_ms:
                    continue
                if len(out) >= recommended_count and node.timestamp != out[-1].timestamp:
                    return out
                out.append(node)
        return out

    def find_by_time_and_resource(
        self, begin_ms: int, end_ms: int, resource: Optional[str] = None
    ) -> List[MetricNode]:
        out: List[MetricNode] = []
        for path in list_metric_files(self.base_dir, self.app_name):
            idx = _read_idx(path)
            if idx and idx[-1][0] < begin_ms:
                continue
            offset = _seek_offset(idx, begin_ms)
            for node in _iter_file(path, offset):
                if node.timestamp < begin_ms:
                    continue
                if node.timestamp > end_ms:
                    break
                if resource is None or node.resource == resource:
                    out.append(node)
        return out


def _seek_offset(idx, begin_ms: int) -> int:
    """Greatest indexed offset whose second <= begin_ms (binary search)."""
    lo, hi, best = 0, len(idx) - 1, 0
    while lo <= hi:
        mid = (lo + hi) // 2
        if idx[mid][0] <= begin_ms:
            best = idx[mid][1]
            lo = mid + 1
        else:
            hi = mid - 1
    return best


def _iter_file(path: str, offset: int):
    try:
        with open(path, "r", encoding="utf-8") as f:
            f.seek(offset)
            for line in f:
                try:
                    yield MetricNode.from_line(line)
                except ValueError:
                    continue
    except OSError:
        return
