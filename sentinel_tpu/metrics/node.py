"""Metric line codec — the analog of the reference's MetricNode.

One line per (second, resource), written to the app metric log and parsed
back by the searcher / dashboard fetcher (reference:
sentinel-core/src/main/java/com/alibaba/csp/sentinel/node/metric/MetricNode.java).

Line format (all counts are totals within the stamped second, so count ==
QPS for that second, as in the reference):

    timestamp|yyyy-mm-dd HH:MM:SS|resource|pass|block|success|exception|rt|occupiedPass|concurrency|classification

Resource names are percent-encoded so ``|`` and newlines can never break
the framing (the reference forbids them instead).
"""

from __future__ import annotations

import time
import urllib.parse
from dataclasses import dataclass, field


@dataclass
class MetricNode:
    timestamp: int = 0  # ms, second-aligned
    resource: str = ""
    pass_qps: float = 0.0
    block_qps: float = 0.0
    success_qps: float = 0.0
    exception_qps: float = 0.0
    rt: float = 0.0  # average RT over the second, ms
    occupied_pass_qps: float = 0.0
    concurrency: int = 0
    classification: int = 0

    def is_active(self) -> bool:
        return (
            self.pass_qps > 0
            or self.block_qps > 0
            or self.success_qps > 0
            or self.exception_qps > 0
            or self.occupied_pass_qps > 0
            or self.concurrency > 0
        )

    def to_line(self) -> str:
        ts = self.timestamp
        human = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts / 1000.0))
        res = urllib.parse.quote(self.resource, safe="")
        nums = "|".join(
            _fmt(v)
            for v in (
                self.pass_qps,
                self.block_qps,
                self.success_qps,
                self.exception_qps,
                self.rt,
                self.occupied_pass_qps,
            )
        )
        return f"{ts}|{human}|{res}|{nums}|{self.concurrency}|{self.classification}"

    @staticmethod
    def from_line(line: str) -> "MetricNode":
        parts = line.rstrip("\n").split("|")
        if len(parts) != 11:
            raise ValueError(f"bad metric line ({len(parts)} fields): {line!r}")
        return MetricNode(
            timestamp=int(parts[0]),
            resource=urllib.parse.unquote(parts[2]),
            pass_qps=float(parts[3]),
            block_qps=float(parts[4]),
            success_qps=float(parts[5]),
            exception_qps=float(parts[6]),
            rt=float(parts[7]),
            occupied_pass_qps=float(parts[8]),
            concurrency=int(parts[9]),
            classification=int(parts[10]),
        )


def _fmt(v: float) -> str:
    # integers print bare, fractions keep precision — keeps files compact
    return str(int(v)) if float(v).is_integer() else repr(float(v))
