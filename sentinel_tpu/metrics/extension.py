"""Metric extension SPI — external-metrics callbacks (Prometheus-style).

The analog of metric/extension/MetricExtension.java +
MetricCallbackInit.java: registered extensions get a callback on every
pass / block / completion so users can bridge verdict telemetry into their
own metrics system.  Callbacks run on the caller thread and must be cheap;
when no extension is registered the hot path pays one truthiness check.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence


class MetricExtension:
    """Subclass and override what you need; all hooks default to no-ops."""

    def on_pass(self, resource: str, count: int, origin: str, args: Optional[Sequence] = None) -> None:
        pass

    def on_block(
        self,
        resource: str,
        count: int,
        origin: str,
        block_exception: BaseException,
        args: Optional[Sequence] = None,
    ) -> None:
        pass

    def on_complete(self, resource: str, rt_ms: float, success: int, origin: str) -> None:
        pass

    def on_exception(self, resource: str, count: int, origin: str) -> None:
        pass

    def on_thread_change(self, resource: str, delta: int) -> None:
        pass


_lock = threading.Lock()
_extensions: List[MetricExtension] = []


def register_extension(ext: MetricExtension) -> None:
    with _lock:
        _extensions.append(ext)


def unregister_extension(ext: MetricExtension) -> None:
    with _lock:
        try:
            _extensions.remove(ext)
        except ValueError:
            pass


def clear_extensions() -> None:
    with _lock:
        _extensions.clear()


def get_extensions() -> List[MetricExtension]:
    return _extensions  # read without lock: list is replaced-in-place rarely
