"""Metric extension SPI — external-metrics callbacks (Prometheus-style).

The analog of metric/extension/MetricExtension.java +
MetricCallbackInit.java: registered extensions get a callback on every
pass / block / completion so users can bridge verdict telemetry into their
own metrics system.  Callbacks run on the caller thread and must be cheap;
when no extension is registered the hot path pays one truthiness check.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence


class MetricExtension:
    """Subclass and override what you need; all hooks default to no-ops."""

    def on_pass(self, resource: str, count: int, origin: str, args: Optional[Sequence] = None) -> None:
        pass

    def on_block(
        self,
        resource: str,
        count: int,
        origin: str,
        block_exception: BaseException,
        args: Optional[Sequence] = None,
    ) -> None:
        pass

    def on_complete(self, resource: str, rt_ms: float, success: int, origin: str) -> None:
        pass

    def on_exception(self, resource: str, count: int, origin: str) -> None:
        pass

    def on_thread_change(self, resource: str, delta: int) -> None:
        pass


_lock = threading.Lock()
_extensions: List[MetricExtension] = []


def register_extension(ext: MetricExtension) -> None:
    global _extensions
    with _lock:
        _extensions = _extensions + [ext]


def unregister_extension(ext: MetricExtension) -> None:
    global _extensions
    with _lock:
        _extensions = [x for x in _extensions if x is not ext]


def clear_extensions() -> None:
    global _extensions
    with _lock:
        _extensions = []


def get_extensions() -> List[MetricExtension]:
    # copy-on-write: registration rebinds a fresh list under the lock, so
    # lock-free readers always iterate an immutable snapshot
    return _extensions


def safe_dispatch(hook: str, *args) -> None:
    """Invoke one hook on every registered extension, isolating failures —
    a throwing user extension must never corrupt engine accounting."""
    exts = _extensions
    if not exts:
        return
    for x in exts:
        try:
            getattr(x, hook)(*args)
        except Exception:  # noqa: BLE001
            from sentinel_tpu.utils.record_log import record_log

            record_log().exception("metric extension %s.%s failed", type(x).__name__, hook)
