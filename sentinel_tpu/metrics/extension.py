"""Metric extension SPI — external-metrics callbacks (Prometheus-style).

The analog of metric/extension/MetricExtension.java +
MetricCallbackInit.java: registered extensions get a callback on every
pass / block / completion so users can bridge verdict telemetry into their
own metrics system.  Callbacks run on the caller thread and must be cheap;
when no extension is registered the hot path pays one truthiness check.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.time_source import mono_s

#: swallowed extension exceptions, visible on /metrics — a throwing user
#: extension must never corrupt engine accounting, but it must not fail
#: SILENTLY either (the counter keeps climbing even while logs are
#: rate-limited)
_C_EXT_ERRORS = _OBS.counter(
    "sentinel_extension_errors_total",
    "metric-extension callbacks that raised and were swallowed",
)

#: seconds between record-log warnings per (extension class, hook) — a
#: hot-path extension failing on EVERY pass would otherwise write the
#: log at traffic rate
_WARN_INTERVAL_S = 10.0


class MetricExtension:
    """Subclass and override what you need; all hooks default to no-ops."""

    def on_pass(self, resource: str, count: int, origin: str, args: Optional[Sequence] = None) -> None:
        pass

    def on_block(
        self,
        resource: str,
        count: int,
        origin: str,
        block_exception: BaseException,
        args: Optional[Sequence] = None,
    ) -> None:
        pass

    def on_complete(self, resource: str, rt_ms: float, success: int, origin: str) -> None:
        pass

    def on_exception(self, resource: str, count: int, origin: str) -> None:
        pass

    def on_thread_change(self, resource: str, delta: int) -> None:
        pass


_lock = threading.Lock()
_extensions: List[MetricExtension] = []
# (ext class name, hook) -> (last warning stamp, failures since that log);
# all writes under _lock (the module's one owning lock)
_warn_state: Dict[Tuple[str, str], Tuple[float, int]] = {}


def register_extension(ext: MetricExtension) -> None:
    global _extensions
    with _lock:
        _extensions = _extensions + [ext]


def unregister_extension(ext: MetricExtension) -> None:
    global _extensions
    with _lock:
        _extensions = [x for x in _extensions if x is not ext]


def clear_extensions() -> None:
    global _extensions
    with _lock:
        _extensions = []


def get_extensions() -> List[MetricExtension]:
    # copy-on-write: registration rebinds a fresh list under the lock, so
    # lock-free readers always iterate an immutable snapshot
    return _extensions


def safe_dispatch(hook: str, *args) -> None:
    """Invoke one hook on every registered extension, isolating failures —
    a throwing user extension must never corrupt engine accounting.

    Every swallowed exception increments
    ``sentinel_extension_errors_total``; the record-log warning is
    rate-limited to one per (extension class, hook) per
    ``_WARN_INTERVAL_S`` and carries the count of failures the limiter
    suppressed since the previous log, so a persistently-failing
    extension stays VISIBLE without writing the log at traffic rate."""
    exts = _extensions
    if not exts:
        return
    for x in exts:
        try:
            getattr(x, hook)(*args)
        except Exception:  # noqa: BLE001
            _C_EXT_ERRORS.inc()
            key = (type(x).__name__, hook)
            now = mono_s()
            with _lock:
                last, suppressed = _warn_state.get(key, (-1e18, 0))
                if now - last >= _WARN_INTERVAL_S:
                    _warn_state[key] = (now, 0)
                    do_log, since = True, suppressed
                else:
                    _warn_state[key] = (last, suppressed + 1)
                    do_log, since = False, 0
            if do_log:
                from sentinel_tpu.utils.record_log import record_log

                record_log().exception(
                    "metric extension %s.%s failed (+%d more failures "
                    "suppressed in the last %.0fs; total on "
                    "sentinel_extension_errors_total)",
                    key[0], hook, since, _WARN_INTERVAL_S,
                )
