"""Per-second metric aggregation → metric log.

The analog of MetricTimerListener (node/metric/MetricTimerListener.java:34-59):
once per second, snapshot every registered resource's trailing-second window
counters and append active ones to the metric log.

TPU twist: instead of walking a ClusterNode map, the snapshot is ONE batched
device gather over all resource rows (ClientStats.snapshot), so cost is
independent of resource count up to the engine capacity.
"""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.metrics.node import MetricNode
from sentinel_tpu.utils.time_source import wall_s
from sentinel_tpu.metrics.writer import MetricWriter


class MetricTimerListener:
    def __init__(self, client, writer: MetricWriter):
        self.client = client
        self.writer = writer
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="sentinel-tpu-metric-timer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.writer.close()

    def run_once(self, now_ms: Optional[int] = None) -> int:
        """Aggregate and write one snapshot; returns #lines written.
        Exposed for tests / virtual-time drives."""
        now_ms = self.client.time.now_ms() if now_ms is None else now_ms
        snap = self.client.stats.snapshot(now_ms)
        # engine time is monotonic-relative; metric lines carry wall-clock
        # stamps so the dashboard/searcher can query by real time
        wall_ms = self.client.time.wall_ms(now_ms)
        nodes = []
        for name, s in snap.items():
            nodes.append(
                MetricNode(
                    timestamp=wall_ms,
                    resource=name,
                    pass_qps=s["passQps"],
                    block_qps=s["blockQps"],
                    success_qps=s["successQps"],
                    exception_qps=s["exceptionQps"],
                    rt=s["avgRt"],
                    occupied_pass_qps=s.get("occupiedPassQps", 0.0),
                    concurrency=int(s["curThreadNum"]),
                )
            )
        active = [n for n in nodes if n.is_active()]
        self.writer.write(wall_ms, nodes)
        return len(active)

    def _loop(self) -> None:
        while not self._stop.is_set():
            # align to the wall-second boundary so each line covers one
            # whole second (the scheduled-at-fixed-rate 1 s cadence)
            delay = 1.0 - (wall_s() % 1.0)
            if self._stop.wait(delay + 0.01):
                break
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — aggregation must never kill the loop
                from sentinel_tpu.utils.record_log import record_log

                record_log().exception("metric timer aggregation failed")
