"""Block-event log — rate-limited ``sentinel-block.log``.

The analog of LogSlot → EagleEyeLogUtil.java:24-36 backed by the embedded
EagleEye StatLogger: every blocked request is recorded, but writes are
aggregated per (resource, exception, origin, provenance) per second so a
block storm costs one line per distinct key per second, not one line per
request.

Aggregation is inline (flushed when the wall second advances) instead of
the reference's async appender thread — the host tick loop already gives
us a natural cadence and this keeps the writer allocation-free.

Line formats (both are valid; ``parse_line`` reads either):

  legacy:   timestamp|resource|exceptionName|count|origin
  explain:  timestamp|resource|exceptionName|count|origin|kind|rule

The two trailing fields are the verdict provenance key from the explain
plane (obs/explain.py): the cause name ("flow"/"degrade"/…) and the
blamed rule slot (empty when unattributable).  Lines carry them only
when the caller supplied provenance, so a client without the explain
plane writes byte-identical legacy lines.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple


def parse_line(line: str) -> Optional[dict]:
    """One log line -> dict, accepting BOTH the legacy 5-field format and
    the 7-field explain format.  Returns None on a malformed line."""
    parts = line.rstrip("\n").split("|")
    if len(parts) not in (5, 7):
        return None
    try:
        out = {
            "ts": int(parts[0]),
            "resource": parts[1],
            "exception": parts[2],
            "count": int(parts[3]),
            "origin": parts[4],
            "kind": None,
            "rule": None,
        }
    except ValueError:
        return None
    if len(parts) == 7:
        out["kind"] = parts[5] or None
        try:
            out["rule"] = int(parts[6]) if parts[6] else None
        except ValueError:
            return None
    return out


class BlockLogger:
    def __init__(
        self,
        base_dir: str,
        filename: str = "sentinel-block.log",
        max_file_size: int = 50 * 1024 * 1024,
        backup_count: int = 3,
    ):
        os.makedirs(base_dir, exist_ok=True)
        self.path = os.path.join(base_dir, filename)
        self.max_file_size = max_file_size
        self.backup_count = backup_count
        self._lock = threading.Lock()
        self._cur_sec = -1
        self._pending: Dict[Tuple[str, str, str, Optional[str], Optional[int]], int] = {}

    def log(
        self,
        now_ms: int,
        resource: str,
        exception_name: str,
        origin: str = "",
        count: int = 1,
        kind: Optional[str] = None,
        rule: Optional[int] = None,
    ) -> None:
        sec = now_ms // 1000
        with self._lock:
            if sec != self._cur_sec:
                self._flush_locked()
                self._cur_sec = sec
            key = (resource, exception_name, origin, kind, rule)
            self._pending[key] = self._pending.get(key, 0) + count

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._pending:
            return
        ts = self._cur_sec * 1000
        lines = []
        for (res, exc, origin, kind, rule), cnt in self._pending.items():
            if kind is None and rule is None:
                lines.append(f"{ts}|{res}|{exc}|{cnt}|{origin}\n")
            else:
                lines.append(
                    f"{ts}|{res}|{exc}|{cnt}|{origin}"
                    f"|{kind or ''}|{'' if rule is None else rule}\n"
                )
        self._pending.clear()
        try:
            self._roll_if_needed()
            with open(self.path, "a", encoding="utf-8") as f:
                f.writelines(lines)
        except OSError:
            pass

    def _roll_if_needed(self) -> None:
        """Size-capped rotation (EagleEyeRollingFileAppender analog):
        block.log → block.log.1 → … → block.log.{backup_count} → dropped."""
        try:
            if os.path.getsize(self.path) < self.max_file_size:
                return
        except OSError:
            return
        for i in range(self.backup_count - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        os.replace(self.path, f"{self.path}.1")


_default: Optional[BlockLogger] = None
_default_lock = threading.Lock()


def default_block_logger() -> BlockLogger:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                from sentinel_tpu.utils.record_log import log_dir

                _default = BlockLogger(log_dir())
    return _default
