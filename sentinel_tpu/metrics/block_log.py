"""Block-event log — rate-limited ``sentinel-block.log``.

The analog of LogSlot → EagleEyeLogUtil.java:24-36 backed by the embedded
EagleEye StatLogger: every blocked request is recorded, but writes are
aggregated per (resource, exception, origin) per second so a block storm
costs one line per distinct key per second, not one line per request.

Aggregation is inline (flushed when the wall second advances) instead of
the reference's async appender thread — the host tick loop already gives
us a natural cadence and this keeps the writer allocation-free.

Line format:  timestamp|resource|exceptionName|count|origin
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple


class BlockLogger:
    def __init__(
        self,
        base_dir: str,
        filename: str = "sentinel-block.log",
        max_file_size: int = 50 * 1024 * 1024,
        backup_count: int = 3,
    ):
        os.makedirs(base_dir, exist_ok=True)
        self.path = os.path.join(base_dir, filename)
        self.max_file_size = max_file_size
        self.backup_count = backup_count
        self._lock = threading.Lock()
        self._cur_sec = -1
        self._pending: Dict[Tuple[str, str, str], int] = {}

    def log(self, now_ms: int, resource: str, exception_name: str, origin: str = "", count: int = 1) -> None:
        sec = now_ms // 1000
        with self._lock:
            if sec != self._cur_sec:
                self._flush_locked()
                self._cur_sec = sec
            key = (resource, exception_name, origin)
            self._pending[key] = self._pending.get(key, 0) + count

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._pending:
            return
        ts = self._cur_sec * 1000
        lines = [
            f"{ts}|{res}|{exc}|{cnt}|{origin}\n"
            for (res, exc, origin), cnt in self._pending.items()
        ]
        self._pending.clear()
        try:
            self._roll_if_needed()
            with open(self.path, "a", encoding="utf-8") as f:
                f.writelines(lines)
        except OSError:
            pass

    def _roll_if_needed(self) -> None:
        """Size-capped rotation (EagleEyeRollingFileAppender analog):
        block.log → block.log.1 → … → block.log.{backup_count} → dropped."""
        try:
            if os.path.getsize(self.path) < self.max_file_size:
                return
        except OSError:
            return
        for i in range(self.backup_count - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        os.replace(self.path, f"{self.path}.1")


_default: Optional[BlockLogger] = None
_default_lock = threading.Lock()


def default_block_logger() -> BlockLogger:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                from sentinel_tpu.utils.record_log import log_dir

                _default = BlockLogger(log_dir())
    return _default
