"""Observability plane: metric log writer/searcher, per-second aggregation,
block log, and the external-metrics callback SPI (SURVEY §3.5)."""

from sentinel_tpu.metrics.node import MetricNode
from sentinel_tpu.metrics.writer import MetricWriter, list_metric_files, metric_file_base
from sentinel_tpu.metrics.searcher import MetricSearcher
from sentinel_tpu.metrics.timer import MetricTimerListener
from sentinel_tpu.metrics.block_log import BlockLogger, default_block_logger
from sentinel_tpu.metrics.extension import (
    MetricExtension,
    register_extension,
    unregister_extension,
    clear_extensions,
    get_extensions,
    safe_dispatch,
)

__all__ = [
    "MetricNode",
    "MetricWriter",
    "MetricSearcher",
    "MetricTimerListener",
    "BlockLogger",
    "default_block_logger",
    "MetricExtension",
    "register_extension",
    "unregister_extension",
    "clear_extensions",
    "get_extensions",
    "safe_dispatch",
    "list_metric_files",
    "metric_file_base",
]
