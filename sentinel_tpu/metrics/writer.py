"""Metric log writer — per-second metric lines + a seek index.

The analog of the reference's MetricWriter (node/metric/MetricWriter.java:36-58):
each app process appends one line per active resource per second to

    {base_dir}/{app}-metrics.log.pid{pid}.{yyyy-mm-dd}[.{n}]

and maintains a companion ``.idx`` file with one ``second_ts offset`` text
line per second written, so a reader can seek straight to a time range
without scanning (MetricSearcher / the dashboard's catch-up fetch).

Rolling: a new dated file per day; within a day, a new ``.n`` suffix when
the current file exceeds ``single_file_size``; at most ``total_file_count``
files are kept (oldest deleted), mirroring SentinelConfig's
``metric file size/count`` knobs (SentinelConfig.java:49-59).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from sentinel_tpu.metrics.node import MetricNode

DEFAULT_SINGLE_FILE_SIZE = 50 * 1024 * 1024
DEFAULT_TOTAL_FILE_COUNT = 6


def metric_file_base(app_name: str, pid: Optional[int] = None) -> str:
    pid = os.getpid() if pid is None else pid
    return f"{app_name}-metrics.log.pid{pid}"


def list_metric_files(base_dir: str, app_name: str) -> List[str]:
    """All metric files for app (any pid), oldest → newest.

    Ordering key: (date, roll-index) — the reference sorts by file name then
    index (MetricWriter.listMetricFiles)."""
    if not os.path.isdir(base_dir):
        return []
    prefix = f"{app_name}-metrics.log.pid"
    out = []
    for fn in os.listdir(base_dir):
        if fn.startswith(prefix) and ".idx" not in fn:
            out.append(fn)
    return [os.path.join(base_dir, f) for f in sorted(out, key=_file_sort_key)]


def _pid_of(basename: str) -> int:
    # {app}-metrics.log.pid{pid}.{date}[.{n}]
    try:
        return int(basename.split(".pid", 1)[1].split(".", 1)[0])
    except (IndexError, ValueError):
        return -1


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else


def _file_sort_key(fn: str):
    # {app}-metrics.log.pid{pid}.{date}[.{n}]
    parts = fn.rsplit(".", 2)
    if len(parts) == 3 and parts[2].isdigit():
        return (parts[1], int(parts[2]))
    return (fn.rsplit(".", 1)[-1], 0)


class MetricWriter:
    def __init__(
        self,
        base_dir: str,
        app_name: str,
        single_file_size: int = DEFAULT_SINGLE_FILE_SIZE,
        total_file_count: int = DEFAULT_TOTAL_FILE_COUNT,
    ):
        self.base_dir = base_dir
        self.app_name = app_name
        self.single_file_size = single_file_size
        self.total_file_count = total_file_count
        self._lock = threading.Lock()
        self._fh = None
        self._idx_fh = None
        self._cur_path: Optional[str] = None
        self._cur_date: Optional[str] = None
        self._roll_n = 0
        self._last_sec = -1
        os.makedirs(base_dir, exist_ok=True)

    # -- public -------------------------------------------------------------

    def write(self, time_ms: int, nodes: List[MetricNode]) -> None:
        """Append nodes stamped at the second containing time_ms.

        Inactive (all-zero) nodes are skipped, as the reference does."""
        sec_ms = (time_ms // 1000) * 1000
        active = [n for n in nodes if n.is_active()]
        if not active:
            return
        with self._lock:
            self._ensure_file(sec_ms)
            if sec_ms // 1000 != self._last_sec:
                self._last_sec = sec_ms // 1000
                self._idx_fh.write(f"{sec_ms} {self._fh.tell()}\n")
                self._idx_fh.flush()
            for n in active:
                n.timestamp = sec_ms
                self._fh.write(n.to_line() + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            for fh in (self._fh, self._idx_fh):
                if fh is not None:
                    fh.close()
            self._fh = self._idx_fh = None
            self._cur_path = None

    # -- internals ----------------------------------------------------------

    def _ensure_file(self, time_ms: int) -> None:
        date = time.strftime("%Y-%m-%d", time.localtime(time_ms / 1000.0))
        need_new = (
            self._fh is None
            or date != self._cur_date
            or self._fh.tell() >= self.single_file_size
        )
        if not need_new:
            return
        if self._fh is not None:
            self._fh.close()
            self._idx_fh.close()
        if date != self._cur_date:
            self._cur_date = date
            self._roll_n = 0
        else:
            self._roll_n += 1
        base = metric_file_base(self.app_name)
        name = f"{base}.{date}" + (f".{self._roll_n}" if self._roll_n else "")
        self._cur_path = os.path.join(self.base_dir, name)
        self._fh = open(self._cur_path, "a", encoding="utf-8")
        self._idx_fh = open(self._cur_path + ".idx", "a", encoding="utf-8")
        self._last_sec = -1
        self._trim_old_files()

    def _trim_old_files(self) -> None:
        # eligible for deletion: this process's own files, plus files left
        # by pids that are no longer alive (dead runs would otherwise
        # accumulate forever).  Files of OTHER LIVE pids are never touched —
        # that process may have one open for append.
        own_prefix = metric_file_base(self.app_name) + "."
        files = []
        for f in list_metric_files(self.base_dir, self.app_name):
            base = os.path.basename(f)
            if base.startswith(own_prefix) or not _pid_alive(_pid_of(base)):
                files.append(f)
        excess = len(files) - self.total_file_count
        for path in files[: max(excess, 0)]:
            if path == self._cur_path:
                continue
            for p in (path, path + ".idx"):
                try:
                    os.remove(p)
                except OSError:
                    pass
