"""Seeded deterministic traffic generator driving the REAL adapters.

The OFFERED half of ROADMAP item 3: `TrafficGenerator` turns a
``WorkloadSpec`` (shapes.py) into a per-step stream of ``OfferedEvent``s
that is a PURE function of the spec's seed — per-shape PRNG streams are
derived exactly like the chaos plane's ``FaultPlan.spec_rng`` (seed ×
odd multiplier + stream index), event counts use error-diffusion
accumulation (no entropy at all), and keys/params come only from those
streams.  Two runs at one seed replay bit-identically; the acceptance
test diffs the full event lists.

Drivers push the stream through each real adapter surface on virtual
or real time (the clock belongs to the caller's ``SentinelClient``):

* ``drive_client``     — check_batch bulk decisions (the TPU-native path)
* ``drive_gateway``    — `GatewayAdapter.entries_for` with real
  `RequestAttributes` (param floods hit the per-param rule path)
* ``drive_asgi``       — `SentinelASGIMiddleware` scopes
* ``drive_streaming``  — `guard_stream` async generators
* ``drive_grpc``       — `SentinelServerInterceptor` handlers (gated on
  the optional `grpc` dependency)

``ServiceModel`` is the queueing backend the closed tuner loop rides:
the same FIFO service model `adaptive/simload.py` established — admitted
events batch into ticks whose cost and firing rule derive from the
ACTIVE ``OperatingPoint`` through a small documented tick-cost model —
so modeled request latency (the ``sentinel_workload_req_ms`` histogram
the SLO objective judges) is engine-time pure and replays exactly.

Chaos: ``workload.gen.emit`` fires once per generator step while armed;
a raise drops that step's whole emission (counted exactly in
``sentinel_workload_emit_drops_total`` — offered accounting never sees
the dropped events, so verdict accounting stays green by construction).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, NamedTuple, Optional, Tuple

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.obs.registry import REGISTRY
from sentinel_tpu.workload.operating_point import OperatingPoint
from sentinel_tpu.workload.shapes import WorkloadSpec

FP_GEN_EMIT = FP.register(
    "workload.gen.emit",
    "traffic-generator per-step emission (a raise drops the step's events)",
    FP.HIT_ACTIONS,
)

_C_OFFERED = {}
_C_OFFERED_LOCK = threading.Lock()


def _c_offered(shape: str):
    c = _C_OFFERED.get(shape)
    if c is None:
        with _C_OFFERED_LOCK:
            c = _C_OFFERED.get(shape)
            if c is None:
                c = _C_OFFERED[shape] = REGISTRY.counter(
                    "sentinel_workload_offered_total",
                    "events the traffic generator offered, by shape",
                    labels={"shape": shape},
                )
    return c


_C_PASSED = REGISTRY.counter(
    "sentinel_workload_passed_total",
    "offered events the driven surface admitted",
)
_C_BLOCKED = REGISTRY.counter(
    "sentinel_workload_blocked_total",
    "offered events the driven surface blocked",
)
_C_EMIT_DROPS = REGISTRY.counter(
    "sentinel_workload_emit_drops_total",
    "generator steps whose emission an armed workload.gen.emit fault dropped",
)
_H_REQ_MS = REGISTRY.histogram(
    "sentinel_workload_req_ms",
    "modeled end-to-end request latency under the workload service model "
    "(queue wait + service, engine-time pure)",
)


class OfferedEvent(NamedTuple):
    """One offered request — everything any adapter driver needs."""

    step: int
    t_ms: int
    key: str
    shape: str
    param: Optional[str]


class TrafficGenerator:
    """Deterministic event stream for one ``WorkloadSpec``."""

    def __init__(self, spec: WorkloadSpec, start_ms: int = 1_000):
        self.spec = spec
        self.start_ms = int(start_ms)

    def _stream_rng(self, idx: int) -> random.Random:
        # the chaos plan derivation (plans.FaultPlan.spec_rng): adjacent
        # seeds must not share streams, stream i is independent of i+1
        return random.Random(
            (int(self.spec.seed) * 0x9E3779B1 + idx) & 0xFFFFFFFF
        )

    def events(self) -> Iterator[Tuple[int, List[OfferedEvent]]]:
        """Yield ``(step, events_this_step)``; counts by error-diffusion
        (zero entropy), keys/params from per-shape seeded streams."""
        spec = self.spec
        rngs = [self._stream_rng(i) for i in range(len(spec.shapes))]
        accs = [0.0] * len(spec.shapes)
        default_cdf = spec.keys._cdf()
        shape_cdf = [
            (s.keys._cdf() if getattr(s, "keys", None) is not None else None)
            for s in spec.shapes
        ]
        for step in range(spec.steps):
            t_ms = self.start_ms + step * spec.step_ms
            out: List[OfferedEvent] = []
            for i, shape in enumerate(spec.shapes):
                accs[i] += float(shape.rate_at(step))
                n = int(accs[i])
                accs[i] -= n
                if n <= 0:
                    continue
                mix = getattr(shape, "keys", None) or spec.keys
                cdf = shape_cdf[i] or default_cdf
                rng = rngs[i]
                for _ in range(n):
                    key = mix.key_for(step, rng.random(), cdf)
                    out.append(
                        OfferedEvent(
                            step=step,
                            t_ms=t_ms,
                            key=key,
                            shape=shape.name,
                            param=getattr(shape, "param", None),
                        )
                    )
            try:
                FP.hit(FP_GEN_EMIT)  # chaos: a raise drops this step
            except Exception:
                _C_EMIT_DROPS.inc()
                yield step, []
                continue
            for ev in out:
                _c_offered(ev.shape).inc()
            yield step, out

    def all_events(self) -> List[OfferedEvent]:
        """The flattened stream (replay-diff surface for tests)."""
        return [ev for _step, evs in self.events() for ev in evs]


# -- service model -----------------------------------------------------------


@dataclass
class ServiceModel:
    """Batched FIFO queueing backend whose behavior derives from the
    active ``OperatingPoint`` — the simload precedent (a service model
    over REAL client decisions) extended with a documented tick-cost
    model so the tuner has a genuine multi-knob tradeoff surface with an
    INTERIOR optimum:

    - a tick costs ``tick_fixed_us + batch_size * per_item_us`` plus
      window-rotation work ``rot_unit_us * sample_count / g`` where
      ``g = ceil(slack_frac * sample_count)`` (slack windows batch
      expiry — arXiv 1703.01166) and an amortized online-audit charge
      ``audit_us / audit_period``;
    - the service budget allows ``budget_us * overlap / tick_us`` ticks
      per step, ``overlap = 1 + 0.35 * min(pipeline_depth, 4)``
      (pipelining overlaps host/device work with diminishing returns)
      — the SMALL-batch failure mode: under a flash crowd the tick rate
      caps throughput and the backlog queues;
    - a tick fires only when ``batch_size`` items are waiting or the
      oldest has aged ``flush_steps`` — the LARGE-batch failure mode:
      at baseline rates requests sit waiting for the batch to fill;
    - each pipeline slot adds ``pipe_wait_frac * step_ms`` of readback
      delay to every request's latency.

    All arithmetic on explicit inputs over virtual step counts —
    engine-time pure, replays exactly.
    """

    step_ms: int = 10
    tick_fixed_us: float = 250.0
    per_item_us: float = 2.0
    rot_unit_us: float = 18.0
    audit_us: float = 900.0
    budget_us: float = 900.0
    flush_steps: int = 8
    svc_steps: int = 1
    pipe_wait_frac: float = 0.5

    def tick_us(self, op: OperatingPoint) -> float:
        import math

        nb = max(1, op.sketch_sample_count or 2)
        g = max(1, math.ceil(op.sketch_slack_frac * nb))
        rot = self.rot_unit_us * nb / g
        audit = self.audit_us / max(1, op.audit_period)
        return self.tick_fixed_us + op.batch_size * self.per_item_us + rot + audit

    def ticks_per_step(self, op: OperatingPoint) -> int:
        overlap = 1.0 + 0.35 * min(op.pipeline_depth, 4)
        return max(1, int(self.budget_us * overlap / self.tick_us(op)))

    def extra_wait_ms(self, op: OperatingPoint) -> float:
        """Pipeline readback delay: each occupied slot holds a fraction
        of a step in front of every request's completion."""
        return op.pipeline_depth * self.pipe_wait_frac * self.step_ms


class ServiceBackend:
    """The FIFO itself: admitted events enter ``submit``; ``advance``
    fires full (or flush-aged) batches within the step's tick budget and
    returns completions with modeled latency."""

    def __init__(self, model: ServiceModel, op: OperatingPoint):
        self.model = model
        self.op = op
        self._backlog: List[Tuple[int, int]] = []  # (submit_step, rid)
        self._in_service: List[Tuple[int, int, int]] = []  # (done, submit, rid)

    def set_op(self, op: OperatingPoint) -> None:
        self.op = op

    def submit(self, step: int, rid: int) -> None:
        self._backlog.append((step, rid))

    def depth(self) -> int:
        return len(self._backlog) + len(self._in_service)

    def advance(self, step: int) -> List[Tuple[float, int]]:
        """Serve one step; returns completions as (latency_ms, rid)."""
        m, op = self.model, self.op
        done = [e for e in self._in_service if e[0] <= step]
        out: List[Tuple[float, int]] = []
        if done:
            self._in_service = [e for e in self._in_service if e[0] > step]
            svc_ms = m.tick_us(op) / 1000.0 + m.extra_wait_ms(op)
            for _due, sub, rid in done:
                out.append(((step - sub) * m.step_ms + svc_ms, rid))
        ticks = m.ticks_per_step(op)
        while ticks > 0 and self._backlog:
            aged = step - self._backlog[0][0] >= m.flush_steps
            if len(self._backlog) < op.batch_size and not aged:
                break  # wait for the batch to fill (the big-batch cost)
            for _ in range(min(op.batch_size, len(self._backlog))):
                sub, rid = self._backlog.pop(0)
                self._in_service.append((step + m.svc_steps, sub, rid))
            ticks -= 1
        return out


# -- adapter drivers ---------------------------------------------------------


@dataclass
class DriveResult:
    submitted: int = 0
    passed: int = 0
    blocked: int = 0
    latencies_ms: List[float] = None  # filled by closed-loop drivers

    def __post_init__(self):
        if self.latencies_ms is None:
            self.latencies_ms = []


def _account(res: DriveResult, passed: bool) -> None:
    res.submitted += 1
    if passed:
        res.passed += 1
        _C_PASSED.inc()
    else:
        res.blocked += 1
        _C_BLOCKED.inc()


def drive_client(
    client,
    gen: TrafficGenerator,
    resource_of: Optional[Callable[[OfferedEvent], str]] = None,
    backend: Optional[ServiceBackend] = None,
    on_step: Optional[Callable[[int, int], None]] = None,
) -> DriveResult:
    """Bulk check_batch driving on the caller's clock; with a
    ``ServiceBackend`` the admitted events flow through the queueing
    model, completions feed ``submit_completion_block`` and the modeled
    latencies land in ``sentinel_workload_req_ms``."""
    import numpy as np

    from sentinel_tpu.core import errors as ERR

    vt = client.time
    res = DriveResult()
    name_of = resource_of or (lambda ev: ev.key)
    rid_cache: Dict[str, int] = {}
    step_ms = gen.spec.step_ms

    def _complete(step: int) -> None:
        done = backend.advance(step)
        if not done:
            return
        lats = np.asarray([l for l, _r in done], np.float32)
        rids = np.asarray([r for _l, r in done], np.int32)
        for lat in lats:
            res.latencies_ms.append(float(lat))
            _H_REQ_MS.observe(float(lat))
        client.submit_completion_block(
            res=rids,
            rt=lats,
            success=np.ones(len(done), np.int32),
            inbound=np.ones(len(done), np.int32),
        )

    for step, evs in gen.events():
        if backend is not None:
            _complete(step)
        if evs:
            names = [name_of(ev) for ev in evs]
            params = [ev.param for ev in evs]
            verdicts = client.check_batch(
                names,
                params=params if any(p is not None for p in params) else None,
                inbound=True,
            )
            for ev, name, (v, _w) in zip(evs, names, verdicts):
                ok = v in (ERR.PASS, ERR.PASS_WAIT)
                _account(res, ok)
                if ok and backend is not None:
                    rid = rid_cache.get(name)
                    if rid is None:
                        rid = rid_cache[name] = client.registry.resource_id(name)
                    backend.submit(step, rid)
        if on_step is not None:
            on_step(step, len(evs))
        vt.sleep_ms(step_ms)
    # drain: let queued work finish so latency accounting is complete
    if backend is not None:
        step = gen.spec.steps
        guard = step + 4000
        while backend.depth() and step < guard:
            _complete(step)
            if on_step is not None:
                on_step(step, 0)
            vt.sleep_ms(step_ms)
            step += 1
    return res


def drive_gateway(adapter, gen: TrafficGenerator, route_id: str = "wl-route") -> DriveResult:
    """Every event becomes one ``entries_for`` acquisition with real
    ``RequestAttributes`` (key → path, param → X-Wl-Param header +
    url param so param-parse strategies see it)."""
    from sentinel_tpu.adapters.gateway import RequestAttributes
    from sentinel_tpu.core.errors import BlockException

    vt = adapter.client.time
    res = DriveResult()
    for _step, evs in gen.events():
        for ev in evs:
            req = RequestAttributes(
                path=f"/{ev.key}",
                client_ip="10.0.0.1",
                host="wl.example",
                headers={"X-Wl-Param": ev.param or ""},
                url_params={"p": ev.param or ""},
            )
            try:
                entries = adapter.entries_for(route_id, req)
            except BlockException:
                _account(res, False)
                continue
            for e in entries:
                e.exit()
            _account(res, True)
        vt.sleep_ms(gen.spec.step_ms)
    return res


def drive_asgi(middleware, gen: TrafficGenerator) -> DriveResult:
    """One ASGI scope per event (GET /{key}); 429 counts as blocked."""
    import asyncio

    res = DriveResult()
    vt = middleware.client.time

    async def one(ev: OfferedEvent) -> int:
        sent = []

        async def send(msg):
            sent.append(msg)

        async def receive():
            return {"type": "http.request"}

        scope = {
            "type": "http",
            "method": "GET",
            "path": f"/{ev.key}",
            "headers": [(b"x-wl-param", (ev.param or "").encode())],
        }
        await middleware(scope, receive, send)
        return sent[0]["status"]

    for _step, evs in gen.events():
        for ev in evs:
            _account(res, asyncio.run(one(ev)) != middleware.block_status)
        vt.sleep_ms(gen.spec.step_ms)
    return res


def drive_streaming(client, gen: TrafficGenerator, chunks: int = 2) -> DriveResult:
    """Each event opens a guarded async stream (``guard_stream``) and
    consumes it to completion; a BlockException on first pull counts as
    blocked."""
    import asyncio

    from sentinel_tpu.adapters.streaming import guard_stream
    from sentinel_tpu.core.errors import BlockException

    res = DriveResult()
    vt = client.time

    async def one(ev: OfferedEvent) -> bool:
        async def source():
            for i in range(chunks):
                yield i

        try:
            async for _chunk in guard_stream(
                ev.key, source(), client=client, inbound=True
            ):
                pass
        except BlockException:
            return False
        return True

    for _step, evs in gen.events():
        for ev in evs:
            _account(res, asyncio.run(one(ev)))
        vt.sleep_ms(gen.spec.step_ms)
    return res


def drive_grpc(client, gen: TrafficGenerator) -> Optional[DriveResult]:
    """Unary-unary handlers through ``SentinelServerInterceptor`` —
    returns None when the optional grpc dependency is absent (the image
    contract: never require an install)."""
    try:
        import grpc  # noqa: F401
    except ImportError:
        return None
    import grpc

    from sentinel_tpu.adapters.grpc_adapter import SentinelServerInterceptor

    res = DriveResult()
    vt = client.time
    interceptor = SentinelServerInterceptor(client=client)

    class _Ctx:
        def abort(self, code, details):
            raise _Aborted()

    class _Aborted(Exception):
        pass

    def inner(request, context):
        return "ok"

    base = grpc.unary_unary_rpc_method_handler(inner)
    for _step, evs in gen.events():
        for ev in evs:
            class _Details:
                method = f"/{ev.key}"
                invocation_metadata = ()

            handler = interceptor.intercept_service(lambda d: base, _Details())
            try:
                handler.unary_unary("req", _Ctx())
                _account(res, True)
            except _Aborted:
                _account(res, False)
        vt.sleep_ms(gen.spec.step_ms)
    return res
