"""`sentinel_tpu.workload` — seeded workload engine + closed-loop live
autotuner (ROADMAP item 3).

Three layers, importable independently:

* :mod:`~sentinel_tpu.workload.shapes` — pure-arithmetic traffic shapes
  (diurnal, flash crowd, Zipf churn, hot-param flood, shard skew);
* :mod:`~sentinel_tpu.workload.generator` — the seeded deterministic
  offered-event stream plus drivers for the real adapters and the
  client, and the queueing service model that turns real verdicts into
  modeled request latencies;
* :mod:`~sentinel_tpu.workload.tuner` /
  :mod:`~sentinel_tpu.workload.operating_point` — the SLO-burn-driven
  autotuner that retunes the shared ``OperatingPoint`` LIVE, guarded by
  the PR-15 instruments (expected-retrace journal, HBM ledger).
"""

from sentinel_tpu.workload.generator import (
    OfferedEvent,
    ServiceBackend,
    ServiceModel,
    TrafficGenerator,
    drive_asgi,
    drive_client,
    drive_gateway,
    drive_grpc,
    drive_streaming,
)
from sentinel_tpu.workload.operating_point import (
    BENCH_WINDOW_EXACT,
    BENCH_WINDOW_MINUTE,
    BENCH_WINDOW_MINUTE_SLACK,
    ENGINE_FIELDS,
    OperatingPoint,
    sim_default_op,
)
from sentinel_tpu.workload.shapes import (
    Constant,
    Diurnal,
    FlashCrowd,
    HotParamFlood,
    SkewedKeys,
    WorkloadSpec,
    ZipfKeys,
    flash_crowd_2x,
)
from sentinel_tpu.workload.tuner import (
    AutoTuner,
    LoopResult,
    TunerConfig,
    run_closed_loop,
    workload_slos,
)

__all__ = [
    "AutoTuner",
    "BENCH_WINDOW_EXACT",
    "BENCH_WINDOW_MINUTE",
    "BENCH_WINDOW_MINUTE_SLACK",
    "Constant",
    "Diurnal",
    "ENGINE_FIELDS",
    "FlashCrowd",
    "HotParamFlood",
    "LoopResult",
    "OfferedEvent",
    "OperatingPoint",
    "ServiceBackend",
    "ServiceModel",
    "SkewedKeys",
    "TrafficGenerator",
    "TunerConfig",
    "WorkloadSpec",
    "ZipfKeys",
    "drive_asgi",
    "drive_client",
    "drive_gateway",
    "drive_grpc",
    "drive_streaming",
    "flash_crowd_2x",
    "run_closed_loop",
    "sim_default_op",
    "workload_slos",
]
