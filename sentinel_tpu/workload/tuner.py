"""Closed-loop LIVE autotuner over an SLO-burn-rate objective.

The TUNING half of ROADMAP item 3: PR 7 built protection, PR 15 built
the instruments; this module turns those read-only instruments into an
actuator.  An ``AutoTuner`` owns a candidate grid of ``OperatingPoint``s
(seeded exploration order — decisions replay bit-identically) and walks
it with a measure → move → settle → judge loop:

* **objective** — the burn rate of one ``SloSpec`` (default the
  ``workload_latency`` spec over ``sentinel_workload_req_ms``), read
  through a real ``obs/slo.SloEngine`` on engine time.  Never raw dps:
  a point that wins throughput while burning latency budget loses.
* **HBM guardrail** — before applying a candidate the tuner projects the
  sketch-pool delta against ``obs/profile.LEDGER``'s configured
  capacity and REJECTS points that would tune into an OOM
  (``sentinel_tuner_retunes_total{outcome="rejected_hbm"}``; the
  capacity-breach counter must stay flat through every retune).
* **retrace guardrail** — every engine move goes through
  ``SentinelClient.apply_operating_point``, whose compiles run under
  ``obs/profile.expected_retrace``; a tuning session journals zero
  surprise retraces by construction (asserted by the chaos scenario).
* **fail-open** — a raising step (the ``workload.tuner.step`` failpoint
  or any internal error) rolls back to the LAST-GOOD operating point
  and touches nothing else: serving decisions continue uninterrupted,
  the failure is counted exactly
  (``sentinel_tuner_step_failures_total``).

``run_closed_loop`` wires generator + service backend + SLO engine +
tuner into the one loop bench/chaos/tests all drive.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.obs import profile as PROF
from sentinel_tpu.obs.registry import REGISTRY
from sentinel_tpu.obs.slo import CounterSum, HistogramOver, SloEngine, SloSpec
from sentinel_tpu.workload.generator import (
    ServiceBackend,
    ServiceModel,
    TrafficGenerator,
    drive_client,
)
from sentinel_tpu.workload.operating_point import OperatingPoint
from sentinel_tpu.workload.shapes import WorkloadSpec

FP_TUNER_STEP = FP.register(
    "workload.tuner.step",
    "autotuner control step (a raise fails OPEN to the last-good point)",
    FP.HIT_ACTIONS,
)

_C_STEPS = REGISTRY.counter(
    "sentinel_tuner_steps_total", "autotuner control steps taken"
)
_C_STEP_FAILURES = REGISTRY.counter(
    "sentinel_tuner_step_failures_total",
    "tuner steps that raised and failed OPEN to the last-good point",
)
_C_RETUNES: Dict[str, object] = {}
_C_RETUNES_LOCK = threading.Lock()


def _c_retunes(outcome: str):
    c = _C_RETUNES.get(outcome)
    if c is None:
        with _C_RETUNES_LOCK:
            c = _C_RETUNES.get(outcome)
            if c is None:
                c = _C_RETUNES[outcome] = REGISTRY.counter(
                    "sentinel_tuner_retunes_total",
                    "live operating-point moves, by outcome "
                    "(applied|accepted|rollback|rejected_hbm)",
                    labels={"outcome": outcome},
                )
    return c


_G_OBJ_BURN = REGISTRY.gauge(
    "sentinel_tuner_objective_burn",
    "objective SLO burn rate at the tuner's last control step",
)


def workload_slos(
    req_ms: float = 60.0,
    short_ms: int = 300,
    long_ms: int = 1_500,
    burn_thr: float = 2.0,
    budget_window_ms: int = 4_000,
) -> Tuple[SloSpec, ...]:
    """The workload plane's objectives, sized for virtual-time runs a
    few engine-seconds long (the stock ``default_slos`` windows are
    production-scale minutes/hours): modeled request latency and the
    offered-stream shed ratio, plus the PR-15 guard objectives the
    tuner must never burn — HBM capacity and sketch-accuracy eps."""
    return (
        SloSpec(
            "workload_latency",
            objective=0.95,
            latency=HistogramOver("sentinel_workload_req_ms", req_ms),
            windows=((short_ms, long_ms, burn_thr),),
            budget_window_ms=budget_window_ms,
            auto_bundle=False,
        ),
        SloSpec(
            "workload_shed",
            objective=0.95,
            bad=CounterSum(("sentinel_workload_blocked_total",)),
            total=CounterSum(
                (
                    "sentinel_workload_passed_total",
                    "sentinel_workload_blocked_total",
                )
            ),
            windows=((short_ms, long_ms, burn_thr),),
            budget_window_ms=budget_window_ms,
            auto_bundle=False,
        ),
        SloSpec(
            "hbm_capacity",
            objective=0.999,
            bad=CounterSum(("sentinel_hbm_capacity_breaches_total",)),
            total=CounterSum(("sentinel_hbm_capacity_checks_total",)),
            windows=((short_ms, long_ms, burn_thr),),
            budget_window_ms=budget_window_ms,
            auto_bundle=False,
        ),
        SloSpec(
            "sketch_eps",
            objective=0.99,
            bad=CounterSum(("sentinel_sketch_eps_violations_total",)),
            total=CounterSum(("sentinel_sketch_audit_checks_total",)),
            windows=((short_ms, long_ms, burn_thr),),
            budget_window_ms=budget_window_ms,
            auto_bundle=False,
        ),
    )


def _sketch_pool_bytes(cfg) -> int:
    """Formulaic sketch-pool HBM for a config (the ledger's sketch pool
    agrees within 10% — PR 15 acceptance), 0 when the sketch tier is
    off."""
    if not getattr(cfg, "sketch_stats", False):
        return 0
    from sentinel_tpu.ops import engine as E

    scfg = E.sketch_config(cfg)
    if cfg.sketch_salsa:
        from sentinel_tpu.sketch import salsa as SA

        return SA.hbm_bytes(scfg)
    from sentinel_tpu.ops import gsketch as GS

    return 4 * scfg.sample_count * scfg.depth * scfg.width * GS.PLANES


@dataclass(frozen=True)
class TunerConfig:
    objective: str = "workload_latency"
    settle_steps: int = 4  # control steps a point serves before judgement
    warmup_steps: int = 1  # leading settle readings discarded: completions
    # draining right after a move were queued under the PREVIOUS point,
    # and judging them would misattribute its latency to the new one
    min_improvement: float = 0.02  # relative burn drop a move must earn
    max_moves: int = 8


class AutoTuner:
    """Deterministic candidate-walk tuner; see module docstring."""

    def __init__(
        self,
        client,
        slo: SloEngine,
        op0: OperatingPoint,
        candidates: Sequence[OperatingPoint],
        seed: int = 7,
        tcfg: Optional[TunerConfig] = None,
        backend: Optional[ServiceBackend] = None,
    ):
        self.client = client
        self.slo = slo
        self.tcfg = tcfg or TunerConfig()
        self.current = op0
        self.best = op0  # last-good: rollback / fail-open target
        self.best_burn: Optional[float] = None
        self.converged = False
        self.backend = backend
        #: ordered decision journal — the bit-replay surface
        self.decisions: List[dict] = []
        # seeded exploration order (the chaos plan derivation: one odd
        # multiplier keeps adjacent seeds on distinct orders)
        cands = [c for c in candidates if c != op0]
        random.Random((int(seed) * 0x9E3779B1) & 0xFFFFFFFF).shuffle(cands)
        self._pending: List[OperatingPoint] = cands
        self._since_move = 0
        self._burn_acc = 0.0
        self._burn_n = 0
        self._moves = 0

    # -- guardrails ----------------------------------------------------------

    def _hbm_ok(self, cand: OperatingPoint) -> bool:
        snap = PROF.LEDGER.snapshot()
        cap = int(snap.get("capacity_bytes") or 0)
        if cap <= 0:
            return True
        delta = _sketch_pool_bytes(
            cand.apply_to_config(self.client.cfg)
        ) - _sketch_pool_bytes(self.client.cfg)
        return PROF.LEDGER.total_bytes() + max(0, delta) <= cap

    # -- moves ---------------------------------------------------------------

    def _journal(self, now_ms: int, action: str, op: OperatingPoint, **kw):
        self.decisions.append(
            {"now_ms": int(now_ms), "action": action, "op": op.describe(), **kw}
        )

    def _apply(self, op: OperatingPoint, now_ms: int, outcome: str) -> None:
        self.client.apply_operating_point(op, cause=f"tuner-{outcome}")
        if self.backend is not None:
            self.backend.set_op(op)
        self.current = op
        _c_retunes(outcome).inc()
        self._journal(now_ms, outcome, op)
        self._since_move = 0
        self._burn_acc = 0.0
        self._burn_n = 0

    def _explore(self, now_ms: int) -> None:
        while self._pending and self._moves < self.tcfg.max_moves:
            cand = self._pending.pop(0)
            if cand == self.current:
                continue
            if not self._hbm_ok(cand):
                _c_retunes("rejected_hbm").inc()
                self._journal(now_ms, "rejected_hbm", cand)
                continue
            self._moves += 1
            self._apply(cand, now_ms, "applied")
            return
        # grid exhausted (or move budget spent): settle on the best
        if self.current != self.best:
            self._apply(self.best, now_ms, "rollback")
        if not self.converged:
            self.converged = True
            self._journal(
                now_ms, "converged", self.best,
                burn=round(self.best_burn or 0.0, 4),
            )

    # -- the control step ----------------------------------------------------

    def step(self, now_ms: int) -> Optional[dict]:
        """One control step: judge SLO burn, settle, move.  Any raise
        (the ``workload.tuner.step`` failpoint included) fails OPEN."""
        _C_STEPS.inc()
        try:
            FP.hit(FP_TUNER_STEP)  # chaos: a raise fails this step open
            return self._step_inner(now_ms)
        except Exception:
            _C_STEP_FAILURES.inc()
            if self.current != self.best:
                try:
                    self._apply(self.best, now_ms, "rollback")
                except Exception:
                    # even the rollback failing must not surface into
                    # the serving path; the next healthy step retries
                    pass
            self._journal(now_ms, "fail_open", self.best)
            return None

    def _step_inner(self, now_ms: int) -> Optional[dict]:
        statuses = self.slo.step(now_ms)
        burn = 0.0
        for st in statuses:
            if st.name == self.tcfg.objective:
                burn = min(st.burn.values()) if st.burn else 0.0
        _G_OBJ_BURN.set(burn)
        if self.converged:
            return None
        self._since_move += 1
        if self._since_move > self.tcfg.warmup_steps:
            self._burn_acc += burn
            self._burn_n += 1
        if self._since_move < self.tcfg.settle_steps:
            return None
        avg = self._burn_acc / max(1, self._burn_n)
        if self.current == self.best:
            # measuring the incumbent (initial baseline or post-rollback)
            if self.best_burn is None or avg < self.best_burn:
                self.best_burn = avg
            self._journal(now_ms, "measured", self.current, burn=round(avg, 4))
        elif self.best_burn is not None and self.best_burn - avg > max(
            1e-9, self.tcfg.min_improvement * self.best_burn
        ):
            # strict improvement only: a tie keeps the incumbent, so a
            # flat objective can never walk the point around for free
            self.best = self.current
            self.best_burn = avg
            _c_retunes("accepted").inc()
            self._journal(now_ms, "accepted", self.current, burn=round(avg, 4))
        else:
            self._journal(now_ms, "worse", self.current, burn=round(avg, 4))
            self._apply(self.best, now_ms, "rollback")
        self._explore(now_ms)
        return self.decisions[-1] if self.decisions else None


# -- the closed loop ---------------------------------------------------------


@dataclass
class LoopResult:
    submitted: int = 0
    passed: int = 0
    blocked: int = 0
    latencies_ms: List[float] = field(default_factory=list)
    req_ms: float = 0.0  # the objective's latency threshold
    objective_burn: float = 0.0  # long-window burn at loop end
    budget_consumed: float = 0.0  # 1 - budget_remaining at loop end
    decisions: List[dict] = field(default_factory=list)
    converged_op: Optional[OperatingPoint] = None

    def p99_ms(self) -> float:
        if not self.latencies_ms:
            return 0.0
        xs = sorted(self.latencies_ms)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def bad_frac(self) -> float:
        """Whole-run SLO-bad fraction (latencies over the objective
        threshold) — the saturation-proof static-vs-converged comparison
        surface: window burns clip once the budget is gone, this
        doesn't."""
        if not self.latencies_ms:
            return 0.0
        bad = sum(1 for x in self.latencies_ms if x > self.req_ms)
        return bad / len(self.latencies_ms)


def run_closed_loop(
    client,
    spec: WorkloadSpec,
    op: OperatingPoint,
    candidates: Sequence[OperatingPoint] = (),
    tune: bool = True,
    tune_every: int = 5,
    model: Optional[ServiceModel] = None,
    tcfg: Optional[TunerConfig] = None,
    slo_specs: Optional[Tuple[SloSpec, ...]] = None,
    req_ms: float = 60.0,
) -> LoopResult:
    """Generator → real client decisions → service model → SLO engine
    [→ tuner] on the client's clock.  ``tune=False`` is the static
    control run the bench row compares against."""
    gen = TrafficGenerator(spec, start_ms=client.time.now_ms())
    svc = model or ServiceModel(step_ms=spec.step_ms)
    backend = ServiceBackend(svc, op)
    slo = SloEngine(
        specs=slo_specs or workload_slos(req_ms=req_ms), registry=REGISTRY
    )
    tuner = (
        AutoTuner(
            client,
            slo,
            op,
            candidates,
            seed=spec.seed,
            tcfg=tcfg,
            backend=backend,
        )
        if tune
        else None
    )
    slo.step(client.time.now_ms())  # anchor the burn windows pre-traffic

    def on_step(step: int, _n: int) -> None:
        if step % tune_every:
            return
        now = client.time.now_ms()
        if tuner is not None:
            tuner.step(now)
        else:
            slo.step(now)

    drive = drive_client(client, gen, backend=backend, on_step=on_step)
    final = slo.step(client.time.now_ms())
    out = LoopResult(
        submitted=drive.submitted,
        passed=drive.passed,
        blocked=drive.blocked,
        latencies_ms=drive.latencies_ms,
        req_ms=req_ms,
        decisions=list(tuner.decisions) if tuner else [],
        converged_op=tuner.best if tuner else op,
    )
    objective = (tcfg or TunerConfig()).objective
    for st in final:
        if st.name == objective:
            out.objective_burn = min(st.burn.values()) if st.burn else 0.0
            out.budget_consumed = 1.0 - st.budget_remaining
    slo.close()
    return out
