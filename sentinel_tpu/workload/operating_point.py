"""The serving operating point as ONE shared frozen dataclass.

Before this module the knobs that decide how the engine is driven —
batch size, pipeline depth, sketch window shape, `slack_frac`, audit
cadence — lived as hard-coded per-row literals in `bench.py` and as
scattered `SentinelClient` constructor arguments, so the benchmarked
point and the served point could silently drift.  `OperatingPoint` is
the single definition all three consumers share:

* **bench rows** (`bench.py` `_window_op_rate` / `workload_bench`) take
  an `OperatingPoint` instead of loose keyword literals;
* **the autotuner** (`workload/tuner.py`) explores a candidate grid of
  `OperatingPoint`s and applies the winner LIVE via
  `SentinelClient.apply_operating_point`;
* **the overload simulator preset** (`adaptive/simload.
  storm_controller_preset`) derives its queue bound from the same
  point, so the chaos scenario and the bench row can never
  desynchronize from the tuner's world.

Engine-compiled knobs (batch/sketch shape) are separated from host-only
knobs (pipeline depth, audit cadence) because applying them has very
different costs: the former require an `expected_retrace`-journaled
recompile + state migration, the latter are a plain attribute write.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Tuple

#: EngineConfig fields an OperatingPoint owns — exactly the knobs a
#: LIVE retune may change (see ops/engine.migrate_state's contract).
ENGINE_FIELDS: Tuple[str, ...] = (
    "batch_size",
    "complete_batch_size",
    "sketch_sample_count",
    "sketch_window_ms",
    "sketch_slack_frac",
)


@dataclass(frozen=True)
class OperatingPoint:
    """One serving configuration the tuner/bench/simulator agree on."""

    # engine-compiled knobs (changing any = one expected retrace)
    batch_size: int = 2048
    complete_batch_size: int = 2048
    sketch_sample_count: int = 0  # 0 inherits the second window shape
    sketch_window_ms: int = 0
    sketch_slack_frac: float = 0.05
    # host-only knobs (applied without touching the traced program)
    pipeline_depth: int = 0
    audit_period: int = 16

    @classmethod
    def from_engine_config(
        cls, cfg: Any, pipeline_depth: int = 0, audit_period: int = 16
    ) -> "OperatingPoint":
        """The point a config already runs at (identity apply)."""
        return cls(
            pipeline_depth=int(pipeline_depth),
            audit_period=int(audit_period),
            **{f: getattr(cfg, f) for f in ENGINE_FIELDS},
        )

    def engine_changes(self, cfg: Any) -> Dict[str, Any]:
        """The EngineConfig field replacements this point requires on
        top of ``cfg`` — empty when the compiled program can stay."""
        return {
            f: getattr(self, f)
            for f in ENGINE_FIELDS
            if getattr(self, f) != getattr(cfg, f)
        }

    def apply_to_config(self, cfg: Any) -> Any:
        changes = self.engine_changes(cfg)
        return dataclasses.replace(cfg, **changes) if changes else cfg

    def replace(self, **kw: Any) -> "OperatingPoint":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def describe(self) -> str:
        """Compact stable label (decision journals, bench rows)."""
        return (
            f"b{self.batch_size}/c{self.complete_batch_size}"
            f"/p{self.pipeline_depth}"
            f"/s{self.sketch_sample_count}x{self.sketch_window_ms}ms"
            f"@{self.sketch_slack_frac:g}/a{self.audit_period}"
        )


def sim_default_op() -> OperatingPoint:
    """The small-config point the overload simulator and the chaos
    scenarios drive — identity against ``small_engine_config()`` so the
    shared definition changes no seeded goldens."""
    from sentinel_tpu.core.config import small_engine_config

    return OperatingPoint.from_engine_config(small_engine_config())


#: bench.py window-compare rows (previously hard-coded literals at the
#: ``_window_op_rate`` signature): the exact-tier second-window shape
#: and the minute-scale rotation shape with/without slack.
BENCH_WINDOW_EXACT = OperatingPoint(
    batch_size=4096,
    complete_batch_size=4096,
    sketch_sample_count=10,
    sketch_window_ms=100,
    sketch_slack_frac=0.0,
)
BENCH_WINDOW_MINUTE = BENCH_WINDOW_EXACT.replace(
    sketch_sample_count=60, sketch_window_ms=1000
)
BENCH_WINDOW_MINUTE_SLACK = BENCH_WINDOW_MINUTE.replace(
    sketch_slack_frac=0.05
)
