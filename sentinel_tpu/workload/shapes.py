"""Composable, seeded traffic shapes for the workload engine.

A shape is a pure function ``rate_at(step) -> float`` (mean offered
events for that virtual-time step) plus an optional key mix override —
diurnal curves, flash crowds, adversarial hot-param floods and
shard-skewed hotspots compose by summation into one offered stream.
Randomness (arrival jitter, key draws, churn) never lives here: shapes
are ARITHMETIC, so the generator's per-shape PRNG streams (the chaos
plane's ``FaultPlan.spec_rng`` derivation) are the only entropy and two
runs at one seed replay bit-identically.

Key mixes map an event index to a concrete key: ``ZipfKeys`` draws
ranks from a truncated Zipf(alpha) over ``n_keys`` keys and CHURNS the
rank→key binding every ``churn_every_steps`` (rotating which keys are
hot — the cache-busting pattern), ``SkewedKeys`` picks from explicit
weights (shard-skewed hotspots: weight mass on one shard's keys).
"""

from __future__ import annotations

import bisect
import math
from dataclasses import dataclass, field
from typing import Optional, Tuple


# -- key mixes ---------------------------------------------------------------


@dataclass(frozen=True)
class ZipfKeys:
    """Truncated Zipf(alpha) over ``{prefix}{i}`` with rank churn: every
    ``churn_every_steps`` the rank→key binding rotates by ``churn_shift``
    so yesterday's cold keys become today's hot set."""

    n_keys: int = 64
    alpha: float = 1.1
    churn_every_steps: int = 0  # 0 = static binding
    churn_shift: int = 7
    prefix: str = "wl/key"

    def _cdf(self) -> Tuple[float, ...]:
        w = [1.0 / (i + 1) ** self.alpha for i in range(self.n_keys)]
        tot = sum(w)
        acc, out = 0.0, []
        for x in w:
            acc += x / tot
            out.append(acc)
        return tuple(out)

    def key_for(self, step: int, u: float, cdf: Tuple[float, ...]) -> str:
        rank = bisect.bisect_left(cdf, u)
        rank = min(rank, self.n_keys - 1)
        if self.churn_every_steps:
            rot = (step // self.churn_every_steps) * self.churn_shift
            rank = (rank + rot) % self.n_keys
        return f"{self.prefix}{rank}"


@dataclass(frozen=True)
class SkewedKeys:
    """Explicit (key, weight) mix — the shard-skewed hotspot: put most
    of the mass on keys one ring shard owns."""

    keys: Tuple[Tuple[str, float], ...] = (("wl/hot", 0.8), ("wl/cold", 0.2))

    def _cdf(self) -> Tuple[float, ...]:
        tot = sum(w for _k, w in self.keys) or 1.0
        acc, out = 0.0, []
        for _k, w in self.keys:
            acc += w / tot
            out.append(acc)
        return tuple(out)

    def key_for(self, step: int, u: float, cdf: Tuple[float, ...]) -> str:
        i = min(bisect.bisect_left(cdf, u), len(self.keys) - 1)
        return self.keys[i][0]


# -- rate shapes -------------------------------------------------------------


@dataclass(frozen=True)
class Constant:
    """Flat offered load."""

    rate: float = 4.0
    name: str = "constant"
    keys: Optional[object] = None  # key-mix override for this shape's events
    param: Optional[str] = None  # hot-param payload carried by events

    def rate_at(self, step: int) -> float:
        return self.rate


@dataclass(frozen=True)
class Diurnal:
    """Sinusoidal day curve: ``base * (1 + amplitude * sin)`` with the
    period in steps (virtual time makes a 'day' as short as the test
    wants)."""

    base: float = 4.0
    amplitude: float = 0.5
    period_steps: int = 200
    phase: float = 0.0
    name: str = "diurnal"
    keys: Optional[object] = None
    param: Optional[str] = None

    def rate_at(self, step: int) -> float:
        w = 2.0 * math.pi * (step / max(1, self.period_steps)) + self.phase
        return max(0.0, self.base * (1.0 + self.amplitude * math.sin(w)))


@dataclass(frozen=True)
class FlashCrowd:
    """Ramp → hold → decay spike on top of zero (compose with a
    Constant/Diurnal baseline): the 2×-sustained flash crowd is
    ``FlashCrowd(peak=base)`` over ``Constant(base)``."""

    peak: float = 8.0
    start_step: int = 50
    ramp_steps: int = 10
    hold_steps: int = 100
    decay_steps: int = 20
    name: str = "flash_crowd"
    keys: Optional[object] = None
    param: Optional[str] = None

    def rate_at(self, step: int) -> float:
        t = step - self.start_step
        if t < 0:
            return 0.0
        if t < self.ramp_steps:
            return self.peak * (t + 1) / self.ramp_steps
        t -= self.ramp_steps
        if t < self.hold_steps:
            return self.peak
        t -= self.hold_steps
        if t < self.decay_steps:
            return self.peak * (self.decay_steps - t) / self.decay_steps
        return 0.0


@dataclass(frozen=True)
class HotParamFlood:
    """Adversarial burst hammering ONE param value on one key — the
    hot-param rule's attack shape.  Events carry ``param`` so the
    drivers route them through the param-flow path."""

    rate: float = 16.0
    start_step: int = 0
    duration_steps: int = 50
    param: Optional[str] = "attacker-1"
    key: str = "wl/param-target"
    name: str = "hot_param_flood"

    @property
    def keys(self) -> object:
        return SkewedKeys(keys=((self.key, 1.0),))

    def rate_at(self, step: int) -> float:
        t = step - self.start_step
        return self.rate if 0 <= t < self.duration_steps else 0.0


@dataclass(frozen=True)
class WorkloadSpec:
    """One offered-traffic plan: shapes summed over ``steps`` virtual
    steps of ``step_ms`` each, keys drawn from ``keys`` unless a shape
    overrides, all entropy derived from ``seed`` (generator.py)."""

    seed: int = 7
    steps: int = 200
    step_ms: int = 10
    shapes: Tuple[object, ...] = field(default_factory=tuple)
    keys: object = field(default_factory=ZipfKeys)

    def with_seed(self, seed: int) -> "WorkloadSpec":
        import dataclasses

        return dataclasses.replace(self, seed=seed)


def flash_crowd_2x(
    seed: int = 7,
    base: float = 4.0,
    steps: int = 240,
    step_ms: int = 10,
    start_step: int = 60,
    keys: Optional[object] = None,
) -> WorkloadSpec:
    """The acceptance shape: sustained ``base`` with a flash crowd that
    doubles the offered load (2× sustained) for the middle third."""
    hold = max(1, steps // 3)
    return WorkloadSpec(
        seed=seed,
        steps=steps,
        step_ms=step_ms,
        shapes=(
            Constant(rate=base, name="sustained"),
            FlashCrowd(
                peak=base,
                start_step=start_step,
                ramp_steps=10,
                hold_steps=hold,
                decay_steps=10,
            ),
        ),
        keys=keys if keys is not None else ZipfKeys(n_keys=16),
    )
