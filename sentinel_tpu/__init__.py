"""sentinel_tpu — a TPU-native flow-control / traffic-shaping framework.

A from-scratch re-design of the capabilities of alibaba/Sentinel v1.8.1
(reference: /root/reference) for TPU hardware.  Where the reference guards
each call site with a per-resource lock-free slot chain (CtSph.java:43,
StatisticSlot.java:51), this framework micro-batches request events into
tensors and runs ONE fused, jit-compiled decision kernel per tick:

    {resource_id, origin, param_hash, ...}[B]  --->  verdict[B], wait_ms[B]

All sliding-window statistics (the reference's LeapArray,
slots/statistic/base/LeapArray.java:41) live as sharded ring-buffer tensors
on device; all rule checks (FlowSlot, DegradeSlot, ParamFlowSlot,
SystemSlot, AuthoritySlot) are vectorized over the batch.  Time is always
an explicit kernel input (``now_ms``) — nothing under jit reads a clock —
which makes the whole engine a pure function (the tensorized analog of the
reference's AbstractTimeBasedTest virtual-time strategy).

Public API mirrors the reference's facade (SphU.java:71 / SphO.java /
Tracer.java / ContextUtil.java:45):

    import sentinel_tpu as st

    st.init(app_name="my-app")
    st.load_flow_rules([st.FlowRule(resource="HelloWorld", count=20)])

    try:
        with st.entry("HelloWorld"):
            do_work()
    except st.BlockException:
        handle_rejection()
"""

from sentinel_tpu.core.errors import (
    AuthorityException,
    BlockException,
    DegradeException,
    FlowException,
    ParamFlowException,
    PriorityWaitException,
    SystemBlockException,
)
from sentinel_tpu.core.rules import (
    AuthorityRule,
    DegradeRule,
    FlowRule,
    ParamFlowItem,
    ParamFlowRule,
    SystemRule,
    # enums
    AUTHORITY_BLACK,
    AUTHORITY_WHITE,
    CB_STRATEGY_ERROR_COUNT,
    CB_STRATEGY_ERROR_RATIO,
    CB_STRATEGY_SLOW_REQUEST_RATIO,
    CONTROL_DEFAULT,
    CONTROL_RATE_LIMITER,
    CONTROL_WARM_UP,
    CONTROL_WARM_UP_RATE_LIMITER,
    GRADE_QPS,
    GRADE_THREAD,
    STRATEGY_CHAIN,
    STRATEGY_DIRECT,
    STRATEGY_RELATE,
)
from sentinel_tpu.core.api import (
    clear_rules,
    context,
    entry,
    get_client,
    init,
    load_authority_rules,
    load_degrade_rules,
    load_flow_rules,
    load_param_flow_rules,
    load_system_rules,
    reset,
    trace,
    entry_async,
    register_init_func,
    try_entry,
)

__version__ = "0.1.0"


def __getattr__(name):
    if name == "SentinelClient":
        from sentinel_tpu.runtime.client import SentinelClient

        return SentinelClient
    if name in ("AdaptiveConfig", "AdaptiveController"):
        # closed-loop system-adaptive protection (sentinel_tpu.adaptive);
        # lazy like SentinelClient so `import sentinel_tpu` stays light
        import sentinel_tpu.adaptive as _ad

        return getattr(_ad, name)
    raise AttributeError(name)

__all__ = [
    "AdaptiveConfig",
    "AdaptiveController",
    "AuthorityException",
    "AuthorityRule",
    "BlockException",
    "DegradeException",
    "DegradeRule",
    "FlowException",
    "FlowRule",
    "ParamFlowException",
    "ParamFlowItem",
    "ParamFlowRule",
    "PriorityWaitException",
    "SentinelClient",
    "SystemBlockException",
    "SystemRule",
    "clear_rules",
    "context",
    "entry",
    "entry_async",
    "register_init_func",
    "get_client",
    "init",
    "load_authority_rules",
    "load_degrade_rules",
    "load_flow_rules",
    "load_param_flow_rules",
    "load_system_rules",
    "reset",
    "trace",
    "try_entry",
]
