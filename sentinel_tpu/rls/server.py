"""Envoy RLS gRPC server.

Implements ``envoy.service.ratelimit.v2.RateLimitService/ShouldRateLimit``
(reference: SentinelEnvoyRlsServiceImpl.java + SentinelRlsGrpcServer.java):
each request descriptor resolves to a cluster flowId via the rule manager
and is checked through the engine-backed token service; any over-limit
descriptor makes the overall verdict OVER_LIMIT.

grpcio is present in this image but grpc_tools (stub codegen) is not, so
the service is registered through a generic handler with the protoc-built
message classes — same wire behavior as a generated servicer.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import grpc

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.rls import rls_pb2 as pb
from sentinel_tpu.rls.rules import EnvoyRlsRuleManager

SERVICE_NAME = "envoy.service.ratelimit.v2.RateLimitService"


class SentinelEnvoyRlsService:
    """The ShouldRateLimit decision logic (unary-unary)."""

    def __init__(self, token_service, rule_manager: Optional[EnvoyRlsRuleManager] = None):
        self.token_service = token_service
        self.rules = rule_manager or EnvoyRlsRuleManager(token_service)

    def should_rate_limit(self, request: pb.RateLimitRequest, context=None) -> pb.RateLimitResponse:
        hits = request.hits_addend or 1
        rsp = pb.RateLimitResponse()
        overall = pb.RateLimitResponse.OK
        for desc in request.descriptors:
            entries = [(e.key, e.value) for e in desc.entries]
            fid = self.rules.lookup_flow_id(request.domain, entries)
            status = rsp.statuses.add()
            if fid is None:
                # no rule for this descriptor → not limited (reference
                # returns OK for unmatched descriptors)
                status.code = pb.RateLimitResponse.OK
                continue
            r = self.token_service.request_token(fid, hits, False)
            if r.status in (C.STATUS_OK, C.STATUS_NO_RULE):
                # NO_RULE happens when a concurrent rule push removed the
                # flow id between lookup and check — unmatched descriptors
                # fail open, same as the fid-is-None path above
                status.code = pb.RateLimitResponse.OK
                status.limit_remaining = max(r.remaining, 0)
            else:
                status.code = pb.RateLimitResponse.OVER_LIMIT
                overall = pb.RateLimitResponse.OVER_LIMIT
        rsp.overall_code = overall
        return rsp


class SentinelRlsGrpcServer:
    """gRPC front door (SentinelRlsGrpcServer.java analog)."""

    def __init__(
        self,
        token_service,
        host: str = "0.0.0.0",
        port: int = 0,
        workers: int = 8,
        rule_manager: Optional[EnvoyRlsRuleManager] = None,
    ):
        self.service = SentinelEnvoyRlsService(token_service, rule_manager)
        self._server = grpc.server(ThreadPoolExecutor(max_workers=workers))
        handler = grpc.method_handlers_generic_handler(
            SERVICE_NAME,
            {
                "ShouldRateLimit": grpc.unary_unary_rpc_method_handler(
                    self.service.should_rate_limit,
                    request_deserializer=pb.RateLimitRequest.FromString,
                    response_serializer=pb.RateLimitResponse.SerializeToString,
                )
            },
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    @property
    def rules(self) -> EnvoyRlsRuleManager:
        return self.service.rules

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


def make_channel_stub(address: str):
    """Client-side helper: callable for ShouldRateLimit on a channel
    (tests and smoke checks; Envoy itself is the production client)."""
    channel = grpc.insecure_channel(address)
    fn = channel.unary_unary(
        f"/{SERVICE_NAME}/ShouldRateLimit",
        request_serializer=pb.RateLimitRequest.SerializeToString,
        response_deserializer=pb.RateLimitResponse.FromString,
    )
    return channel, fn
