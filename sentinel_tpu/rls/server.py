"""Envoy RLS gRPC server.

Implements ``envoy.service.ratelimit.v2.RateLimitService/ShouldRateLimit``
(reference: SentinelEnvoyRlsServiceImpl.java + SentinelRlsGrpcServer.java):
each request descriptor resolves to a cluster flowId via the rule manager
and is checked through the engine-backed token service; any over-limit
descriptor makes the overall verdict OVER_LIMIT.

grpcio is present in this image but grpc_tools (stub codegen) is not, so
the service is registered through a generic handler with the protoc-built
message classes — same wire behavior as a generated servicer.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import grpc

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.rls import rls_pb2 as pb
from sentinel_tpu.rls.rules import EnvoyRlsRuleManager
from sentinel_tpu.utils.record_log import record_log
from sentinel_tpu.utils.time_source import mono_s

SERVICE_NAME = "envoy.service.ratelimit.v2.RateLimitService"

#: rate limit for the fail-closed error log (the error counter carries
#: the rate; the log carries the traceback)
_ERROR_LOG_INTERVAL_S = 10.0
_error_log_lock = threading.Lock()
_last_error_log_s = -_ERROR_LOG_INTERVAL_S

_H_DECISION = _OBS.histogram(
    "sentinel_rls_decision_ms",
    "ShouldRateLimit request latency (descriptor resolution + token "
    "round-trips to the owning shards)",
)
_C_REQUESTS = {
    code: _OBS.counter(
        "sentinel_rls_requests_total",
        "ShouldRateLimit verdicts served by the RLS front door, by "
        "overall code (error = decision raised and was converted to "
        "OVER_LIMIT: the front door fails closed)",
        labels={"code": code},
    )
    for code in ("ok", "over_limit", "error")
}


class SentinelEnvoyRlsService:
    """The ShouldRateLimit decision logic (unary-unary).

    ``token_service`` is anything with the TokenService surface: a local
    ``DefaultTokenService`` (single token server, the embedded shape) or
    a ``ShardedTokenClient``/``ShardFleet.client`` — then each resolved
    flow id routes through the consistent-hash ring to its owning shard,
    and external Envoy traffic is governed by the fleet without linking
    the library.  Unmatched descriptors and unknown domains return OK
    (the reference's semantics); any over-limit descriptor makes the
    overall verdict OVER_LIMIT.
    """

    def __init__(self, token_service, rule_manager: Optional[EnvoyRlsRuleManager] = None):
        self.token_service = token_service
        self.rules = rule_manager or EnvoyRlsRuleManager(token_service)

    def should_rate_limit(self, request: pb.RateLimitRequest, context=None) -> pb.RateLimitResponse:
        _t = OT.t0()
        try:
            rsp = self._traced_decide(request, _t)
        except Exception:  # stlint: disable=fail-open — converted to OVER_LIMIT: an escaping exception surfaces to Envoy as UNKNOWN, and Envoy's default failure_mode admits the request unmetered — the front door must fail CLOSED instead
            global _last_error_log_s
            now = mono_s()
            if now - _last_error_log_s >= _ERROR_LOG_INTERVAL_S:
                # rate-limited: a persistently broken decision path must
                # be diagnosable, not just an error-counter blip
                with _error_log_lock:
                    if now - _last_error_log_s >= _ERROR_LOG_INTERVAL_S:
                        _last_error_log_s = now
                        record_log().exception(
                            "RLS decision failed; failing CLOSED (OVER_LIMIT)"
                        )
            _C_REQUESTS["error"].inc()
            rsp = pb.RateLimitResponse()
            rsp.overall_code = pb.RateLimitResponse.OVER_LIMIT
            return rsp
        _C_REQUESTS[
            "over_limit"
            if rsp.overall_code == pb.RateLimitResponse.OVER_LIMIT
            else "ok"
        ].inc()
        return rsp

    def _traced_decide(self, request: pb.RateLimitRequest, _t) -> pb.RateLimitResponse:
        if not _t:
            rsp = self._decide(request)
        else:
            # front-door span: mint (or adopt) a wire trace id and install
            # it as the ambient context, so every downstream cluster RPC
            # span (ClusterTokenClient._roundtrip) parents to this span —
            # the merged Perfetto dump then shows one request's
            # client → RLS → shard timeline as a single flow
            tid = OT.current_ctx()[0] or OT.new_trace_id()
            sid = OT.new_span_id()
            with OT.trace_ctx(tid, sid):
                rsp = self._decide(request)
            OT.stage(
                "rls.should_rate_limit",
                _t,
                _H_DECISION,
                trace=tid,
                attrs={
                    "span_id": sid,
                    "domain": request.domain,
                    "descriptors": len(request.descriptors),
                    "over_limit": rsp.overall_code == pb.RateLimitResponse.OVER_LIMIT,
                },
            )
        return rsp

    def _decide(self, request: pb.RateLimitRequest) -> pb.RateLimitResponse:
        hits = request.hits_addend or 1
        rsp = pb.RateLimitResponse()
        overall = pb.RateLimitResponse.OK
        # resolve every descriptor up front: a multi-descriptor request
        # against a sharded fleet then rides ONE batched token exchange
        # per owning shard (request_token_many groups by ring owner and
        # sends a protocol-v2 batch frame) instead of paying a blocking
        # round-trip per descriptor
        resolved = [
            self.rules.lookup_flow_id(
                request.domain, [(e.key, e.value) for e in desc.entries]
            )
            for desc in request.descriptors
        ]
        idxs = [i for i, fid in enumerate(resolved) if fid is not None]
        many = getattr(self.token_service, "request_token_many", None)
        results = {}
        if many is not None and len(idxs) > 1:
            batch = many([(resolved[i], hits) for i in idxs])
            results = dict(zip(idxs, batch))
        else:
            for i in idxs:
                results[i] = self.token_service.request_token(
                    resolved[i], hits, False
                )
        for i, _desc in enumerate(request.descriptors):
            status = rsp.statuses.add()
            if resolved[i] is None:
                # no rule for this descriptor → not limited (reference
                # returns OK for unmatched descriptors)
                status.code = pb.RateLimitResponse.OK
                continue
            r = results[i]
            if r.status in (C.STATUS_OK, C.STATUS_NO_RULE):
                # NO_RULE happens when a concurrent rule push removed the
                # flow id between lookup and check — unmatched descriptors
                # fail open, same as the fid-is-None path above
                status.code = pb.RateLimitResponse.OK
                status.limit_remaining = max(r.remaining, 0)
            else:
                # BLOCKED, and also FAIL/TOO_MANY from a tokenless backend:
                # the front door fails CLOSED on ambiguity (a fleet-backed
                # service already converts shard failure into a lease
                # fallback verdict before it reaches here)
                status.code = pb.RateLimitResponse.OVER_LIMIT
                overall = pb.RateLimitResponse.OVER_LIMIT
        rsp.overall_code = overall
        return rsp


class SentinelRlsGrpcServer:
    """gRPC front door (SentinelRlsGrpcServer.java analog)."""

    def __init__(
        self,
        token_service,
        host: str = "0.0.0.0",
        port: int = 0,
        workers: int = 8,
        rule_manager: Optional[EnvoyRlsRuleManager] = None,
    ):
        self.service = SentinelEnvoyRlsService(token_service, rule_manager)
        self._server = grpc.server(ThreadPoolExecutor(max_workers=workers))
        handler = grpc.method_handlers_generic_handler(
            SERVICE_NAME,
            {
                "ShouldRateLimit": grpc.unary_unary_rpc_method_handler(
                    self.service.should_rate_limit,
                    request_deserializer=pb.RateLimitRequest.FromString,
                    response_serializer=pb.RateLimitResponse.SerializeToString,
                )
            },
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    @property
    def rules(self) -> EnvoyRlsRuleManager:
        return self.service.rules

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


def make_channel_stub(address: str):
    """Client-side helper: callable for ShouldRateLimit on a channel
    (tests and smoke checks; Envoy itself is the production client)."""
    channel = grpc.insecure_channel(address)
    fn = channel.unary_unary(
        f"/{SERVICE_NAME}/ShouldRateLimit",
        request_serializer=pb.RateLimitRequest.SerializeToString,
        response_deserializer=pb.RateLimitResponse.FromString,
    )
    return channel, fn
