"""Envoy Rate Limit Service (RLS) front door.

Wire-compatible reimplementation of the reference's
sentinel-cluster-server-envoy-rls module (SURVEY.md §2.5): an Envoy proxy
configured with a gRPC rate_limit_service can point at
``SentinelRlsGrpcServer`` and get cluster-wide token decisions from the
TPU decision engine.

Load-bearing fleet mode (README "Cluster sharding & RLS front door"):
back the server with a ``ShardFleet``'s ``ShardedTokenClient``
(``cluster/shard.py``) and each descriptor's flow id routes through the
consistent-hash ring to its owning token-server shard — descriptor
resolution, ring routing, per-shard failover, and the decision span all
happen behind one ``ShouldRateLimit`` call, so external traffic is
governed without linking the library.  ``sentinel_tpu.rls.server``
imports lazily (it needs grpcio); the rule model here does not.
"""

from sentinel_tpu.rls.rules import (  # noqa: F401
    EnvoyRlsRule,
    EnvoyRlsRuleManager,
    RlsKeyValue,
    RlsResourceDescriptor,
)

__all__ = [
    "EnvoyRlsRule",
    "EnvoyRlsRuleManager",
    "RlsKeyValue",
    "RlsResourceDescriptor",
]
