"""Envoy Rate Limit Service (RLS) front door.

Wire-compatible reimplementation of the reference's
sentinel-cluster-server-envoy-rls module (SURVEY.md §2.5): an Envoy proxy
configured with a gRPC rate_limit_service can point at
``SentinelRlsGrpcServer`` and get cluster-wide token decisions from the
TPU decision engine.
"""

from sentinel_tpu.rls.rules import (  # noqa: F401
    EnvoyRlsRule,
    EnvoyRlsRuleManager,
    RlsKeyValue,
    RlsResourceDescriptor,
)

__all__ = [
    "EnvoyRlsRule",
    "EnvoyRlsRuleManager",
    "RlsKeyValue",
    "RlsResourceDescriptor",
]
