"""Envoy RLS rules: domain + descriptor key/values → cluster flow rules.

The reference converts each EnvoyRlsRule resource descriptor into a
sentinel FlowRule keyed by a generated flowId
(sentinel-cluster-server-envoy-rls/.../EnvoySentinelRuleConverter.java,
EnvoyRlsRule/EnvoyRlsRuleManager).  The identifier is the domain plus the
sorted ``key:value`` pairs, so a ShouldRateLimit descriptor maps to the
same id the rule produced.
"""

from __future__ import annotations

import threading
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.core import rules as R


@dataclass
class RlsKeyValue:
    key: str
    value: str = ""


@dataclass
class RlsResourceDescriptor:
    key_values: List[RlsKeyValue] = field(default_factory=list)
    count: float = 0.0


@dataclass
class EnvoyRlsRule:
    domain: str
    descriptors: List[RlsResourceDescriptor] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "EnvoyRlsRule":
        return cls(
            domain=d["domain"],
            descriptors=[
                RlsResourceDescriptor(
                    key_values=[
                        RlsKeyValue(kv["key"], kv.get("value", ""))
                        for kv in r.get("keyValues", [])
                    ],
                    count=float(r.get("count", 0)),
                )
                for r in d.get("descriptors", [])
            ],
        )

    def to_dict(self) -> dict:
        return {
            "domain": self.domain,
            "descriptors": [
                {
                    "keyValues": [
                        {"key": kv.key, "value": kv.value} for kv in r.key_values
                    ],
                    "count": r.count,
                }
                for r in self.descriptors
            ],
        }


def descriptor_identifier(domain: str, entries: Sequence[Tuple[str, str]]) -> str:
    """Canonical identity of (domain, descriptor): sorted key:value pairs."""
    pairs = sorted(f"{k}:{v}" for k, v in entries)
    return domain + "|" + ",".join(pairs)


def identifier_flow_id(identifier: str) -> int:
    """Deterministic positive flowId from the identifier (stable across
    processes, unlike Python's salted hash())."""
    return zlib.crc32(identifier.encode("utf-8")) + 1  # avoid 0


class EnvoyRlsRuleManager:
    """Loads EnvoyRlsRules and projects them as cluster flow rules onto a
    DefaultTokenService (namespace = domain, GLOBAL threshold)."""

    def __init__(self, token_service):
        self._svc = token_service
        self._lock = threading.Lock()
        self._rules: List[EnvoyRlsRule] = []
        self._id_by_identifier: Dict[str, int] = {}
        self._loaded_namespaces: set = set()

    def load(self, rules: List[EnvoyRlsRule]) -> None:
        with self._lock:
            self._rules = list(rules)
            # build the lookup aside and publish once: lookup_flow_id reads
            # without the lock, so it must never see a half-populated map
            id_by_identifier: Dict[str, int] = {}
            by_ns: Dict[str, List[R.FlowRule]] = {}
            for rule in rules:
                for desc in rule.descriptors:
                    ident = descriptor_identifier(
                        rule.domain, [(kv.key, kv.value) for kv in desc.key_values]
                    )
                    fid = identifier_flow_id(ident)
                    id_by_identifier[ident] = fid
                    by_ns.setdefault(rule.domain, []).append(
                        R.FlowRule(
                            resource=ident,
                            count=desc.count,
                            cluster_mode=True,
                            cluster_flow_id=fid,
                            cluster_threshold_type=1,  # GLOBAL
                        )
                    )
            # clear namespaces dropped by this push, or their old flow rules
            # stay enforced in the token service forever
            for ns in self._loaded_namespaces - set(by_ns):
                self._svc.flow_rules.load(ns, [])
            for ns, flow_rules in by_ns.items():
                self._svc.flow_rules.load(ns, flow_rules)
            self._loaded_namespaces = set(by_ns)
            self._id_by_identifier = id_by_identifier

    def get(self) -> List[EnvoyRlsRule]:
        return list(self._rules)

    def lookup_flow_id(self, domain: str, entries: Sequence[Tuple[str, str]]) -> Optional[int]:
        return self._id_by_identifier.get(descriptor_identifier(domain, entries))
