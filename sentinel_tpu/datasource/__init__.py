"""Dynamic config / property layer (SURVEY.md L4).

Push-based dynamic rules: a ``SentinelProperty`` fans values out to typed
listeners; datasources (file poll, in-memory push, external stores) feed
properties; ``RuleManager.register_property`` subscribes a rule manager so
rule updates flow  datasource → property → manager → engine recompilation
(the reference's tail at DynamicSentinelProperty.java:49 →
FlowPropertyListener.configUpdate).
"""

from sentinel_tpu.datasource.property import (
    DynamicSentinelProperty,
    NoOpSentinelProperty,
    PropertyListener,
    SentinelProperty,
    SimplePropertyListener,
)
from sentinel_tpu.datasource.base import (
    AbstractDataSource,
    AutoRefreshDataSource,
    Converter,
    FileRefreshableDataSource,
    FileWritableDataSource,
    ReadableDataSource,
    WritableDataSource,
)
from sentinel_tpu.datasource.converters import (
    json_rule_converter,
    json_rule_encoder,
)
from sentinel_tpu.datasource.redis import (
    RedisConnection,
    RedisDataSource,
    RespError,
)
from sentinel_tpu.datasource.remote import CallbackDataSource, HttpDataSource

__all__ = [
    "SentinelProperty",
    "DynamicSentinelProperty",
    "NoOpSentinelProperty",
    "PropertyListener",
    "SimplePropertyListener",
    "ReadableDataSource",
    "WritableDataSource",
    "AbstractDataSource",
    "CallbackDataSource",
    "HttpDataSource",
    "AutoRefreshDataSource",
    "FileRefreshableDataSource",
    "FileWritableDataSource",
    "Converter",
    "json_rule_converter",
    "json_rule_encoder",
    "RedisConnection",
    "RedisDataSource",
    "RespError",
]
