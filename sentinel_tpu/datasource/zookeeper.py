"""ZooKeeper datasource over a minimal native wire client.

The reference binding (sentinel-datasource-zookeeper/.../
ZookeeperDataSource.java:1) rides Curator's NodeCache: an initial getData
on the rule path plus a data watcher that re-reads on change.  No ZK
client library ships in this image, so this module speaks the ZooKeeper
jute wire protocol directly — the small subset the datasource needs:

  * session handshake (ConnectRequest/ConnectResponse)
  * getData(path, watch=true)  [op 4]
  * exists(path, watch=true)   [op 3]  — for a not-yet-created rule node
  * ping                       [op 11, xid -2]
  * watcher events             [xid -1: re-arm + re-read]

Framing: every packet is a 4-byte big-endian length prefix; ints/longs
big-endian; strings/buffers are length-prefixed (-1 = null).  A reader
thread dispatches replies by xid and fires the datasource re-read on
watch events, giving the same push semantics as the reference's
NodeCacheListener.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from sentinel_tpu.datasource.base import AbstractDataSource, Converter

OP_EXISTS = 3
OP_GET_DATA = 4
OP_PING = 11
XID_WATCHER = -1
XID_PING = -2
ERR_NONODE = -101


def _record(msg: str, *args, exc: bool = False) -> None:
    from sentinel_tpu.utils.record_log import record_log

    record_log().info(msg, *args, exc_info=exc)


class _Buf:
    """jute reader over one received frame."""

    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def i32(self) -> int:
        (v,) = struct.unpack_from(">i", self.d, self.o)
        self.o += 4
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from(">q", self.d, self.o)
        self.o += 8
        return v

    def buf(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        v = self.d[self.o : self.o + n]
        self.o += n
        return v


def _ustr(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">i", len(b)) + b


class ZkClient:
    """Single-session ZooKeeper wire client (subset; see module doc)."""

    def __init__(
        self,
        host: str,
        port: int,
        session_timeout_ms: int = 30000,
        watch_cb: Optional[Callable[[str], None]] = None,
    ):
        self.watch_cb = watch_cb
        self._sock = socket.create_connection((host, port), timeout=10.0)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._xid = 0
        self._pending: Dict[int, Tuple[threading.Event, list]] = {}
        self._plock = threading.Lock()
        self._closed = threading.Event()
        # ConnectRequest: protoVersion, lastZxidSeen, timeOut, sessionId, passwd
        req = (
            struct.pack(">iqiq", 0, 0, session_timeout_ms, 0)
            + struct.pack(">i", 16)
            + b"\x00" * 16
        )
        self._send_frame(req)
        frame = self._recv_frame()
        b = _Buf(frame)
        b.i32()  # protocolVersion
        self.negotiated_timeout = b.i32()
        self.session_id = b.i64()
        self._reader = threading.Thread(
            target=self._read_loop, name="sentinel-zk-reader", daemon=True
        )
        self._reader.start()
        self._pinger = threading.Thread(
            target=self._ping_loop, name="sentinel-zk-ping", daemon=True
        )
        self._pinger.start()

    # -- framing ------------------------------------------------------------

    def _send_frame(self, payload: bytes) -> None:
        with self._wlock:
            self._sock.sendall(struct.pack(">i", len(payload)) + payload)  # stlint: disable=blocking-under-lock — _wlock is the frame-write lock: serializing sendall is its purpose; replies ride the reader thread under _plock

    def _recv_frame(self) -> bytes:
        hdr = self._recv_n(4)
        (n,) = struct.unpack(">i", hdr)
        return self._recv_n(n)

    def _recv_n(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("zookeeper connection closed")
            out += chunk
        return out

    # -- request/reply ------------------------------------------------------

    def _call(self, op: int, payload: bytes, timeout: float = 10.0) -> _Buf:
        with self._plock:
            self._xid += 1
            xid = self._xid
            evt: Tuple[threading.Event, list] = (threading.Event(), [])
            self._pending[xid] = evt
        self._send_frame(struct.pack(">ii", xid, op) + payload)
        if not evt[0].wait(timeout):
            with self._plock:
                self._pending.pop(xid, None)
            raise TimeoutError(f"zookeeper op {op} timed out")
        frame = evt[1][0]
        b = _Buf(frame)
        b.i32()  # xid
        b.i64()  # zxid
        err = b.i32()
        return b if err == 0 else self._raise(err)

    @staticmethod
    def _raise(err: int):
        if err == ERR_NONODE:
            raise KeyError("NoNode")
        raise OSError(f"zookeeper error {err}")

    def _read_loop(self) -> None:
        try:
            while not self._closed.is_set():
                frame = self._recv_frame()
                (xid,) = struct.unpack_from(">i", frame, 0)
                if xid == XID_WATCHER:
                    b = _Buf(frame)
                    b.i32()  # xid
                    b.i64()  # zxid
                    b.i32()  # err
                    b.i32()  # event type
                    b.i32()  # state
                    path = (b.buf() or b"").decode("utf-8")
                    if self.watch_cb is not None:
                        # OFF the reader thread: the callback re-reads the
                        # node (get_data), whose reply only the reader can
                        # deliver — calling back inline would deadlock
                        threading.Thread(
                            target=self._run_watch_cb,
                            args=(path,),
                            name="sentinel-zk-watch",
                            daemon=True,
                        ).start()
                    continue
                if xid == XID_PING:
                    continue
                with self._plock:
                    evt = self._pending.pop(xid, None)
                if evt is not None:
                    evt[1].append(frame)
                    evt[0].set()
        except Exception:
            if not self._closed.is_set():
                _record("[zk] reader loop ended", exc=True)
            # unblock every waiter (they'll observe the closed connection)
            with self._plock:
                for evt, _f in list(self._pending.values()):
                    evt.set()
                self._pending.clear()

    def _run_watch_cb(self, path: str) -> None:
        try:
            self.watch_cb(path)
        except Exception:
            _record("[zk] watch callback failed", exc=True)

    def _ping_loop(self) -> None:
        interval = max(self.negotiated_timeout / 3000.0, 1.0)
        while not self._closed.wait(interval):
            try:
                self._send_frame(struct.pack(">ii", XID_PING, OP_PING))
            except Exception:
                return

    # -- ops ----------------------------------------------------------------

    def get_data(self, path: str, watch: bool = False) -> bytes:
        b = self._call(OP_GET_DATA, _ustr(path) + (b"\x01" if watch else b"\x00"))
        return b.buf() or b""

    def exists(self, path: str, watch: bool = False) -> bool:
        try:
            self._call(OP_EXISTS, _ustr(path) + (b"\x01" if watch else b"\x00"))
            return True
        except KeyError:
            return False

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass


class ZookeeperDataSource(AbstractDataSource):
    """getData + data watch on one rule node (ZookeeperDataSource.java:1,
    NodeCache semantics): initial read arms the watch; every fired watch
    re-reads AND re-arms (ZK watches are one-shot); a missing node arms an
    exists-watch and publishes when it appears."""

    def __init__(
        self,
        server_addr: str,  # host:port
        path: str,
        parser: Converter,
    ):
        if not path:
            raise ValueError("path can't be empty")
        super().__init__(parser)
        self.path = path
        host, _, port = server_addr.partition(":")
        self._zk = ZkClient(host, int(port or 2181), watch_cb=self._on_watch)
        self._refresh()

    def read_source(self) -> str:
        return self._zk.get_data(self.path, watch=True).decode("utf-8")

    def _refresh(self) -> None:
        try:
            self._property.update_value(self.load_config())
        except KeyError:
            # node absent: watch for creation instead
            self._zk.exists(self.path, watch=True)
        except Exception:
            _record("[zk-datasource] refresh failed", exc=True)

    def _on_watch(self, path: str) -> None:
        if path == self.path:
            self._refresh()

    def close(self) -> None:
        self._zk.close()
