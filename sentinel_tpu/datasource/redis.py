"""Redis push datasource — a real store binding over a real wire.

The reference's sentinel-datasource-redis (RedisDataSource.java) works
like this: read the current rules from ``ruleKey`` once at startup, then
SUBSCRIBE to ``channelKey``; every published message carries the NEW rule
payload, which feeds the property listeners (the subscriber is the push
path; the key read only serves cold start).  This module reimplements
that binding with a from-scratch minimal RESP2 client (no redis library
in this image — and none needed: the protocol subset is GET, AUTH,
SELECT, SUBSCRIBE and the push frames).

Wire format (RESP2): requests are arrays of bulk strings
(``*N\\r\\n$len\\r\\n<bytes>\\r\\n``...); replies are simple strings ``+``,
errors ``-``, integers ``:``, bulk strings ``$`` and arrays ``*``.
Subscribe pushes arrive as 3-element arrays [b"message", channel, data].

Resilience: the subscriber thread reconnects with capped exponential
backoff and re-reads ``rule_key`` after every (re)connect, so missed
publishes during an outage are healed — same recovery shape as the
reference client's connection state listener.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from sentinel_tpu.datasource.base import AbstractDataSource, Converter
from sentinel_tpu.utils.record_log import record_log


class RespError(Exception):
    """Server replied with a RESP error (-ERR ...)."""


def encode_command(*args) -> bytes:
    """RESP array-of-bulk-strings request encoding."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode("utf-8")
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class _Reader:
    """Buffered RESP reply parser over a socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def _fill(self) -> None:
        chunk = self._sock.recv(65536)
        if not chunk:
            raise ConnectionError("redis connection closed")
        self._buf += chunk

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            self._fill()
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            self._fill()
        data, self._buf = self._buf[:n], self._buf[n + 2 :]  # strip \r\n
        return data

    def read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode("utf-8")
        if kind == b"-":
            raise RespError(rest.decode("utf-8"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self.read_reply() for _ in range(n)]
        raise RespError(f"unparseable RESP type byte {kind!r}")


class RedisConnection:
    """One RESP connection: connect + optional AUTH/SELECT + commands."""

    def __init__(
        self,
        host: str,
        port: int,
        password: Optional[str] = None,
        db: int = 0,
        timeout_s: float = 3.0,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.sock.settimeout(timeout_s)
        self.reader = _Reader(self.sock)
        if password:
            self.execute("AUTH", password)
        if db:
            self.execute("SELECT", db)

    def execute(self, *args):
        self.sock.sendall(encode_command(*args))
        return self.reader.read_reply()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class RedisDataSource(AbstractDataSource):
    """Push-mode rule source bound to a redis server.

    - cold start / reconnect: ``GET rule_key`` seeds the property
    - live: ``SUBSCRIBE channel``; each message's payload IS the new rule
      content (reference publish convention, RedisDataSource.java)

    ``start()`` spawns the subscriber daemon; ``close()`` stops it.
    """

    def __init__(
        self,
        parser: Converter,
        host: str,
        port: int,
        rule_key: str,
        channel: str,
        password: Optional[str] = None,
        db: int = 0,
        reconnect_backoff_s: float = 0.2,
        max_backoff_s: float = 5.0,
    ):
        super().__init__(parser)
        self.host = host
        self.port = port
        self.rule_key = rule_key
        self.channel = channel
        self.password = password
        self.db = db
        self._backoff0 = reconnect_backoff_s
        self._max_backoff = max_backoff_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sub_conn: Optional[RedisConnection] = None
        self._connected = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    def start(self, timeout_s: float = 5.0) -> "RedisDataSource":
        self._thread = threading.Thread(
            target=self._run, name="sentinel-redis-ds", daemon=True
        )
        self._thread.start()
        self._connected.wait(timeout_s)
        return self

    def close(self) -> None:
        self._stop.set()
        conn = self._sub_conn  # snapshot: the thread's finally may None it
        if conn is not None:
            conn.close()  # unblocks the subscriber's blocking read
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def read_source(self) -> Optional[str]:
        conn = RedisConnection(self.host, self.port, self.password, self.db)
        try:
            raw = conn.execute("GET", self.rule_key)
            return raw.decode("utf-8") if raw is not None else None
        finally:
            conn.close()

    # -- subscriber loop ----------------------------------------------------

    def _push(self, source: Optional[str]) -> None:
        """Feed a payload to the property; a malformed payload is LOGGED,
        never allowed to tear down the subscription (the reference's
        datasources log converter errors and keep listening)."""
        if source is None:
            return  # key absent — keep current rules (reference null-check)
        try:
            value = self.load_config(source)
        except Exception as e:  # noqa: BLE001 — bad payload, keep old rules
            record_log().warning(
                "redis datasource %s: unparseable rule payload ignored (%s)",
                self.rule_key,
                e,
            )
            return
        self.get_property().update_value(value)

    def _run(self) -> None:
        backoff = self._backoff0
        while not self._stop.is_set():
            try:
                sub = RedisConnection(self.host, self.port, self.password, self.db)
                self._sub_conn = sub
                # seed / heal from the key, then enter push mode
                self._push(self.read_source())
                reply = sub.execute("SUBSCRIBE", self.channel)
                if not (isinstance(reply, list) and reply[0] == b"subscribe"):
                    raise RespError(f"unexpected SUBSCRIBE reply: {reply!r}")
                self._connected.set()
                backoff = self._backoff0
                # Block indefinitely between frames: a read timeout would
                # desynchronize the RESP parser mid-frame (read_reply is
                # not resumable).  close() unblocks the read by closing
                # the socket.
                sub.sock.settimeout(None)
                while not self._stop.is_set():
                    msg = sub.reader.read_reply()
                    if (
                        isinstance(msg, list)
                        and len(msg) == 3
                        and msg[0] == b"message"
                    ):
                        data = msg[2]
                        self._push(
                            data.decode("utf-8") if data is not None else None
                        )
            except Exception as e:  # noqa: BLE001 — reconnect on any failure
                if self._stop.is_set():
                    break
                record_log().warning(
                    "redis datasource %s:%s disconnected (%s); retrying in %.1fs",
                    self.host,
                    self.port,
                    e,
                    backoff,
                )
                self._stop.wait(backoff)
                backoff = min(backoff * 2, self._max_backoff)
            finally:
                conn, self._sub_conn = self._sub_conn, None
                if conn is not None:
                    conn.close()
