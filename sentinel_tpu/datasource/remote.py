"""Remote datasources — the store-specific module family, TPU-build shape.

The reference ships eight store-specific datasource modules (nacos, zk,
etcd, redis, consul, apollo, eureka, spring-cloud-config), each a thin
binding of one client library onto the same two patterns:

- POLL:  re-read the source on an interval (AutoRefreshDataSource)
- PUSH:  a store watcher calls back with the new content

This module provides both patterns store-agnostically:

- ``HttpDataSource``     — polls any HTTP(S) endpoint (config servers,
                           spring-cloud-config, consul KV's HTTP API, ...)
- ``CallbackDataSource`` — push-style: wire ANY client's watch callback to
                           ``.update(source)`` (nacos Listener, zookeeper
                           watcher, etcd watch, redis pub/sub handler)

Store clients themselves are not bundled (none are available in this
image); binding one is 5 lines on top of CallbackDataSource — see the
class docstring.
"""

from __future__ import annotations

import urllib.request
from typing import Callable, Optional

from sentinel_tpu.datasource.base import (
    AbstractDataSource,
    AutoRefreshDataSource,
    Converter,
)


class HttpDataSource(AutoRefreshDataSource[str, object]):
    """Poll an HTTP(S) URL for rule content.

    Uses ETag/Last-Modified when the server provides them (304 → no
    property push), mirroring FileRefreshableDataSource's mtime check."""

    def __init__(
        self,
        url: str,
        parser: Converter,
        refresh_ms: int = 3000,
        timeout_s: float = 3.0,
        headers: Optional[dict] = None,
    ):
        self.url = url
        self.timeout_s = timeout_s
        self.headers = dict(headers or {})
        self._etag: Optional[str] = None
        self._last_modified: Optional[str] = None
        self._not_modified = False
        super().__init__(parser, refresh_ms=refresh_ms)
        try:
            self.get_property().update_value(self.load_config())
        except Exception:  # noqa: BLE001 — initial fetch may fail; poll retries
            from sentinel_tpu.utils.record_log import record_log

            record_log().warning("HttpDataSource initial load failed: %s", url)

    def read_source(self) -> str:
        req = urllib.request.Request(self.url, headers=self.headers)
        if self._etag:
            req.add_header("If-None-Match", self._etag)
        if self._last_modified:
            req.add_header("If-Modified-Since", self._last_modified)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as rsp:
                self._etag = rsp.headers.get("ETag")
                self._last_modified = rsp.headers.get("Last-Modified")
                self._not_modified = False
                return rsp.read().decode("utf-8")
        except urllib.error.HTTPError as e:
            if e.code == 304:
                self._not_modified = True
                return ""
            raise

    def is_modified(self) -> bool:
        return True  # delegated to the conditional GET in read_source

    def refresh(self) -> bool:
        try:
            source = self.read_source()
        except Exception:  # noqa: BLE001
            self.on_refresh_failed()
            return False
        if self._not_modified:
            return False
        self.get_property().update_value(self.load_config(source))
        return True


class CallbackDataSource(AbstractDataSource):
    """Push-style datasource: an external watcher feeds ``update()``.

    Binding a real store is the same 5 lines the reference's modules are
    made of, e.g. nacos:

        ds = CallbackDataSource(json_rule_converter("flow"))
        nacos_client.add_config_watcher(data_id, group,
                                        lambda cfg: ds.update(cfg.content))
        client.flow_rules.register_property(ds.get_property())

    or redis pub/sub:

        pubsub.subscribe(**{channel: lambda m: ds.update(m["data"])})
    """

    def __init__(self, parser: Converter, initial: Optional[str] = None):
        super().__init__(parser)
        if initial is not None:
            self.update(initial)

    def read_source(self) -> str:
        raise NotImplementedError("push-style source; use update()")

    def update(self, source: str) -> None:
        """Called by the store watcher with new raw content."""
        self.get_property().update_value(self.load_config(source))
