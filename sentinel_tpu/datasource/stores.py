"""Store-specific datasource bindings over plain HTTP (stdlib only).

Thin stamps of the datasource SPI (datasource/base.py) against the wire
protocols the reference's per-store modules speak through their client
libraries:

  * NacosDataSource        — sentinel-datasource-nacos/.../NacosDataSource.java:1
                             (listener push + initial load; here the open
                             Nacos HTTP API: long-poll listener)
  * ConsulDataSource       — sentinel-datasource-consul/.../ConsulDataSource.java:37
                             (blocking KV queries keyed by X-Consul-Index)
  * ApolloDataSource       — sentinel-datasource-apollo/.../ApolloDataSource.java:1
                             (namespace config + change listener; here the
                             open Apollo HTTP notifications long-poll)
  * EurekaDataSource       — sentinel-datasource-eureka/.../EurekaDataSource.java:1
                             (AutoRefresh poll of instance metadata)
  * EtcdDataSource         — sentinel-datasource-etcd/.../EtcdDataSource.java:1
                             (initial range read + watch; here etcd's
                             JSON/gRPC-gateway: /v3/kv/range + streaming
                             /v3/watch)
  * SpringCloudConfigDataSource — sentinel-datasource-spring-cloud-config
                             (AutoRefresh poll of the config-server JSON)

Each binding feeds the shared DynamicSentinelProperty, so
``RuleManager.register_property`` wires any of them to live rule reloads.
Long-poll/watch loops run on daemon threads and degrade to retry-with-
backoff on transport errors (the reference's client libs behave the same
way); ``close()`` stops them.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.parse
import urllib.request
from hashlib import md5
from typing import List, Optional

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.datasource.base import AbstractDataSource, AutoRefreshDataSource, Converter

#: chaos failpoint: a raise inside the long-poll/watch loop exercises the
#: error-backoff path of every push-style store binding
_FP_WATCH = FP.register(
    "datasource.store.watch", "push-store long-poll/watch iteration", FP.HIT_ACTIONS
)


def _get(url: str, timeout: float, headers: Optional[dict] = None) -> bytes:
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def _record(msg: str, *args, exc: bool = False) -> None:
    from sentinel_tpu.utils.record_log import record_log

    record_log().info(msg, *args, exc_info=exc)


class _PushLoopDataSource(AbstractDataSource):
    """Shared skeleton for push-style stores: initial load + a daemon
    long-poll/watch loop with error backoff."""

    _ERROR_BACKOFF_S = 2.0

    def __init__(self, parser: Converter, name: str):
        super().__init__(parser)
        self._name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _start(self) -> None:
        self._initial_load()
        self._thread = threading.Thread(
            target=self._loop, name=f"sentinel-{self._name}-ds", daemon=True
        )
        self._thread.start()

    def _initial_load(self) -> None:
        try:
            self._property.update_value(self.load_config())
        except Exception:
            _record("[%s] initial load failed", self._name, exc=True)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                FP.hit(_FP_WATCH)
                changed = self._wait_for_change()
                if self._stop.is_set():
                    return
                if changed:
                    self._property.update_value(self.load_config())
            except Exception:
                _record("[%s] watch loop error", self._name, exc=True)
                self._stop.wait(self._ERROR_BACKOFF_S)

    def _wait_for_change(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class NacosDataSource(_PushLoopDataSource):
    """Nacos config push via the open HTTP API.

    Initial GET /nacos/v1/cs/configs, then the official long-poll listener
    (POST /nacos/v1/cs/configs/listener with ``Listening-Configs`` =
    dataId^2group^2md5[^2tenant]^1 and a Long-Pulling-Timeout): a
    non-empty response names the changed configs → re-fetch.  Same
    semantics as the reference's ConfigService listener + loadInitialConfig
    (NacosDataSource.java:1)."""

    def __init__(
        self,
        server_addr: str,  # host:port
        group_id: str,
        data_id: str,
        parser: Converter,
        tenant: str = "",
        poll_timeout_ms: int = 30000,
        http_timeout_s: float = 5.0,
    ):
        if not group_id or not data_id:
            raise ValueError(
                f"Bad argument: groupId=[{group_id}], dataId=[{data_id}]"
            )
        super().__init__(parser, "nacos")
        self.base = f"http://{server_addr}/nacos/v1/cs/configs"
        self.group_id = group_id
        self.data_id = data_id
        self.tenant = tenant
        self.poll_timeout_ms = poll_timeout_ms
        self.http_timeout_s = http_timeout_s
        self._last_md5 = ""
        self._start()

    def read_source(self) -> str:
        q = {"dataId": self.data_id, "group": self.group_id}
        if self.tenant:
            q["tenant"] = self.tenant
        raw = _get(
            self.base + "?" + urllib.parse.urlencode(q), self.http_timeout_s
        ).decode("utf-8")
        self._last_md5 = md5(raw.encode("utf-8")).hexdigest()
        return raw

    def _wait_for_change(self) -> bool:
        fields = [self.data_id, self.group_id, self._last_md5]
        if self.tenant:
            fields.append(self.tenant)
        listening = "\x02".join(fields) + "\x01"
        req = urllib.request.Request(
            self.base + "/listener",
            data=urllib.parse.urlencode(
                {"Listening-Configs": listening}
            ).encode(),
            headers={"Long-Pulling-Timeout": str(self.poll_timeout_ms)},
            method="POST",
        )
        with urllib.request.urlopen(
            req, timeout=self.poll_timeout_ms / 1000.0 + self.http_timeout_s
        ) as r:
            return bool(r.read().strip())


class ConsulDataSource(_PushLoopDataSource):
    """Consul KV with blocking queries (ConsulDataSource.java:37-66): a
    GET /v1/kv/<key>?index=<last>&wait=<n>s hangs until the key changes or
    the wait elapses; a larger X-Consul-Index means new data."""

    def __init__(
        self,
        host: str,
        port: int,
        rule_key: str,
        parser: Converter,
        watch_timeout_s: int = 60,
        http_timeout_s: float = 5.0,
    ):
        super().__init__(parser, "consul")
        self.base = f"http://{host}:{port}/v1/kv/{urllib.parse.quote(rule_key)}"
        self.watch_timeout_s = watch_timeout_s
        self.http_timeout_s = http_timeout_s
        self._last_index = 0
        self._start()

    def _fetch(self, blocking: bool):
        url = self.base
        if blocking:
            url += f"?index={self._last_index}&wait={self.watch_timeout_s}s"
        req = urllib.request.Request(url)
        timeout = (
            self.watch_timeout_s + self.http_timeout_s
            if blocking
            else self.http_timeout_s
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            idx = int(r.headers.get("X-Consul-Index", "0") or 0)
            items = json.loads(r.read().decode("utf-8"))
        value = ""
        if items:
            value = base64.b64decode(items[0].get("Value") or "").decode("utf-8")
        return idx, value

    def read_source(self) -> str:
        idx, value = self._fetch(blocking=False)
        self._last_index = max(self._last_index, idx)
        return value

    def _wait_for_change(self) -> bool:
        idx, _value = self._fetch(blocking=True)
        if idx > self._last_index:
            self._last_index = idx
            return True
        return False


class ApolloDataSource(_PushLoopDataSource):
    """Apollo namespace config with the open HTTP API: initial
    /configfiles/json/<appId>/<cluster>/<namespace>, then the
    /notifications/v2 long poll; ruleKey selects one property inside the
    namespace and defaultRuleValue fills its absence — the reference's
    ConfigChangeListener semantics (ApolloDataSource.java:1)."""

    def __init__(
        self,
        meta_server: str,  # host:port of config service
        app_id: str,
        cluster: str,
        namespace: str,
        rule_key: str,
        default_rule_value: str,
        parser: Converter,
        http_timeout_s: float = 5.0,
    ):
        if not namespace or not rule_key:
            raise ValueError("namespace and ruleKey must be non-empty")
        super().__init__(parser, "apollo")
        self.base = f"http://{meta_server}"
        self.app_id = app_id
        self.cluster = cluster
        self.namespace = namespace
        self.rule_key = rule_key
        self.default_rule_value = default_rule_value
        self.http_timeout_s = http_timeout_s
        self._notification_id = -1
        self._start()

    def read_source(self) -> str:
        url = (
            f"{self.base}/configfiles/json/{self.app_id}/{self.cluster}/"
            f"{self.namespace}"
        )
        cfg = json.loads(_get(url, self.http_timeout_s).decode("utf-8"))
        v = cfg.get(self.rule_key)
        return v if v is not None else self.default_rule_value

    def _wait_for_change(self) -> bool:
        notifications = json.dumps(
            [
                {
                    "namespaceName": self.namespace,
                    "notificationId": self._notification_id,
                }
            ]
        )
        q = urllib.parse.urlencode(
            {
                "appId": self.app_id,
                "cluster": self.cluster,
                "notifications": notifications,
            }
        )
        req = urllib.request.Request(f"{self.base}/notifications/v2?{q}")
        try:
            with urllib.request.urlopen(req, timeout=90.0) as r:
                if r.status == 304:
                    return False
                for n in json.loads(r.read().decode("utf-8")):
                    if n.get("namespaceName") == self.namespace:
                        self._notification_id = n.get(
                            "notificationId", self._notification_id
                        )
                return True
        except urllib.error.HTTPError as ex:
            if ex.code == 304:  # no change within the hold period
                return False
            raise


class EurekaDataSource(AutoRefreshDataSource):
    """Polls an instance's metadata for the rule key
    (EurekaDataSource.java:1): GET {serviceUrl}apps/<appId>/<instanceId>
    with Accept: application/json, falling through the service-url list on
    failure, every refresh_ms (reference default 10 s)."""

    def __init__(
        self,
        app_id: str,
        instance_id: str,
        service_urls: List[str],
        rule_key: str,
        parser: Converter,
        refresh_ms: int = 10000,
        http_timeout_s: float = 5.0,
    ):
        if not app_id or not instance_id or not service_urls or not rule_key:
            raise ValueError("appId/instanceId/serviceUrls/ruleKey required")
        self.app_id = app_id
        self.instance_id = instance_id
        self.service_urls = [
            u if u.endswith("/") else u + "/" for u in service_urls if u
        ]
        self.rule_key = rule_key
        self.http_timeout_s = http_timeout_s
        super().__init__(parser, refresh_ms)
        try:
            self._property.update_value(self.load_config())
        except Exception:
            _record("[eureka] initial load failed", exc=True)

    def read_source(self) -> str:
        last: Optional[Exception] = None
        for base in self.service_urls:
            url = f"{base}apps/{self.app_id}/{self.instance_id}"
            try:
                body = _get(
                    url, self.http_timeout_s, {"Accept": "application/json"}
                )
                inst = json.loads(body.decode("utf-8"))["instance"]
                meta = inst.get("metadata") or {}
                return meta.get(self.rule_key) or ""
            except Exception as ex:  # next replica (reference fallthrough)
                last = ex
        raise last if last else RuntimeError("no eureka service url")


class EtcdDataSource(_PushLoopDataSource):
    """etcd v3 over the JSON/gRPC-gateway (EtcdDataSource.java:1): initial
    POST /v3/kv/range for the key, then a streaming POST /v3/watch whose
    chunked response emits one JSON object per watch event."""

    def __init__(
        self,
        host: str,
        port: int,
        rule_key: str,
        parser: Converter,
        http_timeout_s: float = 5.0,
    ):
        super().__init__(parser, "etcd")
        self.base = f"http://{host}:{port}"
        self.rule_key = rule_key
        self.http_timeout_s = http_timeout_s
        self._start()

    @staticmethod
    def _b64(s: str) -> str:
        return base64.b64encode(s.encode("utf-8")).decode("ascii")

    def read_source(self) -> str:
        req = urllib.request.Request(
            f"{self.base}/v3/kv/range",
            data=json.dumps({"key": self._b64(self.rule_key)}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.http_timeout_s) as r:
            body = json.loads(r.read().decode("utf-8"))
        kvs = body.get("kvs") or []
        if not kvs:
            return ""
        return base64.b64decode(kvs[0].get("value") or "").decode("utf-8")

    def _wait_for_change(self) -> bool:
        payload = json.dumps(
            {"create_request": {"key": self._b64(self.rule_key)}}
        ).encode()
        req = urllib.request.Request(
            f"{self.base}/v3/watch",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        # streaming read: each line is one watch response; the created
        # handshake has no events, real change notifications do
        with urllib.request.urlopen(req, timeout=3600.0) as r:
            for raw in r:
                if self._stop.is_set():
                    return False
                line = raw.strip()
                if not line:
                    continue
                msg = json.loads(line.decode("utf-8"))
                result = msg.get("result") or msg
                if result.get("events"):
                    return True
        return False


class SpringCloudConfigDataSource(AutoRefreshDataSource):
    """Polls a Spring Cloud Config server's JSON endpoint
    ({server}/{app}/{profile}[/{label}]) and extracts ``rule_key`` from
    the highest-precedence property source — the datasource half of
    sentinel-datasource-spring-cloud-config (which additionally needs a
    bus/refresh event the reference wires through Spring; polling gives
    the same eventual behavior without the Spring runtime)."""

    def __init__(
        self,
        server: str,  # host:port
        app: str,
        profile: str,
        rule_key: str,
        parser: Converter,
        label: str = "",
        refresh_ms: int = 10000,
        http_timeout_s: float = 5.0,
    ):
        self.url = f"http://{server}/{app}/{profile}" + (
            f"/{label}" if label else ""
        )
        self.rule_key = rule_key
        self.http_timeout_s = http_timeout_s
        super().__init__(parser, refresh_ms)
        try:
            self._property.update_value(self.load_config())
        except Exception:
            _record("[spring-cloud-config] initial load failed", exc=True)

    def read_source(self) -> str:
        env = json.loads(_get(self.url, self.http_timeout_s).decode("utf-8"))
        for src in env.get("propertySources") or []:
            v = (src.get("source") or {}).get(self.rule_key)
            if v is not None:
                return v
        return ""
