"""Datasource SPI: readable/writable config sources feeding properties.

Reference surface (sentinel-datasource-extension):
  * ReadableDataSource.java:28 — loadConfig():36 / readSource():44 / getProperty()
  * WritableDataSource.java:24 — write(value)
  * AbstractDataSource holds a DynamicSentinelProperty + a Converter
  * AutoRefreshDataSource polls readSource on a daemon timer (default 3 s),
    guarded by an ``is_modified`` hook
  * FileRefreshableDataSource checks file mtime; first load happens in the
    constructor; oversized files are refused (MAX_SIZE 4 MiB)
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Generic, Optional, TypeVar

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.datasource.property import DynamicSentinelProperty, SentinelProperty

S = TypeVar("S")
T = TypeVar("T")

#: Converter<S, T> (datasource/Converter.java): parse source payload → config.
Converter = Callable[[S], T]

MAX_FILE_SIZE = 4 * 1024 * 1024
DEFAULT_REFRESH_MS = 3000

#: chaos failpoints: a raise on ``refresh.read`` rides the poll loop's
#: existing catch (rules stay, on_refresh_failed re-arms); ``file.read``
#: strikes inside read_source so first loads degrade too
_FP_REFRESH = FP.register(
    "datasource.refresh.read", "auto-refresh poll iteration", FP.HIT_ACTIONS
)
_FP_FILE_READ = FP.register(
    "datasource.file.read", "rule file read", FP.HIT_ACTIONS
)


class ReadableDataSource(Generic[S, T]):
    def load_config(self) -> T:
        raise NotImplementedError

    def read_source(self) -> S:
        raise NotImplementedError

    def get_property(self) -> SentinelProperty[T]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class WritableDataSource(Generic[T]):
    """WritableDataSource.java:24 — persistence sink for ``setRules``."""

    def write(self, value: T) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class AbstractDataSource(ReadableDataSource[S, T]):
    def __init__(self, parser: Converter[S, T]):
        if parser is None:
            raise ValueError("parser can't be None")
        self.parser = parser
        self._property: DynamicSentinelProperty[T] = DynamicSentinelProperty()

    def load_config(self, source: Optional[S] = None) -> T:
        if source is None:
            source = self.read_source()
        return self.parser(source)

    def get_property(self) -> SentinelProperty[T]:
        return self._property


class AutoRefreshDataSource(AbstractDataSource[S, T]):
    """Polling datasource (AutoRefreshDataSource.java:32-80)."""

    def __init__(self, parser: Converter[S, T], refresh_ms: int = DEFAULT_REFRESH_MS):
        super().__init__(parser)
        if refresh_ms <= 0:
            raise ValueError("refresh_ms must be > 0, got %s" % refresh_ms)
        self.refresh_ms = refresh_ms
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="sentinel-datasource-auto-refresh", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.refresh_ms / 1000.0):
            self.refresh()

    def refresh(self) -> bool:
        """One poll iteration; exposed for deterministic tests."""
        try:
            FP.hit(_FP_REFRESH)
            if not self.is_modified():
                return False
            new_value = self.load_config()
            return self._property.update_value(new_value)
        except Exception:
            from sentinel_tpu.utils.record_log import record_log

            record_log().info("[AutoRefreshDataSource] loadConfig exception", exc_info=True)
            self.on_refresh_failed()
            return False

    def on_refresh_failed(self) -> None:
        """Hook: a modified source failed to read/parse; sources that consume
        their modification marker in ``is_modified`` must re-arm it here so
        the next poll retries instead of dropping the update."""

    def is_modified(self) -> bool:
        return True

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=1.0)


class FileRefreshableDataSource(AutoRefreshDataSource[str, T]):
    """File poller keyed on mtime (FileRefreshableDataSource.java:40-150)."""

    def __init__(
        self,
        path: str,
        parser: Converter[str, T],
        refresh_ms: int = DEFAULT_REFRESH_MS,
        max_size: int = MAX_FILE_SIZE,
        encoding: str = "utf-8",
    ):
        if os.path.isdir(path):
            raise ValueError("File can't be a directory: %s" % path)
        self.path = path
        self.max_size = max_size
        self.encoding = encoding
        self._last_modified = os.path.getmtime(path) if os.path.exists(path) else 0.0
        super().__init__(parser, refresh_ms)
        self._first_load()

    def _first_load(self) -> None:
        try:
            self._property.update_value(self.load_config())
        except Exception:
            from sentinel_tpu.utils.record_log import record_log

            record_log().info("[FileRefreshableDataSource] first load failed", exc_info=True)
            self.on_refresh_failed()  # re-arm mtime so the poll loop retries

    def read_source(self) -> str:
        FP.hit(_FP_FILE_READ)
        size = os.path.getsize(self.path)
        if size > self.max_size:
            raise ValueError(
                "%s file size=%d is bigger than max=%d, can't read" % (self.path, size, self.max_size)
            )
        with open(self.path, "r", encoding=self.encoding) as f:
            return f.read()

    def is_modified(self) -> bool:
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            return False
        if mtime != self._last_modified:
            self._last_modified = mtime
            return True
        return False

    def on_refresh_failed(self) -> None:
        # a half-written file consumed the mtime marker; re-arm so the next
        # poll re-reads the (by then complete) file
        self._last_modified = -1.0


class FileWritableDataSource(WritableDataSource[T]):
    """Writes encoded rules back to a file (FileWritableDataSource.java)."""

    def __init__(self, path: str, encoder: Callable[[T], str], encoding: str = "utf-8"):
        if not path:
            raise ValueError("path can't be empty")
        self.path = path
        self.encoder = encoder
        self.encoding = encoding
        self._lock = threading.Lock()

    def write(self, value: T) -> None:
        with self._lock:
            payload = self.encoder(value)
            with open(self.path, "w", encoding=self.encoding) as f:
                f.write(payload)
