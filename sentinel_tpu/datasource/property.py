"""SentinelProperty: push-style typed config values.

Reference semantics (property/SentinelProperty.java:31,
DynamicSentinelProperty.java:24):
  * ``add_listener`` immediately replays the current value (config_load);
  * ``update_value`` no-ops when the value is unchanged, otherwise fans out
    config_update to every listener;
  * listeners are typed callbacks owned by rule managers.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")


class PropertyListener(Generic[T]):
    """Listener interface (property/PropertyListener.java:23)."""

    def config_update(self, value: T) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def config_load(self, value: T) -> None:
        # default: initial load behaves like an update
        self.config_update(value)


class SimplePropertyListener(PropertyListener[T]):
    """Adapts a plain callable to the listener interface."""

    def __init__(self, fn: Callable[[T], None]):
        self._fn = fn

    def config_update(self, value: T) -> None:
        self._fn(value)


class SentinelProperty(Generic[T]):
    """Interface type (property/SentinelProperty.java:31)."""

    def add_listener(self, listener: PropertyListener[T]) -> None:
        raise NotImplementedError

    def remove_listener(self, listener: PropertyListener[T]) -> None:
        raise NotImplementedError

    def update_value(self, value: T) -> bool:
        raise NotImplementedError


class DynamicSentinelProperty(SentinelProperty[T]):
    # A single RLock covers both list mutation and listener fan-out: the
    # add_listener replay and update_value fan-out are serialized so a
    # subscriber can never see a newer value overwritten by a stale replay
    # (a race the reference actually has; RLock so listeners may reenter).
    def __init__(self, value: Optional[T] = None):
        self._listeners: List[PropertyListener[T]] = []
        self._value: Optional[T] = value
        self._lock = threading.RLock()

    @property
    def value(self) -> Optional[T]:
        """Current value (read-side peek for dashboards/tests; the
        reference keeps this package-private but the need is the same)."""
        return self._value

    def add_listener(self, listener: PropertyListener[T]) -> None:
        with self._lock:
            self._listeners.append(listener)
            listener.config_load(self._value)

    def remove_listener(self, listener: PropertyListener[T]) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def update_value(self, value: T) -> bool:
        with self._lock:
            if value == self._value:
                return False  # DynamicSentinelProperty.java:52 skip-unchanged
            self._value = value
            for l in list(self._listeners):
                l.config_update(value)
        return True

    def get_value(self) -> Optional[T]:
        return self._value

    def close(self) -> None:
        with self._lock:
            self._listeners.clear()


class NoOpSentinelProperty(SentinelProperty[T]):
    """Discard-all property (property/NoOpSentinelProperty.java)."""

    def add_listener(self, listener: PropertyListener[T]) -> None:
        pass

    def remove_listener(self, listener: PropertyListener[T]) -> None:
        pass

    def update_value(self, value: T) -> bool:
        return False
