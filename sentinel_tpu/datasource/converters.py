"""Standard converters: JSON payloads ↔ typed rule lists.

The analog of the fastjson converters used throughout the reference demos
(e.g. sentinel-demo-dynamic-file-rule's ``Converter<String, List<FlowRule>>``).
"""

from __future__ import annotations

import json
from typing import Callable, List

from sentinel_tpu.core import rules as R


def json_rule_converter(kind: str) -> Callable[[str], list]:
    """Parser for a JSON array of rules of the given kind
    ("flow" | "degrade" | "system" | "authority" | "param-flow")."""

    def parse(source: str) -> list:
        if not source or not source.strip():
            return []
        return R.rules_from_json_list(kind, json.loads(source))

    return parse


def json_rule_encoder(rules: list) -> str:
    return json.dumps(R.rules_to_json_list(rules), indent=2)
