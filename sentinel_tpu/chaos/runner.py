"""Chaos scenario harness: drive real clients under seeded fault plans.

Each built-in scenario assembles REAL product objects — a sync-mode
``SentinelClient`` on virtual time, and where the scenario calls for it a
localhost ``ClusterTokenServer`` / ``ClusterTokenClient`` pair or a
``RemoteShard`` against that server's RES_CHECK path — arms a
``FaultPlan`` derived from the run seed, drives deterministic traffic,
and evaluates its invariant set (``chaos/invariants.py``).

Determinism contract: a scenario's reported ``injected`` counts are a
pure function of its seed.  Schedules are hit-index or ``max_fires``
gated on sites whose hit order the scenario controls (one round-trip per
request, one resolve per tick); sites with timing-dependent hit counts
(reader-thread recv, TCP segmentation) carry only ``max_fires``-pinned
specs.  The CLI's ``--check-determinism`` mode runs everything twice and
diffs the counts.

Scenarios (the acceptance set):

  rpc_error_burst     token RPC send failures + latency bursts against a
                      live server; STATUS_FAIL only where injected
  cluster_partition   cluster-mode client loses the token server, enters
                      degraded local enforcement, heals, exits
  resolver_exception  verdict readback raises; ticks fail CLOSED instead
                      of stranding futures
  seg_overflow_storm  fail-closed segment-capacity overflow + live
                      seg_u grow-and-swap under injected resize delay
  datasource_flap     rule-file refresh loop faults; rules hold, then
                      the post-heal update applies; a second window
                      faults the timeline metric-log writes, which fail
                      OPEN (decisions untouched, failures counted)
  shard_reconnect     mid-window shard partition: answered chunks stay
                      resolved, unanswered degrade, no replay
  shard_failover      fleet shard kill/partition/rejoin: only the dead
                      shard's flows fail over to the bounded-slack lease
                      fallback, per-shard hysteresis pairs up
  overload_storm      flash crowd at 2× backend capacity: the adaptive
                      ladder climbs and sheds (p99 bounded, goodput
                      held) then recovers to NORMAL; the controller-OFF
                      control run demonstrably queue-collapses
  hotset_promote_fail sketch-tier promotion faults: ruled tail resources
                      stay sketched with stats failing OPEN and
                      tail-rule verdicts failing CLOSED; a clean load
                      heals and enforces exactly; a second window proves
                      the profiling plane (shadow audit + deep capture)
                      fails OPEN with exact counter accounting
  explain_fail_open   explain-section decode corrupt/raise: provenance
                      drops and is counted, while the verdict stream is
                      bit-identical to an unfaulted control run — the
                      provenance plane is strictly observational
  tuner_fail_open     workload autotuner faults: a quiet closed loop
                      retunes the operating point live (expected
                      retraces only), then raising tuner steps fail
                      OPEN to the last-good point and dropped generator
                      emissions are counted exactly
"""

from __future__ import annotations

import json
import os
import tempfile
import time as _time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.chaos.invariants import (
    MetricsDelta,
    ScenarioContext,
    Verdict,
    evaluate,
)
from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec
from sentinel_tpu.utils.time_source import mono_s


@dataclass
class ScenarioResult:
    name: str
    seed: int
    ok: bool
    injected: Dict[str, int]
    verdicts: List[Verdict]
    duration_s: float
    notes: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "ok": self.ok,
            "injected": dict(sorted(self.injected.items())),
            "invariants": [
                {"name": v.name, "ok": v.ok, "detail": v.detail}
                for v in self.verdicts
            ],
            "duration_s": round(self.duration_s, 3),
            "notes": self.notes,
        }


class _Session:
    """Accumulates injected/hit counts over one or more armed windows —
    scenarios that must observe quiet phases (hit counting) around a
    fault window arm several plans in sequence."""

    def __init__(self):
        self.injected: Dict[str, int] = {}
        self.hits: Dict[str, int] = {}

    @contextmanager
    def window(self, plan: FaultPlan):
        st = FP.arm(plan)
        try:
            yield st
        finally:
            FP.disarm()
            for k, v in st.injected().items():
                self.injected[k] = self.injected.get(k, 0) + v
            for k, v in st.hit_counts().items():
                self.hits[k] = self.hits.get(k, 0) + v


# -- builders ----------------------------------------------------------------


def _make_client(**kw):
    """Sync-mode SentinelClient on the small config + fresh virtual time
    (the deterministic test shape); caller stops it."""
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime.client import SentinelClient
    from sentinel_tpu.utils.time_source import VirtualTimeSource

    kw.setdefault("cfg", small_engine_config())
    kw.setdefault("time_source", VirtualTimeSource(start_ms=1_000))
    kw.setdefault("mode", "sync")
    c = SentinelClient(**kw)
    c.start()
    return c


def _make_token_server(flow_count: float = 3.0, flow_id: int = 101):
    """Decision client + DefaultTokenService + localhost TCP server."""
    from sentinel_tpu.cluster.server import ClusterTokenServer
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.core import rules as R

    decision = _make_client()
    svc = DefaultTokenService(decision)
    svc.flow_rules.load(
        "default",
        [
            R.FlowRule(
                resource=f"res-{flow_id}",
                count=flow_count,
                cluster_mode=True,
                cluster_flow_id=flow_id,
            )
        ],
    )
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0)
    server.start()
    # warm the decision engine's first-tick XLA compile on a throwaway
    # resource BEFORE any scenario traffic: the compile takes seconds and
    # would otherwise race RPC timeouts, turning scheduled fault indices
    # into timing lotteries
    decision.registry.resource_id("chaos/warm")
    f = decision.submit_acquire("chaos/warm")
    if f is not None:
        f.result(timeout=120.0)
    return decision, svc, server


def _drain_entries(client, resource: str, n: int) -> Dict[str, int]:
    """n blocking entries; returns {"passed": .., "blocked": ..} with every
    passing entry exited immediately (no leaked concurrency)."""
    passed = blocked = 0
    for _ in range(n):
        e = client.try_entry(resource)
        if e is not None:
            e.exit()
            passed += 1
        else:
            blocked += 1
    return {"passed": passed, "blocked": blocked}


# -- scenarios ---------------------------------------------------------------


def _scn_rpc_error_burst(seed: int) -> ScenarioResult:
    """Token RPC against a live server under a send-failure burst plus
    injected latency: failed round-trips surface as STATUS_FAIL (never
    OK), every request resolves, failure kinds are labeled.  After the
    armed window the scenario loses the server entirely and drives one
    cluster-mode entry so the runtime's degrade path fires — asserting
    the flight recorder (obs/flight.py) captured a post-mortem bundle
    whose journal holds both the injected failpoint fires and the
    degrade-enter transition."""
    from sentinel_tpu.cluster import constants as C
    from sentinel_tpu.cluster.client import ClusterTokenClient
    from sentinel_tpu.cluster.state import ClusterStateManager
    from sentinel_tpu.core import rules as R
    from sentinel_tpu.obs.flight import FLIGHT

    t0 = mono_s()
    decision, svc, server = _make_token_server(flow_count=3.0)
    tok = ClusterTokenClient("127.0.0.1", server.port, timeout_ms=3000)
    tok.reconnect_interval_s = 0.0  # reconnect on every attempt (chaos pace)
    tok.start()
    metrics = MetricsDelta()
    session = _Session()
    n = 12
    burst = (2, 2)  # send-site hit indices [2, 4) raise
    plan = FaultPlan(
        name="rpc_error_burst",
        seed=seed,
        faults=[
            FaultSpec(
                "cluster.rpc.send", "raise",
                burst_start=burst[0], burst_len=burst[1], exc="OSError",
            ),
            FaultSpec(
                "cluster.rpc.send", "delay",
                every_nth=5, delay_ms=2.0, max_fires=2,
            ),
        ],
    )
    flight_detail = "bundle not captured"
    flight_ok = False
    sm = None
    try:
        with session.window(plan):
            results = [tok.request_token(101) for _ in range(n)]
        # -- black-box phase (outside the armed window: injected counts
        # stay a pure function of the seed).  Kill the server, point the
        # decision client at the dead port in cluster mode, and drive one
        # entry: request_token fails -> degrade-to-local -> the flight
        # recorder triggers a cluster-degrade-enter bundle whose journal
        # already holds this run's failpoint.fire events.
        tok.close()
        server.stop()
        sm = ClusterStateManager()
        sm.set_to_client("127.0.0.1", server.port)
        sm.token_service().reconnect_interval_s = 0.0
        decision.set_cluster(sm)
        decision.flow_rules.load(
            [
                R.FlowRule(
                    resource="chaos/flight",
                    count=100.0,
                    cluster_mode=True,
                    cluster_flow_id=424242,
                    cluster_fallback_to_local=True,
                )
            ]
        )
        FLIGHT.reset_rate_limit()  # a prior scenario's bundle must not mask ours
        e = decision.try_entry("chaos/flight")
        if e is not None:
            e.exit()
        b = FLIGHT.last_bundle()
        if b is not None and b["reason"] == "cluster-degrade-enter":
            kinds = {ev["kind"] for ev in b["journal"]}
            flight_ok = "failpoint.fire" in kinds and "cluster.degrade.enter" in kinds
            flight_detail = f"reason={b['reason']} journal_kinds={sorted(kinds)}"
        elif b is not None:
            flight_detail = f"unexpected bundle reason {b['reason']!r}"
    finally:
        # restore FIRST (even when the black-box phase raised): pair the
        # transition and zero the process-global degrade gauge so the
        # degrade-hysteresis invariant of LATER scenarios stays clean
        try:
            decision._exit_cluster_degraded()
        except Exception:  # noqa: BLE001 — cleanup must reach the stops below
            pass
        tok.close()
        if sm is not None:
            sm.stop()
        server.stop()
        decision.stop()

    counts = {"requests": n, "ok": 0, "blocked": 0, "failed": 0, "other": 0}
    degraded_passes = 0
    for i, r in enumerate(results):
        if r.status == C.STATUS_OK:
            counts["ok"] += 1
            if burst[0] <= i < burst[0] + burst[1]:
                degraded_passes += 1  # an injected failure must not grant
        elif r.status == C.STATUS_BLOCKED:
            counts["blocked"] += 1
        elif r.status == C.STATUS_FAIL:
            counts["failed"] += 1
        else:
            counts["other"] += 1
    ctx = ScenarioContext(
        metrics=metrics,
        client=decision,
        submitted=n,
        passed=counts["ok"],
        blocked=counts["blocked"],
        degraded=counts["failed"] + counts["other"],
        degraded_passes=degraded_passes,
        injected=session.injected,
        expect_injected={
            "cluster.rpc.send:raise": burst[1],
            "cluster.rpc.send:delay": 2,
        },
        extra={
            "token_counts": counts,
            "expect_token_failures": burst[1],
            "expect_metric_deltas": {
                'sentinel_cluster_rpc_failures_total{kind="send"}': burst[1],
            },
        },
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "token-conservation",
            "no-degraded-pass",
            "metric-deltas",
            "pipeline-drained",
            "injected-as-planned",
        ],
        ctx,
    )
    verdicts.append(Verdict("flight-bundle-captured", flight_ok, flight_detail))
    return _result("rpc_error_burst", seed, session, verdicts, t0)


def _scn_cluster_partition(seed: int) -> ScenarioResult:
    """A cluster-mode SentinelClient loses its token server mid-traffic:
    it must degrade to local enforcement of fallback-enabled rules (one
    enter), hold the cooldown, and exit on the first healthy probe."""
    from sentinel_tpu.cluster.state import ClusterStateManager
    from sentinel_tpu.core import rules as R

    t0 = mono_s()
    decision, svc, server = _make_token_server(flow_count=100.0)
    sm = ClusterStateManager()
    # generous RPC timeout: the scenario injects failures explicitly and
    # must never pick up an accidental timeout on a loaded CI box
    sm.client_config.request_timeout_ms = 5000
    sm.set_to_client("127.0.0.1", server.port)
    sm.token_service().reconnect_interval_s = 0.0
    main = _make_client()
    main.set_cluster(sm)
    # cooldown far beyond the scenario's span: the degraded phase NEVER
    # probes on its own; the heal step expires the cooldown explicitly so
    # the probe lands on a deterministic entry (no wall-clock sleep race)
    main.cluster_retry_interval_s = 300.0
    main.flow_rules.load(
        [
            R.FlowRule(
                resource="res-101",
                count=2.0,  # local-fallback budget while degraded
                cluster_mode=True,
                cluster_flow_id=101,
                cluster_fallback_to_local=True,
            )
        ]
    )
    metrics = MetricsDelta()
    session = _Session()
    # healthy phase drives exactly 3 send-site hits, so the raise lands
    # on hit 3 — the first partition-phase round-trip
    plan = FaultPlan(
        name="cluster_partition",
        seed=seed,
        faults=[
            FaultSpec(
                "cluster.rpc.send", "raise",
                burst_start=3, burst_len=1, max_fires=1, exc="ConnectionResetError",
            )
        ],
    )
    totals = {"passed": 0, "blocked": 0}
    try:
        with session.window(plan):
            for phase_n in (3, 1, 3):  # healthy, partition hit, degraded local
                got = _drain_entries(main, "res-101", phase_n)
                totals["passed"] += got["passed"]
                totals["blocked"] += got["blocked"]
            # heal: expire the (mono_s-based) cooldown so the very next
            # entry probes the live server and must exit degraded
            with main._cluster_lock:
                main._cluster_degraded_until = 0.0
            got = _drain_entries(main, "res-101", 1)
            totals["passed"] += got["passed"]
            totals["blocked"] += got["blocked"]
    finally:
        main.stop()
        sm.stop()
        server.stop()
        decision.stop()

    ctx = ScenarioContext(
        metrics=metrics,
        client=main,
        submitted=8,
        passed=totals["passed"],
        blocked=totals["blocked"],
        injected=session.injected,
        expect_injected={"cluster.rpc.send:raise": 1},
        extra={
            "expect_degrade_enters": 1,
            "expect_metric_deltas": {
                'sentinel_cluster_rpc_failures_total{kind="send"}': 1,
                'sentinel_cluster_rpc_failures_total{kind="connect"}': 0,
                'sentinel_cluster_rpc_failures_total{kind="timeout"}': 0,
            },
        },
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "degrade-hysteresis",
            "metric-deltas",
            "pipeline-drained",
            "injected-as-planned",
        ],
        ctx,
    )
    return _result("cluster_partition", seed, session, verdicts, t0)


def _scn_resolver_exception(seed: int) -> ScenarioResult:
    """Verdict readback raises inside the resolve path — and, on other
    ticks, the fused packed-wire readback comes back CORRUPTED: both
    failure shapes must fail the affected ticks CLOSED (system block)
    with no stranded futures and no hung pipeline — the _fail_tick
    contract.  The corrupt ticks additionally must be DETECTED by the
    wire checksum (sentinel_packed_decode_failures_total), never fanned
    out as garbage verdicts."""
    from sentinel_tpu.core import errors as ERR

    t0 = mono_s()
    client = _make_client()
    resource = "chaos/resolver"
    client.registry.resource_id(resource)
    # prime one tick outside the plan so XLA compile cost and the warmup
    # resolve don't shift the armed hit indices
    f = client.submit_acquire(resource)
    if f is not None:
        f.result(timeout=60.0)
    metrics = MetricsDelta()
    session = _Session()
    n, nth, fires = 12, 3, 3
    # the packed decoder's hit counter advances only on ticks the raise
    # fault lets reach it (the raise fires FIRST in _resolve_tick_inner):
    # raise hits ticks 3/6/9, so decode sees ticks 1,2,4,5,7,8,10,11,12
    # and every_nth=4 corrupts decode-hits 4 and 8 — ticks 5 and 11.
    # Seed-pure: both schedules are counter-driven, not probabilistic.
    corrupt_fires = 2
    plan = FaultPlan(
        name="resolver_exception",
        seed=seed,
        faults=[
            FaultSpec(
                "runtime.resolve.readback", "raise",
                every_nth=nth, max_fires=fires, exc="RuntimeError",
            ),
            FaultSpec(
                "transport.packed.decode", "corrupt",
                every_nth=4, max_fires=corrupt_fires,
            ),
        ],
    )
    futures = []
    try:
        with session.window(plan):
            for _ in range(n):
                futures.append(client.submit_acquire(resource))
            results = [f.result(timeout=60.0) for f in futures]
    finally:
        client.stop()
    passed = sum(1 for v, _w in results if v in (ERR.PASS, ERR.PASS_WAIT))
    blocked = len(results) - passed
    ctx = ScenarioContext(
        metrics=metrics,
        client=client,
        submitted=n,
        passed=passed,
        blocked=blocked,
        futures=futures,
        injected=session.injected,
        expect_injected={
            "runtime.resolve.readback:raise": fires,
            "transport.packed.decode:corrupt": corrupt_fires,
        },
        extra={
            "expect_metric_deltas": {
                # every raise AND every detected corruption fails its tick
                # closed through the same _resolve_tick handler...
                "sentinel_resolve_failures_total": fires + corrupt_fires,
                # ...but only the corruptions are wire-checksum rejections
                "sentinel_packed_decode_failures_total": corrupt_fires,
            },
        },
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "no-stranded-futures",
            "metric-deltas",
            "pipeline-drained",
            "injected-as-planned",
        ],
        ctx,
    )
    if blocked != fires + corrupt_fires:
        verdicts.append(
            Verdict(
                "fail-closed-count",
                False,
                f"blocked={blocked}, expected exactly the "
                f"{fires + corrupt_fires} injected ticks",
            )
        )
    return _result("resolver_exception", seed, session, verdicts, t0)


def _scn_seg_overflow_storm(seed: int) -> ScenarioResult:
    """Fail-closed segment-capacity overflow: a storm of distinct keys
    exceeds seg_u while the FIRST grow-and-swap attempt is made to fail
    (injected raise) — overflow items must fail CLOSED and be counted,
    serving must continue on the old capacity, and the next storm's
    retry resize must succeed and stop the drops.  Runs the fused/
    segment engine in interpret mode — the runner executes it under
    jax.disable_jit (see run_scenario)."""
    import numpy as np

    from sentinel_tpu.core import errors as ERR
    from sentinel_tpu.core.config import small_engine_config

    t0 = mono_s()
    cfg = small_engine_config(
        max_resources=256,  # room for 64 distinct storm keys + reserved rows
        max_nodes=512,
        use_mxu_tables=True,
        fused_effects=True,
        seg_effects=True,
        seg_fallback=False,
        seg_u=16,
        batch_size=64,
        complete_batch_size=64,
    )
    client = _make_client(cfg=cfg, entry_timeout_s=120.0)
    rids = np.asarray(
        [client.registry.resource_id(f"chaos/seg{i:02d}") for i in range(64)],
        np.int32,
    )
    metrics = MetricsDelta()
    session = _Session()
    # first resize attempt dies mid-compile; the storm's overflow then
    # drops fail-closed on the undersized engine.  The NEXT overflow
    # retries the resize (only delayed this time) and recovers.
    plan = FaultPlan(
        name="seg_overflow_storm",
        seed=seed,
        faults=[
            FaultSpec(
                "runtime.seg.resize", "raise",
                burst_start=0, burst_len=1, exc="RuntimeError",
            ),
            FaultSpec("runtime.seg.resize", "delay", delay_ms=1.0),
        ],
    )
    counts = {"passed": 0, "blocked": 0}
    storm2 = {"passed": 0, "blocked": 0}
    try:
        with session.window(plan):
            for storm, acc in ((0, counts), (1, storm2)):
                v, _w = client.check_batch_ids(rids, timeout_s=120.0)
                acc["passed"] += int((v == ERR.PASS).sum()) + int(
                    (v == ERR.PASS_WAIT).sum()
                )
                acc["blocked"] += int(
                    ((v != ERR.PASS) & (v != ERR.PASS_WAIT)).sum()
                )
    finally:
        client.stop()
    ctx = ScenarioContext(
        metrics=metrics,
        client=client,
        submitted=128,
        passed=counts["passed"] + storm2["passed"],
        blocked=counts["blocked"] + storm2["blocked"],
        injected=session.injected,
        expect_injected={
            "runtime.seg.resize:raise": 1,
            "runtime.seg.resize:delay": 2,
        },
        extra={
            "expect_seg_drops": True,
            "expect_metric_deltas": {"sentinel_seg_resizes_total": 2},
        },
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "seg-drops-counted",
            "metric-deltas",
            "pipeline-drained",
            "injected-as-planned",
        ],
        ctx,
    )
    if storm2["blocked"]:
        verdicts.append(
            Verdict(
                "post-resize-capacity",
                False,
                f"{storm2['blocked']} drops AFTER the seg_u grow-and-swap",
            )
        )
    return _result("seg_overflow_storm", seed, session, verdicts, t0)


def _scn_datasource_flap(seed: int) -> ScenarioResult:
    """The rule-file refresh loop faults for a burst: the loaded rule set
    must hold (enforcement unchanged), and the first healthy refresh must
    apply the update that accumulated during the flap.  A second fault
    window then breaks the TIMELINE metric-log's disk writes
    (``datasource.metriclog.write``): the timeline fails OPEN — entry
    verdicts are untouched, every failed flush is counted in
    ``sentinel_timeline_write_failures_total``, and the injected counts
    stay a pure function of the seed (flushes fire on virtual-time
    second boundaries the scenario controls)."""
    import json as _json

    from sentinel_tpu.core import rules as R
    from sentinel_tpu.datasource.base import FileRefreshableDataSource

    t0 = mono_s()
    tl_dir = tempfile.mkdtemp(prefix="sentinel_chaos_timeline_")
    client = _make_client(timeline_log=True, timeline_dir=tl_dir)
    vt = client.time
    resource = "chaos/ds"

    def parser(s):
        return [R.FlowRule(resource=resource, count=float(_json.loads(s)["count"]))]

    fd, path = tempfile.mkstemp(prefix="sentinel_chaos_rules_", suffix=".json")
    os.close(fd)
    ds = None
    metrics = MetricsDelta()
    session = _Session()
    plan = FaultPlan(
        name="datasource_flap",
        seed=seed,
        faults=[
            FaultSpec(
                "datasource.refresh.read", "raise",
                burst_start=0, burst_len=3, exc="OSError",
            )
        ],
    )
    totals = {"passed": 0, "blocked": 0}
    extra = {}
    try:
        with open(path, "w") as f:
            f.write('{"count": 2}')
        # refresh_ms is huge: the daemon poll never fires; the scenario
        # calls refresh() itself so hit indices are exact
        ds = FileRefreshableDataSource(path, parser, refresh_ms=3_600_000)
        client.flow_rules.register_property(ds.get_property())
        with session.window(plan):
            got = _drain_entries(client, resource, 4)  # limit 2 -> 2/2
            totals["passed"] += got["passed"]
            totals["blocked"] += got["blocked"]
            with open(path, "w") as f:
                f.write('{"count": 5}')
            for _ in range(3):  # faulted refreshes: rules must hold
                ds.refresh()
            intact = [r.count for r in client.flow_rules.get()] == [2.0]
            vt.advance(1100)
            got = _drain_entries(client, resource, 4)
            intact = intact and got == {"passed": 2, "blocked": 2}
            extra["rules_intact_during_fault"] = intact
            totals["passed"] += got["passed"]
            totals["blocked"] += got["blocked"]
            ds.refresh()  # healed: the count-5 update applies
            extra["rules_updated_after_heal"] = [
                r.count for r in client.flow_rules.get()
            ] == [5.0]
            vt.advance(1100)
            got = _drain_entries(client, resource, 6)  # limit 5 -> 5/1
            totals["passed"] += got["passed"]
            totals["blocked"] += got["blocked"]
        # phase 2: timeline metric-log disk writes fail — the timeline
        # must fail OPEN.  Each virtual-second advance makes the next
        # tick flush exactly one completed second of rows, so the site's
        # hit order (and therefore the injected count) is seed-pure.
        plan_tl = FaultPlan(
            name="datasource_flap_timeline",
            seed=seed + 1,
            faults=[
                FaultSpec(
                    "datasource.metriclog.write", "raise",
                    burst_start=0, burst_len=2, exc="OSError",
                )
            ],
        )
        with session.window(plan_tl):
            for _ in range(2):  # two flushes, both injected to fail
                vt.advance(1100)
                got = _drain_entries(client, resource, 6)  # limit 5 -> 5/1
                extra["timeline_fall_open_decisions"] = (
                    extra.get("timeline_fall_open_decisions", True)
                    and got == {"passed": 5, "blocked": 1}
                )
                totals["passed"] += got["passed"]
                totals["blocked"] += got["blocked"]
    finally:
        if ds is not None:
            ds.close()
        os.unlink(path)
        client.stop()
        import shutil

        shutil.rmtree(tl_dir, ignore_errors=True)
    extra["expect_metric_deltas"] = {
        "sentinel_timeline_write_failures_total": 2,
    }
    ctx = ScenarioContext(
        metrics=metrics,
        client=client,
        submitted=26,
        passed=totals["passed"],
        blocked=totals["blocked"],
        injected=session.injected,
        expect_injected={
            "datasource.refresh.read:raise": 3,
            "datasource.metriclog.write:raise": 2,
        },
        extra=extra,
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "rules-intact",
            "pipeline-drained",
            "injected-as-planned",
            "metric-deltas",
        ],
        ctx,
    )
    verdicts.append(
        Verdict(
            "timeline-fails-open",
            bool(extra.get("timeline_fall_open_decisions")),
            "entry verdicts must not change while metric-log writes fail",
        )
    )
    return _result("datasource_flap", seed, session, verdicts, t0)


def _scn_shard_reconnect(seed: int) -> ScenarioResult:
    """Mid-window shard partition: with chunks pipelined, the transport
    dies between dispatch and reply.  Answered chunks keep their remote
    verdicts, written-but-unanswered chunks degrade to the fallback, the
    shard host never sees a chunk twice, and a later batch reconnects."""
    from sentinel_tpu.parallel.remote_shard import RemoteShard

    t0 = mono_s()
    decision, svc, server = _make_token_server(flow_count=100.0)
    fallback = _make_client()
    shard = RemoteShard(
        "127.0.0.1",
        server.port,
        timeout_s=2.0,
        fallback=fallback,
        retry_interval_s=0.1,
    )
    shard.CHUNK = 4
    names = [f"chaos/shard{i}" for i in range(12)]
    metrics = MetricsDelta()
    session = _Session()
    observe = FaultPlan(name="observe", seed=seed, faults=[])
    partition = FaultPlan(
        name="partition",
        seed=seed,
        faults=[FaultSpec("parallel.shard.recv", "drop", max_fires=1)],
    )
    results = {}
    server_hits = 0

    def _await_server_chunks(st, want: int):
        # the server processes written chunks asynchronously (worker
        # pool); the count converges — only its final value is asserted
        deadline = mono_s() + 10.0
        while st.hit_counts().get("cluster.server.process", 0) < want:
            if mono_s() > deadline:
                break
            _time.sleep(0.01)
        return st.hit_counts().get("cluster.server.process", 0)

    try:
        with session.window(observe) as st:
            results["a"] = shard.check_batch(names)  # 3 chunks answered
            server_hits += _await_server_chunks(st, 3)
        with session.window(partition) as st:
            # chunks dispatched, then the first reply read is dropped ->
            # peer-closed -> all in-flight chunks forfeited, no replay
            results["b"] = shard.check_batch(names)
            server_hits += _await_server_chunks(st, 3)
        _time.sleep(0.15)  # past retry_interval_s: the shard may reconnect
        with session.window(observe) as st:
            results["c"] = shard.check_batch(names[:4])  # 1 chunk, remote again
            server_hits += _await_server_chunks(st, 1)
    finally:
        shard.close()
        fallback.stop()
        server.stop()
        decision.stop()

    from sentinel_tpu.core import errors as ERR

    submitted = sum(len(v) for v in results.values())
    passed = sum(
        1
        for out in results.values()
        for v, _w in out
        if v in (ERR.PASS, ERR.PASS_WAIT)
    )
    ctx = ScenarioContext(
        metrics=metrics,
        client=fallback,
        submitted=submitted,
        passed=passed,
        blocked=submitted - passed,
        injected=session.injected,
        expect_injected={"parallel.shard.recv:drop": 1},
        extra={
            "chunks_written": 7,  # 3 + 3 + 1
            "server_chunks_processed": server_hits,
            "expect_metric_deltas": {
                "sentinel_shard_chunks_total": 4,
                "sentinel_shard_chunks_degraded_total": 3,
            },
        },
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "no-chunk-replay",
            "metric-deltas",
            "pipeline-drained",
            "injected-as-planned",
        ],
        ctx,
    )
    return _result("shard_reconnect", seed, session, verdicts, t0)


def _scn_shard_failover(seed: int) -> ScenarioResult:
    """Shard-kill / partition / rejoin against a real 2-shard fleet
    (cluster/shard.py), under protocol-v2 LEASE-FIRST admission: after
    the first remote decision bootstraps the standing lease, healthy
    repeats admit locally with zero RPCs, so the injected route failure
    is delivered through a param-token request (param budgets never
    lease, every one routes — the hit index stays a pure function of
    the seed).  One shard partitions; its flows drain the bounded-slack
    lease (local admits, then metered fallback) and fail CLOSED at
    exhaustion while the other shard is untouched; an injected
    ``cluster.lease.refresh_async`` raise drops exactly one
    ahead-of-exhaustion top-up (the lease keeps draining, the next
    trigger refills); a REAL kill + rejoin exercises the same protocol
    over an actual dead socket.  Token conservation: every local admit
    and fallback pass debits a lease the owner granted out of the
    global budget beforehand."""
    from sentinel_tpu.cluster import constants as CC
    from sentinel_tpu.cluster.shard import ShardFleet
    from sentinel_tpu.core import rules as R

    t0 = mono_s()
    decisions = []

    def factory():
        c = _make_client()
        decisions.append(c)
        return c

    fleet = ShardFleet(
        factory,
        n_shards=2,
        lease_slack=0.5,
        retry_interval_s=300.0,  # heal is explicit, never a wall-clock race
        lease_ttl_ms=600_000,
        timeout_ms=5000,
        reconnect_interval_s=0.0,
    )
    # one flow per shard, found through the ring itself so the scenario
    # never hardcodes placement; big budget => healthy phases always pass
    fid_a = next(f for f in range(101, 500) if fleet.client.owner_of(f) == "shard-0")
    fid_b = next(f for f in range(101, 500) if fleet.client.owner_of(f) == "shard-1")
    fleet.load_flow_rules(
        "default",
        [
            R.FlowRule(
                resource=f"res-{fid}",
                count=100.0,
                cluster_mode=True,
                cluster_flow_id=fid,
                cluster_threshold_type=1,
            )
            for fid in (fid_a, fid_b)
        ],
    )
    metrics = MetricsDelta()
    session = _Session()
    # lease-first leaves exactly 2 route hits in the healthy phase (one
    # bootstrap decision per shard — repeats admit locally), so the
    # param-token partition probe is route hit 2.  The refresh_async
    # raise fires on that site's FIRST hit: the drain below crosses the
    # refresh threshold (remaining <= 50%) once at used=25.
    plan = FaultPlan(
        name="shard_failover",
        seed=seed,
        faults=[
            FaultSpec(
                "cluster.shard.route", "raise",
                burst_start=2, burst_len=1, max_fires=1, exc="ConnectionResetError",
            ),
            FaultSpec(
                "cluster.lease.refresh_async", "raise",
                max_fires=1, exc="RuntimeError",
            ),
        ],
    )
    counts = {"requests": 0, "ok": 0, "blocked": 0, "failed": 0, "other": 0}

    def drive(fid, n=1):
        for _ in range(n):
            r = fleet.client.request_token(fid)
            counts["requests"] += 1
            if r.status == CC.STATUS_OK:
                counts["ok"] += 1
            elif r.status == CC.STATUS_BLOCKED:
                counts["blocked"] += 1
            elif r.status == CC.STATUS_FAIL:
                counts["failed"] += 1
            else:
                counts["other"] += 1

    sh_a = fleet.client._shards["shard-0"]
    sh_b = fleet.client._shards["shard-1"]
    try:
        with session.window(plan):
            drive(fid_a, 2)          # route hit 0 + lease grant 50; repeat = local admit
            drive(fid_b, 2)          # route hit 1 + lease grant 50; repeat = local admit
            # param budgets never lease -> always route: hit 2 raises
            r = fleet.client.request_param_token(fid_a, 1, ["chaos"])
            counts["requests"] += 1
            counts["blocked" if r.status == CC.STATUS_BLOCKED else "other"] += 1
            failover_one_window = sh_a.degraded_active  # within ONE hysteresis window
            drive(fid_a, 3)          # degraded: metered lease-fallback passes, no route hits
            drive(fid_b, 2)          # other shard untouched: local admits, no route hits
            with sh_a.lock:          # heal: expire the cooldown explicitly
                sh_a.degraded_until = 0.0
            drive(fid_a, 1)          # probe (route hit 3) -> healthy -> exit degraded
            healed = not sh_a.degraded_active
            # drain fid_b toward the refresh threshold: used 3 -> 25
            # triggers top-up #1 (the injected raise eats it: lease
            # keeps draining), used 26 triggers top-up #2, which
            # refills inline (armed => deterministic) to granted=50
            drive(fid_b, 23)
        # -- real-kill phase (outside the armed window: injected counts
        # stay a pure function of the seed).  shard-1's server dies for
        # real; lease-first keeps its flow passing LOCALLY for exactly
        # the refilled slack (50), then fail-CLOSED; shard-0's flow is
        # untouched; rejoin on the ORIGINAL port + explicit cooldown
        # expiry brings it back.
        fleet.kill("shard-1")
        _time.sleep(0.2)  # let the client's reader observe the close
        drive(fid_b, 50)             # exactly-slack local admits against the dead owner
        drive(fid_b, 1)              # spent -> remote -> dead socket -> degraded, fail closed
        killed_over = sh_b.degraded_active
        drive(fid_b, 1)              # degraded + spent lease: still fail closed
        drive(fid_a, 1)              # shard-0 untouched: local admit
        fleet.rejoin("shard-1")
        with sh_b.lock:
            sh_b.degraded_until = 0.0
        drive(fid_b, 1)              # probe the rejoined server -> exit
        rejoined = not sh_b.degraded_active
        # quiesce the background refresher (disarmed kill-phase admits
        # may have queued async top-ups against the dead socket)
        fleet.client.flush_lease_refresh(5.0)
    finally:
        fleet.stop()
        for c in decisions:
            c.stop()

    lease_cap = 50  # ceil(100 * lease_slack); passes beyond it would be unmetered
    fallback_passes = int(
        metrics.delta('sentinel_shard_fallback_total{shard="shard-0",verdict="pass"}')
        + metrics.delta('sentinel_shard_fallback_total{shard="shard-1",verdict="pass"}')
    )
    local_admits = int(
        metrics.delta('sentinel_lease_local_admits_total{shard="shard-0"}')
        + metrics.delta('sentinel_lease_local_admits_total{shard="shard-1"}')
    )
    ctx = ScenarioContext(
        metrics=metrics,
        client=decisions[0],
        submitted=counts["requests"],
        passed=counts["ok"],
        blocked=counts["blocked"],
        degraded=counts["failed"] + counts["other"],
        # local admits + fallback passes both spend lease units: beyond
        # 2 × (cap + one top-up refill) they would be unmetered grants
        degraded_passes=max(fallback_passes + local_admits - 2 * lease_cap - 26, 0),
        injected=session.injected,
        expect_injected={
            "cluster.shard.route:raise": 1,
            "cluster.lease.refresh_async:raise": 1,
        },
        extra={
            "token_counts": counts,
            "expect_token_failures": 0,
            "expect_shard_transitions": {"shard-0": (1, 1), "shard-1": (1, 1)},
            "expect_metric_deltas": {
                'sentinel_shard_fallback_total{shard="shard-0",verdict="pass"}': 3,
                'sentinel_shard_fallback_total{shard="shard-0",verdict="block"}': 1,
                'sentinel_shard_fallback_total{shard="shard-1",verdict="pass"}': 0,
                'sentinel_shard_fallback_total{shard="shard-1",verdict="block"}': 2,
                'sentinel_shard_lease_tokens_total{shard="shard-0"}': lease_cap,
                # bootstrap grant (50) + the surviving top-up (26)
                'sentinel_shard_lease_tokens_total{shard="shard-1"}': lease_cap + 26,
                'sentinel_lease_local_admits_total{shard="shard-0"}': 2,
                # 1 healthy + 2 untouched + 23 drain + 50 exactly-slack
                'sentinel_lease_local_admits_total{shard="shard-1"}': 76,
            },
        },
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "token-conservation",
            "no-degraded-pass",
            "shard-degrade-hysteresis",
            "metric-deltas",
            "pipeline-drained",
            "injected-as-planned",
        ],
        ctx,
    )
    for nm, ok in (
        ("failover-within-one-window", failover_one_window),
        ("healed-on-first-probe", healed),
        ("real-kill-failover", killed_over),
        ("rejoin-restores-remote", rejoined),
    ):
        verdicts.append(Verdict(nm, ok, "" if ok else "expected transition missing"))
    return _result("shard_failover", seed, session, verdicts, t0)


def _scn_overload_storm(seed: int) -> ScenarioResult:
    """Flash crowd at 2× backend capacity against the adaptive plane
    (adaptive/simload.py — a real sync client on virtual time over a
    fixed-capacity FIFO backend):

    * controller ON: the degrade ladder climbs rung by rung, excess
      admissions shed CLOSED, storm p99 stays bounded (< 10× healthy),
      goodput holds ≥ 50% of healthy, and recovery walks the ladder
      back to NORMAL — every transition monotone and journaled in the
      flight recorder;
    * controller OFF: the identical offered schedule demonstrably
      queue-collapses (p99 ≥ 10× healthy).

    A seeded ``runtime.client.admit`` raise-burst rides along: chaos on
    the admission check itself must shed CLOSED, never admit."""
    import sentinel_tpu.runtime.client  # noqa: F401 — registers the admit/watchdog failpoints before the plan validates
    from sentinel_tpu.adaptive.degrade import NORMAL
    from sentinel_tpu.adaptive.simload import (
        run_overload_sim,
        storm_controller_preset,
    )
    from sentinel_tpu.obs.flight import FLIGHT

    t0 = mono_s()
    metrics = MetricsDelta()
    session = _Session()
    fires = 3
    plan = FaultPlan(
        name="overload_storm",
        seed=seed,
        faults=[
            FaultSpec(
                "runtime.client.admit", "raise",
                every_nth=50, max_fires=fires, exc="RuntimeError",
            )
        ],
    )
    # SLO burn-rate phase (obs/slo.py): a shed-ratio objective anchored
    # BEFORE the storm must page on the storm's registry deltas and land
    # an auto-captured flight bundle.  Evaluation is registry reads only
    # — it crosses no failpoint site, so injected counts stay seed-pure.
    from sentinel_tpu.obs.slo import CounterSum, SloEngine, SloSpec

    slo_spec = SloSpec(
        "shed_ratio",
        objective=0.999,  # ≤0.1% shed budget: the 2× storm must page
        bad=CounterSum(("sentinel_shed_total",)),
        total=CounterSum(
            ("sentinel_shed_total", "sentinel_device_verdicts_total")
        ),
    )
    slo = SloEngine(specs=(slo_spec,))
    slo.step(0)  # pre-storm anchor snapshot
    seq0 = FLIGHT.recorded_total()
    with session.window(plan):
        # the preset is shared with bench.adaptive_overload_bench so the
        # gated experiment and the BENCH_r0N numbers stay one experiment
        on = run_overload_sim(
            adaptive=True, adaptive_cfg=storm_controller_preset()
        )
    FLIGHT.reset_rate_limit()  # pin bundle capture (prior scenarios may
    # have triggered within the min-interval window)
    slo_status = slo.step(6_000_000)[0]
    slo_bundle = FLIGHT.last_bundle()
    slo.close()
    off = run_overload_sim(adaptive=False)
    journal = [
        e
        for e in FLIGHT.events()
        if e["seq"] >= seq0 and e["kind"] == "adaptive.ladder"
    ]
    ctx = ScenarioContext(
        metrics=metrics,
        submitted=on.submitted,
        passed=on.passed,
        blocked=on.blocked,
        injected=session.injected,
        expect_injected={"runtime.client.admit:raise": fires},
        extra={
            "ladder_transitions": on.ladder_transitions,
            "expect_ladder_climb": True,
            "goodput_floor": on.goodput_floor,
        },
    )
    verdicts = evaluate(
        ["verdict-accounting", "ladder-monotone", "injected-as-planned"],
        ctx,
    )
    checks = [
        (
            "p99-bounded-on",
            on.p99_storm_ms <= 10 * max(on.p99_healthy_ms, 1.0),
            f"storm p99 {on.p99_storm_ms:.0f}ms vs healthy "
            f"{on.p99_healthy_ms:.0f}ms",
        ),
        (
            "goodput-held-on",
            on.goodput_storm >= 0.5 * on.goodput_healthy,
            f"storm {on.goodput_storm:.2f}/step vs healthy "
            f"{on.goodput_healthy:.2f}/step",
        ),
        (
            "queue-collapse-off",
            off.p99_storm_ms >= 10 * max(off.p99_healthy_ms, 1.0),
            f"controller OFF storm p99 {off.p99_storm_ms:.0f}ms vs healthy "
            f"{off.p99_healthy_ms:.0f}ms — no collapse means the storm "
            "proves nothing",
        ),
        (
            "ladder-recovered",
            on.final_level == NORMAL,
            f"final level {on.final_level}",
        ),
        (
            "ladder-journaled",
            len(journal) == len(on.ladder_transitions)
            and len(journal) > 0,
            f"{len(journal)} flight events vs "
            f"{len(on.ladder_transitions)} transitions",
        ),
        (
            "slo-burn-alert-fired",
            slo_status.fired and slo_status.alerting,
            f"shed-ratio burn {max(slo_status.burn.values(), default=0.0):.1f}"
            f" never crossed the page thresholds",
        ),
        (
            "slo-bundle-captured",
            slo_bundle is not None
            and slo_bundle.get("reason") == "slo-burn-shed_ratio"
            and "slo" in (slo_bundle.get("providers") or {})
            and any(
                e["kind"] == "slo.alert" and e["seq"] >= seq0
                for e in FLIGHT.events()
            ),
            "no auto-captured slo-burn bundle with an slo provider section",
        ),
    ]
    for nm, ok, detail in checks:
        verdicts.append(Verdict(nm, bool(ok), "" if ok else detail))
    return _result("overload_storm", seed, session, verdicts, t0)


def _scn_hotset_promote_fail(seed: int) -> ScenarioResult:
    """Hot-set promotion failures (``runtime.hotset.promote`` raises):
    the ruled tail resources must stay sketched with stats failing OPEN
    (the sketch keeps observing them) and tail-rule verdicts failing
    CLOSED (the CMS threshold tables keep blocking).  After the armed
    window — all traffic is appended AFTER it, keeping injected counts a
    pure function of the seed (one promotion attempt per ruled tail
    resource in the load) — a clean rule load proves promotion heals and
    the healed resource enforces exactly.

    A second armed window exercises the profiling plane's failpoints
    (obs/profile.py): ``sketch.audit.shadow`` raising on every shadow
    tick must fail OPEN into ``sentinel_sketch_audit_failures_total``
    with EXACT seed-pure counts (no check/underestimate/eps counter
    moves), and ``obs.profile.capture`` raising must return an error
    payload with the tracer's enabled state restored; both heal on the
    first un-armed call."""
    import numpy as np

    from sentinel_tpu.core import rules as R
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.obs import profile as PROF
    from sentinel_tpu.obs import trace as OT

    t0 = mono_s()
    # tiny exact space (1-row promotion reserve) + sketch tail; the
    # manager's own promote loop is parked far above any scenario volume
    # so every runtime.hotset.promote hit comes from the rule loads
    client = _make_client(
        cfg=small_engine_config(
            max_resources=8, max_nodes=16, sketch_stats=True,
            sketch_width=256, hotset_promote_qps=1.0e9,
        )
    )
    vt = client.time
    metrics = MetricsDelta()
    session = _Session()
    totals = {"passed": 0, "blocked": 0}
    extra = {}
    try:
        # exhaust organic exact rows; two ruled + one heal resource intern
        # as sketch ids
        i = 0
        while not client.registry.is_sketch_id(
            client.registry.resource_id(f"burn-{i}")
        ):
            i += 1
        for n in ("tail-a", "tail-b"):
            assert client.registry.is_sketch_id(client.registry.resource_id(n))
        plan = FaultPlan(
            name="hotset_promote_fail",
            seed=seed,
            faults=[
                FaultSpec(
                    "runtime.hotset.promote", "raise",
                    burst_start=0, burst_len=2, exc="RuntimeError",
                )
            ],
        )
        with session.window(plan):
            # the ONLY armed-site traffic: one promotion attempt per
            # ruled tail resource, in load order — both injected to fail
            client.flow_rules.load(
                [
                    R.FlowRule(resource="tail-a", count=2.0),
                    R.FlowRule(resource="tail-b", count=2.0),
                ]
            )
        still_tail = all(
            client.registry.is_sketch_id(client.registry.peek_resource_id(n))
            for n in ("tail-a", "tail-b")
        )
        extra["stayed_sketched"] = still_tail
        # appended after the window: verdicts fail CLOSED (tail tables
        # enforce the un-promoted rules) ...
        closed = True
        for n in ("tail-a", "tail-b"):
            got = _drain_entries(client, n, 6)
            totals["passed"] += got["passed"]
            totals["blocked"] += got["blocked"]
            closed = closed and 1 <= got["passed"] <= 2
        extra["tail_verdicts_closed"] = closed
        # ... and stats fail OPEN (the sketch kept observing them)
        extra["stats_open"] = all(
            client.stats.resource(n)["passQps"] >= 1 for n in ("tail-a", "tail-b")
        )
        # heal: a CLEAN reload retries promotion — the first rule in load
        # order claims the one reserve row and enforces EXACTLY; the
        # other stays on its conservative tail fallback
        client.flow_rules.load(
            [
                R.FlowRule(resource="tail-a", count=2.0),
                R.FlowRule(resource="tail-b", count=2.0),
            ]
        )
        healed = not client.registry.is_sketch_id(
            client.registry.peek_resource_id("tail-a")
        ) and client.registry.is_sketch_id(
            client.registry.peek_resource_id("tail-b")
        )
        vt.advance(1_100)
        got = _drain_entries(client, "tail-a", 4)
        totals["passed"] += got["passed"]
        totals["blocked"] += got["blocked"]
        extra["heal_promotes_and_enforces"] = healed and got == {
            "passed": 2,
            "blocked": 2,
        }
        # -- profiling-plane fault window (obs/profile.py) ----------------
        # standalone shadow audit: every observe under the armed raise
        # burst fails OPEN (failure counter only — check/underestimate/
        # eps counters must not move), and one armed capture returns an
        # error payload with tracer state restored.  Counts are a pure
        # function of the loop bounds — seed-pure by construction.
        AUDIT_TICKS = 4
        audit = PROF.SketchAudit(
            node_rows=8, window_ms=500, sample_count=2, slack_buckets=1,
            width=256, k=1, period=2,
        )
        a_res = np.asarray([9], np.int32)
        a_cnt = np.asarray([1], np.int32)
        tracer_was = OT.TRACER.enabled
        plan2 = FaultPlan(
            name="profile_plane_fail",
            seed=seed,
            faults=[
                FaultSpec(
                    "sketch.audit.shadow", "raise",
                    burst_start=0, burst_len=AUDIT_TICKS, exc="RuntimeError",
                ),
                FaultSpec(
                    "obs.profile.capture", "raise",
                    burst_start=0, burst_len=1, exc="RuntimeError",
                ),
            ],
        )
        with session.window(plan2):
            for i in range(AUDIT_TICKS):
                audit.observe(1_000 + i, a_res, a_cnt)
            cap = PROF.capture_profile(
                ms=1.0, min_interval_s=0.0, sleep=lambda _s: None
            )
        extra["capture_failed_open"] = (
            "error" in cap and OT.TRACER.enabled == tracer_was
        )
        # heal: the first un-armed observe folds (shadow admits the id)
        # and a clean capture returns a chrome trace
        audit.observe(2_000, a_res, a_cnt)
        cap2 = PROF.capture_profile(
            ms=1.0, min_interval_s=0.0, sleep=lambda _s: None
        )
        extra["profile_plane_heals"] = (
            len(audit._tracked) == 1
            and "chrome_trace" in cap2
            and OT.TRACER.enabled == tracer_was
        )
    finally:
        client.stop()
    extra["expect_metric_deltas"] = {
        "sentinel_sketch_promotion_failures_total": 2,
        # profiling-plane window: EXACT fail-open accounting — the raise
        # burst lands only in the failure counter, never in the audit's
        # comparison counters
        "sentinel_sketch_audit_failures_total": float(AUDIT_TICKS),
        "sentinel_sketch_audit_checks_total": 0.0,
        "sentinel_sketch_underestimates_total": 0.0,
        "sentinel_sketch_eps_violations_total": 0.0,
        'sentinel_profile_captures_total{result="error"}': 1.0,
        'sentinel_profile_captures_total{result="ok"}': 1.0,
    }
    ctx = ScenarioContext(
        metrics=metrics,
        client=client,
        submitted=16,
        passed=totals["passed"],
        blocked=totals["blocked"],
        injected=session.injected,
        expect_injected={
            "runtime.hotset.promote:raise": 2,
            "sketch.audit.shadow:raise": 4,
            "obs.profile.capture:raise": 1,
        },
        extra=extra,
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "pipeline-drained",
            "injected-as-planned",
            "metric-deltas",
        ],
        ctx,
    )
    for nm, key, detail in (
        ("promote-fails-stay-sketched", "stayed_sketched",
         "failed promotions must leave resources on sketch ids"),
        ("tail-verdicts-fail-closed", "tail_verdicts_closed",
         "un-promoted tail rules must still block from the CMS tables"),
        ("stats-fail-open", "stats_open",
         "the sketch must keep observing resources promotion failed for"),
        ("heal-promotes-exactly", "heal_promotes_and_enforces",
         "a clean load must promote into the reserve and enforce exactly"),
        ("profile-capture-fails-open", "capture_failed_open",
         "an injected capture fault must return an error payload and "
         "restore the tracer's enabled state"),
        ("profile-plane-heals", "profile_plane_heals",
         "the first un-armed audit tick and capture must succeed"),
    ):
        verdicts.append(Verdict(nm, bool(extra.get(key)), detail))
    return _result("hotset_promote_fail", seed, session, verdicts, t0)


def _scn_tuner_fail_open(seed: int) -> ScenarioResult:
    """Workload autotuner chaos (workload/tuner.py + generator.py).

    Phase 1 (quiet): a seeded flash-crowd closed loop retunes the live
    operating point at least once — expected retraces only, HBM breach
    counter flat.  Phase 2 (armed): ``workload.tuner.step`` raises on a
    hit-index burst and ``workload.gen.emit`` drops seeded generator
    steps.  A raising tuner step must fail OPEN — serving verdicts
    untouched (accounting stays exact), the point rolled back to
    last-good, failures counted exactly in
    ``sentinel_tuner_step_failures_total`` — and dropped emissions land
    only in ``sentinel_workload_emit_drops_total`` (never offered, so
    verdict accounting is green by construction).  All injected counts
    are hit-index/max_fires gated on single-threaded sites: seed-pure."""
    from sentinel_tpu.obs import profile as PROF
    from sentinel_tpu.workload import (
        TunerConfig,
        flash_crowd_2x,
        run_closed_loop,
        sim_default_op,
    )

    t0 = mono_s()
    metrics = MetricsDelta()
    session = _Session()
    surprises0 = PROF.RETRACE.surprise_count()
    client = _make_client()
    op0 = sim_default_op()
    cands = [
        op0.replace(batch_size=16, complete_batch_size=16),
        op0.replace(batch_size=8, complete_batch_size=8),
    ]
    tcfg = TunerConfig(settle_steps=3, warmup_steps=1)
    extra = {}
    try:
        # -- phase 1: quiet closed loop — the tuner must actually move --
        quiet = run_closed_loop(
            client,
            flash_crowd_2x(seed=seed, base=3.0, steps=60, start_step=10),
            op0,
            cands,
            tune=True,
            tune_every=4,
            tcfg=tcfg,
        )
        extra["retuned_live"] = any(
            d["action"] == "applied" for d in quiet.decisions
        )
        # -- phase 2: armed window -------------------------------------
        tuner_fires, emit_fires = 2, 2
        plan = FaultPlan(
            name="tuner_fail_open",
            seed=seed,
            faults=[
                FaultSpec(
                    "workload.tuner.step", "raise",
                    burst_start=1, burst_len=tuner_fires,
                    exc="RuntimeError",
                ),
                FaultSpec(
                    "workload.gen.emit", "raise",
                    every_nth=7, max_fires=emit_fires, exc="RuntimeError",
                ),
            ],
        )
        with session.window(plan):
            armed = run_closed_loop(
                client,
                flash_crowd_2x(
                    seed=seed + 1, base=3.0, steps=40, start_step=8
                ),
                op0.replace(
                    batch_size=client.cfg.batch_size,
                    complete_batch_size=client.cfg.complete_batch_size,
                ),
                cands,
                tune=True,
                tune_every=4,
                tcfg=tcfg,
            )
        fail_opens = [
            d for d in armed.decisions if d["action"] == "fail_open"
        ]
        best = armed.converged_op
        extra["fail_open_exact"] = len(fail_opens) == tuner_fires
        # fail-open target: the engine must END the armed phase ON the
        # tuner's last-good point, not stranded on a mid-walk candidate
        extra["on_last_good"] = (
            client.cfg.batch_size == best.batch_size
            and client.cfg.complete_batch_size == best.complete_batch_size
        )
        extra["zero_surprise_retraces"] = (
            PROF.RETRACE.surprise_count() == surprises0
        )
        submitted = quiet.submitted + armed.submitted
        passed = quiet.passed + armed.passed
        blocked = quiet.blocked + armed.blocked
    finally:
        client.stop()
    extra["expect_metric_deltas"] = {
        "sentinel_tuner_step_failures_total": float(tuner_fires),
        "sentinel_workload_emit_drops_total": float(emit_fires),
        # retuning must never trade latency for capacity headroom
        "sentinel_hbm_capacity_breaches_total": 0.0,
    }
    ctx = ScenarioContext(
        metrics=metrics,
        client=client,
        submitted=submitted,
        passed=passed,
        blocked=blocked,
        injected=session.injected,
        expect_injected={
            "workload.tuner.step:raise": tuner_fires,
            "workload.gen.emit:raise": emit_fires,
        },
        extra=extra,
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "pipeline-drained",
            "injected-as-planned",
            "metric-deltas",
        ],
        ctx,
    )
    for nm, key, detail in (
        ("retuned-live", "retuned_live",
         "the quiet phase must apply at least one live retune"),
        ("fail-open-exact", "fail_open_exact",
         "each injected tuner-step raise must journal exactly one "
         "fail-open decision"),
        ("fail-open-to-last-good", "on_last_good",
         "after the armed window the engine must sit on the tuner's "
         "last-good operating point"),
        ("zero-surprise-retraces", "zero_surprise_retraces",
         "every retune recompile must journal an expected_retrace cause"),
    ):
        verdicts.append(Verdict(nm, bool(extra.get(key)), detail))
    return _result("tuner_fail_open", seed, session, verdicts, t0)


def _scn_explain_fail_open(seed: int) -> ScenarioResult:
    """The verdict provenance plane is strictly observational: with the
    ``obs.explain.decode`` failpoint mangling (corrupt window) and then
    raising inside (raise window) the explain-section decode, the verdict
    stream must be BIT-IDENTICAL to an unfaulted control run over the
    same traffic — explanation loss is counted
    (``sentinel_explain_decode_failures_total``) and records demonstrably
    go missing from the plane, but no decision ever changes."""
    from sentinel_tpu.core import errors as ERR
    from sentinel_tpu.core import rules as R

    t0 = mono_s()
    resource = "chaos/explain"
    rule = [R.FlowRule(resource=resource, count=2.0)]
    ticks, per_tick = 6, 4

    def _drive(client):
        """Identical deterministic traffic: one warm tick, then `ticks`
        batches inside one unadvanced window so the filled window keeps
        every later item BLOCKED (explain records on every tick)."""
        client.flow_rules.load(rule)
        client.check_batch([resource])  # warm XLA compile outside windows
        out = []
        for _ in range(ticks):
            out.extend(client.check_batch([resource] * per_tick))
        return out

    metrics = MetricsDelta()
    session = _Session()
    control = _make_client()
    faulted = _make_client()
    corrupt_fires, raise_fires = 2, 1
    try:
        baseline = _drive(control)
        control_explained = control.explain_coverage()["explained"]
        faulted.flow_rules.load(rule)
        faulted.check_batch([resource])  # same warm tick, outside windows
        got = []
        # window 1: mangled section bytes on decode hits 2 and 4
        plan = FaultPlan(
            name="explain-corrupt", seed=seed,
            faults=[FaultSpec(
                "obs.explain.decode", "corrupt",
                every_nth=2, max_fires=corrupt_fires,
            )],
        )
        with session.window(plan):
            for _ in range(4):
                got.extend(faulted.check_batch([resource] * per_tick))
        # window 2: the decode path itself raises (same fail-open contract)
        plan = FaultPlan(
            name="explain-raise", seed=seed,
            faults=[FaultSpec(
                "obs.explain.decode", "raise",
                max_fires=raise_fires, exc="RuntimeError",
            )],
        )
        with session.window(plan):
            for _ in range(2):
                got.extend(faulted.check_batch([resource] * per_tick))
    finally:
        control.stop()
        faulted.stop()
    passed = sum(1 for v, _w in got if v in (ERR.PASS, ERR.PASS_WAIT))
    blocked = len(got) - passed
    ctx = ScenarioContext(
        metrics=metrics,
        client=faulted,
        submitted=ticks * per_tick,
        passed=passed,
        blocked=blocked,
        injected=session.injected,
        expect_injected={
            "obs.explain.decode:corrupt": corrupt_fires,
            "obs.explain.decode:raise": raise_fires,
        },
        extra={
            "expect_metric_deltas": {
                # every injected mangle/raise is one dropped section —
                # and zero of them touched the verdict decode path
                "sentinel_explain_decode_failures_total": (
                    corrupt_fires + raise_fires
                ),
                "sentinel_packed_decode_failures_total": 0,
                "sentinel_resolve_failures_total": 0,
            },
        },
    )
    verdicts = evaluate(
        [
            "verdict-accounting",
            "metric-deltas",
            "pipeline-drained",
            "injected-as-planned",
        ],
        ctx,
    )
    verdicts.append(
        Verdict(
            "verdicts-bit-identical",
            got == baseline,
            f"faulted run diverged from control: {got} != {baseline}"
            if got != baseline else "",
        )
    )
    verdicts.append(
        Verdict(
            "blocks-under-fault",
            blocked > 0,
            f"blocked={blocked}: the armed windows must cover real blocks",
        )
    )
    lost = control_explained - faulted.explain_coverage()["explained"]
    verdicts.append(
        Verdict(
            "explanations-actually-lost",
            lost > 0,
            f"control explained {control_explained}, faulted explained "
            f"{control_explained - lost} — the faults must cost records",
        )
    )
    return _result("explain_fail_open", seed, session, verdicts, t0)


def _result(name, seed, session, verdicts, t0) -> ScenarioResult:
    return ScenarioResult(
        name=name,
        seed=seed,
        ok=all(v.ok for v in verdicts),
        injected=dict(sorted(session.injected.items())),
        verdicts=verdicts,
        duration_s=mono_s() - t0,
    )


# -- registry ----------------------------------------------------------------


@dataclass(frozen=True)
class Scenario:
    name: str
    fn: Callable[[int], ScenarioResult]
    description: str
    fast: bool = True  # tier-1 CI subset member
    eager: bool = False  # run under jax.disable_jit (interpret-mode Pallas)


SCENARIOS: Dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario(
            "rpc_error_burst",
            _scn_rpc_error_burst,
            "token RPC send-failure + latency burst against a live server",
            fast=False,
        ),
        Scenario(
            "cluster_partition",
            _scn_cluster_partition,
            "token-server partition: degrade to local, hold, heal, exit",
        ),
        Scenario(
            "resolver_exception",
            _scn_resolver_exception,
            "readback raises + fused-wire corruption; ticks fail closed, "
            "nothing strands",
        ),
        Scenario(
            "seg_overflow_storm",
            _scn_seg_overflow_storm,
            "fail-closed segment overflow + live seg_u grow-and-swap",
            fast=False,
            eager=True,
        ),
        Scenario(
            "datasource_flap",
            _scn_datasource_flap,
            "rule-file refresh faults; rules hold, post-heal update applies",
        ),
        Scenario(
            "shard_reconnect",
            _scn_shard_reconnect,
            "mid-window shard partition: degrade forfeited chunks, no replay",
        ),
        Scenario(
            "shard_failover",
            _scn_shard_failover,
            "fleet shard kill/partition/rejoin: lease fallback, per-shard hysteresis",
        ),
        Scenario(
            "overload_storm",
            _scn_overload_storm,
            "2x-capacity flash crowd: ladder climbs, sheds, recovers; OFF collapses",
        ),
        Scenario(
            "hotset_promote_fail",
            _scn_hotset_promote_fail,
            "hot-set promotion + profiling-plane faults: stats/audit/capture "
            "fail open, tail verdicts fail closed",
        ),
        Scenario(
            "explain_fail_open",
            _scn_explain_fail_open,
            "explain-section decode faults: provenance drops (counted), "
            "verdicts bit-identical to the unfaulted control run",
        ),
        Scenario(
            "tuner_fail_open",
            _scn_tuner_fail_open,
            "workload autotuner faults: raising steps fail OPEN to the "
            "last-good operating point, dropped emissions counted exactly",
            eager=True,
        ),
    )
}


def run_scenario(name: str, seed: int) -> ScenarioResult:
    scn = SCENARIOS[name]
    if scn.eager:
        import jax

        with jax.disable_jit():
            return scn.fn(seed)
    return scn.fn(seed)


def run_all(
    seed: int, names: Optional[List[str]] = None, fast_only: bool = False
) -> List[ScenarioResult]:
    picked = names or [
        n for n, s in SCENARIOS.items() if (s.fast or not fast_only)
    ]
    return [run_scenario(n, seed) for n in picked]


def report(results: List[ScenarioResult], as_json: bool = False) -> str:
    if as_json:
        return json.dumps([r.to_dict() for r in results], indent=2, sort_keys=True)
    lines = []
    for r in results:
        mark = "PASS" if r.ok else "FAIL"
        lines.append(f"[{mark}] {r.name} (seed={r.seed}, {r.duration_s:.2f}s)")
        inj = ", ".join(f"{k}={v}" for k, v in sorted(r.injected.items())) or "none"
        lines.append(f"       injected: {inj}")
        for v in r.verdicts:
            lines.append(
                f"       {'ok ' if v.ok else 'RED'} {v.name}"
                + (f" — {v.detail}" if (v.detail and not v.ok) else "")
            )
    total = sum(1 for r in results if r.ok)
    lines.append(f"{total}/{len(results)} scenarios green")
    return "\n".join(lines)
