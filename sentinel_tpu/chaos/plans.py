"""Declarative fault plans: what to inject, where, and on which schedule.

A ``FaultPlan`` is a seed plus a list of ``FaultSpec`` entries; it is the
unit the runner arms (``failpoints.arm(plan)``) and the unit that
round-trips through JSON, so a failing chaos run can be replayed exactly
from its serialized plan:

    plan = FaultPlan(name="burst", seed=7, faults=[
        FaultSpec("cluster.rpc.send", "raise", burst_start=2, burst_len=2),
        FaultSpec("cluster.rpc.send", "delay", every_nth=5, delay_ms=2.0),
    ])
    FaultPlan.from_json(plan.to_json()) == plan

Schedules compose as an AND over whichever gates are set, evaluated per
SITE-hit in order (see ``_LiveFault.decide``):

  * ``burst_start``/``burst_len`` — fire only within a hit-index window
  * ``every_nth``                 — fire on every Nth hit
  * ``probability``               — seeded Bernoulli draw per hit
  * ``max_fires``                 — hard cap on total fires (the lever
                                    that pins injected-event counts when
                                    hit counts could vary with timing)

With no gate set a spec fires on every hit.  All randomness comes from a
per-spec ``random.Random`` derived from ``(plan.seed, spec index)``, so
identical plans driven over identical per-site hit sequences inject the
identical event sequence — the determinism contract the CLI asserts.
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, field
from typing import Dict, List

from sentinel_tpu.chaos import failpoints as FP

ACTIONS = ("delay", "raise", "drop", "corrupt", "short_read", "clock_skew")


@dataclass(frozen=True)
class FaultSpec:
    """One fault: a site, an action, a schedule, and action parameters."""

    site: str
    action: str
    # schedule gates (AND of the ones set; none set = every hit)
    probability: float = 0.0
    every_nth: int = 0
    burst_start: int = 0
    burst_len: int = 0
    max_fires: int = 0
    # action parameters
    delay_ms: float = 0.0
    skew_ms: int = 0
    exc: str = "OSError"

    def validate(self, sites: Dict[str, FP.Site]) -> None:
        if self.action not in ACTIONS:
            raise ValueError(f"unknown action {self.action!r}")
        site = sites.get(self.site)
        if site is None:
            raise ValueError(f"failpoint site {self.site!r} is not registered")
        if self.action not in site.kinds:
            raise ValueError(
                f"site {self.site!r} honors {site.kinds}, not {self.action!r}"
            )
        if self.action == "raise" and self.exc not in FP.EXCEPTIONS:
            raise ValueError(f"unknown exception class {self.exc!r}")
        if not (0.0 <= self.probability <= 1.0):
            raise ValueError("probability must be in [0, 1]")
        if min(self.every_nth, self.burst_start, self.burst_len, self.max_fires) < 0:
            raise ValueError("schedule fields must be >= 0")
        if self.burst_start and not self.burst_len:
            # burst_len == 0 disables the burst gate entirely; a lone
            # burst_start would silently fire on EVERY hit, not a window
            raise ValueError("burst_start requires burst_len > 0")
        if self.delay_ms < 0:
            raise ValueError("delay_ms must be >= 0")


@dataclass(frozen=True)
class FaultPlan:
    """A named, seeded set of FaultSpecs — the armable/replayable unit."""

    name: str = ""
    seed: int = 0
    faults: List[FaultSpec] = field(default_factory=list)

    def validate(self, sites: Dict[str, FP.Site]) -> None:
        for spec in self.faults:
            spec.validate(sites)

    def spec_rng(self, idx: int) -> random.Random:
        """Per-spec PRNG stream: seeded from (plan seed, spec index) with
        a fixed odd multiplier so adjacent seeds don't share streams."""
        return random.Random((int(self.seed) * 0x9E3779B1 + idx) & 0xFFFFFFFF)

    # -- JSON round-trip -----------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "faults": [asdict(s) for s in self.faults],
        }

    def to_json(self, indent: int = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @staticmethod
    def from_dict(d: dict) -> "FaultPlan":
        return FaultPlan(
            name=str(d.get("name", "")),
            seed=int(d.get("seed", 0)),
            faults=[FaultSpec(**f) for f in d.get("faults", ())],
        )

    @staticmethod
    def from_json(s: str) -> "FaultPlan":
        return FaultPlan.from_dict(json.loads(s))
