"""Failpoint registry: named fault-injection sites on the product's
failure-handling paths.

Every place the system claims to degrade gracefully — cluster RPC
round-trips, remote-shard chunk pipelines, resolver-pool readbacks,
datasource refresh loops, the command plane — declares a named SITE here
at import time and calls one of the three hot-path hooks at the exact
point a real fault would strike:

    FP.hit("cluster.rpc.send")              # may raise / delay
    data = FP.pipe("parallel.shard.recv", data)  # may drop / corrupt /
                                                 # short-read / raise / delay
    t += FP.skew_ms("runtime.tick.clock")   # deterministic clock skew

Overhead discipline (same contract as ``obs/trace.py``, guarded by the
same <5 µs/site-call CI test): a DISARMED site costs exactly one module
flag check — no dict lookup, no allocation, no clock read.  Arming
happens only inside the chaos harness (``chaos/runner.py``) or an
explicit test; production processes never pay more than the flag.

Site naming scheme (enforced by ``register`` and the catalog test):
``<layer>.<component>.<operation>``, three dot-separated ``[a-z0-9_]``
segments, where ``<layer>`` is the owning subsystem (``transport``,
``cluster``, ``runtime``, ``parallel``, ``datasource``).

Determinism: when armed, every fire decision comes from the plan's
seeded PRNG and per-spec hit counters (``chaos/plans.py``), so a run
replays exactly from its seed; injected events are counted per
(site, action) and exposed via the ``ArmedState`` handle plus the
``sentinel_chaos_injections_total`` registry counter.

Time-source note: the ``delay`` action sleeps (``time.sleep`` is not a
clock READ) and ``clock_skew`` only returns a configured offset — but
this module is the chaos plane's single sanctioned home for any clock
manipulation, and the stlint ``time-source`` pass allowlists it (see
``analysis/README.md``).  Keep all such code HERE.
"""

from __future__ import annotations

import re
import threading
import time as _time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

#: process-global arm flag — the ONE check disarmed sites pay
_ARMED = False
_STATE: Optional["ArmedState"] = None
#: guards arm/disarm and site registration (never on the hot path)
_LOCK = threading.Lock()

_SITE_RE = re.compile(
    r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$"
)
_LAYERS = (
    "transport", "cluster", "runtime", "parallel", "datasource", "obs",
    "sketch", "workload",
)

#: actions a call style supports: ``hit`` sites can only raise or stall,
#: ``pipe`` sites additionally mangle the payload, ``skew`` sites shift
#: a clock value
HIT_ACTIONS = ("raise", "delay")
PIPE_ACTIONS = ("raise", "delay", "drop", "corrupt", "short_read")
SKEW_ACTIONS = ("clock_skew",)

#: exception classes the ``raise`` action may instantiate, by name —
#: the plan format stays JSON-serializable
EXCEPTIONS = {
    "OSError": OSError,
    "ConnectionResetError": ConnectionResetError,
    "ConnectionRefusedError": ConnectionRefusedError,
    "TimeoutError": TimeoutError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
}


@dataclass(frozen=True)
class Site:
    """One registered injection point."""

    name: str
    desc: str
    kinds: Tuple[str, ...]  # actions the call site honors


#: name -> Site; populated at import time by the instrumented modules
SITES: Dict[str, Site] = {}


def register(name: str, desc: str = "", kinds: Tuple[str, ...] = HIT_ACTIONS) -> str:
    """Declare an injection site (idempotent for identical re-imports).
    Returns ``name`` so call sites can bind it to a module constant."""
    if not _SITE_RE.match(name):
        raise ValueError(
            f"failpoint {name!r} violates the <layer>.<component>.<operation> scheme"
        )
    if name.split(".", 1)[0] not in _LAYERS:
        raise ValueError(
            f"failpoint {name!r}: layer must be one of {_LAYERS}"
        )
    unknown = [k for k in kinds if k not in HIT_ACTIONS + PIPE_ACTIONS + SKEW_ACTIONS]
    if unknown:
        raise ValueError(f"failpoint {name!r}: unknown action kinds {unknown}")
    with _LOCK:
        old = SITES.get(name)
        if old is not None and (old.desc, old.kinds) != (desc, tuple(kinds)):
            raise ValueError(f"failpoint {name!r} already registered differently")
        SITES[name] = Site(name, desc, tuple(kinds))
    return name


# -- armed-run state ---------------------------------------------------------


class _LiveFault:
    """A FaultSpec compiled against one armed run: its own PRNG stream and
    hit/fire counters, so replaying a seed replays every decision."""

    __slots__ = ("spec", "rng", "hits", "fires", "counter")

    def __init__(self, spec, rng, counter):
        self.spec = spec
        self.rng = rng
        self.hits = 0
        self.fires = 0
        self.counter = counter  # obs counter (or None in bare tests)

    def decide(self) -> bool:
        """One hit: advance counters, decide whether to fire.  The PRNG is
        drawn exactly once per hit when probability gating is set, so the
        decision stream depends only on the per-site hit ORDER."""
        s = self.spec
        i = self.hits
        self.hits += 1
        if s.max_fires and self.fires >= s.max_fires:
            return False
        if s.burst_len and not (s.burst_start <= i < s.burst_start + s.burst_len):
            return False
        if s.every_nth and (i + 1) % s.every_nth != 0:
            return False
        if s.probability > 0.0 and self.rng.random() >= s.probability:
            return False
        self.fires += 1
        return True


_EVENT_CAP = 50_000


class ArmedState:
    """Handle for one armed plan: per-site hit counts, injected events,
    and the compiled per-spec state.  Returned by ``arm`` and kept valid
    after ``disarm`` (the scenario report reads it afterwards)."""

    def __init__(self, plan):
        from sentinel_tpu.obs.registry import REGISTRY

        self.plan = plan
        self.lock = threading.Lock()
        self.hits: Dict[str, int] = {}
        self.events: List[Tuple[str, str, int]] = []  # (site, action, site-hit idx)
        self.by_site: Dict[str, List[_LiveFault]] = {}
        for idx, spec in enumerate(plan.faults):
            counter = REGISTRY.counter(
                "sentinel_chaos_injections_total",
                "faults injected by armed chaos plans",
                labels={"site": spec.site, "action": spec.action},
            )
            self.by_site.setdefault(spec.site, []).append(
                _LiveFault(spec, plan.spec_rng(idx), counter)
            )

    def injected(self) -> Dict[str, int]:
        """``{"site:action": fires}`` over every spec of the plan."""
        out: Dict[str, int] = {}
        with self.lock:
            for site, lives in sorted(self.by_site.items()):
                for lf in lives:
                    key = f"{site}:{lf.spec.action}"
                    out[key] = out.get(key, 0) + lf.fires
        return out

    def hit_counts(self) -> Dict[str, int]:
        """Site -> times the armed run crossed it (fired or not)."""
        with self.lock:
            return dict(self.hits)


def arm(plan) -> ArmedState:
    """Install a FaultPlan process-wide.  Exactly one plan may be armed;
    call ``disarm()`` first (the runner's sessions always pair them)."""
    global _ARMED, _STATE
    plan.validate(SITES)
    st = ArmedState(plan)
    with _LOCK:
        if _ARMED:
            raise RuntimeError("a chaos plan is already armed")
        _STATE = st
        _ARMED = True
    return st


def disarm() -> Optional[ArmedState]:
    """Remove the armed plan (idempotent); returns its state handle."""
    global _ARMED, _STATE
    with _LOCK:
        st, _STATE = _STATE, None
        _ARMED = False
    return st


@contextmanager
def armed(plan):
    """``with armed(plan) as st:`` — arm/disarm bracketed."""
    st = arm(plan)
    try:
        yield st
    finally:
        disarm()


# -- hot-path hooks ----------------------------------------------------------


def is_armed() -> bool:
    """Whether a chaos plan is currently armed.  Instrumented code may
    consult this to keep injected counts a pure function of the seed —
    e.g. the shard lease refresher runs its async hop inline while a
    plan is armed, so a refresh-site raise lands on the driving thread
    deterministically instead of racing a background worker."""
    return _ARMED


def hit(site: str) -> None:
    """Cross a raise/delay site.  Disarmed: one flag check."""
    if not _ARMED:
        return
    _apply(site, None)


def pipe(site: str, data: bytes) -> bytes:
    """Pass a payload through a byte-mangling site.  Disarmed: one flag
    check, payload returned untouched."""
    if not _ARMED:
        return data
    return _apply(site, data)


def skew_ms(site: str) -> int:
    """Clock-skew offset (ms) for a time-reading site; 0 when disarmed."""
    if not _ARMED:
        return 0
    out = _apply(site, 0)
    return out if isinstance(out, int) else 0


def _apply(site: str, value):
    """Armed-path dispatch: count the hit, run each matching spec's
    schedule, execute fired actions.  Raise/delay execute OUTSIDE the
    state lock so a stall never blocks other sites."""
    st = _STATE
    if st is None:
        return value
    delay_s = 0.0
    raise_exc = None
    with st.lock:
        st.hits[site] = hit_idx = st.hits.get(site, 0) + 1
        lives = st.by_site.get(site)
        if not lives:
            return value
        for lf in lives:
            if not lf.decide():
                continue
            s = lf.spec
            if len(st.events) < _EVENT_CAP:
                st.events.append((site, s.action, hit_idx - 1))
            if lf.counter is not None:
                lf.counter.inc()
            # black-box journal: every injected fault lands in the flight
            # recorder so a post-mortem bundle shows WHAT was injected
            # right next to the state transitions it caused.  Lazy import
            # (armed-only path) keeps the disarmed module import-light.
            from sentinel_tpu.obs.flight import FLIGHT as _FLIGHT

            _FLIGHT.note(
                "failpoint.fire", site=site, action=s.action, hit=hit_idx - 1
            )
            if s.action == "delay":
                delay_s += s.delay_ms / 1000.0
            elif s.action == "raise":
                raise_exc = EXCEPTIONS.get(s.exc, OSError)(
                    f"chaos[{site}] injected {s.exc}"
                )
            elif s.action == "clock_skew":
                value = int(value or 0) + int(s.skew_ms)
            elif isinstance(value, (bytes, bytearray)):
                if s.action == "drop":
                    value = b""
                elif s.action == "corrupt" and len(value) > 0:
                    i = lf.rng.randrange(len(value))
                    value = value[:i] + bytes([value[i] ^ 0xFF]) + value[i + 1 :]
                elif s.action == "short_read" and len(value) > 1:
                    value = value[: lf.rng.randrange(1, len(value))]
    if delay_s > 0.0:
        _time.sleep(delay_s)
    if raise_exc is not None:
        raise raise_exc
    return value


def catalog() -> Dict[str, Site]:
    """Immutable view of every registered site (the catalog test and the
    CLI's ``--sites`` listing read this)."""
    with _LOCK:
        return dict(SITES)
