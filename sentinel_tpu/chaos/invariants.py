"""Safety invariants evaluated over a chaos run.

Each invariant is a small pure check over a ``ScenarioContext`` — the
counts the scenario gathered, deltas of ``obs.REGISTRY`` metrics across
the run (the registry is process-global and cumulative, so monitors
always diff a before/after snapshot), and the driven client's state.
A scenario names the invariants it must keep green; the runner evaluates
them after the faults and reports one verdict per invariant.

The catalog (README "Chaos & fault injection" documents each):

  verdict-accounting   passed + blocked + degraded == submitted — no
                       request vanishes, none is double-decided
  no-degraded-pass     zero PASS verdicts produced BY a degraded/failed
                       cluster decision (STATUS_FAIL may fall back to
                       local enforcement, never map to OK)
  degrade-hysteresis   degrade enter/exit transitions pair up and the
                       live gauge equals enters - exits ∈ {0, 1}
  token-conservation   every token request returned exactly one result;
                       failures equal the injected fault count
  no-chunk-replay      the shard host processed every chunk at most once
                       (answered + degraded == chunks submitted)
  pipeline-drained     the client's tick pipeline is empty at rest:
                       occupancy and resolver-queue gauges at 0, no
                       pending ticks
  no-stranded-futures  every future the scenario submitted is resolved
  seg-drops-counted    fail-closed segment-overflow drops surfaced on
                       the seg-drop counter (and only when expected)
  rules-intact         the rule set survived the datasource fault window
                       unchanged, then applied the post-heal update
  metric-deltas        named registry series moved exactly as expected
                       (e.g. the labeled RPC failure KIND that fired)
  injected-as-planned  observed injected-event counts equal the
                       scenario's expectation (the determinism anchor)
  shard-degrade-hysteresis
                       per-SHARD failover transitions pair up (the fleet
                       analog of degrade-hysteresis): for every shard the
                       scenario names, enter/exit counts match the
                       expectation and the live per-shard gauge equals
                       enters - exits ∈ {0, 1}
  ladder-monotone      degrade-ladder transitions move one rung at a time
                       (monotone steps within the hysteresis holds), the
                       run climbed when the scenario expected it to, and
                       goodput never hit zero while the ladder sat below
                       FAIL_CLOSED
  no-order-violations  the runtime lock witness recorded zero lock-order
                       inversions and no dynamic held→acquired edge the
                       static tier-3 graph missed (trivially green when
                       the witness is not installed)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from sentinel_tpu.obs.registry import REGISTRY


class MetricsDelta:
    """Before/after diff over REGISTRY's scalar series (counters/gauges
    by `name{labels}` key, histograms by their count)."""

    def __init__(self):
        self._before = self._flatten()

    @staticmethod
    def _flatten() -> Dict[str, float]:
        out: Dict[str, float] = {}
        for key, v in REGISTRY.snapshot().items():
            out[key] = float(v["count"]) if isinstance(v, dict) else float(v)
        return out

    def delta(self, key: str) -> float:
        """Change of one series since construction (0.0 if never seen)."""
        now = self._flatten()
        return now.get(key, 0.0) - self._before.get(key, 0.0)

    def deltas(self, keys) -> Dict[str, float]:
        """Changes for many series off ONE registry snapshot — checks
        over several keys must not re-walk every histogram per key."""
        now = self._flatten()
        return {
            k: now.get(k, 0.0) - self._before.get(k, 0.0) for k in keys
        }

    @staticmethod
    def value(key: str) -> float:
        """Current absolute value (gauges)."""
        now = MetricsDelta._flatten()
        return now.get(key, 0.0)


@dataclass
class ScenarioContext:
    """Everything the invariant checks read.  Scenarios fill the counts
    they can attest to; unused fields stay at their neutral defaults."""

    metrics: MetricsDelta
    client: Optional[object] = None  # the driven SentinelClient
    submitted: int = 0
    passed: int = 0
    blocked: int = 0
    degraded: int = 0  # items decided by an explicit degrade path
    degraded_passes: int = 0  # PASS produced BY a failed cluster decision
    futures: list = field(default_factory=list)
    injected: Dict[str, int] = field(default_factory=dict)  # observed
    expect_injected: Dict[str, int] = field(default_factory=dict)
    extra: dict = field(default_factory=dict)


@dataclass(frozen=True)
class Verdict:
    name: str
    ok: bool
    detail: str = ""


def _v(name: str, ok: bool, detail: str = "") -> Verdict:
    return Verdict(name, bool(ok), detail)


# -- checks ------------------------------------------------------------------


def verdict_accounting(ctx: ScenarioContext) -> Verdict:
    total = ctx.passed + ctx.blocked + ctx.degraded
    return _v(
        "verdict-accounting",
        total == ctx.submitted,
        f"submitted={ctx.submitted} passed={ctx.passed} "
        f"blocked={ctx.blocked} degraded={ctx.degraded}",
    )


def no_degraded_pass(ctx: ScenarioContext) -> Verdict:
    return _v(
        "no-degraded-pass",
        ctx.degraded_passes == 0,
        f"degraded_passes={ctx.degraded_passes}",
    )


def degrade_hysteresis(ctx: ScenarioContext) -> Verdict:
    enters = ctx.metrics.delta(
        'sentinel_cluster_degrade_transitions_total{transition="enter"}'
    )
    exits = ctx.metrics.delta(
        'sentinel_cluster_degrade_transitions_total{transition="exit"}'
    )
    gauge = MetricsDelta.value("sentinel_cluster_degraded")
    open_ = enters - exits
    ok = open_ in (0.0, 1.0) and gauge == open_
    want = ctx.extra.get("expect_degrade_enters")
    if want is not None:
        ok = ok and enters == want and exits == want
    return _v(
        "degrade-hysteresis",
        ok,
        f"enters={enters:g} exits={exits:g} gauge={gauge:g}",
    )


def shard_degrade_hysteresis(ctx: ScenarioContext) -> Verdict:
    """Fleet failover discipline: ``extra["expect_shard_transitions"]``
    maps shard name → expected (enters, exits) over the run; every named
    shard must also leave its ``sentinel_shard_degraded`` gauge equal to
    the open transition count (0 or 1)."""
    want: Dict[str, tuple] = ctx.extra.get("expect_shard_transitions", {})
    bad = []
    k_enter = {
        name: f'sentinel_shard_degrade_transitions_total{{shard="{name}",transition="enter"}}'
        for name in want
    }
    k_exit = {
        name: f'sentinel_shard_degrade_transitions_total{{shard="{name}",transition="exit"}}'
        for name in want
    }
    # ONE registry walk for every shard's pair (deltas contract), plus
    # one for the gauges, instead of 3 full walks per shard
    d = ctx.metrics.deltas(list(k_enter.values()) + list(k_exit.values()))
    now = MetricsDelta._flatten()
    for name, (w_enter, w_exit) in want.items():
        enters = d[k_enter[name]]
        exits = d[k_exit[name]]
        gauge = now.get(f'sentinel_shard_degraded{{shard="{name}"}}', 0.0)
        open_ = enters - exits
        if not (
            enters == w_enter
            and exits == w_exit
            and open_ in (0.0, 1.0)
            and gauge == open_
        ):
            bad.append(
                f"{name}: enters={enters:g}/{w_enter} exits={exits:g}/{w_exit} "
                f"gauge={gauge:g}"
            )
    return _v(
        "shard-degrade-hysteresis",
        not bad,
        "; ".join(bad) or f"{len(want)} shards paired",
    )


def token_conservation(ctx: ScenarioContext) -> Verdict:
    c = ctx.extra.get("token_counts", {})
    requests = c.get("requests", 0)
    resolved = sum(v for k, v in c.items() if k != "requests")
    want_failed = ctx.extra.get("expect_token_failures")
    ok = requests == resolved
    if want_failed is not None:
        ok = ok and c.get("failed", 0) == want_failed
    return _v("token-conservation", ok, f"{c}")


def no_chunk_replay(ctx: ScenarioContext) -> Verdict:
    processed = ctx.extra.get("server_chunks_processed", 0)
    written = ctx.extra.get("chunks_written", 0)
    answered = ctx.metrics.delta("sentinel_shard_chunks_total")
    degr = ctx.metrics.delta("sentinel_shard_chunks_degraded_total")
    ok = processed <= written and answered + degr == written
    return _v(
        "no-chunk-replay",
        ok,
        f"written={written} server_processed={processed} "
        f"answered={answered:g} degraded={degr:g}",
    )


def pipeline_drained(ctx: ScenarioContext) -> Verdict:
    occ = MetricsDelta.value("sentinel_pipeline_occupancy")
    rq = MetricsDelta.value("sentinel_resolver_queue_depth")
    pend = len(ctx.client._pending_ticks) if ctx.client is not None else 0
    return _v(
        "pipeline-drained",
        occ == 0.0 and rq == 0.0 and pend == 0,
        f"occupancy={occ:g} resolver_q={rq:g} pending_ticks={pend}",
    )


def no_stranded_futures(ctx: ScenarioContext) -> Verdict:
    stranded = sum(1 for f in ctx.futures if f is not None and not f.done())
    return _v(
        "no-stranded-futures",
        stranded == 0,
        f"{stranded}/{len(ctx.futures)} unresolved",
    )


def seg_drops_counted(ctx: ScenarioContext) -> Verdict:
    drops = ctx.metrics.delta("sentinel_seg_dropped_total")
    expect = ctx.extra.get("expect_seg_drops", True)
    ok = drops > 0 if expect else drops == 0
    return _v("seg-drops-counted", ok, f"drops={drops:g} expected={expect}")


def rules_intact(ctx: ScenarioContext) -> Verdict:
    ok = bool(ctx.extra.get("rules_intact_during_fault")) and bool(
        ctx.extra.get("rules_updated_after_heal")
    )
    return _v(
        "rules-intact",
        ok,
        f"during_fault={ctx.extra.get('rules_intact_during_fault')} "
        f"after_heal={ctx.extra.get('rules_updated_after_heal')}",
    )


def metric_deltas(ctx: ScenarioContext) -> Verdict:
    """Exact expected movement of named registry series over the run —
    the scenario's way of asserting WHICH counter (e.g. which labeled
    failure kind) recorded the injected fault."""
    want: Dict[str, float] = ctx.extra.get("expect_metric_deltas", {})
    got = ctx.metrics.deltas(want)
    bad = {k: (got[k], v) for k, v in want.items() if got[k] != v}
    return _v(
        "metric-deltas",
        not bad,
        "; ".join(f"{k}: got {g:g}, want {w:g}" for k, (g, w) in bad.items())
        or f"{len(want)} series as expected",
    )


def ladder_monotone(ctx: ScenarioContext) -> Verdict:
    """Degrade-ladder discipline over one run:
    ``extra["ladder_transitions"]`` is the controller's ordered
    ``(now_ms, from, to)`` list.  Every move must be exactly one rung
    (the shared hysteresis makes jumps impossible — a jump means a
    second transition path snuck in); a climb must have happened iff
    ``extra["expect_ladder_climb"]``; and ``extra["goodput_floor"]``
    (min rolling-window goodput while below FAIL_CLOSED) must stay
    positive — protection that zeroes goodput before fail-closed is
    just an outage with extra steps."""
    trans = ctx.extra.get("ladder_transitions", [])
    jumps = [t for t in trans if abs(t[2] - t[1]) != 1]
    climbed = any(t[2] > t[1] for t in trans)
    want_climb = ctx.extra.get("expect_ladder_climb", True)
    floor = ctx.extra.get("goodput_floor")
    ok = (
        not jumps
        and climbed == bool(want_climb)
        and (floor is None or floor > 0)
    )
    return _v(
        "ladder-monotone",
        ok,
        f"transitions={[(t[1], t[2]) for t in trans]} jumps={len(jumps)} "
        f"climbed={climbed} goodput_floor={floor}",
    )


def injected_as_planned(ctx: ScenarioContext) -> Verdict:
    return _v(
        "injected-as-planned",
        ctx.injected == ctx.expect_injected,
        f"observed={ctx.injected} expected={ctx.expect_injected}",
    )


def no_order_violations(ctx: ScenarioContext) -> Verdict:
    """The runtime lock witness (analysis/concurrency/witness.py) saw no
    lock-order inversion and no dynamic held→acquired edge the static
    tier-3 graph missed.  Trivially green when the witness was never
    installed — scenarios run unwitnessed by default; the witness matrix
    turns it on."""
    from sentinel_tpu.analysis.concurrency import witness as W

    ok, detail = W.verdict()
    return _v("no-order-violations", ok, detail)


#: name -> check; scenarios select by name, README documents each
CATALOG: Dict[str, Callable[[ScenarioContext], Verdict]] = {
    "verdict-accounting": verdict_accounting,
    "no-degraded-pass": no_degraded_pass,
    "degrade-hysteresis": degrade_hysteresis,
    "shard-degrade-hysteresis": shard_degrade_hysteresis,
    "token-conservation": token_conservation,
    "no-chunk-replay": no_chunk_replay,
    "pipeline-drained": pipeline_drained,
    "no-stranded-futures": no_stranded_futures,
    "seg-drops-counted": seg_drops_counted,
    "rules-intact": rules_intact,
    "metric-deltas": metric_deltas,
    "ladder-monotone": ladder_monotone,
    "injected-as-planned": injected_as_planned,
    "no-order-violations": no_order_violations,
}


def evaluate(names: List[str], ctx: ScenarioContext) -> List[Verdict]:
    """Run the named invariants in order; unknown names fail loudly (a
    scenario typo must not silently skip a safety check).  Any RED
    verdict triggers a flight-recorder bundle (obs/flight.py) so the
    state that produced the breach survives for post-mortem.

    ``no-order-violations`` is UNIVERSAL: every scenario evaluates it
    whether it names it or not (appended here, deterministically — the
    check reads the witness ledger, never the seed), because a lock
    acquired against the blessed order during ANY fault window is a
    latent deadlock regardless of what the scenario was probing."""
    out: List[Verdict] = []
    if "no-order-violations" not in names:
        names = list(names) + ["no-order-violations"]
    for n in names:
        chk = CATALOG.get(n)
        if chk is None:
            out.append(_v(n, False, "unknown invariant"))
            continue
        try:
            out.append(chk(ctx))
        except Exception as e:  # noqa: BLE001 — a crashed monitor is a RED verdict, never a skipped one
            out.append(_v(n, False, f"monitor crashed: {type(e).__name__}: {e}"))
    breached = [v.name for v in out if not v.ok]
    if breached:
        from sentinel_tpu.obs.flight import FLIGHT

        FLIGHT.note("invariant.breach", invariants=breached)
        FLIGHT.trigger("invariant-breach")
    return out
