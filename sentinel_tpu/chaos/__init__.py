"""sentinel_tpu.chaos — deterministic fault-injection plane.

Three pieces, mirroring the obs plane's structure:

  * ``failpoints`` — named injection sites threaded through transport,
    cluster, runtime, parallel, and datasource code; one flag check when
    disarmed, seeded deterministic actions when armed
  * ``plans`` — declarative fault plans (what/where/when), JSON
    round-trippable so any run replays from its serialized plan + seed
  * ``invariants`` + ``runner`` — safety monitors over ``obs.REGISTRY``
    metrics and client state, plus built-in scenarios driving a real
    pipelined ``SentinelClient`` (and optionally a cluster token server
    and a remote-shard pair) under a plan

CLI: ``python -m sentinel_tpu.chaos --seed 7`` runs every built-in
scenario and reports per-scenario invariant verdicts and injected-event
counts (identical for identical seeds — the determinism contract).

NOTE: importing this package must stay cheap — ``failpoints`` is
imported by hot product modules at process start.  Heavy imports (jax,
the runner's scenarios) stay inside ``runner``/``__main__``.
"""

from sentinel_tpu.chaos import failpoints
from sentinel_tpu.chaos.failpoints import arm, armed, catalog, disarm, hit, pipe, skew_ms
from sentinel_tpu.chaos.plans import ACTIONS, FaultPlan, FaultSpec

__all__ = [
    "ACTIONS",
    "FaultPlan",
    "FaultSpec",
    "arm",
    "armed",
    "catalog",
    "disarm",
    "failpoints",
    "hit",
    "pipe",
    "skew_ms",
]
