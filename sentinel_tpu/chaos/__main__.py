"""CLI: ``python -m sentinel_tpu.chaos [--seed N] [--scenario NAME ...]``.

Runs the built-in chaos scenarios under their seeded fault plans and
prints per-scenario invariant verdicts plus injected-event counts.
Exit status 0 iff every invariant of every selected scenario is green.

Options:
  --seed N              plan seed (default 7); identical seeds inject
                        identical per-scenario event counts
  --scenario NAME       run only NAME (repeatable); default: all
  --fast                only the tier-1 CI subset
  --json                machine-readable report (the determinism check
                        diffs this)
  --check-determinism   run everything twice and fail on any injected-
                        count difference
  --list                list scenarios and exit
  --sites               list registered failpoint sites and exit
  --plan FILE           print a scenario-free replay note: validates the
                        JSON plan against the registered sites
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m sentinel_tpu.chaos")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--scenario", action="append", default=None)
    ap.add_argument("--fast", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--check-determinism", action="store_true")
    ap.add_argument("--list", action="store_true", dest="list_scenarios")
    ap.add_argument("--sites", action="store_true")
    ap.add_argument("--plan", default=None)
    args = ap.parse_args(argv)

    from sentinel_tpu.chaos import failpoints as FP

    # sites register at module import; pull in every instrumented layer so
    # the catalog (and plan validation) is complete regardless of what the
    # process happened to import already
    import sentinel_tpu.cluster.client  # noqa: F401
    import sentinel_tpu.cluster.server  # noqa: F401
    import sentinel_tpu.cluster.shard  # noqa: F401
    import sentinel_tpu.datasource.stores  # noqa: F401
    import sentinel_tpu.parallel.remote_shard  # noqa: F401
    import sentinel_tpu.runtime.client  # noqa: F401
    import sentinel_tpu.transport.heartbeat  # noqa: F401
    import sentinel_tpu.transport.http_server  # noqa: F401

    if args.sites:
        for name, site in sorted(FP.catalog().items()):
            print(f"{name:32s} [{','.join(site.kinds)}] {site.desc}")
        return 0

    if args.plan:
        from sentinel_tpu.chaos.plans import FaultPlan

        with open(args.plan) as f:
            plan = FaultPlan.from_json(f.read())
        plan.validate(FP.catalog())
        print(
            f"plan {plan.name or '<unnamed>'}: seed={plan.seed}, "
            f"{len(plan.faults)} fault spec(s) — valid against "
            f"{len(FP.catalog())} registered sites"
        )
        return 0

    from sentinel_tpu.chaos.runner import SCENARIOS, report, run_all

    if args.list_scenarios:
        for name, s in SCENARIOS.items():
            tags = []
            if s.fast:
                tags.append("fast")
            if s.eager:
                tags.append("eager")
            print(f"{name:24s} [{','.join(tags) or '-'}] {s.description}")
        return 0

    unknown = [n for n in (args.scenario or ()) if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {unknown}", file=sys.stderr)
        return 2

    results = run_all(args.seed, names=args.scenario, fast_only=args.fast)
    if args.check_determinism:
        again = run_all(args.seed, names=args.scenario, fast_only=args.fast)
        mismatches = {
            a.name: (a.injected, b.injected)
            for a, b in zip(results, again)
            if a.injected != b.injected
        }
        if mismatches:
            print(report(results, as_json=args.as_json))
            print(f"DETERMINISM VIOLATION: {json.dumps(mismatches, indent=2)}")
            return 1
        print(report(results, as_json=args.as_json))
        print("determinism: two runs injected identical per-scenario counts")
        return 0 if all(r.ok for r in results) else 1
    print(report(results, as_json=args.as_json))
    return 0 if all(r.ok for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
