"""Static configuration layering.

Equivalent of the reference's SentinelConfig/SentinelConfigLoader
(sentinel-core/.../config/SentinelConfig.java:49-63,
SentinelConfigLoader.java): values resolve, highest priority first, from

  1. programmatic overrides (``set_config``)
  2. environment variables  (``CSP_SENTINEL_*`` — dots become underscores)
  3. a properties file      (``sentinel.properties`` in cwd, or the path in
                             ``CSP_SENTINEL_CONFIG_FILE``)
  4. built-in defaults

Also holds the EngineConfig dataclass — the capacity/shape knobs of the
device engine (the analog of Constants.MAX_SLOT_CHAIN_SIZE=6000 and the
window-shape defaults in StatisticNode.java:96-103).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

_DEFAULTS: Dict[str, str] = {
    "csp.sentinel.app.name": "sentinel-tpu-app",
    "csp.sentinel.app.type": "0",
    "csp.sentinel.metric.file.single.size": str(1024 * 1024 * 50),
    "csp.sentinel.metric.file.total.count": "6",
    "csp.sentinel.flow.cold.factor": "3",
    "csp.sentinel.statistic.max.rt": "5000",  # SentinelConfig.java:63
    "csp.sentinel.log.dir": os.path.expanduser("~/logs/csp/"),
    "csp.sentinel.api.port": "8719",  # TransportConfig default
    "csp.sentinel.dashboard.server": "",
    "csp.sentinel.heartbeat.interval.ms": "10000",
}

_overrides: Dict[str, str] = {}
_overrides_lock = threading.Lock()
_file_props: Optional[Dict[str, str]] = None


def _load_file_props() -> Dict[str, str]:
    global _file_props
    if _file_props is not None:
        return _file_props
    path = os.environ.get("CSP_SENTINEL_CONFIG_FILE", "sentinel.properties")
    props: Dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, _, v = line.partition("=")
                props[k.strip()] = v.strip()
    except OSError:
        pass
    _file_props = props
    return props


def get_config(key: str, default: Optional[str] = None) -> Optional[str]:
    if key in _overrides:
        return _overrides[key]
    env_key = key.upper().replace(".", "_")
    if env_key in os.environ:
        return os.environ[env_key]
    props = _load_file_props()
    if key in props:
        return props[key]
    if key in _DEFAULTS:
        return _DEFAULTS[key]
    return default


def get_int(key: str, default: int = 0) -> int:
    v = get_config(key)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def set_config(key: str, value: Any) -> None:
    with _overrides_lock:
        _overrides[key] = str(value)


def reset_overrides() -> None:
    with _overrides_lock:
        _overrides.clear()


def app_name() -> str:
    return get_config("csp.sentinel.app.name") or "sentinel-tpu-app"


@dataclass(frozen=True)
class EngineConfig:
    """Capacity & window-shape configuration of the device engine.

    Defaults mirror the reference where one exists:
    - second window 2 x 500 ms, minute window 60 x 1 s
      (StatisticNode.java:96-103)
    - max_resources generalizes MAX_SLOT_CHAIN_SIZE (Constants.java:37)
      from 6,000 to 2^17; beyond capacity new resources degrade to
      pass-through, same as lookProcessChain returning null
      (CtSph.java:200-205).
    """

    # id spaces
    max_resources: int = 1 << 17  # rows [0, max_resources) = per-resource nodes
    max_nodes: int = 1 << 18  # total stat rows incl. origin/context nodes
    # rule capacity (structure-of-arrays tensors)
    max_flow_rules: int = 4096
    max_degrade_rules: int = 1024
    max_param_rules: int = 32
    flow_rules_per_resource: int = 4
    degrade_rules_per_resource: int = 4
    param_rules_per_resource: int = 2
    authority_origins_per_resource: int = 8
    # batch shape
    batch_size: int = 2048
    complete_batch_size: int = 2048
    # windows
    second_sample_count: int = 2
    second_window_ms: int = 500
    minute_sample_count: int = 60
    minute_window_ms: int = 1000
    enable_minute_window: bool = True
    # circuit-breaker window buckets (per-rule interval / cb_sample_count)
    cb_sample_count: int = 2
    # param-flow hashed-row store (ops/param.py v2): rows are
    # hash(rule, value) in [0, param_width) per depth; all rules share one
    # bucket grid of param_sample_count x param_bucket_ms; distinct rule
    # durations group into <= param_classes window classes; each entry
    # carries param_dims hashed argument lanes
    param_depth: int = 2
    param_width: int = 1 << 14
    param_sample_count: int = 8
    param_bucket_ms: int = 500
    param_classes: int = 4
    param_dims: int = 2
    # digit planes of the hot-param windowed estimate gather: estimates
    # saturate at 256^d - 1, so thresholds >= that per window cannot trip
    # (enforcement stays EXACT for thresholds below it — saturation only
    # over-estimates).  Default 3 preserves the historical ~16.7M cap;
    # deployments with per-value thresholds under 65535/window can set 2
    # for 1/3 less gather cost (the benchmark config does).
    param_est_digits: int = 3
    # top-k tracking for hot params
    topk_k: int = 32
    # statistic max RT clamp (SentinelConfig.java:63)
    statistic_max_rt: int = 5000
    # memory-access strategy: True routes every big-table gather/scatter in
    # the tick through one-hot MXU contractions (ops/tables.py) — the TPU
    # path; False uses plain XLA gather/scatter — the CPU/test path
    use_mxu_tables: bool = False
    mxu_n_lo: int = 512
    # fuse the tick's effects-phase scatters (stat windows + circuit
    # breakers + sketch + per-rule scatters) into one Pallas megakernel per
    # phase (ops/fused.py).  Requires use_mxu_tables; bit-identical to the
    # unfused MXU path within the max_batch_count envelope.  On non-TPU
    # backends the kernels run in interpret mode (tests); enable for real
    # ticks only on TPU.
    fused_effects: bool = False
    # largest per-item token count the fused kernels carry exactly (one
    # base-256 digit plane per byte; every MXU dot streams the whole item
    # axis, so each extra digit costs a full pass).  The reference's
    # acquireCount is 1 in practice (SphU.entry(name) default); clients
    # clamp larger counts at entry.  The unfused paths remain exact to
    # 65535 regardless.
    max_batch_count: int = 255
    # segment-compacted effects (ops/engine_seg.py): contract scatter
    # payloads per key-run segment instead of per item — ~10x fewer MXU
    # digit-dot items on Zipf traffic when the host presorts batches by
    # resource.  Requires fused_effects; falls back per-tick to the
    # per-item kernels when live segments exceed seg_u (bit-identical
    # either way, sorted or not).
    seg_effects: bool = False
    seg_u: int = 0  # compacted-axis capacity; 0 = auto (~B/8 + B/256)
    # True compiles BOTH the compacted and per-item paths (effects AND
    # checks) and picks per tick (lax.cond on live-segment count) — always
    # exact, but the check-phase cond boundary alone costs ~1.4 ms at
    # B=128K in operand/result copies.  False compiles ONLY the compacted
    # path, cond-free: when live segments exceed seg_u, overflow segments'
    # EFFECTS are dropped (windows under-count), their items' VERDICTS
    # fail closed as system rejections (never pass unchecked), and
    # TickOutput.seg_dropped reports the dropped item count.  Use only
    # when the caller presorts batches and sizes seg_u with headroom;
    # also halves the compiled code size, which the tunnel-attached
    # benchmark needs (program-cache thrash)
    seg_fallback: bool = True
    # compile ONLY the segmented-scan ranks in the seg check phase (no
    # lax.cond to the sort-based rank kernels — each such cond boundary
    # costs ~0.3-0.8 ms at B=128K).  Caller contract: batches are
    # presorted by resource AND every enabled flow rule is DIRECT with
    # limitApp "default" (rank keys contiguous).  The engine still
    # verifies the contract at runtime and FAILS CLOSED loudly (blocks
    # flow-ruled / tail-ruled items, elects no probes) instead of
    # misranking silently; a caller whose rules stop qualifying must
    # clear the flag and re-jit.  Requires seg_effects.
    seg_static_ranks: bool = False
    # global stats sketch: resources beyond the exact row space get sketch
    # ids and windowed CMS observability instead of pass-through (ops/
    # gsketch.py) — tick cost independent of resource count
    sketch_stats: bool = False
    sketch_depth: int = 2
    sketch_width: int = 1 << 14  # CMS eps = e/width of window volume
    sketch_capacity: int = 1 << 22  # max interned sketch resources
    # SALSA self-adjusting sketch tier (sentinel_tpu/sketch/salsa.py):
    # int8 cells packed 4-per-int32 that merge with neighbors on
    # saturation (width bitmap tracked per word), plus O(1) windowed
    # reads from incrementally maintained running sums — ~4x the width
    # per HBM byte vs the plain int32 CMS and read cost independent of
    # the window shape.  False falls back to the seed ops/gsketch.py.
    sketch_salsa: bool = True
    # sketch tier window shape; 0 inherits the second window.  The 1 M+
    # tier runs minute-scale windows here (e.g. 60 x 1000 ms) without
    # touching the exact tier's shape; tail-rule thresholds scale by the
    # interval (rule_tensors.compile_tail_flow_rules)
    sketch_sample_count: int = 0
    sketch_window_ms: int = 0
    # slack-window maintenance for the sketch tier (arXiv 1703.01166):
    # batch bucket rotation/expiry to every ceil(slack_frac * sample_count)
    # buckets, carrying slack_buckets - 1 extra physical ring columns so
    # the write cursor only reaches already-purged columns.  Expired
    # buckets linger in the running sums for up to that many bucket
    # lengths — a bounded OVERESTIMATE (fail-closed).  At the default
    # second-window fallback shape (nb=2) this rounds to g=1 (exact, no
    # extra columns); at the minute-scale tier (nb=60) it batches expiry
    # to every 3 buckets.  The EXACT second/minute windows never take
    # slack — their WindowConfig pins slack_frac=0.
    sketch_slack_frac: float = 0.05
    # hot-set manager (sentinel_tpu/sketch/hotset.py): the tick emits the
    # top-K sketched resources of each batch by windowed pass estimate
    # (TickOutput.hot, device top_k over ids the batch actually carried);
    # the host manager promotes heavy ones into exact rows and demotes
    # cold promoted rows back to the tail.  0 disables emission (the
    # traced program is unchanged).
    hotset_k: int = 32
    hotset_eval_s: float = 1.0  # manager evaluation cadence (host seconds)
    hotset_promote_qps: float = 100.0  # windowed pass estimate to qualify
    hotset_demote_qps: float = 1.0  # exact windowed pass to demote below
    hotset_cooldown_s: float = 30.0  # re-promotion hysteresis after demote
    # device-resident telemetry (ops/engine._device_stats): the tick emits
    # one compact float32 stats row (verdict mix by block reason, admitted/
    # blocked token sums, seg occupancy, adaptive-ceiling utilization, and
    # the ENTRY node's O(1) sliding-window pass/RT sums) alongside the
    # verdicts — the client folds it into the obs registry instead of
    # re-deriving the same numbers from a host-side verdict scan.  The row
    # is engine.N_STATS floats (<= 256 bytes of extra readback per tick);
    # off => TickOutput.stats is None and the tick program is unchanged
    device_telemetry: bool = True
    # per-resource timeline rows (obs/timeline.py): with device telemetry
    # on, each tick additionally emits a float32 [K, TL_COLS] matrix —
    # the top-K resource rows by windowed pass+block (selected ON-DEVICE
    # from the O(1) sliding-window sums the tick already maintains) with
    # their CURRENT second-window bucket's cumulative pass/block/success/
    # exception/rt/concurrency.  The host folds successive bucket reads
    # into exact per-second records and serves them from an indexed
    # on-disk metric log (GET /api/metric).  Clamped to the resource-row
    # space; 0 disables the matrix (TickOutput.res_stats is None and the
    # traced program is unchanged vs. timeline off).  K*32 bytes of extra
    # readback per tick (4 KiB at the default 128).
    timeline_k: int = 128
    # packed wire format (ops/wire.py): the tick returns ONE flat uint32
    # buffer — 3-bit-packed verdict bitmap + sparse PASS_WAIT sidecar +
    # bitcast telemetry/timeline/hot blocks behind a checksummed header —
    # instead of four separate device arrays, and the batch's low-range
    # columns (prio/inbound/pre_verdict, clamped counts) travel at int8/
    # int16 and widen on-device.  Tri-state: None resolves to False here
    # (direct tick() callers and the traced legacy entries keep the
    # classic TickOutput) and to True in SentinelClient (the client path
    # is where the wire is the bottleneck).  TickOutput.wait_ms survives
    # as the sidecar-overflow escape hatch; everything else rides the
    # fused buffer.
    packed_wire: Optional[bool] = None
    # verdict provenance plane (ops/wire.py explain section + obs/
    # explain.py): with the packed wire on, the tick additionally packs
    # up to explain_k fixed-point "explain" records — one per BLOCKED
    # item: rule slot + verdict kind + sketch-tier flag, observed value
    # vs threshold — into a separately-checksummed trailing section of
    # the SAME fused readback.  Corruption of that section drops the
    # explanations for the tick (fail-OPEN for the explanation only);
    # the main section's checksum still fails the verdicts CLOSED.
    # 0 disables the section (wire layout and traced program unchanged);
    # ignored without packed_wire (provenance rides only the fused wire).
    explain_k: int = 32

    def __post_init__(self):
        # the native completion ring transports exactly four hot-param
        # release lanes (sx_event.aux0..aux3); a wider engine batch would
        # silently leak THREAD-grade concurrency for the extra lanes, so
        # reject it here instead (ParamFlowChecker.java:78 dispatches on
        # arbitrary paramIdx — four distinct indices per resource covers
        # it; beyond that, rule_tensors.param_lanes warns and drops)
        if not (1 <= self.param_dims <= 4):
            raise ValueError(
                f"param_dims must be 1..4 (ring transport carries four "
                f"release lanes); got {self.param_dims}"
            )
        # seg_effects rides the fused megakernels; without them the flag
        # would silently do nothing (tick gates on seg_effects AND fused)
        if self.seg_effects and not self.fused_effects:
            raise ValueError(
                "seg_effects=True requires fused_effects=True (the "
                "segment-compacted phases replace the fused megakernels, "
                "not the plain scatter path)"
            )
        if self.seg_static_ranks and not self.seg_effects:
            raise ValueError(
                "seg_static_ranks=True requires seg_effects=True (it "
                "specializes the segment check phase's rank scans)"
            )
        if self.sketch_stats and self.sketch_salsa and self.sketch_width % 64:
            raise ValueError(
                "sketch_salsa packs 4 int8 lanes/word and 16 words per "
                "bitmap int32, so sketch_width must be a multiple of 64; "
                f"got {self.sketch_width}"
            )
        if self.sketch_stats and self.node_rows + self.sketch_capacity >= 1 << 24:
            # TickOutput.hot rides sketch ids through a float32 column
            # (engine._device_hot_candidates); an id at or above 2^24
            # would round and fold/promote the WRONG resource
            raise ValueError(
                "node_rows + sketch_capacity must stay below 2^24 (sketch "
                "ids must be float32-exact for the hot-candidate rows); "
                f"got {self.node_rows} + {self.sketch_capacity}"
            )

    @property
    def sketch_shape(self) -> tuple:
        """(sample_count, window_ms) of the sketch tier's bucket grid —
        the sketch knobs when set, else the second window's shape."""
        return (
            self.sketch_sample_count or self.second_sample_count,
            self.sketch_window_ms or self.second_window_ms,
        )

    # dtype policy: counters int32, rt sums float32
    @property
    def count_digits(self) -> int:
        """Base-256 digit planes for count-valued scatters in the fused
        kernels (ops/fused.py)."""
        return max(1, (int(self.max_batch_count).bit_length() + 7) // 8)

    @property
    def rt_digits(self) -> int:
        """Digit planes for the quantized (1/8 ms) RT scatter plane."""
        return max(1, (int(self.statistic_max_rt * 8).bit_length() + 7) // 8)

    @property
    def entry_node_row(self) -> int:
        """Reserved stat row for the global inbound ENTRY_NODE
        (Constants.ENTRY_NODE in the reference)."""
        return 0

    @property
    def trash_row(self) -> int:
        """Scatter target for padded/invalid items (first padding row).

        Using an explicit trash row (instead of out-of-bounds dropping)
        keeps every gather/scatter index in range.
        """
        return self.max_nodes

    @property
    def node_rows(self) -> int:
        # max_nodes + 8 keeps the row axis divisible by typical mesh sizes
        # (max_nodes is a power of two) so it shards evenly; rows
        # [max_nodes, max_nodes+8) are trash/padding.
        return self.max_nodes + 8


DEFAULT_ENGINE_CONFIG = EngineConfig()


def _backend_is_tpu() -> bool:
    """True when the live JAX backend is a TPU — specifically TPU, not
    merely non-CPU: the fused Pallas kernels compile via Mosaic only on
    TPU and would run in interpret mode anywhere else (ops/fused.py), so
    a GPU backend must keep the plain scatter path.

    Initializes the backend on first call — the client constructor calls
    this exactly where it would first touch jax anyway."""
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def platform_engine_config(**kw) -> EngineConfig:
    """EngineConfig whose memory-access strategy matches the live JAX
    backend.  On TPU the fast path is ON by default — one-hot MXU table
    reads (`use_mxu_tables`), fused Pallas effects megakernels
    (`fused_effects`), and segment-compacted aggregation (`seg_effects`)
    with the always-exact capacity fallback (`seg_fallback=True`, the
    engine per-tick lax.conds to the per-item kernels when live segments
    exceed `seg_u`).  On CPU (tests, dev laptops) everything stays on the
    plain scatter path, where those flags would only add interpret-mode
    Pallas overhead.

    This is the runtime client's default config factory: ``st.entry()``
    on a TPU serves the same engine `bench.py` measures, the way the
    reference's measured artifact IS its product hot path
    (sentinel-core/.../CtSph.java:117-157 — the JMH harness calls plain
    ``SphU.entry``).  Explicit keyword overrides win."""
    on_tpu = _backend_is_tpu()
    base = dict(
        use_mxu_tables=on_tpu,
        fused_effects=on_tpu,
        seg_effects=on_tpu,
        seg_fallback=True,
    )
    base.update(kw)
    return EngineConfig(**base)


def small_engine_config(**kw) -> EngineConfig:
    """A tiny config for tests."""
    base = dict(
        max_resources=64,
        max_nodes=128,
        max_flow_rules=64,
        max_degrade_rules=32,
        max_param_rules=8,
        batch_size=64,
        complete_batch_size=64,
        param_width=512,
    )
    base.update(kw)
    return EngineConfig(**base)
