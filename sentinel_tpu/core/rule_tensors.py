"""Rule compilation: rule objects → structure-of-arrays device tensors.

The analog of FlowRuleUtil.buildFlowRuleMap/generateRater
(slots/block/flow/FlowRuleUtil.java:45-136): when rules are (re)loaded, the
whole rule set is recompiled into dense tensors indexed by *rule slot*, plus
per-resource lookup tables ``res_* : int32[max_resources, K]`` mapping a
resource id to its rule slots.  Controller state (warm-up token bucket,
leaky-bucket latest-passed-time) is keyed by rule slot, mirroring the
reference's one-controller-instance-per-rule design
(TrafficShapingController per FlowRule).

Every tensor family has one extra "trash" slot at index ``max_*`` with
``enabled=False`` so lookups never need bounds branches.
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Optional

import numpy as np

from sentinel_tpu.core import rules as R
from sentinel_tpu.core.config import EngineConfig

# limit_app encodings
LIMIT_ANY = -1  # "default" — matches every origin
LIMIT_OTHER = -2  # "other" — matches origins not named by any sibling rule


class FlowRuleTensors(NamedTuple):
    enabled: np.ndarray  # bool [F+1]
    res: np.ndarray  # int32 [F+1]
    grade: np.ndarray  # int32 — 0 thread / 1 qps
    count: np.ndarray  # float32 threshold
    behavior: np.ndarray  # int32 control behavior
    strategy: np.ndarray  # int32 direct/relate/chain
    ref_node: np.ndarray  # int32 node row for RELATE (-1 = none)
    ref_ctx: np.ndarray  # int32 interned context name for CHAIN (-1 = none)
    limit_app: np.ndarray  # int32 (LIMIT_ANY / LIMIT_OTHER / origin id)
    max_queue_ms: np.ndarray  # int32 (rate-limiter queueing budget)
    cluster_mode: np.ndarray  # bool
    # warm-up precomputation (WarmUpController.java:103-112)
    warning_token: np.ndarray  # float32
    max_token: np.ndarray  # float32
    slope: np.ndarray  # float32
    cold_factor: np.ndarray  # float32
    res_rules: np.ndarray  # int32 [max_resources, K] rule slots (trash padded)


class DegradeRuleTensors(NamedTuple):
    enabled: np.ndarray  # bool [D+1]
    res: np.ndarray  # int32
    grade: np.ndarray  # int32 (0 slow-ratio, 1 error-ratio, 2 error-count)
    count: np.ndarray  # float32 (max RT / ratio / count)
    slow_ratio: np.ndarray  # float32
    retry_timeout_ms: np.ndarray  # int32
    min_request: np.ndarray  # int32
    window_ms: np.ndarray  # int32 per-rule bucket length (= statInterval / nb)
    res_cbs: np.ndarray  # int32 [max_resources, KD]


class ParamRuleTensors(NamedTuple):
    enabled: np.ndarray  # bool [P+1]
    res: np.ndarray  # int32
    grade: np.ndarray  # int32 — GRADE_QPS (windowed budget) or GRADE_THREAD
    threshold: np.ndarray  # float32 — count * duration + burst (window budget)
    cls: np.ndarray  # int32 [P+1] duration-class index (ops/param.py v2)
    lane: np.ndarray  # int32 [P+1] which param_hash lane the rule reads (-1 none)
    item_hash: np.ndarray  # int32 [P+1, KI] per-value exceptions
    item_threshold: np.ndarray  # float32 [P+1, KI]
    res_params: np.ndarray  # int32 [max_resources, KP]
    class_k: np.ndarray  # int32 [param_classes] window length (buckets) per class


class TailFlowTensors(NamedTuple):
    """Approximate QPS thresholds for SKETCH-TAIL resources (ids beyond
    the exact row space).  Thresholds live in depth hashed cells (same
    hashes as the observability sketch, ops/gsketch.py); a lookup takes
    max-over-depth, so a collision in one depth row cannot tighten an
    unruled resource's budget — only a resource colliding with a ruled
    cell in EVERY depth can be falsely limited:

        P(false limit) <= (n_tail_rules / width) ** depth        (delta)

    and enforcement reads the sketch's windowed pass CMS, whose classic
    overestimate over-blocks by at most eps = e/width of window volume —
    both errors in the conservative direction (FlowRuleChecker.java:85
    semantics with bounded approximation instead of a hard 6,000-resource
    cap)."""

    thr: np.ndarray  # float32 [sketch_depth, sketch_width]; >= TAIL_UNRULED = unruled


#: finite "unruled" sentinel — +inf would turn the MXU one-hot contraction
#: into 0*inf = NaN and silently disable tail enforcement on TPU; 2e38 is
#: bf16/f32-representable and no real threshold approaches it
TAIL_UNRULED = 2.0e38


def compile_tail_flow_rules(
    tail_rules: List[tuple], cfg: EngineConfig
) -> TailFlowTensors:
    """tail_rules: [(sketch_resource_id, count), ...] — QPS grade only
    (other grades/behaviors require exact windows; they promote or drop
    with a warning at the call site).

    ``count`` is a QPS; the compiled cell threshold is count * the sketch
    tier's window interval in seconds, since enforcement compares it
    against the sketch's WINDOWED pass sum (a minute-window sketch tier
    must admit 60x the per-second rate per interval).  Vectorized over
    rules — the 1 M-ruled-resource tier compiles in one numpy pass, not a
    per-rule Python loop."""
    import numpy as _np

    thr = np.full((cfg.sketch_depth, cfg.sketch_width), TAIL_UNRULED, dtype=np.float32)
    if tail_rules:
        import jax.numpy as _jnp

        from sentinel_tpu.ops.param import cms_cell

        nb, wms = cfg.sketch_shape
        scale = (nb * wms) / 1000.0
        ids = _np.asarray([rid for rid, _ in tail_rules], dtype=_np.int32)
        counts = _np.asarray(
            [count for _rid, count in tail_rules], dtype=_np.float32
        ) * _np.float32(scale)
        # the enforcement read clamps the windowed estimate at 2^24 - 1
        # (estimate_plane_mxu), so a scaled threshold at or above the
        # clamp could never trip — clamp thresholds just BELOW it instead
        # (the rule then enforces at the cap, conservative, rather than
        # silently not at all)
        counts = _np.minimum(counts, _np.float32((1 << 24) - 2))
        cols = _np.asarray(
            cms_cell(_jnp.asarray(ids), cfg.sketch_depth, cfg.sketch_width)
        )
        for d in range(cfg.sketch_depth):
            # colliding rules take the MIN threshold per cell (conservative)
            _np.minimum.at(thr[d], cols[:, d], counts)
    return TailFlowTensors(thr=thr)


class AuthorityTensors(NamedTuple):
    mode: np.ndarray  # int32 [max_resources] 0 none / 1 white / 2 black
    origins: np.ndarray  # int32 [max_resources, KA] (-9 = empty)


class SystemTensors(NamedTuple):
    # scalar thresholds, negative = unset (SystemRuleManager.java:68-97)
    load: np.ndarray  # float32 []
    cpu: np.ndarray
    qps: np.ndarray
    avg_rt: np.ndarray
    max_thread: np.ndarray


AUTH_EMPTY = -9  # never a valid origin id (-1 means "no origin")

_PARAM_ITEM_SLOTS = 8


def compile_flow_rules(
    rules: List[R.FlowRule], cfg: EngineConfig, registry
) -> FlowRuleTensors:
    F = cfg.max_flow_rules
    K = cfg.flow_rules_per_resource
    t = FlowRuleTensors(
        enabled=np.zeros(F + 1, dtype=bool),
        res=np.zeros(F + 1, dtype=np.int32),
        grade=np.full(F + 1, R.GRADE_QPS, dtype=np.int32),
        count=np.zeros(F + 1, dtype=np.float32),
        behavior=np.zeros(F + 1, dtype=np.int32),
        strategy=np.zeros(F + 1, dtype=np.int32),
        ref_node=np.full(F + 1, -1, dtype=np.int32),
        ref_ctx=np.full(F + 1, -1, dtype=np.int32),
        limit_app=np.full(F + 1, LIMIT_ANY, dtype=np.int32),
        max_queue_ms=np.full(F + 1, 500, dtype=np.int32),
        cluster_mode=np.zeros(F + 1, dtype=bool),
        warning_token=np.zeros(F + 1, dtype=np.float32),
        max_token=np.zeros(F + 1, dtype=np.float32),
        slope=np.zeros(F + 1, dtype=np.float32),
        cold_factor=np.full(F + 1, 3.0, dtype=np.float32),
        res_rules=np.full((cfg.max_resources + 1, K), F, dtype=np.int32),
    )
    slot = 0
    per_res_count: dict = {}
    for rule in rules:
        if not rule.is_valid() or slot >= F:
            continue
        rid = registry.resource_id(rule.resource)
        if rid is None or rid > cfg.max_resources:
            # no exact row (sketch-id / pass-through resource) -> the rule
            # cannot be enforced; observability continues via the sketch
            continue
        k = per_res_count.get(rid, 0)
        if k >= K:
            continue  # per-resource rule capacity
        per_res_count[rid] = k + 1
        t.res_rules[rid, k] = slot

        t.enabled[slot] = True
        t.res[slot] = rid
        t.grade[slot] = rule.grade
        t.count[slot] = rule.count
        t.behavior[slot] = rule.control_behavior
        t.strategy[slot] = rule.strategy
        t.max_queue_ms[slot] = rule.max_queueing_time_ms
        t.cluster_mode[slot] = rule.cluster_mode

        if rule.strategy == R.STRATEGY_RELATE and rule.ref_resource:
            ref = registry.resource_id(rule.ref_resource)
            t.ref_node[slot] = ref if ref is not None else -1
        elif rule.strategy == R.STRATEGY_CHAIN and rule.ref_resource:
            # CHAIN: rule applies when the item's context name equals
            # refResource (FlowRuleChecker.selectReferenceNode)
            t.ref_ctx[slot] = registry.context_id(rule.ref_resource)

        la = rule.limit_app or R.LIMIT_APP_DEFAULT
        if la == R.LIMIT_APP_DEFAULT:
            t.limit_app[slot] = LIMIT_ANY
        elif la == R.LIMIT_APP_OTHER:
            t.limit_app[slot] = LIMIT_OTHER
        else:
            t.limit_app[slot] = registry.origin_id(la)

        # Guava-style warm-up precomputation (WarmUpController.java:103-112)
        cf = max(float(rule.cold_factor), 2.0)
        count = max(float(rule.count), 1e-9)
        wp = max(int(rule.warm_up_period_sec), 1)
        warning = (wp * count) / (cf - 1.0)
        max_tok = warning + 2.0 * wp * count / (1.0 + cf)
        slope_v = (cf - 1.0) / count / max(max_tok - warning, 1e-9)
        t.warning_token[slot] = warning
        t.max_token[slot] = max_tok
        t.slope[slot] = slope_v
        t.cold_factor[slot] = cf
        slot += 1
    return t


def compile_degrade_rules(
    rules: List[R.DegradeRule], cfg: EngineConfig, registry
) -> DegradeRuleTensors:
    D = cfg.max_degrade_rules
    KD = cfg.degrade_rules_per_resource
    nb = cfg.cb_sample_count
    t = DegradeRuleTensors(
        enabled=np.zeros(D + 1, dtype=bool),
        res=np.zeros(D + 1, dtype=np.int32),
        grade=np.zeros(D + 1, dtype=np.int32),
        count=np.zeros(D + 1, dtype=np.float32),
        slow_ratio=np.ones(D + 1, dtype=np.float32),
        retry_timeout_ms=np.full(D + 1, 1000, dtype=np.int32),
        min_request=np.full(D + 1, 5, dtype=np.int32),
        window_ms=np.full(D + 1, 1000 // nb, dtype=np.int32),
        res_cbs=np.full((cfg.max_resources + 1, KD), D, dtype=np.int32),
    )
    slot = 0
    per_res_count: dict = {}
    for rule in rules:
        if not rule.is_valid() or slot >= D:
            continue
        rid = registry.resource_id(rule.resource)
        if rid is None or rid > cfg.max_resources:
            # no exact row (sketch-id / pass-through resource) -> the rule
            # cannot be enforced; observability continues via the sketch
            continue
        k = per_res_count.get(rid, 0)
        if k >= KD:
            continue
        per_res_count[rid] = k + 1
        t.res_cbs[rid, k] = slot
        t.enabled[slot] = True
        t.res[slot] = rid
        t.grade[slot] = rule.grade
        t.count[slot] = rule.count
        t.slow_ratio[slot] = rule.slow_ratio_threshold
        t.retry_timeout_ms[slot] = rule.time_window * 1000
        t.min_request[slot] = rule.min_request_amount
        t.window_ms[slot] = max(rule.stat_interval_ms // nb, 1)
        slot += 1
    return t


def hash_param(value) -> int:
    """Stable 31-bit hash of a parameter value (int or str).

    Kept host-side so the device only ever sees int32 hashes; the native
    extension (sentinel_tpu/native) accelerates the str path.
    """
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, int):
        h = (value * 0x9E3779B1) & 0x7FFFFFFF
    else:
        h = 2166136261
        for b in str(value).encode("utf-8"):
            h = ((h ^ b) * 16777619) & 0xFFFFFFFF
        h &= 0x7FFFFFFF
    return h if h != 0 else 1  # 0 is reserved for "no parameter"


def param_lanes(
    rules: List[R.ParamFlowRule], max_dims: int, priority: List[R.ParamFlowRule] = ()
) -> dict:
    """resource -> ordered distinct param_idx list (length <= max_dims).

    Each entry hashes its first ``max_dims`` *distinct rule indices* into
    lanes; a rule reads the lane its param_idx was assigned.  ``priority``
    rules (gateway) claim lanes first.  The host client derives its
    per-entry hash lanes from the SAME function so engine and host agree
    (ParamFlowChecker.java:78 dispatches on paramIdx per rule)."""
    lanes: dict = {}
    for r in list(priority) + [r for r in rules if r not in priority]:
        ls = lanes.setdefault(r.resource, [])
        if r.param_idx not in ls and len(ls) < max_dims:
            ls.append(r.param_idx)
    return lanes


def compile_param_rules(
    rules: List[R.ParamFlowRule], cfg: EngineConfig, registry, lanes: dict = None
) -> ParamRuleTensors:
    P = cfg.max_param_rules
    KP = cfg.param_rules_per_resource
    KI = _PARAM_ITEM_SLOTS
    nb = cfg.param_sample_count
    C = cfg.param_classes
    if lanes is None:
        lanes = param_lanes(rules, cfg.param_dims)
    t = ParamRuleTensors(
        enabled=np.zeros(P + 1, dtype=bool),
        res=np.zeros(P + 1, dtype=np.int32),
        grade=np.full(P + 1, R.GRADE_QPS, dtype=np.int32),
        threshold=np.zeros(P + 1, dtype=np.float32),
        cls=np.zeros(P + 1, dtype=np.int32),
        lane=np.full(P + 1, -1, dtype=np.int32),
        item_hash=np.zeros((P + 1, KI), dtype=np.int32),
        item_threshold=np.zeros((P + 1, KI), dtype=np.float32),
        res_params=np.full((cfg.max_resources + 1, KP), P, dtype=np.int32),
        class_k=np.ones(C, dtype=np.int32),
    )
    slot = 0
    per_res_count: dict = {}
    classes: list = []  # distinct window lengths (buckets), first-seen order
    for rule in rules:
        if not rule.is_valid() or slot >= P:
            continue
        rid = registry.resource_id(rule.resource)
        if rid is None or rid > cfg.max_resources:
            # no exact row (sketch-id / pass-through resource) -> the rule
            # cannot be enforced; observability continues via the sketch
            continue
        k = per_res_count.get(rid, 0)
        if k >= KP:
            continue
        dur = max(int(rule.duration_in_sec), 1)
        # window length in global buckets; durations beyond the grid clamp
        # to the full grid with the threshold scaled to preserve the RATE
        # (divergence from the reference's per-duration token bucket: a
        # >grid-duration rule enforces count*duration*(grid/duration) per
        # grid window instead of count*duration per duration window)
        want_k = max((dur * 1000) // cfg.param_bucket_ms, 1)
        k_buckets = min(want_k, nb)
        scale = k_buckets / want_k
        if k_buckets not in classes:
            if len(classes) >= C:
                # class table full: reuse the nearest class, scale threshold
                k_buckets = min(classes, key=lambda c: abs(c - k_buckets))
                scale = k_buckets / want_k
            else:
                classes.append(k_buckets)
        cls_idx = classes.index(k_buckets)
        per_res_count[rid] = k + 1
        t.res_params[rid, k] = slot
        t.enabled[slot] = True
        t.res[slot] = rid
        t.grade[slot] = rule.grade
        if rule.grade == R.GRADE_THREAD:
            # THREAD grade caps CONCURRENCY at plain `count` — duration and
            # burst are QPS-budget concepts (ParamFlowChecker THREAD branch)
            t.threshold[slot] = rule.count
        else:
            # windowed budget over the rule's duration (ParamFlowChecker
            # token bucket capacity: count * duration + burst, :127-188)
            t.threshold[slot] = (rule.count * dur + rule.burst_count) * scale
        t.cls[slot] = cls_idx
        lane_list = lanes.get(rule.resource, [])
        t.lane[slot] = (
            lane_list.index(rule.param_idx) if rule.param_idx in lane_list else -1
        )
        if t.lane[slot] < 0:
            # the rule's param_idx lost the per-resource lane assignment —
            # it cannot be enforced; surface it instead of silently no-oping
            from sentinel_tpu.utils.record_log import record_log

            record_log().warning(
                "param rule on %r with param_idx=%d exceeds the %d hash "
                "lanes for this resource and will NOT be enforced "
                "(raise EngineConfig.param_dims or consolidate rule indices)",
                rule.resource,
                rule.param_idx,
                len(lane_list),
            )
        for i, item in enumerate(rule.param_flow_item_list[:KI]):
            t.item_hash[slot, i] = hash_param(item.object)
            t.item_threshold[slot, i] = (
                item.count
                if rule.grade == R.GRADE_THREAD
                else item.count * dur * scale
            )
        slot += 1
    for i, kb in enumerate(classes[:C]):
        t.class_k[i] = kb
    return t


def compile_authority_rules(
    rules: List[R.AuthorityRule], cfg: EngineConfig, registry
) -> AuthorityTensors:
    KA = cfg.authority_origins_per_resource
    t = AuthorityTensors(
        mode=np.zeros(cfg.max_resources + 1, dtype=np.int32),
        origins=np.full((cfg.max_resources + 1, KA), AUTH_EMPTY, dtype=np.int32),
    )
    for rule in rules:
        if not rule.is_valid():
            continue
        rid = registry.resource_id(rule.resource)
        if rid is None or rid > cfg.max_resources:
            # no exact row (sketch-id / pass-through resource) -> the rule
            # cannot be enforced; observability continues via the sketch
            continue
        t.mode[rid] = 1 if rule.strategy == R.AUTHORITY_WHITE else 2
        # true last-wins: clear the resource's slots before writing, so a
        # second rule on the same resource REPLACES the first instead of
        # leaving the device matching the union of both origin lists
        # (the host mirror in runtime/client.py keeps only the last rule;
        # a union here made the mirror host-stricter under WHITE, skipping
        # _cluster_check on traffic the device then passed — ADVICE r5)
        t.origins[rid, :] = AUTH_EMPTY
        for i, o in enumerate(rule.origins()[:KA]):
            t.origins[rid, i] = registry.origin_id(o)
    return t


def tightest_threshold(*vals) -> np.float32:
    """Fold negative-means-unset system thresholds to the tightest SET
    one (SystemRuleManager.loadSystemConf semantics); -1 when all unset.
    The single authority for this fold — compile_system_rules and the
    adaptive controller's live-column merge both use it."""
    set_ = [float(v) for v in vals if float(v) >= 0]
    return np.float32(min(set_)) if set_ else np.float32(-1.0)


def compile_system_rules(rules: List[R.SystemRule], cfg: EngineConfig) -> SystemTensors:
    # fold multiple rules by taking the tightest threshold of each dimension,
    # as SystemRuleManager.loadSystemConf does
    return SystemTensors(
        load=tightest_threshold(*[r.highest_system_load for r in rules]),
        cpu=tightest_threshold(*[r.highest_cpu_usage for r in rules]),
        qps=tightest_threshold(*[r.qps for r in rules]),
        avg_rt=tightest_threshold(*[r.avg_rt for r in rules]),
        max_thread=tightest_threshold(*[r.max_thread for r in rules]),
    )
