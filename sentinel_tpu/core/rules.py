"""Rule definitions.

Python dataclass equivalents of the reference's rule POJOs:

- FlowRule        (sentinel-core/.../slots/block/flow/FlowRule.java)
- DegradeRule     (sentinel-core/.../slots/block/degrade/DegradeRule.java)
- SystemRule      (sentinel-core/.../slots/system/SystemRule.java)
- AuthorityRule   (sentinel-core/.../slots/block/authority/AuthorityRule.java)
- ParamFlowRule   (sentinel-extension/sentinel-parameter-flow-control/
                   .../ParamFlowRule.java:34-83)

``to_dict``/``from_dict`` use the reference's camelCase JSON field names so
rule payloads round-trip with Sentinel dashboards / datasources unchanged.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---- enums (values match the reference's RuleConstant.java) ----------------

GRADE_THREAD = 0  # FLOW_GRADE_THREAD
GRADE_QPS = 1  # FLOW_GRADE_QPS

STRATEGY_DIRECT = 0
STRATEGY_RELATE = 1
STRATEGY_CHAIN = 2

CONTROL_DEFAULT = 0
CONTROL_WARM_UP = 1
CONTROL_RATE_LIMITER = 2
CONTROL_WARM_UP_RATE_LIMITER = 3

CB_STRATEGY_SLOW_REQUEST_RATIO = 0  # DEGRADE_GRADE_RT
CB_STRATEGY_ERROR_RATIO = 1  # DEGRADE_GRADE_EXCEPTION_RATIO
CB_STRATEGY_ERROR_COUNT = 2  # DEGRADE_GRADE_EXCEPTION_COUNT

AUTHORITY_WHITE = 0
AUTHORITY_BLACK = 1

LIMIT_APP_DEFAULT = "default"
LIMIT_APP_OTHER = "other"

# System rule "not set" sentinel (SystemRuleManager treats negatives as off)
_UNSET = -1.0


def _camel(d: Dict[str, Any], **kv) -> Dict[str, Any]:
    d.update(kv)
    return d


@dataclass
class FlowRule:
    """QPS / concurrency limit for one resource (FlowRule.java)."""

    resource: str
    count: float = 0.0
    grade: int = GRADE_QPS
    limit_app: str = LIMIT_APP_DEFAULT
    strategy: int = STRATEGY_DIRECT
    ref_resource: str = ""  # for RELATE (resource) / CHAIN (context) strategy
    control_behavior: int = CONTROL_DEFAULT
    warm_up_period_sec: int = 10
    cold_factor: int = 3  # SentinelConfig default cold factor
    max_queueing_time_ms: int = 500
    cluster_mode: bool = False
    cluster_flow_id: int = 0
    cluster_threshold_type: int = 0  # 0=avg-local(per node), 1=global
    cluster_fallback_to_local: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return {
            "resource": self.resource,
            "count": self.count,
            "grade": self.grade,
            "limitApp": self.limit_app,
            "strategy": self.strategy,
            "refResource": self.ref_resource,
            "controlBehavior": self.control_behavior,
            "warmUpPeriodSec": self.warm_up_period_sec,
            "maxQueueingTimeMs": self.max_queueing_time_ms,
            "clusterMode": self.cluster_mode,
            "clusterConfig": {
                "flowId": self.cluster_flow_id,
                "thresholdType": self.cluster_threshold_type,
                "fallbackToLocalWhenFail": self.cluster_fallback_to_local,
            },
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FlowRule":
        cc = d.get("clusterConfig") or {}
        return cls(
            resource=d["resource"],
            count=float(d.get("count", 0)),
            grade=int(d.get("grade", GRADE_QPS)),
            limit_app=d.get("limitApp") or LIMIT_APP_DEFAULT,
            strategy=int(d.get("strategy", STRATEGY_DIRECT)),
            ref_resource=d.get("refResource") or "",
            control_behavior=int(d.get("controlBehavior", CONTROL_DEFAULT)),
            warm_up_period_sec=int(d.get("warmUpPeriodSec", 10)),
            max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 500)),
            cluster_mode=bool(d.get("clusterMode", False)),
            cluster_flow_id=int(cc.get("flowId", 0) or 0),
            cluster_threshold_type=int(cc.get("thresholdType", 0)),
            cluster_fallback_to_local=bool(cc.get("fallbackToLocalWhenFail", True)),
        )

    def is_valid(self) -> bool:
        return bool(self.resource) and self.count >= 0


@dataclass
class DegradeRule:
    """Circuit-breaker rule (DegradeRule.java).

    grade 0: slow-request ratio — ``count`` is max allowed RT in ms,
             ``slow_ratio_threshold`` the trip ratio.
    grade 1: error ratio — ``count`` in [0, 1].
    grade 2: error count — ``count`` is absolute errors in the window.
    """

    resource: str
    grade: int = CB_STRATEGY_SLOW_REQUEST_RATIO
    count: float = 0.0
    time_window: int = 0  # recovery timeout, SECONDS (Java field name)
    min_request_amount: int = 5  # DEFAULT_MIN_REQUEST_AMOUNT (RuleConstant)
    stat_interval_ms: int = 1000
    slow_ratio_threshold: float = 1.0
    limit_app: str = LIMIT_APP_DEFAULT

    def to_dict(self) -> Dict[str, Any]:
        return {
            "resource": self.resource,
            "grade": self.grade,
            "count": self.count,
            "timeWindow": self.time_window,
            "minRequestAmount": self.min_request_amount,
            "statIntervalMs": self.stat_interval_ms,
            "slowRatioThreshold": self.slow_ratio_threshold,
            "limitApp": self.limit_app,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DegradeRule":
        return cls(
            resource=d["resource"],
            grade=int(d.get("grade", 0)),
            count=float(d.get("count", 0)),
            time_window=int(d.get("timeWindow", 0)),
            min_request_amount=int(d.get("minRequestAmount", 5)),
            stat_interval_ms=int(d.get("statIntervalMs", 1000)),
            slow_ratio_threshold=float(d.get("slowRatioThreshold", 1.0)),
            limit_app=d.get("limitApp") or LIMIT_APP_DEFAULT,
        )

    def is_valid(self) -> bool:
        if not self.resource or self.count < 0 or self.time_window <= 0:
            return False
        if self.grade == CB_STRATEGY_ERROR_RATIO and self.count > 1:
            return False
        return True


@dataclass
class SystemRule:
    """Global adaptive-protection thresholds (SystemRule.java).

    Negative means "not set", matching SystemRuleManager.java:68-97.
    """

    highest_system_load: float = _UNSET
    highest_cpu_usage: float = _UNSET
    qps: float = _UNSET
    avg_rt: float = _UNSET
    max_thread: float = _UNSET
    limit_app: str = LIMIT_APP_DEFAULT

    def to_dict(self) -> Dict[str, Any]:
        return {
            "highestSystemLoad": self.highest_system_load,
            "highestCpuUsage": self.highest_cpu_usage,
            "qps": self.qps,
            "avgRt": self.avg_rt,
            "maxThread": self.max_thread,
            "limitApp": self.limit_app,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SystemRule":
        return cls(
            highest_system_load=float(d.get("highestSystemLoad", _UNSET)),
            highest_cpu_usage=float(d.get("highestCpuUsage", _UNSET)),
            qps=float(d.get("qps", _UNSET)),
            avg_rt=float(d.get("avgRt", _UNSET)),
            max_thread=float(d.get("maxThread", _UNSET)),
            limit_app=d.get("limitApp") or LIMIT_APP_DEFAULT,
        )


@dataclass
class AuthorityRule:
    """Origin allow/deny list for a resource (AuthorityRule.java).

    ``limit_app`` is a comma-separated list of origins, matched against
    the caller origin exactly as AuthorityRuleChecker.java:28-54 does.
    """

    resource: str
    limit_app: str = ""
    strategy: int = AUTHORITY_WHITE

    def origins(self) -> List[str]:
        return [o.strip() for o in self.limit_app.split(",") if o.strip()]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "resource": self.resource,
            "limitApp": self.limit_app,
            "strategy": self.strategy,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AuthorityRule":
        return cls(
            resource=d["resource"],
            limit_app=d.get("limitApp") or "",
            strategy=int(d.get("strategy", AUTHORITY_WHITE)),
        )

    def is_valid(self) -> bool:
        return bool(self.resource) and bool(self.origins())


@dataclass
class ParamFlowItem:
    """Per-value threshold exception (ParamFlowItem.java)."""

    object: str = ""
    count: int = 0
    class_type: str = "java.lang.String"

    def to_dict(self) -> Dict[str, Any]:
        return {"object": self.object, "count": self.count, "classType": self.class_type}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ParamFlowItem":
        return cls(
            object=str(d.get("object", "")),
            count=int(d.get("count", 0)),
            class_type=d.get("classType") or "java.lang.String",
        )


@dataclass
class ParamFlowRule:
    """Hot-parameter limit (ParamFlowRule.java:34-83)."""

    resource: str
    count: float = 0.0
    grade: int = GRADE_QPS
    param_idx: int = 0
    duration_in_sec: int = 1
    burst_count: int = 0
    max_queueing_time_ms: int = 0
    control_behavior: int = CONTROL_DEFAULT
    param_flow_item_list: List[ParamFlowItem] = field(default_factory=list)
    cluster_mode: bool = False
    cluster_flow_id: int = 0
    limit_app: str = LIMIT_APP_DEFAULT

    def to_dict(self) -> Dict[str, Any]:
        return {
            "resource": self.resource,
            "count": self.count,
            "grade": self.grade,
            "paramIdx": self.param_idx,
            "durationInSec": self.duration_in_sec,
            "burstCount": self.burst_count,
            "maxQueueingTimeMs": self.max_queueing_time_ms,
            "controlBehavior": self.control_behavior,
            "paramFlowItemList": [i.to_dict() for i in self.param_flow_item_list],
            "clusterMode": self.cluster_mode,
            "clusterConfig": {"flowId": self.cluster_flow_id},
            "limitApp": self.limit_app,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ParamFlowRule":
        cc = d.get("clusterConfig") or {}
        return cls(
            resource=d["resource"],
            count=float(d.get("count", 0)),
            grade=int(d.get("grade", GRADE_QPS)),
            param_idx=int(d.get("paramIdx", 0)),
            duration_in_sec=int(d.get("durationInSec", 1)),
            burst_count=int(d.get("burstCount", 0)),
            max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 0)),
            control_behavior=int(d.get("controlBehavior", CONTROL_DEFAULT)),
            param_flow_item_list=[
                ParamFlowItem.from_dict(i) for i in d.get("paramFlowItemList") or []
            ],
            cluster_mode=bool(d.get("clusterMode", False)),
            cluster_flow_id=int(cc.get("flowId", 0) or 0),
            limit_app=d.get("limitApp") or LIMIT_APP_DEFAULT,
        )

    def is_valid(self) -> bool:
        return bool(self.resource) and self.count >= 0 and self.duration_in_sec > 0


RULE_TYPES = {
    "flow": FlowRule,
    "degrade": DegradeRule,
    "system": SystemRule,
    "authority": AuthorityRule,
    "param-flow": ParamFlowRule,
}


def rules_to_json_list(rules) -> List[Dict[str, Any]]:
    return [r.to_dict() for r in rules]


def rules_from_json_list(kind: str, items) -> list:
    cls = RULE_TYPES[kind]
    return [cls.from_dict(i) for i in items]
