"""Public facade — the analog of SphU/SphO/Tracer/ContextUtil.

(Filled in alongside the host runtime; see sentinel_tpu/runtime/.)
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Iterable, Optional

from sentinel_tpu.core import rules as R

_client = None
_client_lock = threading.Lock()
_init_funcs: list = []
# a DEDICATED lock for the registration list: init() calls user init
# funcs while holding _client_lock, and an init func (or a module import
# it triggers) may legitimately register more funcs — sharing the
# non-reentrant client lock would self-deadlock that path
_init_funcs_lock = threading.Lock()


def register_init_func(fn, order: int = 0):
    """Register a one-time init callback run when the process-wide client
    first starts, ordered ascending — the InitFunc SPI + @InitOrder analog
    (init/InitExecutor.java:41-64).  Receives the SentinelClient."""
    # the read-modify-write on the registration SEQUENCE (len() is the
    # FIFO tiebreak) must be serialized or concurrent registrations can
    # claim the same tiebreak
    with _init_funcs_lock:
        _init_funcs.append((order, len(_init_funcs), fn))


def init(**kwargs):
    """Create (or return) the process-wide SentinelClient.

    Analog of Env.java:31-38 — the singleton CtSph + one-time init
    (InitExecutor.doInit running the registered InitFuncs exactly once).
    """
    global _client
    with _client_lock:
        if _client is None:
            from sentinel_tpu.runtime.client import SentinelClient

            c = SentinelClient(**kwargs)
            c.start()
            try:
                with _init_funcs_lock:
                    funcs = sorted(_init_funcs)
                # funcs registered DURING init (by an init func itself)
                # take effect on a later init() — matching the reference's
                # one-shot InitExecutor semantics
                for _, _, fn in funcs:
                    fn(c)
            except Exception:
                # a failing init func must not leave a half-initialized
                # singleton behind: tear down and let the caller retry
                c.stop()
                raise
            _client = c
        return _client


def get_client():
    return init()


def reset():
    """Tear down the process-wide client (tests)."""
    global _client
    with _client_lock:
        if _client is not None:
            _client.stop()
            _client = None


def entry(resource: str, count: int = 1, prioritized: bool = False, args=None):
    """Guard a code block; raises BlockException when rejected.

    Analog of SphU.entry (SphU.java:84); usable as a context manager:

        with st.entry("res") as e:
            ...
    """
    return get_client().entry(resource, count=count, prioritized=prioritized, args=args)


def entry_async(resource: str, count: int = 1, prioritized: bool = False, args=None):
    """Awaitable entry (AsyncEntry analog): ``e = await st.entry_async(r)``;
    exit with ``e.exit()`` (non-blocking)."""
    return get_client().entry_async(
        resource, count=count, prioritized=prioritized, args=args
    )


def try_entry(resource: str, count: int = 1, args=None):
    """Boolean variant (SphO.java). Returns an Entry or None."""
    return get_client().try_entry(resource, count=count, args=args)


def trace(exc: BaseException, count: int = 1):
    """Record a business exception on the current entry (Tracer.java)."""
    return get_client().trace(exc, count)


@contextmanager
def context(name: str, origin: str = ""):
    """Set the invocation context (ContextUtil.enter/exit)."""
    client = get_client()
    token = client.enter_context(name, origin)
    try:
        yield
    finally:
        client.exit_context(token)


def load_flow_rules(rules: Iterable[R.FlowRule]):
    get_client().flow_rules.load(list(rules))


def load_degrade_rules(rules: Iterable[R.DegradeRule]):
    get_client().degrade_rules.load(list(rules))


def load_system_rules(rules: Iterable[R.SystemRule]):
    get_client().system_rules.load(list(rules))


def load_authority_rules(rules: Iterable[R.AuthorityRule]):
    get_client().authority_rules.load(list(rules))


def load_param_flow_rules(rules: Iterable[R.ParamFlowRule]):
    get_client().param_flow_rules.load(list(rules))


def clear_rules():
    c = get_client()
    for mgr in (
        c.flow_rules,
        c.degrade_rules,
        c.system_rules,
        c.authority_rules,
        c.param_flow_rules,
    ):
        mgr.load([])


def __getattr__(name):
    if name == "SentinelClient":
        from sentinel_tpu.runtime.client import SentinelClient

        return SentinelClient
    raise AttributeError(name)
