"""Block exception hierarchy.

Mirrors the reference's BlockException subclasses
(sentinel-core/.../slots/block/BlockException.java and its five concrete
subclasses: FlowException, DegradeException, ParamFlowException,
SystemBlockException, AuthorityException), plus PriorityWaitException
(sentinel-core/.../slots/block/flow/PriorityWaitException.java) which in
the reference signals "entry granted after waiting for a future window".

Verdict codes are the wire/tensor representation: the decision kernel
emits an int8 verdict per request; the host maps nonzero codes onto these
exception types.
"""

from __future__ import annotations

# Verdict codes emitted by the decision kernel (int8 tensor values).
PASS = 0
BLOCK_FLOW = 1
BLOCK_DEGRADE = 2
BLOCK_PARAM = 3
BLOCK_SYSTEM = 4
BLOCK_AUTHORITY = 5
# Pass, but the caller must wait `wait_ms` before proceeding (leaky-bucket
# pacing / prioritized occupancy).  Maps to TokenResultStatus.SHOULD_WAIT in
# the reference's cluster protocol.
PASS_WAIT = 6


class BlockException(Exception):
    """Base for all flow-control rejections (reference: BlockException.java)."""

    #: verdict code this exception corresponds to
    code = -1

    def __init__(self, resource: str = "", rule=None, limit_origin: str = "default"):
        super().__init__(f"blocked: {resource}")
        self.resource = resource
        self.rule = rule
        self.limit_origin = limit_origin


class FlowException(BlockException):
    code = BLOCK_FLOW


class DegradeException(BlockException):
    code = BLOCK_DEGRADE


class ParamFlowException(BlockException):
    code = BLOCK_PARAM


class SystemBlockException(BlockException):
    code = BLOCK_SYSTEM


class AuthorityException(BlockException):
    code = BLOCK_AUTHORITY


class PriorityWaitException(Exception):
    """Entry granted after occupying a future window; not a rejection."""

    def __init__(self, wait_ms: int):
        super().__init__(f"priority wait {wait_ms} ms")
        self.wait_ms = wait_ms


#: verdict code -> exception class
EXCEPTION_BY_CODE = {
    BLOCK_FLOW: FlowException,
    BLOCK_DEGRADE: DegradeException,
    BLOCK_PARAM: ParamFlowException,
    BLOCK_SYSTEM: SystemBlockException,
    BLOCK_AUTHORITY: AuthorityException,
}


def exception_for_verdict(code: int, resource: str) -> BlockException:
    """The BlockException instance matching a nonzero verdict code."""
    return EXCEPTION_BY_CODE.get(int(code), BlockException)(resource)


def raise_for_verdict(code: int, resource: str, wait_ms: int = 0) -> None:
    """Raise the BlockException matching a nonzero verdict code."""
    if code == PASS or code == PASS_WAIT:
        return
    raise exception_for_verdict(code, resource)
