"""Heartbeat sender — registers this instance with the dashboard.

The analog of SimpleHttpHeartbeatSender.java:61 + HeartbeatSenderInitFunc:
a daemon loop POSTs ``/registry/machine`` on every configured dashboard
address at a fixed interval, carrying app/ip/port/hostname/version, so the
dashboard's machine discovery stays fresh.  Failures rotate to the next
dashboard address and never propagate.
"""

from __future__ import annotations

import os
import socket
import threading
import urllib.parse
import urllib.request
from typing import List, Optional

from sentinel_tpu.chaos import failpoints as FP

DEFAULT_INTERVAL_S = 10.0

#: chaos failpoint: a raise rides the rotate-on-failure catch below
_FP_HB_SEND = FP.register(
    "transport.heartbeat.send", "dashboard heartbeat POST", FP.HIT_ACTIONS
)


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class HeartbeatSender:
    def __init__(
        self,
        app_name: str,
        command_port: Optional[int] = None,
        dashboard_addresses: List[str] = (),
        interval_s: float = DEFAULT_INTERVAL_S,
        ip: Optional[str] = None,
        auth_token: Optional[str] = None,
        center=None,
    ):
        # auth_token is the DASHBOARD's bearer token: when the dashboard
        # runs with auth, /registry/machine requires it too (an open
        # registry would feed its proxy allowlist and metric fetcher).
        # Passing center= (the SimpleHttpCommandCenter) derives both the
        # port and the advertised ip: a loopback-bound center must
        # advertise 127.0.0.1 — advertising the NIC ip would make the
        # dashboard dial an address nothing listens on.
        self.app_name = app_name
        if center is not None:
            if command_port is None:
                command_port = center.port
                if command_port is None:
                    raise ValueError("center is not started yet (center.port is None)")
            if ip is None:
                if center.host in ("127.0.0.1", "localhost", "::1"):
                    ip = "127.0.0.1"
                elif center.host not in ("", "0.0.0.0", "::"):
                    # bound to one concrete NIC address: advertise exactly
                    # that — _local_ip() could pick a different interface
                    ip = center.host
        if command_port is None:
            raise ValueError("command_port or center is required")
        self.command_port = command_port
        self.auth_token = auth_token
        self.addresses = [a.strip() for a in dashboard_addresses if a.strip()]
        self.interval_s = interval_s
        self.ip = ip or _local_ip()
        self.hostname = socket.gethostname()
        self._idx = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sent_ok = 0
        self.sent_fail = 0

    def start(self) -> None:
        if self._thread is not None or not self.addresses:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="sentinel-tpu-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def send_once(self, timeout_s: float = 3.0) -> bool:
        """One heartbeat to the current dashboard address; rotates on failure."""
        import sentinel_tpu

        if not self.addresses:
            return False

        params = urllib.parse.urlencode(
            {
                "app": self.app_name,
                "ip": self.ip,
                "port": self.command_port,
                "pid": os.getpid(),
                "hostname": self.hostname,
                "version": getattr(sentinel_tpu, "__version__", "0.1.0"),
            }
        )
        addr = self.addresses[self._idx % len(self.addresses)]
        url = f"http://{addr}/registry/machine"
        try:
            FP.hit(_FP_HB_SEND)
            from sentinel_tpu.utils.authn import bearer_header

            # the custom header doubles as CSRF proof: a cross-site form
            # POST cannot set it, so a browser on the operator's machine
            # can't forge registrations into a loopback-bound dashboard
            headers = {"X-Sentinel-Heartbeat": "1", **bearer_header(self.auth_token)}
            req = urllib.request.Request(
                url,
                data=params.encode("ascii"),
                method="POST",
                headers=headers,
            )
            with urllib.request.urlopen(req, timeout=timeout_s) as rsp:
                ok = 200 <= rsp.status < 300
        except Exception:  # noqa: BLE001 — a bad address (InvalidURL is not
            # an OSError) must rotate, never kill the heartbeat loop
            ok = False
        if ok:
            self.sent_ok += 1
        else:
            self.sent_fail += 1
            self._idx += 1  # rotate to the next dashboard address
        return ok

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.send_once()
