"""Built-in command handlers — the analog of the ~20 handlers in
sentinel-transport-common/.../command/handler/ (ModifyRulesCommandHandler,
FetchActiveRuleCommandHandler, SendMetricCommandHandler, FetchJsonTree...,
FetchClusterNode..., ModifyClusterMode..., OnOffSet..., BasicInfo...).

All handlers are methods on one group object bound to a SentinelClient so
the registry stays explicit and testable.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from sentinel_tpu.core import rules as R
from sentinel_tpu.transport.command import (
    CommandRegistry,
    CommandRequest,
    CommandResponse,
    command_mapping,
)

#: command rule-type value → SentinelClient manager attribute
RULE_TYPE_TO_MANAGER = {
    "flow": "flow_rules",
    "degrade": "degrade_rules",
    "system": "system_rules",
    "authority": "authority_rules",
    "paramFlow": "param_flow_rules",
}

#: command rule-type value → converter kind (core.rules codec)
RULE_TYPE_TO_KIND = {
    "flow": "flow",
    "degrade": "degrade",
    "system": "system",
    "authority": "authority",
    "paramFlow": "param-flow",
}


class DefaultHandlerGroup:
    def __init__(self, client, cluster=None, metric_searcher=None, writable_registry=None):
        self.client = client
        self.cluster = cluster
        self.metric_searcher = metric_searcher
        self.writable_registry = writable_registry

    # -- info ---------------------------------------------------------------

    @command_mapping("version", "framework version")
    def version(self, req: CommandRequest) -> CommandResponse:
        import sentinel_tpu

        return CommandResponse.of_success(getattr(sentinel_tpu, "__version__", "0.1.0"))

    @command_mapping("basicInfo", "app/runtime basic info")
    def basic_info(self, req: CommandRequest) -> CommandResponse:
        c = self.client
        return CommandResponse.of_success(
            {
                "appName": c.app_name,
                "pid": os.getpid(),
                "mode": c.mode,
                "enabled": c.enabled,
                "maxResources": c.cfg.max_resources,
                "registeredResources": c.registry.num_resources,
            }
        )

    @command_mapping("api", "list available commands")
    def api(self, req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(
            [{"name": n, "desc": d} for n, d in self._registry.names()]
        )

    # -- rules --------------------------------------------------------------

    def _manager(self, type_: Optional[str]):
        attr = RULE_TYPE_TO_MANAGER.get(type_ or "")
        return getattr(self.client, attr) if attr else None

    @command_mapping("getRules", "fetch active rules by type")
    def get_rules(self, req: CommandRequest) -> CommandResponse:
        type_ = req.param("type")
        mgr = self._manager(type_)
        if mgr is None:
            return CommandResponse.of_failure(f"invalid type: {type_}")
        return CommandResponse.of_success(R.rules_to_json_list(mgr.get()))

    @command_mapping("setRules", "replace active rules by type")
    def set_rules(self, req: CommandRequest) -> CommandResponse:
        type_ = req.param("type")
        mgr = self._manager(type_)
        if mgr is None:
            return CommandResponse.of_failure(f"invalid type: {type_}")
        data = req.param("data") or req.body or "[]"
        rules = R.rules_from_json_list(RULE_TYPE_TO_KIND[type_], json.loads(data))
        mgr.load(rules)
        # write-through to the registered writable datasource, so pushed
        # rules survive restart (WritableDataSourceRegistry semantics)
        if self.writable_registry is not None:
            self.writable_registry.write(RULE_TYPE_TO_KIND[type_], rules)
        return CommandResponse.of_success("success")

    @command_mapping("getParamFlowRules", "fetch hot-param rules")
    def get_param_rules(self, req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(
            R.rules_to_json_list(self.client.param_flow_rules.get())
        )

    @command_mapping("topParams", "hottest parameter values for a resource")
    def top_params(self, req: CommandRequest) -> CommandResponse:
        res = req.param("id")
        if not res:
            return CommandResponse.of_failure("id is required")
        n = int(req.param("n", "16"))
        return CommandResponse.of_success(
            [{"param": repr(v), "sightings": c} for v, c in self.client.top_params(res, n)]
        )

    # -- metrics ------------------------------------------------------------

    @command_mapping("metric", "query metric log lines by time range")
    def metric(self, req: CommandRequest) -> CommandResponse:
        if self.metric_searcher is None:
            return CommandResponse.of_success("")
        start = int(req.param("startTime", "0"))
        end = req.param("endTime")
        identity = req.param("identity")
        max_lines = int(req.param("maxLines", "6000"))
        if end or identity:
            nodes = self.metric_searcher.find_by_time_and_resource(
                start, int(end) if end else 2**62, identity
            )[:max_lines]
        else:
            nodes = self.metric_searcher.find(start, max_lines)
        return CommandResponse.of_success("\n".join(n.to_line() for n in nodes))

    @command_mapping("api/metric", "per-resource per-second timeline rows")
    def api_metric(self, req: CommandRequest) -> CommandResponse:
        """``GET /api/metric?resource=&start=&end=`` — the device-driven
        per-second metric timeline (obs/timeline.py): one JSON row per
        (second, resource) with pass/block/success/exception counts,
        rt_sum/rt_min and concurrency, served read-through from the
        indexed on-disk MetricLog + the recorder's open buckets.  The
        reference's ``/metric?startTime&endTime`` channel, binary-backed
        and top-K device-batched; ``obs.fleet.merge_timelines`` aligns
        and sums these rows across a fleet."""
        tl = getattr(self.client, "timeline", None)
        if tl is None:
            return CommandResponse.of_success([])
        resource = req.param("resource") or None
        start = int(req.param("start", "0"))
        end_raw = req.param("end")
        end = int(end_raw) if end_raw else 2**62
        # bounded like the sibling `metric` handler's maxLines: an
        # unbounded default range over a full 8x8MiB log would decode and
        # serialize tens of MB per dashboard poll.  Newest rows win — the
        # catch-up pull wants the recent edge, not the pruned past.
        max_rows = int(req.param("maxRows", "6000"))
        rows = tl.find(resource, start, end)
        if max_rows > 0:
            rows = rows[-max_rows:]
        return CommandResponse.of_success([r.to_dict() for r in rows])

    @command_mapping("clusterNode", "per-resource statistics snapshot")
    def cluster_node(self, req: CommandRequest) -> CommandResponse:
        snap = self.client.stats.snapshot()
        out = [dict(resource=name, **s) for name, s in snap.items()]
        return CommandResponse.of_success(out)

    @command_mapping("origin", "per-origin statistics for one resource")
    def origin(self, req: CommandRequest) -> CommandResponse:
        res = req.param("id")
        if not res:
            return CommandResponse.of_failure("id is required")
        out = []
        for (kind, key), row in self.client.registry.extra_rows().items():
            if kind != "origin":
                continue
            r, _, origin = key.partition("\x00")
            if r == res:
                s = self.client.stats._row_stats(row)
                out.append(dict(resource=res, origin=origin, **s))
        return CommandResponse.of_success(out)

    @command_mapping("jsonTree", "invocation tree with live stats")
    def json_tree(self, req: CommandRequest) -> CommandResponse:
        c = self.client
        root = dict(resource="machine-root", **c.stats.entry_node(), children=[])
        snap = c.stats.snapshot()
        origins = {}
        for (kind, key), row in c.registry.extra_rows().items():
            if kind == "origin":
                r, _, origin = key.partition("\x00")
                origins.setdefault(r, []).append((origin, row))
        for name, s in snap.items():
            node = dict(resource=name, **s, children=[])
            for origin, row in origins.get(name, []):
                node["children"].append(
                    dict(resource=f"{name}|{origin}", origin=origin, **c.stats._row_stats(row))
                )
            root["children"].append(node)
        return CommandResponse.of_success(root)

    @command_mapping("metrics", "Prometheus text exposition (obs registry)")
    def prometheus_metrics(self, req: CommandRequest) -> CommandResponse:
        """``GET /metrics`` — the standard scrape surface: every counter /
        gauge / histogram in the process-global obs registry (tick-stage
        latencies, pipeline occupancy, seg drops, cluster degrade state,
        RPC latencies) in Prometheus text format 0.0.4.

        ``?fleet=1`` merges in every configured fleet member
        (``obs.fleet.add_fleet_target`` / ``SENTINEL_FLEET_TARGETS``):
        counters sum, histograms merge bucket-wise, per-shard labels
        survive, same-process duplicates drop (obs/fleet.py)."""
        from sentinel_tpu.obs import REGISTRY

        if (req.param("fleet") or "").lower() in ("1", "true"):
            from sentinel_tpu.obs.fleet import fleet_exposition

            return CommandResponse.of_success(fleet_exposition())
        return CommandResponse.of_success(REGISTRY.exposition())

    @command_mapping("api/traces", "span-tracer ring dump (Chrome trace JSON)")
    def api_traces(self, req: CommandRequest) -> CommandResponse:
        """``GET /api/traces`` — the current span ring as Chrome Trace
        Event JSON: load in Perfetto / chrome://tracing, or feed to
        ``python -m sentinel_tpu.obs --summary``.  ``?enable=true|false``
        flips tracing on the instance first (an ops toggle, like
        setSwitch)."""
        from sentinel_tpu.obs import TRACER

        enable = (req.param("enable") or "").lower()
        if enable == "true":
            TRACER.enable()
        elif enable == "false":
            TRACER.disable()
        return CommandResponse.of_success(TRACER.chrome_trace())

    @command_mapping("api/flight", "flight-recorder bundle (black-box post-mortem)")
    def api_flight(self, req: CommandRequest) -> CommandResponse:
        """``GET /api/flight`` — the black-box surface: by default a
        FRESH bundle captured on demand (not rate-limited — an operator
        asking for state deserves current state); ``?stored=N`` returns
        the last N automatically-triggered bundles instead (degrade
        entries, invariant breaches).  Feed either to
        ``python -m sentinel_tpu.obs --postmortem``."""
        from sentinel_tpu.obs.flight import FLIGHT

        stored = req.param("stored")
        if stored is not None:
            n = max(int(stored), 0)
            # [-0:] would slice the WHOLE list; stored=0 means none
            return CommandResponse.of_success(FLIGHT.bundles()[-n:] if n else [])
        return CommandResponse.of_success(FLIGHT.dump_bundle(reason="api"))

    @command_mapping("api/profile", "bounded deep-profile capture (Chrome trace)")
    def api_profile(self, req: CommandRequest) -> CommandResponse:
        """``GET /api/profile?ms=250`` — one bounded dense-capture window
        (obs/profile.capture_profile): the span tracer is force-enabled
        (with jax.profiler annotation passthrough) for at most ``ms``
        milliseconds and the window's spans come back as a Chrome-trace
        payload, mergeable via ``python -m sentinel_tpu.obs --merge``.
        Rate-limited (a second capture inside the interval returns
        ``{"error": "rate_limited", "retry_after_s": ...}``) and
        fail-OPEN: errors return a payload, decisions are untouched."""
        from sentinel_tpu.obs.profile import capture_profile

        return CommandResponse.of_success(
            capture_profile(req.param("ms") or 250.0)
        )

    @command_mapping("api/memory", "HBM memory-ledger reconciliation")
    def api_memory(self, req: CommandRequest) -> CommandResponse:
        """``GET /api/memory`` — the memory ledger's view (per-pool
        bytes, per-entry breakdown, capacity posture) reconciled on
        demand against ``jax.live_arrays()`` and the backend's own
        memory stats (``unaccounted_bytes`` = live bytes no ledger entry
        claims).  Backend reads fail open on CPU-only processes."""
        from sentinel_tpu.obs.profile import LEDGER

        return CommandResponse.of_success(LEDGER.reconcile())

    @command_mapping("api/shards", "token-fleet topology + per-shard health")
    def api_shards(self, req: CommandRequest) -> CommandResponse:
        """``GET /api/shards`` — every live sharded token client in the
        process: ring parameters, per-flow spread, and per-shard address
        / connection / failover state (the operator's view of WHICH
        shard is degraded and how long its cooldown has left)."""
        from sentinel_tpu.cluster.shard import describe_fleets

        return CommandResponse.of_success(describe_fleets())

    @command_mapping("api/explain", "verdict provenance: why decisions blocked")
    def api_explain(self, req: CommandRequest) -> CommandResponse:
        """``GET /api/explain`` — the verdict provenance plane
        (obs/explain.py): coverage (what fraction of blocked decisions
        carry an explanation), the top block-cause leaderboard, and the
        newest device-packed block explanations.  ``?resource=NAME``
        restricts the record list to one resource's provenance ring;
        ``?top=N`` sizes the leaderboard.  Also the backing surface for
        ``python -m sentinel_tpu.obs explain --target``."""
        plane = getattr(self.client, "explain_plane", None)
        if plane is None:
            return CommandResponse.of_success(
                {"enabled": False, "coverage": {"blocked": 0, "explained": 0,
                                                "frac": 1.0},
                 "top_causes": [], "recent": []}
            )
        top = int(req.param("top") or 10)
        resource = req.param("resource")
        if resource:
            recs = self.client.explain(resource, limit=64)
        else:
            recs = plane.recent(64)
        return CommandResponse.of_success(
            {
                "enabled": True,
                "coverage": plane.coverage(),
                "top_causes": plane.top_causes(top),
                "recent": [r.to_dict() for r in recs],
            }
        )

    @command_mapping("rtQuantiles", "inbound RT quantiles (p50/p90/p99)")
    def rt_quantiles(self, req: CommandRequest) -> CommandResponse:
        qs = [float(x) for x in (req.param("q") or "0.5,0.9,0.99").split(",")]
        out = self.client.rt_quantiles(tuple(qs))
        # keys match the advertised percent form: p50 / p90 / p99 / p99.9
        return CommandResponse.of_success(
            {f"p{round(q * 100, 3):g}": v for q, v in out.items()}
        )

    @command_mapping("systemStatus", "system adaptive-protection inputs")
    def system_status(self, req: CommandRequest) -> CommandResponse:
        load, cpu = self.client._sys.sample()
        entry = self.client.stats.entry_node()
        return CommandResponse.of_success(
            {
                "load": load,
                "cpuUsage": cpu,
                "qps": entry["passQps"],
                "avgRt": entry["avgRt"],
                "threadNum": entry["curThreadNum"],
            }
        )

    # -- switches -----------------------------------------------------------

    @command_mapping("setSwitch", "turn entry protection on/off")
    def set_switch(self, req: CommandRequest) -> CommandResponse:
        value = (req.param("value") or "").lower()
        if value not in ("true", "false"):
            return CommandResponse.of_failure("value must be true|false")
        self.client.enabled = value == "true"
        return CommandResponse.of_success("success")

    @command_mapping("getSwitch", "read the protection switch")
    def get_switch(self, req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success({"enabled": self.client.enabled})

    # -- cluster ------------------------------------------------------------

    @command_mapping("getClusterMode", "cluster role of this instance")
    def get_cluster_mode(self, req: CommandRequest) -> CommandResponse:
        if self.cluster is None:
            return CommandResponse.of_success({"mode": 0, "available": False})
        return CommandResponse.of_success(
            {"mode": self.cluster.mode, "available": self.cluster.is_available()}
        )

    @command_mapping("setClusterMode", "flip cluster role (0=client 1=server)")
    def set_cluster_mode(self, req: CommandRequest) -> CommandResponse:
        """ModifyClusterModeCommandHandler analog. Becoming a server needs a
        DefaultTokenService; the instance keeps its last one, so the flip is
        client↔server with the wiring established at setup time."""
        if self.cluster is None:
            return CommandResponse.of_failure("cluster not configured")
        from sentinel_tpu.cluster import state as CS

        mode = int(req.param("mode", "-99"))
        if mode == CS.CLUSTER_CLIENT:
            # optional assignment: which token server this client consults
            # (the dashboard's assign flow pushes it with the flip —
            # ClusterClientAssignConfig analog)
            host = req.param("host", "") or None
            port = req.param("tokenPort", "")
            self.cluster.set_to_client(
                host=host, port=int(port) if port else None
            )
        elif mode == CS.CLUSTER_SERVER:
            svc = self.cluster._embedded or getattr(
                self.cluster, "_last_service", None
            )
            if svc is None:
                return CommandResponse.of_failure("no token service configured for server mode")
            port = req.param("tokenPort", "")
            self.cluster.set_to_server(svc, port=int(port) if port else None)
        else:
            return CommandResponse.of_failure(f"invalid mode: {mode}")
        return CommandResponse.of_success("success")

    @command_mapping("clusterServerInfo", "embedded token server state")
    def cluster_server_info(self, req: CommandRequest) -> CommandResponse:
        """Port + liveness of this instance's token server — the assign
        flow reads it to point client machines at the right address
        (ClusterServerStateVO analog)."""
        if self.cluster is None:
            return CommandResponse.of_failure("cluster not configured")
        srv = self.cluster.server
        return CommandResponse.of_success(
            {
                "mode": self.cluster.mode,
                "tokenPort": srv.port if srv is not None else -1,
                "running": srv is not None,
            }
        )


def build_default_handlers(
    client, cluster=None, metric_searcher=None, writable_registry=None
) -> CommandRegistry:
    registry = CommandRegistry()
    group = DefaultHandlerGroup(client, cluster, metric_searcher, writable_registry)
    registry.register_group(group)  # also injects group._registry for "api"
    return registry
