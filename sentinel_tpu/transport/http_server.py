"""HTTP command center — the per-instance command plane.

The analog of sentinel-transport-simple-http's SimpleHttpCommandCenter:
a small HTTP/1.1 server (stdlib ThreadingHTTPServer — the reference
hand-rolls one on ServerSocket) exposing every registered command at
``GET/POST /<commandName>``.  Default port 8719; when taken, the port
auto-increments, as TransportConfig does.

Responses: JSON for structured results, text/plain for strings; failures
get HTTP 400 with the message.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.transport.command import CommandRegistry, CommandRequest

DEFAULT_PORT = 8719

#: chaos failpoint: a raise aborts just this HTTP exchange (the threading
#: server's per-connection handler); the command center stays up
_FP_HTTP_REQ = FP.register(
    "transport.http.request", "command-center HTTP request service", FP.HIT_ACTIONS
)
MAX_PORT_PROBES = 100


class _Handler(BaseHTTPRequestHandler):
    registry: CommandRegistry = None  # set by server factory
    auth_token: Optional[str] = None  # set by server factory
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs to command log
        from sentinel_tpu.utils.record_log import command_center_log

        command_center_log().info("%s - %s", self.address_string(), fmt % args)

    def _dispatch(self, body: str = "") -> None:
        from sentinel_tpu.utils.authn import check_bearer

        if not check_bearer(self.headers.get("Authorization"), self.auth_token):
            payload = b"unauthorized"
            self.send_response(401)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        parsed = urllib.parse.urlparse(self.path)
        name = parsed.path.strip("/")
        params = {k: v[-1] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        if body and "=" in body and not body.lstrip().startswith(("[", "{")):
            # form-encoded POST body merges into params (data=... uploads)
            for k, v in urllib.parse.parse_qs(body).items():
                params.setdefault(k, v[-1])
            body = params.get("data", body)
        FP.hit(_FP_HTTP_REQ)
        rsp = self.registry.handle(name, CommandRequest(parameters=params, body=body))
        if rsp.success:
            if isinstance(rsp.result, str):
                payload = rsp.result.encode("utf-8")
                ctype = "text/plain; charset=utf-8"
            else:
                payload = json.dumps(rsp.result).encode("utf-8")
                ctype = "application/json; charset=utf-8"
            self.send_response(200)
        else:
            payload = str(rsp.result).encode("utf-8")
            ctype = "text/plain; charset=utf-8"
            self.send_response(400)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        self._dispatch()

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length).decode("utf-8") if length else ""
        self._dispatch(body)


class SimpleHttpCommandCenter:
    """Command-plane HTTP server.

    ``host=None`` binds 127.0.0.1; serving other machines (mutating
    commands: setRules, setSwitch, setClusterMode) requires an explicit
    ``host='0.0.0.0'``, ideally with ``auth_token`` — when a token is set
    every command requires ``Authorization: Bearer``.
    """

    def __init__(
        self,
        registry: CommandRegistry,
        host: Optional[str] = None,
        port: int = DEFAULT_PORT,
        auth_token: Optional[str] = None,
    ):
        from sentinel_tpu.utils.authn import default_bind_host, normalize_token

        self.registry = registry
        self.auth_token = normalize_token(auth_token)
        self.host = default_bind_host(host)
        self.requested_port = port
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._server is not None:
            return
        handler = type(
            "BoundHandler",
            (_Handler,),
            {"registry": self.registry, "auth_token": self.auth_token},
        )
        last_err = None
        for probe in range(MAX_PORT_PROBES):
            try:
                self._server = ThreadingHTTPServer((self.host, self.requested_port + probe), handler)
                break
            except OSError as e:
                last_err = e
        if self._server is None:
            raise OSError(f"no free command-center port near {self.requested_port}: {last_err}")
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sentinel-tpu-command-center", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.port = None
