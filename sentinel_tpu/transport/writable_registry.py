"""Write-back registry: dashboard-pushed rules → durable datasources.

The analog of WritableDataSourceRegistry.java: when ``setRules`` arrives on
the command plane, the new rule list is also written to the
WritableDataSource registered for that rule kind, so pushed config survives
process restart (rules durable, counters disposable — SURVEY §5).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class WritableDataSourceRegistry:
    def __init__(self):
        self._sources: Dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, kind: str, source) -> None:
        """kind: "flow" | "degrade" | "system" | "authority" | "param-flow"."""
        with self._lock:
            self._sources[kind] = source

    def get(self, kind: str) -> Optional[object]:
        return self._sources.get(kind)

    def write(self, kind: str, rules: list) -> bool:
        src = self._sources.get(kind)
        if src is None:
            return False
        src.write(rules)
        return True


_default = WritableDataSourceRegistry()


def default_registry() -> WritableDataSourceRegistry:
    return _default
