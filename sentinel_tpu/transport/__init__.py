"""Transport / command plane (SURVEY §2.4): per-instance HTTP command
center, built-in command handlers, heartbeat to the dashboard, and the
writable-datasource write-back registry."""

from sentinel_tpu.transport.command import (
    CommandRegistry,
    CommandRequest,
    CommandResponse,
    command_mapping,
)
from sentinel_tpu.transport.handlers import DefaultHandlerGroup, build_default_handlers
from sentinel_tpu.transport.http_server import DEFAULT_PORT, SimpleHttpCommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender
from sentinel_tpu.transport.writable_registry import (
    WritableDataSourceRegistry,
    default_registry,
)


def start_command_center(
    client,
    cluster=None,
    metric_searcher=None,
    writable_registry=None,
    host=None,
    port: int = DEFAULT_PORT,
    auth_token=None,
) -> SimpleHttpCommandCenter:
    """Build the default handler set and serve it (CommandCenterInitFunc).

    Binds loopback by default; pass ``host='0.0.0.0'`` (ideally with
    ``auth_token``) to serve the dashboard across machines.
    """
    registry = build_default_handlers(client, cluster, metric_searcher, writable_registry)
    center = SimpleHttpCommandCenter(registry, host=host, port=port, auth_token=auth_token)
    center.start()
    return center


__all__ = [
    "CommandRegistry",
    "CommandRequest",
    "CommandResponse",
    "command_mapping",
    "DefaultHandlerGroup",
    "build_default_handlers",
    "SimpleHttpCommandCenter",
    "HeartbeatSender",
    "WritableDataSourceRegistry",
    "default_registry",
    "start_command_center",
    "DEFAULT_PORT",
]
