"""Command plane primitives — the analog of sentinel-transport-common's
CommandHandler SPI (@CommandMapping name/desc + CommandHandlerProvider).

Handlers are plain callables ``fn(CommandRequest) -> CommandResponse``
registered in a CommandRegistry under their command name; the HTTP command
center dispatches ``GET/POST /<name>`` to them.  Registration is explicit
(build_default_handlers) or via the ``@command_mapping`` decorator on
methods of a handler group class.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from sentinel_tpu.chaos import failpoints as FP

#: chaos failpoint: a raise converts to the command plane's of_failure
#: response — the "command plane must not crash" contract under test
_FP_DISPATCH = FP.register(
    "transport.command.dispatch", "command handler dispatch", FP.HIT_ACTIONS
)


@dataclass
class CommandRequest:
    parameters: Dict[str, str] = field(default_factory=dict)
    body: str = ""

    def param(self, name: str, default: Optional[str] = None) -> Optional[str]:
        v = self.parameters.get(name)
        return v if v not in (None, "") else default


@dataclass
class CommandResponse:
    success: bool
    result: Any = None

    @staticmethod
    def of_success(result: Any) -> "CommandResponse":
        return CommandResponse(True, result)

    @staticmethod
    def of_failure(message: str) -> "CommandResponse":
        return CommandResponse(False, message)


def command_mapping(name: str, desc: str = ""):
    """Mark a method as a command handler (@CommandMapping analog)."""

    def wrap(fn):
        fn.__command_name__ = name
        fn.__command_desc__ = desc
        return fn

    return wrap


class CommandRegistry:
    def __init__(self):
        self._handlers: Dict[str, Tuple[str, Callable[[CommandRequest], CommandResponse]]] = {}

    def register(self, name: str, fn, desc: str = "") -> None:
        self._handlers[name] = (desc, fn)

    def register_group(self, group: Any) -> None:
        """Register every @command_mapping-decorated method of an object."""
        if getattr(group, "_registry", None) is None:
            group._registry = self  # lets handlers like "api" introspect us
        for attr in dir(group):
            fn = getattr(group, attr)
            name = getattr(fn, "__command_name__", None)
            if name:
                self.register(name, fn, getattr(fn, "__command_desc__", ""))

    def handle(self, name: str, request: CommandRequest) -> CommandResponse:
        entry = self._handlers.get(name)
        if entry is None:
            return CommandResponse.of_failure(f"unknown command: {name}")
        try:
            FP.hit(_FP_DISPATCH)
            return entry[1](request)
        except Exception as e:  # noqa: BLE001 — command plane must not crash
            return CommandResponse.of_failure(f"{type(e).__name__}: {e}")

    def names(self) -> List[Tuple[str, str]]:
        return [(n, d) for n, (d, _) in sorted(self._handlers.items())]
