"""Per-tick system signals for the closed-loop controller.

``SignalCollector`` turns what the tick loop already knows — queue
depths, batch sizes, the completion columns' RT minima, the obs plane's
stage histograms and gauges, cluster RPC failure counters, and the
host's load/CPU sample — into one ``SystemSignals`` row per tick,
without locks on the hot path:

* the tick thread is the only writer of the EWMA/ring state
  (``observe_tick``); readers get a consistent-enough snapshot the same
  way the span tracer's ring does — torn reads cost one stale sample,
  never a crash;
* the resolver pool feeds verdict counts through ``note_resolved``
  (plain int adds under the GIL — a lost increment skews one tick's
  rate by <1%, which the EWMA smooths out anyway);
* percentile reads come from the existing ``obs`` histograms
  (``sentinel_tick_device_ms`` et al.) — the collector never keeps its
  own histogram.

Windowed extrema (BBR's maxPass and minRT) ride small fixed rings of
per-tick values in ENGINE time, so the whole collector is deterministic
under a VirtualTimeSource.  Disabled mode costs nothing: a client
without adaptive protection never constructs a collector, and its tick
hook is one ``is None`` check (guarded by the <5 µs test).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from sentinel_tpu.obs.registry import REGISTRY as _OBS

#: ring length for windowed max-pass-rate / min-RT (per-tick samples);
#: at a 1 ms tick this spans ~64 ms of saturated serving, and idle ticks
#: stretch it — plenty against the 1 s admission windows it feeds
_RING = 64


@dataclass
class SystemSignals:
    """One tick's view of system health (the controller's input row)."""

    now_ms: int = 0
    #: un-ticked acquire queue depth at drain time
    queue_depth: int = 0
    #: dispatched-but-unresolved engine ticks / in-flight readbacks
    pipeline_occupancy: int = 0
    resolver_queue_depth: int = 0
    #: admitted (PASS/PASS_WAIT) vs blocked items per second, windowed
    pass_rate: float = 0.0
    block_rate: float = 0.0
    #: BBR inputs: best recent admitted rate and the windowed RT floor
    max_pass_rate: float = 0.0
    min_rt_ms: float = 0.0
    #: EWMA of completion RT (the "how slow is service NOW" signal)
    rt_ewma_ms: float = 0.0
    #: host-estimated in-flight entries (admitted minus completed)
    inflight: float = 0.0
    #: cluster RPC failures per second (all kinds), windowed
    rpc_fail_rate: float = 0.0
    #: device-stage p99 from the obs histogram (0 when tracing is off)
    device_p99_ms: float = 0.0
    #: host sample (utils/system_status.py)
    sys_load: float = 0.0
    sys_cpu: float = 0.0


class SignalCollector:
    """Lock-light EWMA / windowed-extrema state behind ``SystemSignals``."""

    def __init__(self, ewma_alpha: float = 0.2):
        self.alpha = float(ewma_alpha)
        self.rt_ewma_ms = 0.0
        self.inflight = 0.0
        self._pass_total = 0
        self._block_total = 0
        self._comp_total = 0
        self._rpc_fail_prev = 0.0
        # per-tick rings: (now_ms, cumulative pass, cumulative block) for
        # rates, per-tick completion RT minima for the windowed floor
        self._rate_ring = [(0, 0, 0)] * _RING
        self._rate_i = 0
        self._rt_min_ring = [float("inf")] * _RING
        self._rt_i = 0
        self._last_now_ms = 0
        # last device telemetry row's windowed RT floor / pass sum
        # (runtime/client feeds these from TickOutput.stats; they back the
        # ring-based floor when no completion batch fed it this window)
        self._dev_min_rt = 0.0
        self._dev_win_pass = 0.0
        # the labeled cluster RPC failure counters already on the global
        # registry; get-or-create returns the live instances
        self._rpc_fail_counters = [
            _OBS.counter(
                "sentinel_cluster_rpc_failures_total",
                "token-server round-trips that degraded, by failure kind "
                "(connect|send|timeout|conn_lost|decode)",
                labels={"kind": k},
            )
            for k in ("connect", "send", "timeout", "conn_lost", "decode")
        ]
        self._dev_hist = _OBS.histogram(
            "sentinel_tick_device_ms",
            "dispatch to verdicts-host-visible per tick (device compute + "
            "transfer; includes pipeline queue wait)",
        )

    # -- feeders (tick thread / resolver pool) -------------------------------

    def note_resolved(self, passed: int, blocked: int) -> None:
        """Per-tick verdict counts from the resolver (any thread)."""
        self._pass_total += int(passed)
        self._block_total += int(blocked)

    def note_device_stats(self, row) -> None:
        """One device telemetry row (ops/engine.STAT_* float32 vector,
        already host-resident — runtime/client reads it back with the
        verdicts).  The on-device ENTRY-window RT floor and pass sum are
        kept as fallbacks: a verdict-only workload (no completion batches)
        otherwise never feeds the BBR minRT input."""
        from sentinel_tpu.ops import engine as E
        from sentinel_tpu.ops import window as W

        mn = float(row[E.STAT_WIN_RT_MIN])
        self._dev_min_rt = 0.0 if mn >= W.RT_MIN_INIT else mn
        self._dev_win_pass = float(row[E.STAT_WIN_PASS])

    def note_completions(self, n: int, rt_min_ms: float) -> None:
        """Completion batch summary from the tick builder."""
        self._comp_total += int(n)
        if n > 0:
            a = self.alpha
            self.rt_ewma_ms = (
                rt_min_ms
                if self.rt_ewma_ms == 0.0
                else (1 - a) * self.rt_ewma_ms + a * rt_min_ms
            )
            i = self._rt_i
            self._rt_min_ring[i & (_RING - 1)] = float(rt_min_ms)
            self._rt_i = i + 1

    # -- snapshot (tick thread, once per tick) -------------------------------

    def observe_tick(
        self,
        now_ms: int,
        queue_depth: int,
        pipeline_occupancy: int,
        resolver_queue_depth: int,
        sys_load: float,
        sys_cpu: float,
    ) -> SystemSignals:
        i = self._rate_i
        ring = self._rate_ring
        ring[i & (_RING - 1)] = (int(now_ms), self._pass_total, self._block_total)
        self._rate_i = i + 1
        # windowed rates against the OLDEST ring sample ≤1 s back (engine
        # time); the ring naturally spans less when ticks are sparse
        anchor_ms, anchor_pass, anchor_blk = ring[(i + 1) & (_RING - 1)]
        span_ms = max(now_ms - anchor_ms, 1)
        if span_ms > 1000:
            # walk forward to the newest sample still ≥1 s old so a long
            # idle gap doesn't dilute the rate to ~0 and unlearn capacity
            for k in range(2, _RING):
                t_ms, p, b = ring[(i + k) & (_RING - 1)]
                if now_ms - t_ms <= 1000:
                    break
                anchor_ms, anchor_pass, anchor_blk = t_ms, p, b
            span_ms = max(now_ms - anchor_ms, 1)
        pass_rate = (self._pass_total - anchor_pass) * 1000.0 / span_ms
        block_rate = (self._block_total - anchor_blk) * 1000.0 / span_ms
        # max pass rate: best adjacent-sample rate in the ring window
        # (maxSuccessQps's "best bucket" shape, host side)
        max_rate = pass_rate
        prev = None
        for k in range(1, _RING):
            t_ms, p, _b = ring[(i + k) & (_RING - 1)]
            if prev is not None and t_ms > prev[0] and now_ms - t_ms <= 1000:
                r = (p - prev[1]) * 1000.0 / (t_ms - prev[0])
                if r > max_rate:
                    max_rate = r
            prev = (t_ms, p)
        rt_floor = min(self._rt_min_ring)
        rpc_now = sum(c.value for c in self._rpc_fail_counters)
        rpc_rate = (rpc_now - self._rpc_fail_prev) * 1000.0 / max(
            now_ms - self._last_now_ms, 1
        ) if self._last_now_ms else 0.0
        self._rpc_fail_prev = rpc_now
        self._last_now_ms = int(now_ms)
        self.inflight = max(float(self._pass_total - self._comp_total), 0.0)
        return SystemSignals(
            now_ms=int(now_ms),
            queue_depth=int(queue_depth),
            pipeline_occupancy=int(pipeline_occupancy),
            resolver_queue_depth=int(resolver_queue_depth),
            pass_rate=pass_rate,
            block_rate=block_rate,
            max_pass_rate=max_rate,
            min_rt_ms=(
                self._dev_min_rt if rt_floor == float("inf") else rt_floor
            ),
            rt_ewma_ms=self.rt_ewma_ms,
            inflight=self.inflight,
            rpc_fail_rate=max(rpc_rate, 0.0),
            device_p99_ms=(
                self._dev_hist.quantile(0.99) if self._dev_hist.count else 0.0
            ),
            sys_load=float(sys_load),
            sys_cpu=float(sys_cpu),
        )
