"""Unified degrade semantics: ONE hysteresis primitive, ONE ladder.

Before this module the repo had grown three copy-paste cousins of the
same enter-on-failure/cooldown/exit-on-probe shape — the runtime's
cluster degrade (runtime/client.py), the per-shard failover state
(cluster/shard.py) and the remote-shard span degrade
(parallel/remote_shard.py) — each with its own field names and its own
idea of what gets journaled.  All three now delegate to ``Hysteresis``;
the reconnect throttle in ``cluster/client.py`` delegates to ``Backoff``
(exponential, full jitter — a fixed interval lets N clients stampede a
recovering shard in lockstep).

On top of the shared primitive sits the ONE ordered degrade ladder the
closed-loop controller climbs under overload::

    NORMAL -> SHED_LOW_PRIORITY -> PARAM_TAIL_OFF -> CLUSTER_FALLBACK
           -> FAIL_CLOSED

Climbing requires ``climb_hold_ms`` of sustained overload evidence per
rung; descending requires ``cool_hold_ms`` of sustained health — both in
ENGINE time (the tick's ``now_ms``), so ladder motion is a pure function
of the driven traffic and replays deterministically under virtual time
(the chaos plane's requirement).  Every transition is journaled in
``obs.flight`` and mirrored on the ``sentinel_adaptive_level`` gauge.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Dict, Optional

from sentinel_tpu.obs import flight as FL
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.time_source import mono_s

# -- the ladder rungs --------------------------------------------------------

NORMAL = 0
SHED_LOW_PRIORITY = 1  # non-prioritized work sheds above the soft queue mark
PARAM_TAIL_OFF = 2  # host param-tail bookkeeping (hot-param values) off
CLUSTER_FALLBACK = 3  # cluster token RPCs bypassed; local fallback enforces
FAIL_CLOSED = 4  # every new admission fails closed until health returns

LEVEL_NAMES = (
    "NORMAL",
    "SHED_LOW_PRIORITY",
    "PARAM_TAIL_OFF",
    "CLUSTER_FALLBACK",
    "FAIL_CLOSED",
)

_G_LEVEL = _OBS.gauge(
    "sentinel_adaptive_level",
    "current degrade-ladder rung (0=NORMAL .. 4=FAIL_CLOSED)",
)
_C_LADDER = {
    d: _OBS.counter(
        "sentinel_adaptive_ladder_transitions_total",
        "degrade-ladder moves, by direction",
        labels={"direction": d},
    )
    for d in ("up", "down")
}


class Hysteresis:
    """Enter-on-failure / cooldown-hold / exit-on-healthy-probe state.

    The shape every degrade site in the tree shares: ``enter()`` arms (or
    re-arms) a cooldown of ``cooldown_s`` REAL seconds — degrade windows
    deliberately track wall progress even under a VirtualTimeSource, like
    the reconnect back-offs they pair with; ``cooling`` is True while the
    cooldown runs (serve the fallback, don't probe); ``probe_due`` is
    True once it lapses (exactly one caller should pay the probe);
    ``exit()`` disarms on the first healthy answer.

    Transitions are journaled as ``<kind>.enter`` / ``<kind>.exit`` in
    ``obs.flight`` with the site's ``attrs`` (shard name etc.), mirrored
    as zero-duration trace events, and counted/flagged on the metrics the
    caller hands in — keeping every existing series name and invariant
    (degrade-hysteresis, shard-degrade-hysteresis) intact.
    """

    __slots__ = (
        "kind", "cooldown_s", "attrs", "active", "until",
        "_clock", "_lock", "_c_enter", "_c_exit", "_gauge",
    )

    def __init__(
        self,
        kind: str,
        cooldown_s: float,
        attrs: Optional[Dict[str, str]] = None,
        counter_enter=None,
        counter_exit=None,
        gauge=None,
        clock: Callable[[], float] = mono_s,
    ):
        self.kind = kind
        self.cooldown_s = float(cooldown_s)
        self.attrs = dict(attrs or {})
        self.active = False
        self.until = 0.0
        self._clock = clock
        self._lock = threading.Lock()
        self._c_enter = counter_enter
        self._c_exit = counter_exit
        self._gauge = gauge

    def enter(self, cooldown_s: Optional[float] = None, **extra) -> bool:
        """Arm (idempotent: extends the cooldown without re-journaling
        when already active).  Returns True on the enter TRANSITION."""
        cd = self.cooldown_s if cooldown_s is None else float(cooldown_s)
        with self._lock:
            self.until = self._clock() + cd
            if self.active:
                return False
            self.active = True
            if self._c_enter is not None:
                self._c_enter.inc()
            if self._gauge is not None:
                self._gauge.set(1)
        OT.event(f"{self.kind}.enter", attrs=self.attrs or None)
        FL.note(f"{self.kind}.enter", cooldown_s=cd, **self.attrs, **extra)
        return True

    def exit(self, **extra) -> bool:
        """Disarm on a healthy probe.  Returns True on the transition."""
        with self._lock:
            if not self.active:
                return False
            self.active = False
            if self._c_exit is not None:
                self._c_exit.inc()
            if self._gauge is not None:
                self._gauge.set(0)
        OT.event(f"{self.kind}.exit", attrs=self.attrs or None)
        FL.note(f"{self.kind}.exit", **self.attrs, **extra)
        return True

    @property
    def cooling(self) -> bool:
        """Degraded and inside the cooldown: serve the fallback."""
        return self.active and self._clock() < self.until

    @property
    def probe_due(self) -> bool:
        """Degraded with the cooldown lapsed: a probe may go out."""
        return self.active and self._clock() >= self.until

    def remaining_s(self) -> float:
        return max(self.until - self._clock(), 0.0) if self.active else 0.0


class Backoff:
    """Exponential backoff with FULL jitter (the AWS architecture-blog
    shape): attempt ``n`` waits ``uniform(0, min(cap, base * 2**n))``.

    A fixed retry interval synchronizes every client that lost the same
    server — they all retry on the same beat and stampede it exactly when
    it tries to come back.  Full jitter decorrelates the fleet while
    keeping the expected backoff exponential.

    ``base_s == 0`` degrades to "always ready" (the tests' no-throttle
    configuration).  ``clock``/``rng`` are injectable so unit tests run on
    virtual time with a seeded stream.
    """

    __slots__ = ("base_s", "cap_s", "attempt", "_next_at", "_rng", "_clock")

    def __init__(
        self,
        base_s: float,
        cap_s: float = 30.0,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = mono_s,
    ):
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.attempt = 0
        self._next_at = 0.0
        # seeded per-instance stream: never the shared global Random (two
        # clients sharing a module RNG would re-correlate under load)
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock

    def ready(self) -> bool:
        """May an attempt go out now?"""
        return self._clock() >= self._next_at

    def failure(self) -> float:
        """Record a failed attempt; returns the jittered delay armed."""
        # exponent clamped: the product is min()'d against cap_s anyway,
        # and 2.0**1024 after a long outage would raise OverflowError
        ceil = min(self.cap_s, self.base_s * (2.0 ** min(self.attempt, 63)))
        delay = self._rng.uniform(0.0, ceil) if ceil > 0 else 0.0
        self.attempt += 1
        self._next_at = self._clock() + delay
        return delay

    def success(self) -> None:
        """Healthy attempt: reset to the un-backed-off state."""
        self.attempt = 0
        self._next_at = 0.0


class DegradeLadder:
    """The ordered overload ladder, driven once per tick in engine time.

    ``observe(now_ms, overloaded, severe)``: ``overloaded`` is this
    tick's pressure verdict (the controller computes it from live
    signals); ``severe`` escalates straight past the hold (a watchdog
    firing or an already-expired-deadline flood must not wait out the
    hysteresis window rung by rung — it still climbs ONE rung at a time,
    so transitions stay monotone steps).

    Climb: ``climb_hold_ms`` of uninterrupted overload per rung.
    Descend: ``cool_hold_ms`` of uninterrupted health per rung.  Any
    contradicting tick resets the opposite hold — that IS the hysteresis.
    """

    def __init__(
        self,
        climb_hold_ms: int = 200,
        cool_hold_ms: int = 1000,
        max_level: int = FAIL_CLOSED,
    ):
        self.level = NORMAL
        self.climb_hold_ms = int(climb_hold_ms)
        self.cool_hold_ms = int(cool_hold_ms)
        self.max_level = int(max_level)
        self._over_since: Optional[int] = None
        self._calm_since: Optional[int] = None
        self.transitions: list = []  # [(now_ms, from, to)] — bounded below
        self._lock = threading.Lock()

    _TRANSITION_CAP = 4096

    def observe(self, now_ms: int, overloaded: bool, severe: bool = False) -> int:
        """Advance the ladder for one tick; returns the (new) level."""
        with self._lock:
            if overloaded:
                self._calm_since = None
                if self._over_since is None:
                    self._over_since = now_ms
                held = now_ms - self._over_since
                if (
                    self.level < self.max_level
                    and (severe or held >= self.climb_hold_ms)
                ):
                    self._move(now_ms, self.level + 1)
                    # each rung re-arms its own hold (severe re-climbs
                    # next tick; ordinary pressure waits the full hold)
                    self._over_since = now_ms
            else:
                self._over_since = None
                if self.level > NORMAL:
                    if self._calm_since is None:
                        self._calm_since = now_ms
                    if now_ms - self._calm_since >= self.cool_hold_ms:
                        self._move(now_ms, self.level - 1)
                        self._calm_since = now_ms
            return self.level

    def _move(self, now_ms: int, to: int) -> None:
        frm, self.level = self.level, to
        if len(self.transitions) < self._TRANSITION_CAP:
            self.transitions.append((int(now_ms), frm, to))
        _G_LEVEL.set(to)
        _C_LADDER["up" if to > frm else "down"].inc()
        OT.event(
            "adaptive.ladder",
            attrs={"from": LEVEL_NAMES[frm], "to": LEVEL_NAMES[to]},
        )
        FL.note(
            "adaptive.ladder",
            now_ms=int(now_ms),
            frm=LEVEL_NAMES[frm],
            to=LEVEL_NAMES[to],
        )

    def reset(self) -> None:
        with self._lock:
            self.level = NORMAL
            self._over_since = None
            self._calm_since = None
            self.transitions = []
            _G_LEVEL.set(0)
