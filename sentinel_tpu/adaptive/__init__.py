"""sentinel_tpu.adaptive — closed-loop system-adaptive protection.

Three pieces (see each module's docstring):

* ``signals``   — lock-light per-tick ``SystemSignals`` rows collected
  from the obs plane and the tick loop's own state;
* ``controller``— the BBR-style closed loop that republishes the
  SystemSlot ceilings (maxPass × minRT) as live rule-tensor columns,
  and drives the degrade ladder;
* ``degrade``   — the shared ``Hysteresis`` / ``Backoff`` primitives and
  the ONE ordered ladder
  (NORMAL → SHED_LOW_PRIORITY → PARAM_TAIL_OFF → CLUSTER_FALLBACK →
  FAIL_CLOSED) every degrade site in the tree delegates to.

Enable on a client with ``client.enable_adaptive()`` (see
``runtime/client.py``); disabled mode costs one ``is None`` check per
tick/submission, same contract as obs tracing and chaos failpoints.
"""

from sentinel_tpu.adaptive.controller import AdaptiveConfig, AdaptiveController
from sentinel_tpu.adaptive.degrade import (
    CLUSTER_FALLBACK,
    FAIL_CLOSED,
    LEVEL_NAMES,
    NORMAL,
    PARAM_TAIL_OFF,
    SHED_LOW_PRIORITY,
    Backoff,
    DegradeLadder,
    Hysteresis,
)
from sentinel_tpu.adaptive.signals import SignalCollector, SystemSignals

__all__ = [
    "AdaptiveConfig",
    "AdaptiveController",
    "Backoff",
    "DegradeLadder",
    "Hysteresis",
    "SignalCollector",
    "SystemSignals",
    "NORMAL",
    "SHED_LOW_PRIORITY",
    "PARAM_TAIL_OFF",
    "CLUSTER_FALLBACK",
    "FAIL_CLOSED",
    "LEVEL_NAMES",
]
