"""Deterministic flash-crowd simulator for the adaptive plane.

Drives a REAL sync-mode ``SentinelClient`` on virtual time through a
healthy → 2×-capacity storm → recovery schedule, with a queueing service
model on top: admitted requests enter a FIFO backend that serves at most
``capacity_per_step`` of them per step, each taking ``base_svc_steps``
more steps to finish — latency is queue wait plus service.  Offered
load under capacity rides at base latency; 2× capacity with unbounded
admission grows the queue linearly and latency collapses (the
BENCH_r05 req_p99 ≈ 1 s failure mode, reproduced in miniature), while
the adaptive gate bounds in-flight work at the BBR product and keeps
latency flat at ~capacity goodput.  Everything is engine-time
pure: the same inputs replay the same admissions, ladder transitions and
latencies, which is what the chaos plane's seed-determinism check needs.

Used by the ``overload_storm`` chaos scenario (pass/fail invariants) and
the ``adaptive_overload`` bench row (numbers for BENCH_r0N) — one model,
two consumers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


def storm_controller_preset(op=None):
    """Controller tuning for the simulator's scales, shared by BOTH
    consumers (the ``overload_storm`` chaos scenario and the
    ``adaptive_overload`` bench row) so the invariant-gated experiment
    and the published BENCH numbers can never desynchronize: host-CPU
    input disabled (a busy CI box must not steer the ladder), blocking
    pressure on (the sim's overload shows up as sustained shedding),
    engine-time holds sized to the 10 ms step.

    ``op`` is the serving ``workload.OperatingPoint`` (default
    ``sim_default_op()``): the admission queue bound follows its
    pipeline depth, so the preset can never drift from the point the
    tuner/bench actually run — the same shared definition bench rows
    consume."""
    from sentinel_tpu.adaptive.controller import AdaptiveConfig

    if op is None:
        from sentinel_tpu.workload.operating_point import sim_default_op

        op = sim_default_op()
    return AdaptiveConfig(
        rt_tolerance=3.0,
        cpu_high=2.0,
        min_ceiling=4.0,
        climb_hold_ms=50,
        cool_hold_ms=300,
        block_pressure_ratio=1.0,
        queue_max=int(op.pipeline_depth),
    )


@dataclass
class SimResult:
    p99_healthy_ms: float = 0.0
    p99_storm_ms: float = 0.0
    goodput_healthy: float = 0.0  # completions/step over the healthy tail
    goodput_storm: float = 0.0  # completions/step over the storm window
    #: min rolling-window completions while the ladder sat BELOW
    #: FAIL_CLOSED (the "goodput never hits zero" invariant input)
    goodput_floor: float = 0.0
    submitted: int = 0
    passed: int = 0
    blocked: int = 0
    final_level: int = 0
    max_level: int = 0
    ladder_transitions: List[tuple] = field(default_factory=list)
    max_inflight: int = 0

    def to_dict(self) -> dict:
        return {
            "p99_healthy_ms": round(self.p99_healthy_ms, 3),
            "p99_storm_ms": round(self.p99_storm_ms, 3),
            "goodput_healthy_per_step": round(self.goodput_healthy, 3),
            "goodput_storm_per_step": round(self.goodput_storm, 3),
            "goodput_floor": round(self.goodput_floor, 3),
            "submitted": self.submitted,
            "passed": self.passed,
            "blocked": self.blocked,
            "final_level": self.final_level,
            "max_level": self.max_level,
            "ladder_transitions": len(self.ladder_transitions),
            "max_inflight": self.max_inflight,
        }


def run_overload_sim(
    adaptive: bool = True,
    adaptive_cfg=None,
    healthy_steps: int = 100,
    storm_steps: int = 200,
    recover_steps: int = 120,
    step_ms: int = 10,
    offered_healthy: int = 3,
    offered_storm: int = 8,
    capacity_per_step: int = 4,
    base_svc_steps: int = 2,
    prio_every: int = 2,
    resource: str = "storm/api",
    op=None,
) -> SimResult:
    """One full healthy→storm→recover run; see module docstring.

    ``op`` (a ``workload.OperatingPoint``, default ``sim_default_op()``
    — identity against the small config, so seeded goldens are
    unchanged) decides the client's engine config and pipeline depth:
    the one shared operating-point definition."""
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.core import errors as ERR
    from sentinel_tpu.runtime.client import SentinelClient
    from sentinel_tpu.utils.time_source import VirtualTimeSource

    if op is None:
        from sentinel_tpu.workload.operating_point import sim_default_op

        op = sim_default_op()
    vt = VirtualTimeSource(start_ms=1_000)
    client = SentinelClient(
        cfg=op.apply_to_config(small_engine_config()),
        time_source=vt,
        mode="sync",
        pipeline_depth=op.pipeline_depth,
    )
    client.start()
    rid = client.registry.resource_id(resource)
    assert rid is not None
    ad = client.enable_adaptive(adaptive_cfg) if adaptive else None

    out = SimResult()
    backlog: List[int] = []  # FIFO of submit_step awaiting a server slot
    in_service: List[tuple] = []  # (done_step, submit_step)
    lat_healthy: List[float] = []
    lat_storm: List[float] = []
    per_step_completed: List[int] = []
    per_step_level: List[int] = []
    total_steps = healthy_steps + storm_steps + recover_steps
    storm_lo, storm_hi = healthy_steps, healthy_steps + storm_steps

    def offered_at(step: int) -> int:
        if step >= total_steps:
            return 0  # drain phase
        return offered_storm if storm_lo <= step < storm_hi else offered_healthy

    step = 0
    max_steps = total_steps + 4000  # drain bound (queue collapse is long)
    while step < max_steps:
        # 1) completions due this step (one bulk completion tick)
        done = [e for e in in_service if e[0] <= step]
        if done:
            in_service[:] = [e for e in in_service if e[0] > step]
            k = len(done)
            lat = np.asarray(
                [(step - sub) * step_ms for _due, sub in done], np.float32
            )
            client.submit_completion_block(
                res=np.full(k, rid, np.int32),
                rt=lat,
                success=np.ones(k, np.int32),
                inbound=np.ones(k, np.int32),
            )
            per_step_completed.append(k)
            for _due, sub in done:
                l = float((step - sub) * step_ms)
                if sub < storm_lo:
                    lat_healthy.append(l)
                elif sub < storm_hi:
                    lat_storm.append(l)
        else:
            per_step_completed.append(0)
        per_step_level.append(ad.ladder.level if ad is not None else 0)

        # 2) the backend serves at most capacity_per_step queued requests
        for _ in range(min(capacity_per_step, len(backlog))):
            in_service.append((step + base_svc_steps, backlog.pop(0)))

        # 3) offered load (one bulk decision tick)
        n = offered_at(step)
        if n:
            prio = [(i % prio_every) == 0 for i in range(n)]
            verdicts = client.check_batch(
                [resource] * n, prioritized=prio, inbound=True
            )
            out.submitted += n
            for v, _w in verdicts:
                if v in (ERR.PASS, ERR.PASS_WAIT):
                    out.passed += 1
                    backlog.append(step)
                else:
                    out.blocked += 1
            out.max_inflight = max(
                out.max_inflight, len(backlog) + len(in_service)
            )
        elif not backlog and not in_service:
            break  # drained
        vt.advance(step_ms)
        step += 1

    if ad is not None:
        out.final_level = ad.ladder.level
        out.ladder_transitions = list(ad.ladder.transitions)
        out.max_level = max(
            (t[2] for t in out.ladder_transitions), default=0
        )
    client.stop()

    def p99(xs: List[float]) -> float:
        return float(np.percentile(np.asarray(xs), 99)) if xs else 0.0

    out.p99_healthy_ms = p99(lat_healthy)
    out.p99_storm_ms = p99(lat_storm)
    tail = per_step_completed[max(storm_lo - 50, 0) : storm_lo]
    out.goodput_healthy = float(np.mean(tail)) if tail else 0.0
    storm_done = per_step_completed[storm_lo:storm_hi]
    out.goodput_storm = float(np.mean(storm_done)) if storm_done else 0.0
    # rolling 10-step goodput floor while the ladder sat below FAIL_CLOSED
    # (healthy warm-up excluded; completions only start after the first
    # service time anyway)
    from sentinel_tpu.adaptive.degrade import FAIL_CLOSED

    win = 10
    floors = []
    comp = per_step_completed
    for i in range(storm_lo, min(len(comp), total_steps) - win):
        if all(lv < FAIL_CLOSED for lv in per_step_level[i : i + win]):
            floors.append(sum(comp[i : i + win]))
    out.goodput_floor = float(min(floors)) if floors else 0.0
    return out
