"""Closed-loop system-adaptive protection controller.

The reference's SystemSlot is an *adaptive* gate — ``checkBbr`` admits
new work only while concurrency fits ``maxSuccessQps × minRt`` — but it
still needs an operator-authored ``SystemRule`` to arm it.  This
controller closes the loop: each engine tick it folds the live
``SystemSignals`` row into a BBR-style capacity estimate and republishes
the SystemSlot ceilings as fresh values of the existing rule-tensor
columns (``SystemTensors.qps`` / ``max_thread``).  The columns are
ordinary traced arguments of the jitted tick, so new values are a
five-scalar upload — **no recompile, jaxpr fingerprints untouched**.

Control law (AIMD around the BBR estimate):

* capacity estimate ``cap = max_pass_rate × max(min_rt, floor) / 1000``
  — admitted throughput at its recent best times the windowed RT floor,
  i.e. the concurrency the pipe fits (checkBbr's product, host side);
* overloaded ticks multiply the concurrency ceiling by ``shrink``
  (never below ``min_ceiling`` — the controller must not choke the very
  traffic that re-measures capacity);
* healthy ticks grow it by ``grow`` toward ``cap × headroom`` so a
  recovered system re-opens quickly but never past what it measured;
* the QPS column follows via Little's law (``ceiling × 1000 / min_rt``).

The same pressure verdict drives the unified degrade ladder
(``degrade.DegradeLadder``); the runtime applies each rung's effect
(shed low-priority, param tail off, cluster fallback, fail closed).
Everything runs in ENGINE time off the signals row — fully
deterministic under a VirtualTimeSource, which is what lets the chaos
plane replay overload storms from a seed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from sentinel_tpu.adaptive import degrade as DG
from sentinel_tpu.adaptive.signals import SignalCollector, SystemSignals
from sentinel_tpu.obs.registry import REGISTRY as _OBS

_G_CEILING = _OBS.gauge(
    "sentinel_adaptive_ceiling",
    "live adaptive concurrency ceiling (maxPass x minRT; -1 while unarmed)",
)


@dataclass(frozen=True)
class AdaptiveConfig:
    """Tuning knobs; the defaults suit millisecond ticks."""

    #: overloaded when service RT runs this many times above the floor
    rt_tolerance: float = 5.0
    #: un-ticked admission queue depth that counts as overload
    queue_high: int = 4096
    #: host CPU fraction that counts as overload
    cpu_high: float = 0.95
    #: ceiling may probe up to cap × headroom while healthy
    headroom: float = 2.0
    #: multiplicative decrease per overloaded control step
    shrink: float = 0.9
    #: multiplicative increase per healthy control step
    grow: float = 1.05
    #: concurrency floor — capacity re-measurement must keep flowing
    min_ceiling: float = 8.0
    #: minRT floor (ms): a sub-ms RT must not collapse the BBR product
    min_rt_floor_ms: float = 1.0
    #: re-upload threshold — skip the device transfer for <5% moves
    update_epsilon: float = 0.05
    #: ladder hysteresis (engine-time ms)
    climb_hold_ms: int = 200
    cool_hold_ms: int = 1000
    #: fraction of queue_high where non-prioritized work starts shedding
    #: at SHED_LOW_PRIORITY and above
    shed_lowprio_frac: float = 0.5
    #: hard admission bound (items); beyond it every submit sheds
    queue_max: int = 65536
    #: treat sustained blocking as overload evidence when
    #: block_rate > ratio × pass_rate (0 disables — a rule-heavy service
    #: blocking by POLICY must not read as overload by default)
    block_pressure_ratio: float = 0.0
    #: long-memory capacity estimate: per control step the stored
    #: estimate decays by this factor unless re-measured higher.  The 1 s
    #: signal window alone forgets healthy capacity the moment the gate
    #: starts suppressing traffic; the slow decay keeps the BBR product
    #: anchored at what the system actually served when it was well.
    cap_decay: float = 0.999
    #: AIMD adjustment cadence (engine-time ms): shrink/grow act at most
    #: once per interval, not once per tick — a 1 ms tick train must not
    #: multiply the ceiling to the floor within one RT window
    adjust_interval_ms: int = 50


class AdaptiveController:
    """One per client; the tick loop drives ``on_tick`` once per tick."""

    def __init__(self, cfg: Optional[AdaptiveConfig] = None):
        self.cfg = cfg or AdaptiveConfig()
        self.signals = SignalCollector()
        self.ladder = DG.DegradeLadder(
            climb_hold_ms=self.cfg.climb_hold_ms,
            cool_hold_ms=self.cfg.cool_hold_ms,
        )
        #: live concurrency ceiling; inf = unarmed (no overload seen and
        #: nothing measured yet — the gate stays open)
        self.ceiling = float("inf")
        #: long-memory BBR capacity estimate (concurrency units)
        self.cap_est = 0.0
        # None = never adjusted (engine clocks may legitimately start at
        # 0, so 0 cannot be the sentinel)
        self._last_adjust_ms: Optional[int] = None
        self._uploaded = (-1.0, -1.0)  # (qps, max_thread) last published
        self.last: SystemSignals = SystemSignals()
        self._severe_pending = False
        _G_CEILING.set(-1)

    def disarm(self) -> None:
        """Full reset at disable: gate open, ladder down, gauges back to
        their unarmed values (a disabled plane must not keep reporting
        an armed ceiling on /metrics)."""
        self.ceiling = float("inf")
        self.cap_est = 0.0
        self._last_adjust_ms = None
        self._uploaded = (-1.0, -1.0)
        self.ladder.reset()
        _G_CEILING.set(-1)

    # -- external severity hints --------------------------------------------

    def note_severe(self) -> None:
        """A watchdog fire / fail-closed tick: escalate on the next
        observation without waiting out the climb hold."""
        self._severe_pending = True

    # -- control step --------------------------------------------------------

    def overloaded(self, s: SystemSignals) -> bool:
        c = self.cfg
        if s.queue_depth > c.queue_high:
            return True
        if s.sys_cpu > c.cpu_high and s.inflight > c.min_ceiling:
            # host CPU saturation counts only WITH traffic pressure — a
            # busy co-tenant must not climb the ladder of an idle service
            return True
        floor = max(s.min_rt_ms, c.min_rt_floor_ms)
        if (
            s.min_rt_ms > 0
            and s.rt_ewma_ms > c.rt_tolerance * floor
            and s.inflight > c.min_ceiling
        ):
            return True
        if (
            c.block_pressure_ratio > 0
            and s.block_rate > c.block_pressure_ratio * max(s.pass_rate, 1.0)
        ):
            return True
        return False

    def on_tick(self, s: SystemSignals):
        """One control step.  Returns the (qps, max_thread) pair to
        publish into the system columns, or None when the last upload
        still stands (within ``update_epsilon``)."""
        self.last = s
        c = self.cfg
        over = self.overloaded(s)
        severe = self._severe_pending
        self._severe_pending = False
        self.ladder.observe(s.now_ms, over or severe, severe=severe)

        min_rt = max(s.min_rt_ms, c.min_rt_floor_ms)
        cap_now = s.max_pass_rate * min_rt / 1000.0  # BBR: maxPass × minRT
        # long-memory capacity: re-measure up, decay down slowly — the
        # gate's own suppression must not erase what the pipe fits
        self.cap_est = max(cap_now, self.cap_est * c.cap_decay)
        cap = self.cap_est
        adjust = (
            self._last_adjust_ms is None
            or s.now_ms - self._last_adjust_ms >= c.adjust_interval_ms
        )
        if over:
            if self.ceiling == float("inf"):
                # arm at the measured capacity (not current inflight —
                # that is exactly the runaway value being cut back)
                self.ceiling = max(cap, c.min_ceiling)
                self._last_adjust_ms = s.now_ms
            elif adjust:
                self.ceiling = max(self.ceiling * c.shrink, c.min_ceiling)
                self._last_adjust_ms = s.now_ms
        elif self.ceiling != float("inf") and adjust:
            limit = cap * c.headroom if cap > 0 else self.ceiling * c.grow
            self.ceiling = min(self.ceiling * c.grow, max(limit, c.min_ceiling))
            self._last_adjust_ms = s.now_ms
            if self.ladder.level == DG.NORMAL and cap > 0 and (
                self.ceiling >= cap * c.headroom
            ):
                # fully recovered and re-opened: disarm (gate off) so a
                # long-healthy system pays zero admission friction
                self.ceiling = float("inf")

        if self.ceiling == float("inf"):
            want = (-1.0, -1.0)
        else:
            qps = self.ceiling * 1000.0 / min_rt
            want = (qps, self.ceiling)
        _G_CEILING.set(-1 if want[1] < 0 else want[1])
        prev = self._uploaded
        if want == prev:
            return None
        if want[1] > 0 and prev[1] > 0:
            rel = abs(want[1] - prev[1]) / prev[1]
            if rel < c.update_epsilon:
                return None
        self._uploaded = want
        return want

    # -- rung effects (read by the runtime's admission path) -----------------

    @property
    def level(self) -> int:
        return self.ladder.level

    def system_columns(
        self, static, qps: float, max_thread: float
    ):
        """Fold the adaptive ceilings into a static ``SystemTensors``:
        tightest-wins per column (an operator rule stricter than the
        controller keeps enforcing, via the same fold
        ``compile_system_rules`` uses), adaptive values replace unset
        statics.  Returns plain ``np.float32`` leaves for device_put."""
        from sentinel_tpu.core.rule_tensors import tightest_threshold

        return type(static)(
            load=np.float32(static.load),
            cpu=np.float32(static.cpu),
            qps=tightest_threshold(static.qps, qps),
            avg_rt=np.float32(static.avg_rt),
            max_thread=tightest_threshold(static.max_thread, max_thread),
        )
