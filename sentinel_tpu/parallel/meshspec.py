"""The ONE mesh description every SPMD consumer shares.

Three things need the same answer to "what mesh do we shard over, and
how do we boot a virtual copy of it on CPU?":

* ``parallel/spmd.py``      — builds the runtime ``Mesh`` and shardings;
* ``__graft_entry__.py``    — the multichip dry-run re-execs a child with
  a forced n-device CPU platform;
* ``analysis/spmd/``        — the tier-4 analyzer lowers the real entry
  points under the same mesh in a subprocess (runner.py) and its tests
  run inside the tier-1 suite, whose conftest forces the same topology.

Before this module each of those restated "8 devices, axis 'res',
``--xla_force_host_platform_device_count``" by hand, and a drift between
them would mean the analyzer blesses shardings the runtime never uses.

IMPORT CONSTRAINT: stdlib only.  tests/conftest.py loads this file by
path BEFORE jax is imported (the env mutation must precede backend
init), so nothing here may import jax or any sentinel_tpu module.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import MutableMapping, Optional

#: the resource/node-row mesh axis every sharded tensor splits on
MESH_AXIS = "res"

#: blessed virtual-mesh width: the dry-run, the tier-4 analyzer, and the
#: test suite all force this many CPU devices (a v5e-8 tray's shape)
MESH_DEVICES = 8

_FORCE_FLAG = "xla_force_host_platform_device_count"


@dataclass(frozen=True)
class MeshSpec:
    """Shape of the blessed device mesh (1-D over the resource axis)."""

    n_devices: int = MESH_DEVICES
    axis: str = MESH_AXIS


def mesh_spec() -> MeshSpec:
    """The single source of truth consumed by runtime and analyzer."""
    return MeshSpec()


def force_cpu_mesh_env(
    environ: MutableMapping[str, str],
    n_devices: Optional[int] = None,
    keep_existing_count: bool = False,
) -> int:
    """Mutate ``environ`` so JAX boots a virtual n-device CPU platform.

    Must run before the target process initializes its jax backends
    (XLA_FLAGS and JAX_PLATFORMS are read at backend init).  With
    ``keep_existing_count`` a device count already forced in XLA_FLAGS
    wins (the conftest contract: a caller who pre-forced a topology gets
    to keep it); otherwise any prior forcing is stripped and replaced.
    Returns the device count actually in effect.
    """
    n = n_devices if n_devices is not None else mesh_spec().n_devices
    environ["JAX_PLATFORMS"] = "cpu"
    flags = environ.get("XLA_FLAGS", "").split()
    if keep_existing_count:
        for f in flags:
            if _FORCE_FLAG in f:
                _, _, v = f.partition("=")
                try:
                    return int(v)
                except ValueError:
                    break  # malformed: fall through and replace it
    flags = [f for f in flags if _FORCE_FLAG not in f]
    flags.append(f"--{_FORCE_FLAG}={n}")
    environ["XLA_FLAGS"] = " ".join(flags)
    return n
