"""Multi-chip SPMD: shard the resource/node axis over a device mesh.

The reference scales horizontally by adding JVMs behind a token server
(sentinel-cluster, SURVEY.md §2.5); intra-process it scales by striped
LongAdders.  The TPU-native scale-out axis is the *resource cardinality*:
all window/stat tensors are sharded on their node-row dimension across a
``Mesh(('res',))``, batches stay replicated, and XLA inserts the gathers /
reductions over ICI (the scaling-book recipe: annotate shardings, let the
partitioner place collectives).

Why this layout: per-tick the engine reads O(B·K) scattered rows and
writes O(B) rows of a [node_rows, ...] table.  Sharding rows means each
chip owns 1/n of the table (HBM capacity scales with the mesh — 8M
resources on a v5e-8 at default shapes), while the replicated [B]-sized
batch and verdict tensors ride ICI once per tick.

Controller/rule-slot state (per-rule tensors) is replicated: it is small
(O(rules)) and every chip derives identical updates from the replicated
batch, so no communication is needed for it.

The layout is declared twice over: MESH-FREE ``PartitionSpec`` pytrees
(``state_partition_specs`` and friends — what the tier-4 SPMD analyzer
consumes, no devices needed) and their ``NamedSharding`` bindings to a
live mesh (``state_shardings``).  The mesh shape itself comes from
``meshspec.mesh_spec()`` — the same source the dry-run, the analyzer
subprocess, and the test conftest force their virtual topology from.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from sentinel_tpu.core.config import EngineConfig
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import gsketch as GS
from sentinel_tpu.ops import rtq as RQ
from sentinel_tpu.ops import token_col as TC
from sentinel_tpu.ops import window as W
from sentinel_tpu.parallel.meshspec import mesh_spec


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.asarray(devices), axis_names=(mesh_spec().axis,))


# -- mesh-free PartitionSpec pytrees ----------------------------------------
#
# These are the BLESSED shardings: pure data, no jax devices touched.
# The tier-4 analyzer (analysis/spmd) folds them with eval_shape'd leaf
# shapes to project per-shard bytes and check axis divisibility without
# a mesh; state_shardings() below binds the same specs to a live mesh,
# so runtime and analyzer cannot drift.


def window_partition_specs(rows_sharded: bool = True) -> W.WindowState:
    """PartitionSpec pytree for one WindowState: bucket/running tensors
    split on their row axis, epoch/rotation scalars replicated."""
    axis = mesh_spec().axis
    r = PS(axis) if rows_sharded else PS()
    rep = PS()
    # the O(1) running sums are row-indexed like the bucket tensors,
    # so they shard on the same axis; epoch/rotation scalars replicate
    return W.WindowState(
        counts=r, rt_sum=r, rt_min=r, epochs=rep,
        run=r, run_rt=r, run_rt_min=r, rot_wid=rep,
    )


def token_col_partition_specs() -> TC.TokenColState:
    """PartitionSpec pytree for the cluster token-column ledger: flow
    slots are the scale-out axis (one row per flow), limits ride along."""
    axis = mesh_spec().axis
    return TC.TokenColState(
        win=window_partition_specs(rows_sharded=True),
        limits=PS(axis),
    )


def _sketch_partition_specs(cfg: EngineConfig):
    """PartitionSpec pytree for EngineState.gs, per the live sketch impl."""
    axis = mesh_spec().axis
    rep = PS()
    if not cfg.sketch_stats:
        return GS.SketchState(counts=rep, epochs=rep)
    if cfg.sketch_salsa:
        from sentinel_tpu.sketch import salsa as SA

        return SA.SalsaState(
            words=PS(None, None, None, axis),
            lvlmap=PS(None, None, None, axis),
            run=PS(None, None, axis),
            epochs=rep,
            rot_wid=rep,
            # the unpacked current bucket shards on width like run
            cur=PS(None, None, axis),
            cur_wid=rep,
        )
    return GS.SketchState(counts=PS(None, None, axis, None), epochs=rep)


def state_partition_specs(cfg: EngineConfig) -> E.EngineState:
    """PartitionSpec pytree matching EngineState: node-row tensors split
    on the mesh axis, per-rule tensors replicated."""
    axis = mesh_spec().axis
    row = PS(axis)
    rep = PS()

    return E.EngineState(
        win_sec=window_partition_specs(True),
        win_min=window_partition_specs(cfg.enable_minute_window),
        concurrency=row,
        latest_passed_ms=rep,
        warmup_tokens=rep,
        warmup_last_s=rep,
        warm_acc=rep,
        occ_tokens=row,  # node-keyed borrow pools shard with their rows
        occ_epoch=row,
        cb_state=rep,
        cb_retry_ms=rep,
        cb_counts=rep,
        cb_epochs=rep,
        # the hashed param store is REPLICATED, deliberately: the tier-4
        # SPMD analyzer's collective ledger measured the width-sharded
        # layout paying four partial-result all-reduces per tick
        # (s32[2B] x2 + s32[2B,P] x2 — the param scatter/read computing
        # per-shard partials and reducing them across the mesh; ~5 KiB
        # per tick at CI scale, scaling with batch x depth x planes).
        # The store is small (single-digit MiB even at the 1M-resource
        # config) next to the row tables, so replication costs little
        # HBM and removes those collectives entirely.  Re-shard only
        # together with a shard-local param kernel, and re-pin
        # analysis/spmd/collectives.json when you do.
        pcms=rep,
        pcms_epochs=rep,
        pconc=rep,
        # the global sketch shards on its width axis so tail-resource
        # observability scales with chips; with the sketch off the state
        # is a unit dummy — replicate it.  The salsa tier (sketch/salsa)
        # shards its packed words/bitmap on the word axis and the running
        # sums on the logical width axis — all width-aligned, so the
        # shards stay co-local with the seed layout's
        gs=_sketch_partition_specs(cfg),
        rtq=RQ.RtqState(counts=rep, epochs=rep),
    )


def bind_shardings(specs, mesh: Mesh):
    """PartitionSpec pytree -> NamedSharding pytree on ``mesh``."""
    return jax.tree.map(
        lambda ps: NamedSharding(mesh, ps),
        specs,
        is_leaf=lambda x: isinstance(x, PS),
    )


def state_shardings(cfg: EngineConfig, mesh: Mesh) -> E.EngineState:
    """Sharding pytree matching EngineState (the blessed specs bound to
    a live mesh)."""
    return bind_shardings(state_partition_specs(cfg), mesh)


def shard_state(state: E.EngineState, cfg: EngineConfig, mesh: Mesh) -> E.EngineState:
    return jax.device_put(state, state_shardings(cfg, mesh))


def make_sharded_tick(cfg: EngineConfig, mesh: Mesh, donate: bool = True):
    """jit the engine tick with sharded-in/sharded-out state.

    Batches and rule tensors are replicated; verdict outputs are
    replicated (every host sees every verdict).  XLA partitions the
    scatters/gathers over the row-sharded tables and inserts the ICI
    collectives.
    """
    import functools

    rep = NamedSharding(mesh, PS())
    st_sh = state_shardings(cfg, mesh)

    fn = functools.partial(E.tick, cfg=cfg)
    # sharding pytree prefixes: `rep` covers whole RuleSet / batch subtrees
    return jax.jit(
        fn,
        in_shardings=(st_sh, rep, rep, rep, rep, rep, rep),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,) if donate else (),
    )
