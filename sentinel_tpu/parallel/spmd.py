"""Multi-chip SPMD: shard the resource/node axis over a device mesh.

The reference scales horizontally by adding JVMs behind a token server
(sentinel-cluster, SURVEY.md §2.5); intra-process it scales by striped
LongAdders.  The TPU-native scale-out axis is the *resource cardinality*:
all window/stat tensors are sharded on their node-row dimension across a
``Mesh(('res',))``, batches stay replicated, and XLA inserts the gathers /
reductions over ICI (the scaling-book recipe: annotate shardings, let the
partitioner place collectives).

Why this layout: per-tick the engine reads O(B·K) scattered rows and
writes O(B) rows of a [node_rows, ...] table.  Sharding rows means each
chip owns 1/n of the table (HBM capacity scales with the mesh — 8M
resources on a v5e-8 at default shapes), while the replicated [B]-sized
batch and verdict tensors ride ICI once per tick.

Controller/rule-slot state (per-rule tensors) is replicated: it is small
(O(rules)) and every chip derives identical updates from the replicated
batch, so no communication is needed for it.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from sentinel_tpu.core.config import EngineConfig
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import gsketch as GS
from sentinel_tpu.ops import rtq as RQ
from sentinel_tpu.ops import window as W


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.asarray(devices), axis_names=("res",))


def _sketch_shardings(cfg: EngineConfig, mesh: Mesh, rep):
    """Sharding pytree for EngineState.gs, per the live sketch impl."""
    if not cfg.sketch_stats:
        return GS.SketchState(counts=rep, epochs=rep)
    if cfg.sketch_salsa:
        from sentinel_tpu.sketch import salsa as SA

        return SA.SalsaState(
            words=NamedSharding(mesh, PS(None, None, None, "res")),
            lvlmap=NamedSharding(mesh, PS(None, None, None, "res")),
            run=NamedSharding(mesh, PS(None, None, "res")),
            epochs=rep,
            rot_wid=rep,
            # the unpacked current bucket shards on width like run
            cur=NamedSharding(mesh, PS(None, None, "res")),
            cur_wid=rep,
        )
    return GS.SketchState(
        counts=NamedSharding(mesh, PS(None, None, "res", None)),
        epochs=rep,
    )


def state_shardings(cfg: EngineConfig, mesh: Mesh) -> E.EngineState:
    """Sharding pytree matching EngineState: node-row tensors split on
    'res', per-rule tensors replicated."""
    row = NamedSharding(mesh, PS("res"))
    rep = NamedSharding(mesh, PS())

    def win(ws_rows_sharded: bool) -> W.WindowState:
        r = row if ws_rows_sharded else rep
        # the O(1) running sums are row-indexed like the bucket tensors,
        # so they shard on the same axis; epoch/rotation scalars replicate
        return W.WindowState(
            counts=r, rt_sum=r, rt_min=r, epochs=rep,
            run=r, run_rt=r, run_rt_min=r, rot_wid=rep,
        )

    return E.EngineState(
        win_sec=win(True),
        win_min=win(cfg.enable_minute_window),
        concurrency=row,
        latest_passed_ms=rep,
        warmup_tokens=rep,
        warmup_last_s=rep,
        warm_acc=rep,
        occ_tokens=row,  # node-keyed borrow pools shard with their rows
        occ_epoch=row,
        cb_state=rep,
        cb_retry_ms=rep,
        cb_counts=rep,
        cb_epochs=rep,
        # the hashed param store shards on its row axis (pcms [depth, Q, nb],
        # pconc [depth, Q]) — per-(rule,value) budgets scale with chips
        pcms=NamedSharding(mesh, PS(None, "res", None)),
        pcms_epochs=rep,
        pconc=NamedSharding(mesh, PS(None, "res")),
        # the global sketch shards on its width axis so tail-resource
        # observability scales with chips; with the sketch off the state
        # is a unit dummy — replicate it.  The salsa tier (sketch/salsa)
        # shards its packed words/bitmap on the word axis and the running
        # sums on the logical width axis — all width-aligned, so the
        # shards stay co-local with the seed layout's
        gs=_sketch_shardings(cfg, mesh, rep),
        rtq=RQ.RtqState(counts=rep, epochs=rep),
    )


def shard_state(state: E.EngineState, cfg: EngineConfig, mesh: Mesh) -> E.EngineState:
    return jax.device_put(state, state_shardings(cfg, mesh))


def make_sharded_tick(cfg: EngineConfig, mesh: Mesh, donate: bool = True):
    """jit the engine tick with sharded-in/sharded-out state.

    Batches and rule tensors are replicated; verdict outputs are
    replicated (every host sees every verdict).  XLA partitions the
    scatters/gathers over the row-sharded tables and inserts the ICI
    collectives.
    """
    import functools

    rep = NamedSharding(mesh, PS())
    st_sh = state_shardings(cfg, mesh)

    fn = functools.partial(E.tick, cfg=cfg)
    # sharding pytree prefixes: `rep` covers whole RuleSet / batch subtrees
    return jax.jit(
        fn,
        in_shardings=(st_sh, rep, rep, rep, rep, rep, rep),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,) if donate else (),
    )
