"""Host-layer shard-by-resource routing — the multi-host story (SURVEY §2.9).

A multi-host deployment splits the resource space across hosts: each host's
engine owns the exact rows for its shard (decisions + stats stay local, no
cross-host chatter on the hot path), and GLOBAL budgets ride the cluster
token protocol exactly as in a single-host deployment.  This router is the
host-layer piece: deterministic resource→shard assignment and a fan-out
`check_batch` that groups a mixed batch per shard.

Within one host, chips scale via the SPMD row sharding (parallel/spmd.py,
ICI); ACROSS hosts, this router is the DCN-level partitioning.  Shards are
any objects with the SentinelClient surface — in-process clients in tests,
remote host stubs in a real deployment.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple


def shard_of(resource: str, n_shards: int) -> int:
    """Deterministic, process-independent shard assignment (crc32 — the
    same stability argument as the RLS flow-id derivation)."""
    return zlib.crc32(resource.encode("utf-8")) % n_shards


class ShardRouter:
    def __init__(self, shards: Sequence[Any]):
        assert shards, "at least one shard"
        self.shards = list(shards)

    def shard_for(self, resource: str):
        return self.shards[shard_of(resource, len(self.shards))]

    def entry(self, resource: str, **kw):
        """Single entry routes to the owning shard (SphU.entry surface)."""
        return self.shard_for(resource).entry(resource, **kw)

    def check_batch(
        self,
        resources: Sequence[str],
        counts: Optional[Sequence[int]] = None,
        origins: Optional[Sequence[str]] = None,
        params: Optional[Sequence[Any]] = None,
        prioritized: Optional[Sequence[bool]] = None,
        **kw,
    ) -> List[Tuple[int, int]]:
        """Mixed-shard bulk check: group per shard (EVERY per-item sequence
        sliced with its group), shards consulted concurrently — one DCN
        round-trip of latency, not one per shard — results restored to
        input order."""
        n = len(resources)
        groups: Dict[int, List[int]] = {}
        for i, r in enumerate(resources):
            groups.setdefault(shard_of(r, len(self.shards)), []).append(i)
        out: List[Optional[Tuple[int, int]]] = [None] * n

        def pick(seq, idxs):
            return [seq[i] for i in idxs] if seq is not None else None

        def run(s, idxs):
            return self.shards[s].check_batch(
                pick(resources, idxs),
                counts=pick(counts, idxs),
                origins=pick(origins, idxs),
                params=pick(params, idxs),
                prioritized=pick(prioritized, idxs),
                **kw,
            )

        if len(groups) == 1:
            ((s, idxs),) = groups.items()
            results = {s: run(s, idxs)}
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(groups)) as pool:
                futures = {s: pool.submit(run, s, idxs) for s, idxs in groups.items()}
                results = {s: f.result() for s, f in futures.items()}
        for s, idxs in groups.items():
            for j, i in enumerate(idxs):
                out[i] = results[s][j]
        return out  # type: ignore[return-value]

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Merged per-resource stats across shards.  A resource can appear
        on several hosts (cluster-mode traffic through load-balanced
        ingress), so numeric fields are SUMMED, not overwritten; minRt
        takes the min of observed values."""
        merged: Dict[str, Dict[str, float]] = {}
        for s in self.shards:
            for name, stats in s.stats.snapshot().items():
                prev = merged.get(name)
                if prev is None:
                    merged[name] = dict(stats)
                    continue
                for k, v in stats.items():
                    if k == "minRt":
                        nonzero = [x for x in (prev[k], v) if x > 0]
                        prev[k] = min(nonzero) if nonzero else 0.0
                    elif k == "avgRt":
                        pass  # recomputed below from summed successes
                    else:
                        prev[k] = prev[k] + v
                # weighted avgRt over summed successes
                s_prev = prev["successQps"] - stats["successQps"]
                if prev["successQps"] > 0:
                    prev["avgRt"] = (
                        prev["avgRt"] * s_prev + stats["avgRt"] * stats["successQps"]
                    ) / prev["successQps"]
        return merged
