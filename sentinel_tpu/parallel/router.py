"""Host-layer shard-by-resource routing — the multi-host story (SURVEY §2.9).

A multi-host deployment splits the resource space across hosts: each host's
engine owns the exact rows for its shard (decisions + stats stay local, no
cross-host chatter on the hot path), and GLOBAL budgets ride the cluster
token protocol exactly as in a single-host deployment.  This router is the
host-layer piece: deterministic resource→shard assignment and a fan-out
`check_batch` that groups a mixed batch per shard.

Within one host, chips scale via the SPMD row sharding (parallel/spmd.py,
ICI); ACROSS hosts, this router is the DCN-level partitioning.  Shards are
any objects with the SentinelClient surface — in-process clients in tests,
remote host stubs in a real deployment.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.cluster.ring import HashRing
from sentinel_tpu.obs.registry import REGISTRY as _OBS

_ROUTE_FAIL_HELP = (
    "shard check_batch fan-out legs that raised, by shard index and "
    "failure kind (timeout|io|error); the affected spans degrade per "
    "on_shard_error (block = fail closed, fallback = local re-check)"
)

#: shared index rings so ``shard_of`` and every ``ShardRouter`` of the
#: same width agree on placement (and tests can derive expectations)
_RINGS: Dict[int, HashRing] = {}
_RINGS_LOCK = threading.Lock()


def _index_ring(n_shards: int) -> HashRing:
    ring = _RINGS.get(n_shards)
    if ring is None:
        with _RINGS_LOCK:
            ring = _RINGS.get(n_shards)
            if ring is None:
                ring = HashRing([str(i) for i in range(n_shards)])
                _RINGS[n_shards] = ring
    return ring


def shard_of(resource: str, n_shards: int) -> int:
    """Deterministic, process-independent shard assignment — now through
    the consistent-hash ring (``cluster/ring.py``) instead of the old
    bare ``crc32 % n``, so growing the host set remaps ~1/N of the
    resource space rather than reshuffling nearly all of it."""
    return int(_index_ring(n_shards).owner(resource))


class ShardRouter:
    """Deterministic resource→shard fan-out.

    ``on_shard_error`` governs what happens to the spans of a shard
    whose ``check_batch`` leg RAISES mid-fan-out (the other shards'
    results are always kept):

      ``"block"``     (default) those spans fail CLOSED — verdict
                      ``BLOCK_SYSTEM``, the engine's explicit degrade
                      verdict, never a silent pass
      ``"fallback"``  those spans re-check on the ``fallback`` client
                      (local enforcement, the degrade-to-local shape)
      ``"raise"``     legacy behavior: the first failing leg's exception
                      propagates (once every leg has finished) and the
                      whole batch is lost

    Every failed leg counts in
    ``sentinel_shard_route_failures_total{shard,kind}``.
    """

    def __init__(
        self,
        shards: Sequence[Any],
        on_shard_error: str = "block",
        fallback: Optional[Any] = None,
    ):
        assert shards, "at least one shard"
        if on_shard_error not in ("block", "fallback", "raise"):
            raise ValueError(f"bad on_shard_error {on_shard_error!r}")
        if on_shard_error == "fallback" and fallback is None:
            raise ValueError("on_shard_error='fallback' needs a fallback client")
        self.shards = list(shards)
        self.on_shard_error = on_shard_error
        self.fallback = fallback
        # PRIVATE copy, not the shared _RINGS instance (HashRing
        # advertises add/remove — mutating a shared ring would corrupt
        # every same-width router and shard_of), and it IS this router's
        # routing authority: shard_for/check_batch consult it, so a
        # mutation at least fails loudly instead of silently diverging
        self.ring = HashRing([str(i) for i in range(len(self.shards))])

    def _owner(self, resource: str) -> int:
        return int(self.ring.owner(resource))

    @staticmethod
    def _fail_kind(exc: BaseException) -> str:
        if isinstance(exc, TimeoutError):
            return "timeout"
        if isinstance(exc, OSError):
            return "io"
        return "error"

    @staticmethod
    def _count_route_failure(shard: int, kind: str) -> None:
        _OBS.counter(
            "sentinel_shard_route_failures_total",
            _ROUTE_FAIL_HELP,
            labels={"shard": str(shard), "kind": kind},
        ).inc()

    def shard_for(self, resource: str):
        return self.shards[self._owner(resource)]

    def entry(self, resource: str, **kw):
        """Single entry routes to the owning shard (SphU.entry surface)."""
        return self.shard_for(resource).entry(resource, **kw)

    def check_batch(
        self,
        resources: Sequence[str],
        counts: Optional[Sequence[int]] = None,
        origins: Optional[Sequence[str]] = None,
        params: Optional[Sequence[Any]] = None,
        prioritized: Optional[Sequence[bool]] = None,
        **kw,
    ) -> List[Tuple[int, int]]:
        """Mixed-shard bulk check: group per shard (EVERY per-item sequence
        sliced with its group), shards consulted concurrently — one DCN
        round-trip of latency, not one per shard — results restored to
        input order.

        A shard leg that raises no longer loses its spans (nor the other
        shards' answers, which the old first-``result()``-raises shape
        discarded): the failed group degrades per ``on_shard_error`` and
        the failure is counted by (shard, kind)."""
        from sentinel_tpu.core import errors as ERR

        n = len(resources)
        groups: Dict[int, List[int]] = {}
        for i, r in enumerate(resources):
            groups.setdefault(self._owner(r), []).append(i)
        out: List[Optional[Tuple[int, int]]] = [None] * n

        def pick(seq, idxs):
            return [seq[i] for i in idxs] if seq is not None else None

        def run(s, idxs, client=None):
            return (client or self.shards[s]).check_batch(
                pick(resources, idxs),
                counts=pick(counts, idxs),
                origins=pick(origins, idxs),
                params=pick(params, idxs),
                prioritized=pick(prioritized, idxs),
                **kw,
            )

        def capture(s, call):
            # one leg-failure policy for BOTH fan-out shapes: a raising
            # leg becomes its exception (counted by shard+kind) instead
            # of poisoning the whole batch
            try:
                return call()
            except Exception as e:  # stlint: disable=fail-open — captured exception routes to the fail-closed BLOCK_SYSTEM fill below
                self._count_route_failure(s, self._fail_kind(e))
                return e

        if len(groups) == 1:
            ((s, idxs),) = groups.items()
            results = {s: capture(s, lambda: run(s, idxs))}
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(groups)) as pool:
                futures = {s: pool.submit(run, s, idxs) for s, idxs in groups.items()}
                results = {s: capture(s, f.result) for s, f in futures.items()}
        if self.on_shard_error == "raise":
            for got in results.values():
                if isinstance(got, Exception):
                    raise got
        for s, idxs in groups.items():
            got = results[s]
            if isinstance(got, Exception):
                if self.on_shard_error == "fallback":
                    try:
                        got = run(s, idxs, client=self.fallback)
                    except Exception as e:  # stlint: disable=fail-open — double fault: the spans fall through to the fail-closed fill below
                        self._count_route_failure(s, self._fail_kind(e))
                        got = e
                if isinstance(got, Exception):
                    got = [(ERR.BLOCK_SYSTEM, 0)] * len(idxs)
            for j, i in enumerate(idxs):
                out[i] = got[j]
        return out  # type: ignore[return-value]

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Merged per-resource stats across shards.  A resource can appear
        on several hosts (cluster-mode traffic through load-balanced
        ingress), so numeric fields are SUMMED, not overwritten; minRt
        takes the min of observed values."""
        merged: Dict[str, Dict[str, float]] = {}
        for s in self.shards:
            for name, stats in s.stats.snapshot().items():
                prev = merged.get(name)
                if prev is None:
                    merged[name] = dict(stats)
                    continue
                for k, v in stats.items():
                    if k == "minRt":
                        nonzero = [x for x in (prev[k], v) if x > 0]
                        prev[k] = min(nonzero) if nonzero else 0.0
                    elif k == "avgRt":
                        pass  # recomputed below from summed successes
                    else:
                        prev[k] = prev[k] + v
                # weighted avgRt over summed successes
                s_prev = prev["successQps"] - stats["successQps"]
                if prev["successQps"] > 0:
                    prev["avgRt"] = (
                        prev["avgRt"] * s_prev + stats["avgRt"] * stats["successQps"]
                    ) / prev["successQps"]
        return merged
