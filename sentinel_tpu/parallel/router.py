"""Host-layer shard-by-resource routing — the multi-host story (SURVEY §2.9).

A multi-host deployment splits the resource space across hosts: each host's
engine owns the exact rows for its shard (decisions + stats stay local, no
cross-host chatter on the hot path), and GLOBAL budgets ride the cluster
token protocol exactly as in a single-host deployment.  This router is the
host-layer piece: deterministic resource→shard assignment and a fan-out
`check_batch` that groups a mixed batch per shard.

Within one host, chips scale via the SPMD row sharding (parallel/spmd.py,
ICI); ACROSS hosts, this router is the DCN-level partitioning.  Shards are
any objects with the SentinelClient surface — in-process clients in tests,
remote host stubs in a real deployment.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple


def shard_of(resource: str, n_shards: int) -> int:
    """Deterministic, process-independent shard assignment (crc32 — the
    same stability argument as the RLS flow-id derivation)."""
    return zlib.crc32(resource.encode("utf-8")) % n_shards


class ShardRouter:
    def __init__(self, shards: Sequence[Any]):
        assert shards, "at least one shard"
        self.shards = list(shards)

    def shard_for(self, resource: str):
        return self.shards[shard_of(resource, len(self.shards))]

    def entry(self, resource: str, **kw):
        """Single entry routes to the owning shard (SphU.entry surface)."""
        return self.shard_for(resource).entry(resource, **kw)

    def check_batch(
        self,
        resources: Sequence[str],
        counts: Optional[Sequence[int]] = None,
        **kw,
    ) -> List[Tuple[int, int]]:
        """Mixed-shard bulk check: group per shard, one check_batch per
        shard, results restored to input order."""
        n = len(resources)
        groups: Dict[int, List[int]] = {}
        for i, r in enumerate(resources):
            groups.setdefault(shard_of(r, len(self.shards)), []).append(i)
        out: List[Optional[Tuple[int, int]]] = [None] * n
        for s, idxs in groups.items():
            sub = self.shards[s].check_batch(
                [resources[i] for i in idxs],
                counts=[counts[i] for i in idxs] if counts else None,
                **kw,
            )
            for j, i in enumerate(idxs):
                out[i] = sub[j]
        return out  # type: ignore[return-value]

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Merged per-resource stats across shards (disjoint key spaces)."""
        merged: Dict[str, Dict[str, float]] = {}
        for s in self.shards:
            merged.update(s.stats.snapshot())
        return merged
