"""Remote host shard for the ShardRouter — a real network client.

The round-1 router fanned out over in-process objects; this stub speaks
the cluster TCP protocol's RES_CHECK extension to a shard HOST process
(cluster/server.py answers it from its decision client), with the failure
behavior the reference's token client has (NettyTransportClient reconnect,
DefaultClusterTokenClient.java:45 degrade):

- one live connection, lazily (re)established; one reconnect attempt per
  call, then the call degrades
- degrade-on-shard-loss: ``fallback`` is either a local SentinelClient
  (fallbackToLocal — local rules enforce while the shard is gone) or None
  (fail-open PASS, the reference's pass-through default)
- a failed shard is retried after ``retry_interval_s`` so a restarted
  host picks the traffic back up
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, List, Optional, Sequence, Tuple

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.record_log import record_log

_H_CHUNK = _OBS.histogram(
    "sentinel_shard_chunk_ms",
    "remote-shard chunk write-to-response latency (pipelined window)",
)
_C_CHUNKS = _OBS.counter(
    "sentinel_shard_chunks_total", "remote-shard RES_CHECK chunks answered"
)
_C_CHUNKS_DEGRADED = _OBS.counter(
    "sentinel_shard_chunks_degraded_total",
    "remote-shard chunks that fell back locally (unreachable / forfeited / unencodable)",
)

#: chaos failpoints on the shard transport — mid-window partitions land
#: here (a recv `drop` reads as peer-close; send `drop`/`corrupt` leaves
#: the chunk unanswered until the socket timeout)
_FP_CONNECT = FP.register(
    "parallel.shard.connect", "shard host TCP connect", FP.HIT_ACTIONS
)
_FP_SEND = FP.register(
    "parallel.shard.send", "shard RES_CHECK chunk frame write", FP.PIPE_ACTIONS
)
_FP_RECV = FP.register(
    "parallel.shard.recv", "shard response bytes (per recv call)", FP.PIPE_ACTIONS
)


class RemoteShard:
    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 3.0,
        fallback: Optional[Any] = None,
        retry_interval_s: float = 2.0,
    ):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.fallback = fallback
        self.retry_interval_s = retry_interval_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._xid = 0
        # span-degrade state: the shared hysteresis primitive
        # (adaptive/degrade.py) — enter on shard loss, serve fallback
        # through the cooldown, exit on the first healthy exchange; every
        # transition journaled as remote_shard.degrade.enter/exit
        from sentinel_tpu.adaptive.degrade import Hysteresis

        self._hy = Hysteresis(
            "remote_shard.degrade",
            cooldown_s=retry_interval_s,
            attrs={"peer": f"{host}:{port}"},
        )

    # attribute-compatible view of the hysteresis cooldown (tests poke it)
    @property
    def _down_until(self) -> float:
        return self._hy.until

    @_down_until.setter
    def _down_until(self, v: float) -> None:
        self._hy.until = float(v)

    # -- connection ----------------------------------------------------------

    def _connect(self) -> socket.socket:
        FP.hit(_FP_CONNECT)
        s = socket.create_connection((self.host, self.port), timeout=self.timeout_s)
        s.settimeout(self.timeout_s)
        return s

    def _close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    @staticmethod
    def _read_response(s: socket.socket) -> P.ClusterResponse:
        """Read one length-prefixed response frame; raises OSError on any
        transport trouble (caller degrades)."""
        head = b""
        while len(head) < 2:
            chunk = FP.pipe(_FP_RECV, s.recv(2 - len(head)))
            if not chunk:
                raise OSError("peer closed")
            head += chunk
        (n,) = struct.unpack(">H", head)
        body = b""
        while len(body) < n:
            chunk = FP.pipe(_FP_RECV, s.recv(n - len(body)))
            if not chunk:
                raise OSError("peer closed")
            body += chunk
        try:
            return P.decode_response(body)
        except (ValueError, struct.error, IndexError) as e:
            # a frame that parses as a length but not as a response means
            # the stream is desynced — surface it as transport trouble so
            # the caller's OSError path closes the socket and degrades
            # instead of the admission path crashing
            raise OSError(f"undecodable response frame: {e}") from e

    # -- shard surface -------------------------------------------------------

    #: items per wire chunk — ~20 B/item for typical names keeps a chunk
    #: around 3 KB, well under MAX_FRAME (65535) even with long resource
    #: names / origins / stringified params
    CHUNK = 128
    #: frames in flight per connection: big batches PIPELINE their chunks
    #: (send-ahead window) so shard-side engine ticks overlap this side's
    #: encode + socket IO instead of paying a full RTT per chunk
    WINDOW = 8

    def check_batch(
        self,
        resources: Sequence[str],
        counts: Optional[Sequence[int]] = None,
        origins: Optional[Sequence[str]] = None,
        params: Optional[Sequence[Any]] = None,
        prioritized: Optional[Sequence[bool]] = None,
        **kw,
    ) -> List[Tuple[int, int]]:
        n = len(resources)
        spans = [(lo, min(lo + self.CHUNK, n)) for lo in range(0, n, self.CHUNK)]
        # distributed trace context: one wire trace id per batch (the
        # ambient one if a caller installed it, else fresh), one span id
        # per chunk — the shard host's server.res_check span adopts them,
        # so a merged dump shows every chunk's client and server halves
        # on one timeline.  Zero work when tracing is off.
        trace_id = 0
        sids: Optional[List[int]] = None
        if OT.TRACER.enabled:
            trace_id, _parent = OT.current_ctx()
            if not trace_id:
                trace_id = OT.new_trace_id()
            sids = [OT.new_span_id() for _ in spans]
        wires = [
            self._encode_chunk(
                resources[lo:hi],
                counts[lo:hi] if counts else None,
                origins[lo:hi] if origins else None,
                params[lo:hi] if params else None,
                prioritized[lo:hi] if prioritized else None,
                trace_id=trace_id,
                span_id=sids[k] if sids else 0,
            )
            for k, (lo, hi) in enumerate(spans)
        ]
        rsps = self._rpc_pipeline(wires, trace_id=trace_id, sids=sids)
        out: List[Tuple[int, int]] = []
        for (lo, hi), rsp in zip(spans, rsps):
            k = hi - lo
            if (
                rsp is not None
                and rsp.status == C.STATUS_OK
                and len(rsp.items) == k
            ):
                out.extend((int(v), int(w)) for v, w in rsp.items)
            else:
                _C_CHUNKS_DEGRADED.inc()
                # degrade THIS span: local fallback rules, else fail-open
                if self.fallback is not None:
                    out.extend(
                        self.fallback.check_batch(
                            resources[lo:hi],
                            counts=counts[lo:hi] if counts else None,
                            origins=origins[lo:hi] if origins else None,
                            params=params[lo:hi] if params else None,
                            prioritized=prioritized[lo:hi] if prioritized else None,
                            **kw,
                        )
                    )
                else:
                    out.extend([(ERR.PASS, 0)] * k)
        return out

    def _encode_chunk(
        self, resources, counts, origins, params, prioritized,
        trace_id: int = 0, span_id: int = 0,
    ) -> Optional[bytes]:
        # wire layout: 5-tuples (name, count, prio, origin, param) with the
        # param TYPED via prefix — "i:<n>" int, "s:<text>" string, "" none —
        # so hash_param's int-vs-str dispatch matches local enforcement for
        # every value (a bare marker would collide with real strings)
        flat: List[Any] = []
        for i, name in enumerate(resources):
            pv = params[i] if params else None
            if isinstance(pv, bool):
                pv = int(pv)
            if isinstance(pv, int):
                pv_s = f"i:{pv}"
            elif pv is None:
                pv_s = ""
            else:
                pv_s = f"s:{pv}"
            flat += [
                name,
                counts[i] if counts else 1,
                bool(prioritized[i]) if prioritized else False,
                (origins[i] or "") if origins else "",
                pv_s,
            ]
        # encode BEFORE touching the socket: an oversized frame is a
        # CLIENT-side problem and must not close a healthy connection or
        # trip the cool-down (same convention as ClusterTokenClient's
        # bad-request sentinel) — it degrades just this span
        try:
            self._xid += 1
            return P.encode_request(
                P.ClusterRequest(
                    xid=self._xid, type=C.MSG_TYPE_RES_CHECK, params=flat,
                    trace_id=trace_id, span_id=span_id,
                )
            )
        except ValueError:
            record_log().warning(
                "RES_CHECK chunk exceeds frame cap — degrading this span"
            )
            return None

    def _rpc_pipeline(
        self, wires, trace_id: int = 0, sids: Optional[List[int]] = None
    ) -> List[Optional[P.ClusterResponse]]:
        """Windowed request/response exchange: up to WINDOW frames on the
        wire before the first read (the server answers in order per
        connection).

        At-most-once on failure: answered chunks keep their responses,
        and any chunk WRITTEN to a socket that subsequently failed is
        treated as possibly-processed-with-the-response-lost — it is
        NEVER re-sent (the shard may already have admitted it; replaying
        would double-count admission, and WINDOW=8 pipelining would widen
        that to up to 8 chunks / 1024 items per failure).  Those spans
        come back as None and the caller degrades them (local fallback
        rules, else fail-open pass-through, exactly like an unreachable
        shard).  Only chunks never written to a socket ride the single
        reconnect attempt."""
        m = len(wires)
        rsps: List[Optional[P.ClusterResponse]] = [None] * m
        pending = [i for i in range(m) if wires[i] is not None]
        if not pending:
            return rsps
        with self._lock:
            if self._hy.cooling:
                return rsps
            for attempt in (0, 1):  # one reconnect, like the netty client
                # chunks written to THIS attempt's socket; on failure they
                # are forfeited (degraded), not retried — see docstring
                inflight: List[int] = []
                t_sent: dict = {}  # chunk idx -> send stamp (tracing only)
                try:
                    if self._sock is None:
                        self._sock = self._connect()  # stlint: disable=blocking-under-lock — _lock is this connection's pipeline mutex: it serializes frames on ONE socket (the lock guards the socket itself, not shared engine state); reconnect cost is paid by the one pipelining thread
                    s = self._sock
                    queue = list(pending)
                    while queue and len(inflight) < self.WINDOW:
                        i = queue.pop(0)
                        # count as written BEFORE sendall: a mid-write
                        # failure may still deliver a parseable frame
                        inflight.append(i)
                        _t = OT.t0()
                        if _t:
                            t_sent[i] = _t
                        s.sendall(FP.pipe(_FP_SEND, wires[i]))  # stlint: disable=blocking-under-lock — _lock is this connection's pipeline mutex: it serializes frames on ONE socket (the lock guards the socket itself, not shared engine state)
                    while inflight:
                        rsp = self._read_response(s)  # stlint: disable=blocking-under-lock — _lock is this connection's pipeline mutex: it serializes frames on ONE socket (the lock guards the socket itself, not shared engine state)
                        i = inflight.pop(0)
                        rsps[i] = rsp
                        _C_CHUNKS.inc()
                        _t = t_sent.pop(i, 0)
                        if _t:
                            # write→response of one pipelined chunk: the
                            # send-ahead WINDOW means later chunks' spans
                            # include queueing behind earlier ones
                            OT.stage(
                                "shard.chunk", _t, _H_CHUNK, trace=trace_id,
                                attrs={
                                    "chunk": i,
                                    "inflight": len(inflight),
                                    "span_id": sids[i] if sids else 0,
                                },
                            )
                        pending.remove(i)
                        if queue:
                            j = queue.pop(0)
                            inflight.append(j)
                            _t = OT.t0()
                            if _t:
                                t_sent[j] = _t
                            s.sendall(FP.pipe(_FP_SEND, wires[j]))  # stlint: disable=blocking-under-lock — _lock is this connection's pipeline mutex: it serializes frames on ONE socket (the lock guards the socket itself, not shared engine state)
                    # a full healthy exchange is the probe that heals the
                    # shard (no-op unless a prior failure entered degrade)
                    self._hy.exit()
                    return rsps
                except OSError:
                    self._close()
                    for i in inflight:
                        # possibly processed shard-side, response lost —
                        # degrade this span instead of re-admitting it
                        pending.remove(i)
                    if inflight:
                        record_log().warning(
                            "shard %s:%d failed with %d chunk(s) in flight "
                            "— degrading those spans (no replay)",
                            self.host,
                            self.port,
                            len(inflight),
                        )
                    if attempt == 1 or not pending:
                        # cool-down anchored at FAILURE time: connect
                        # timeouts can burn seconds inside the attempts,
                        # and an entry-time anchor would already be in
                        # the past, silently disabling the cool-down.
                        # Also armed when a mid-exchange failure forfeited
                        # every remaining chunk — a shard that dies after
                        # accepting the connection is as unhealthy as one
                        # that refused it, and without the cool-down every
                        # subsequent batch would re-pay the connect+write+
                        # fail latency and forfeit another window
                        self._hy.enter(cooldown_s=self.retry_interval_s)
                        record_log().warning(
                            "shard %s:%d unreachable — degrading for %.1fs",
                            self.host,
                            self.port,
                            self.retry_interval_s,
                        )
                        break
        return rsps

    def entry(self, resource: str, count: int = 1, prioritized: bool = False, **kw):
        """Single-entry surface for ShardRouter.entry: returns a handle
        whose exit is a no-op on the remote (the shard host records its own
        completions for locally-entered traffic; remote entries are
        token-style grants)."""
        v, w = self.check_batch([resource], counts=[count], prioritized=[prioritized])[0]
        if v in (ERR.PASS, ERR.PASS_WAIT):
            return _RemoteEntry()
        return None

    class stats:  # noqa: N801 — namespace matching the client surface
        @staticmethod
        def snapshot() -> dict:
            return {}

    def close(self) -> None:
        with self._lock:
            self._close()


class _RemoteEntry:
    def exit(self, count: Optional[int] = None) -> None:
        pass

    def trace(self, exc=None, count: int = 1) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.exit()
        return False
