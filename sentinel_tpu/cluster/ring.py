"""Consistent-hash ring with virtual nodes — the fleet's placement law.

Replaces the bare ``crc32 % n`` placement (``parallel/router.py``'s
original scheme) for anything that must survive membership change: with
``V`` virtual nodes per member, adding or removing one member of ``N``
remaps only the keys the arriving/departing member owns — ~``K/N`` of
``K`` keys — instead of reshuffling ~``(N-1)/N`` of the space the way a
modulus does.  Both the cluster token fleet (``cluster/shard.py``) and
the host-layer resource router (``parallel/router.py``) place through
this ring LAW — same hash, same vnode scheme, same stability bound —
but each over its OWN member set and keyspace (shard names × ``flow/``
keys vs shard indices × raw resource strings), so the two layers'
assignments are deterministic per layer, not equal across layers.

Determinism contract (pinned by the golden test in
``tests/test_ring.py``): hashes are ``zlib.crc32`` — process- and
version-independent, unlike Python's salted ``hash()`` — and ties on
the ring are broken by member name, so the assignment is a pure
function of ``(members, vnodes, key)``.
"""

from __future__ import annotations

import bisect
import zlib
from typing import Dict, Iterable, List, Sequence, Tuple

DEFAULT_VNODES = 64


def _h(s: str) -> int:
    return zlib.crc32(s.encode("utf-8"))


def flow_key(flow_id: int) -> str:
    """Canonical ring key for a cluster flow id (stable across layers:
    the RLS front door, the sharded token client, and tests all derive
    the owner from this one string)."""
    return f"flow/{int(flow_id)}"


class HashRing:
    """Immutable-point consistent-hash ring; membership edits rebuild
    the point list (cheap: ``N × vnodes`` crc32 calls, never on a
    request path)."""

    def __init__(self, members: Iterable[str], vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._members: List[str] = []
        #: (sorted hashes, sorted (hash, member) points) — ONE attribute,
        #: so a reader never pairs one membership's points with another's
        #: hash index (see ``_rebuild``)
        self._table: Tuple[List[int], List[Tuple[int, str]]] = ([], [])
        for m in members:
            if m in self._members:
                raise ValueError(f"duplicate ring member {m!r}")
            self._members.append(m)
        if not self._members:
            raise ValueError("ring needs at least one member")
        self._rebuild()

    # -- membership ----------------------------------------------------------

    @property
    def members(self) -> List[str]:
        return list(self._members)

    def add(self, member: str) -> None:
        if member in self._members:
            raise ValueError(f"ring member {member!r} already present")
        self._members.append(member)
        self._rebuild()

    def remove(self, member: str) -> None:
        if member not in self._members:
            raise ValueError(f"ring member {member!r} not present")
        if len(self._members) == 1:
            raise ValueError("cannot remove the last ring member")
        self._members.remove(member)
        self._rebuild()

    def _rebuild(self) -> None:
        # ties (two vnodes hashing equal) break by member name — the
        # tuple sort — so the walk order is a pure function of members
        pts = sorted(
            (_h(f"{m}#{v}"), m)
            for m in self._members
            for v in range(self.vnodes)
        )
        # atomic publish: a concurrent owner() during add/remove either
        # sees the old table or the new one, never a torn pair
        self._table = ([h for h, _m in pts], pts)

    # -- placement -----------------------------------------------------------

    def owner(self, key: str) -> str:
        """The member owning ``key``: first ring point clockwise of the
        key's hash (wrapping at the top)."""
        hashes, points = self._table
        i = bisect.bisect_right(hashes, _h(key))
        if i == len(points):
            i = 0
        return points[i][1]

    def owner_of_flow(self, flow_id: int) -> str:
        return self.owner(flow_key(flow_id))

    def assignment(self, keys: Sequence[str]) -> Dict[str, str]:
        """key -> owner for a batch (test/diagnostic convenience)."""
        return {k: self.owner(k) for k in keys}

    def spread(self, keys: Sequence[str]) -> Dict[str, int]:
        """owner -> owned-key count over ``keys`` (balance diagnostics;
        the ``/api/shards`` exposition reports this for live fleets)."""
        out: Dict[str, int] = {m: 0 for m in self._members}
        for k in keys:
            out[self.owner(k)] += 1
        return out
