"""Native token-server front door: C epoll ingestion, per-tick Python.

The asyncio token server (cluster/server.py) costs ~100-300 us of Python
per request on its event loop, capping a single server around a few
thousand tokens/s.  This front door moves the per-REQUEST work into C
(native/sentinel_host.cpp sx_front_*):

    socket -> frame parse -> flow-id map -> acquire ring      (C io thread)
    ring -> engine batch columns -> tick -> verdicts          (Python tick)
    verdict ring -> response frames -> socket                 (C io thread)

Python executes once per TICK: the SentinelClient's tick loop drains the
door's acquire ring straight into engine batch lanes and answers through
``respond`` — no Python objects, no futures, no per-request code.

Protocol: PING, MSG_TYPE_FLOW, MSG_TYPE_PARAM_FLOW (values hashed in C
with hash_param parity; doubles answer STATUS_FAIL) and CONCURRENT
acquire/release (TTL token table on the host, batched per tick) — every
token type on ONE port, the TokenServerHandler.java:61-75 dispatch map.
SO_REUSEPORT sharding (``shards=N``) runs N io threads on the same port
for multi-core hosts.

Reference analog: the Netty pipeline + TokenServerHandler
(NettyTransportServer.java:88-93, TokenServerHandler.java:61-75) — the
JVM runs per-request code on event-loop threads; here the per-request
code is native and the "business logic" is one batched device tick.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster.rules import flow_resource, param_resource
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.native.loader import load_native
from sentinel_tpu.obs.registry import REGISTRY as _OBS

#: param rules the ENGINE cannot enforce on any transport (no hash lane
#: for their param_idx) — the log warning alone was invisible to
#: monitoring; this makes the misconfiguration a /metrics fact.  Counts
#: SIGHTINGS: every rule-map rebuild that still carries the bad rule
#: increments, so a non-flat curve means the condition persists.
_C_UNENFORCEABLE = _OBS.counter(
    "sentinel_front_door_unenforceable_rules",
    "param rules seen without a hash lane for their param_idx (engine "
    "cannot enforce them); incremented per rule-map rebuild",
)


def resolve_param_lane(service, fid: int, name: str):
    """Hash lane for a decision param rule, or None when the C ring can't
    serve it.  Lane-less rules (engine-unenforceable) warn AND count in
    ``sentinel_front_door_unenforceable_rules``; lane>1 rules only warn —
    the asyncio server still enforces those."""
    lane = service.client.param_lane(name, 0)
    if lane is not None and lane <= 1:
        return lane
    from sentinel_tpu.utils.record_log import record_log

    if lane is None:
        # no hash lane at all: the ENGINE cannot enforce this rule on any
        # transport — a misconfiguration, not a front-door limitation
        _C_UNENFORCEABLE.inc()
        record_log().warning(
            "front door: param rule %s on %r has no hash lane for "
            "param_idx 0 — the rule is not enforceable (raise param_dims "
            "or consolidate indices)", fid, name,
        )
    else:
        record_log().warning(
            "front door: param rule %s on %r maps to lane %d (ring "
            "carries lanes 0-1); served by the asyncio server only",
            fid, name, lane,
        )
    return None


class NativeFrontDoor:
    """Owns one sx_front instance and its flow-id → engine-row map.

    Attach to a SentinelClient via ``client.attach_front_door(door)``;
    the client's tick loop then serves the door's traffic.  Rule mapping
    follows a DefaultTokenService's flow rules via ``follow(service)``.
    """

    def __init__(
        self,
        port: int = 0,
        ring_pow2: int = 1 << 16,
        pending: int = 1 << 16,
        fmap_pow2: int = 1 << 12,
        max_qps: Optional[float] = None,
        reuseport: bool = False,
    ):
        self._lib = load_native()
        if self._lib is None:
            raise RuntimeError("native library unavailable — front door needs C")
        self._f = self._lib.sx_front_new(
            port, ring_pow2, pending, fmap_pow2, 1 if reuseport else 0
        )
        if not self._f:
            raise RuntimeError("sx_front_new failed (bind error?)")
        if max_qps is not None:
            self._lib.sx_front_set_guard(self._f, int(max_qps))
        self._started = False
        self._service = None  # set by follow(); serves concurrent tokens
        # tick-side drain buffers (single consumer — the tick thread)
        self._buf_n = 0
        self._bufs = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return int(self._lib.sx_front_port(self._f))

    def start(self) -> None:
        if not self._started:
            if self._lib.sx_front_start(self._f) != 0:
                raise RuntimeError("sx_front_start failed")
            self._started = True

    def stop(self) -> None:
        if self._started:
            self._lib.sx_front_stop(self._f)
            self._started = False

    def close(self) -> None:
        if self._f:
            self._lib.sx_front_free(self._f)
            self._f = None

    # -- rule mapping --------------------------------------------------------

    def map_flow(self, flow_id: int, row: int) -> None:
        self._lib.sx_front_map_flow(self._f, int(flow_id), int(row))

    def map_param(self, flow_id: int, row: int, lane: int = 0) -> None:
        self._lib.sx_front_map_param(self._f, int(flow_id), int(row), int(lane))

    def follow(self, service) -> None:
        """Track a DefaultTokenService's cluster flow AND param rules:
        whenever either (re)loads, refresh the id → engine-row maps.  Also
        binds the service for host-managed CONCURRENT tokens."""
        self._service = service

        def _sync(*_a) -> None:
            reg = service.client.registry
            # clear-then-rebuild so DELETED rules stop resolving (the map
            # has no per-key delete; a clear briefly answers NO_RULE, the
            # same window the asyncio server has mid-reload)
            self._lib.sx_front_clear_flows(self._f)
            for fid in service.flow_rules.all_ids():
                row = reg.resource_id(flow_resource(fid))
                if row is not None:
                    self.map_flow(fid, row)
            for fid in service.param_rules.all_ids():
                name = param_resource(fid)
                row = reg.resource_id(name)
                if row is None:
                    continue
                # the decision rule's param_idx is 0; its hash lane is
                # wherever the compile assigned idx 0.  The C ring carries
                # two hash lanes, and sx_front_map_param rejects lane>1 —
                # such rules keep flowing through the asyncio server
                lane = resolve_param_lane(service, fid, name)
                if lane is None:
                    continue
                self.map_param(fid, row, lane)

        service.flow_rules.add_listener(_sync)
        service.param_rules.add_listener(_sync)
        _sync()

    # -- tick-side API -------------------------------------------------------

    def pending(self) -> int:
        """Acquire-ring backlog (tick loop: drain again without waiting)."""
        return int(self._lib.sx_front_acq_backlog(self._f))

    def drain(self, max_n: int):
        """(row, count, prio, corr, kind, a0, a1) int32 arrays of length
        n <= max_n.  kind = wire MSG_TYPE: 1 flow, 2 param (a0/a1 = hash
        lanes), 3/4 concurrent acquire/release (a0/a1 = 64-bit id halves).
        Buffers are preallocated once (single consumer: the tick thread);
        callers must consume the views before the next drain."""
        if self._bufs is None or self._buf_n < max_n:
            self._bufs = tuple(np.empty(max_n, np.int32) for _ in range(7))
            self._buf_n = max_n
        row, cnt, prio, corr, kind, a0, a1 = self._bufs
        cp = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        n = self._lib.sx_front_drain_acquires2(
            self._f, max_n, cp(row), cp(cnt), cp(prio), cp(corr), cp(kind),
            cp(a0), cp(a1)
        )
        return row[:n], cnt[:n], prio[:n], corr[:n], kind[:n], a0[:n], a1[:n]

    def handle_host_events(self, kind, cnt, corr, a0, a1) -> None:
        """Serve CONCURRENT acquire/release events against the followed
        service's token manager and answer through the typed respond path.
        Per-event host work is a dict op (~us) — concurrent-mode traffic is
        orders below flow traffic (reference: TokenCacheNodeManager)."""
        svc = self._service
        n = len(kind)
        status = np.empty(n, np.int32)
        tok_hi = np.zeros(n, np.int32)
        tok_lo = np.zeros(n, np.int32)
        for i in range(n):
            ident = (int(np.uint32(a0[i])) << 32) | int(np.uint32(a1[i]))
            if svc is None:
                status[i] = C.STATUS_FAIL
            elif kind[i] == C.MSG_TYPE_CONCURRENT_ACQUIRE:
                r = svc.request_concurrent_token(ident, int(cnt[i]))
                status[i] = r.status
                tok_hi[i] = np.uint32((r.token_id >> 32) & 0xFFFFFFFF).astype(np.int32)
                tok_lo[i] = np.uint32(r.token_id & 0xFFFFFFFF).astype(np.int32)
            else:
                r = svc.release_concurrent_token(ident)
                status[i] = r.status
        corr = np.ascontiguousarray(corr, np.int32)
        waits = np.zeros(n, np.int32)
        cp = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        self._lib.sx_front_respond_ex(
            self._f, n, cp(corr), cp(status), cp(waits), cp(tok_hi), cp(tok_lo)
        )

    def respond(self, corr: np.ndarray, verdicts: np.ndarray, waits: np.ndarray) -> None:
        """Answer drained acquires: engine verdicts map to wire statuses."""
        status = np.where(
            verdicts == ERR.PASS,
            np.int32(C.STATUS_OK),
            np.where(
                verdicts == ERR.PASS_WAIT,
                np.int32(C.STATUS_SHOULD_WAIT),
                np.int32(C.STATUS_BLOCKED),
            ),
        ).astype(np.int32)
        corr = np.ascontiguousarray(corr, np.int32)
        waits = np.ascontiguousarray(waits, np.int32)
        cp = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        self._lib.sx_front_respond(
            self._f, len(corr), cp(corr), cp(status), cp(waits)
        )
