"""Sharded cluster token fleet: N token servers behind a consistent-hash
ring, with per-shard failover and bounded-slack budget leases.

This is the distributed L6 the reference architecture describes (PAPER.md
§2.9): instead of one localhost ``ClusterTokenServer``, the flow-id space
is split across N real token servers — each shard owns the flows the
``HashRing`` (``cluster/ring.py``) assigns to it, so capacity scales with
shards and a membership change remaps only ~1/N of the id space.

Pieces:

  ``ShardedTokenClient``  a ``TokenService`` that routes every request to
      the owning shard's ``ClusterTokenClient``.  Per-shard health rides
      the SAME hysteresis shape as the runtime's cluster degrade
      (enter-on-failure with a cooldown, hold, exit on the first healthy
      probe) but scoped to ONE shard: a dead shard degrades only its own
      flows, the rest of the fleet keeps answering remotely.

  budget leases  while a shard is healthy, the client keeps a standing
      LEASE of ``lease_slack × rule_count`` tokens per active flow
      (``MSG_TYPE_LEASE``, granted by the owner out of the same engine
      budget as ordinary tokens).  When the shard dies, decisions for its
      flows are served by debiting the lease balance — and fail CLOSED
      (``STATUS_BLOCKED``) once it is spent or expired, or when no lease
      was ever established (ambiguity never passes).  Token conservation:
      every fallback grant was debited from the global budget when the
      lease was acquired, so the worst-case overshoot is one outstanding
      lease per (client, flow) — the bounded-slack window of
      "Give Me Some Slack" (arXiv 1703.01166) — not an unmetered local
      re-enforcement.

  ``ShardFleet``  in-process N-shard fleet builder (tests, chaos
      scenarios, the bench's ``cluster_sharded`` row, local demos): N
      ``DefaultTokenService`` + ``ClusterTokenServer`` pairs, rules
      partitioned onto their owners through the ring, one
      ``ShardedTokenClient`` fronting them, plus ``kill``/``rejoin`` to
      exercise failover.  Its ``flow_rules`` facade quacks like a
      ``ClusterFlowRuleManager`` so the Envoy RLS rule manager
      (``rls/rules.py``) can project descriptors straight onto a fleet.

Observability: every decision, failover transition, and lease grant is
labeled by shard (``sentinel_shard_*`` series); degrade transitions land
in the flight recorder; routed requests adopt the ambient trace context
so a merged dump shows client → RLS → shard as one timeline.
"""

from __future__ import annotations

import math
import threading
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from sentinel_tpu.adaptive.degrade import Hysteresis
from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.ring import DEFAULT_VNODES, HashRing, flow_key
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.obs import flight as FL
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.time_source import mono_s, wall_ms_now

#: chaos failpoints — the exact points a fleet-level fault strikes.  The
#: route site guards every remote dispatch (a raise here is "the shard is
#: unreachable" without tearing down real sockets, so scheduled hit
#: indices stay deterministic); the probe site marks health re-probes of
#: a degraded shard; the lease site covers the slack-lease refresh RPC.
_FP_ROUTE = FP.register(
    "cluster.shard.route", "sharded-client dispatch to the owning shard", FP.HIT_ACTIONS
)
_FP_PROBE = FP.register(
    "cluster.shard.probe", "health re-probe of a degraded shard", FP.HIT_ACTIONS
)
_FP_LEASE = FP.register(
    "cluster.shard.lease", "bounded-slack lease refresh round-trip", FP.HIT_ACTIONS
)
_FP_LEASE_ASYNC = FP.register(
    "cluster.lease.refresh_async",
    "ahead-of-exhaustion lease top-up dispatch",
    FP.HIT_ACTIONS,
)

_REQ_HELP = "token requests routed by the sharded client, by owning shard"
_LOCAL_ADMIT_HELP = (
    "decisions admitted locally against a healthy shard's standing lease "
    "(the zero-RPC fast path), by shard"
)
_FALLBACK_HELP = (
    "decisions served by the shard-local lease fallback while the owning "
    "shard is degraded, by verdict (pass = lease debit, block = fail-closed)"
)
_TRANSITION_HELP = "per-shard failover transitions (enter|exit)"
_DEGRADED_HELP = "1 while this shard is degraded to lease-fallback serving"
_LEASE_HELP = "budget tokens granted to this client as slack leases, by shard"

#: live fleets, for the ``/api/shards`` exposition (weak: a stopped
#: fleet must not be pinned by the command plane)
_FLEET_REGISTRY: "weakref.WeakSet[ShardedTokenClient]" = weakref.WeakSet()


def describe_fleets() -> List[dict]:
    """Topology + health of every live ``ShardedTokenClient`` in the
    process (the ``GET /api/shards`` payload)."""
    return [c.describe() for c in list(_FLEET_REGISTRY)]


class _Lease:
    """One flow's standing slack lease: ``granted`` tokens spendable
    until ``expires_ms`` (wall clock, the wire's accounting domain).
    ``retry_at_ms`` backs off ahead-of-exhaustion top-ups after the
    owner DENIED one while this lease still had spendable carry — the
    carry keeps draining, but re-asking before the horizon would retry
    a saturated budget on every local admit."""

    __slots__ = ("granted", "used", "expires_ms", "retry_at_ms")

    def __init__(self, granted: int, expires_ms: int):
        self.granted = granted
        self.used = 0
        self.expires_ms = expires_ms
        self.retry_at_ms = 0


class _ShardState:
    """Health + lease bookkeeping for one ring member."""

    def __init__(self, name: str, client: ClusterTokenClient):
        self.name = name
        self.client = client
        self.lock = threading.Lock()
        self.leases: Dict[int, _Lease] = {}
        #: flows with a LEASE RPC in flight — a second concurrent refresh
        #: would debit the global budget twice and keep only one grant
        self.lease_inflight: set = set()
        #: the shard's lease validity window as last reported by a grant
        #: (denials answer wait_ms=0, so they borrow this for their cache
        #: expiry — a 600 s-window fleet must not retry denials every 1 s)
        self.lease_ttl_hint_ms: int = C.DEFAULT_LEASE_TTL_MS
        #: single-flight gate for the failover probe: when the cooldown
        #: expires, exactly one thread pays the RPC against the
        #: maybe-still-dead shard; the rest keep serving the fallback
        self.probe_lock = threading.Lock()
        labels = {"shard": name}
        self.c_requests = _OBS.counter(
            "sentinel_shard_requests_total", _REQ_HELP, labels=labels
        )
        self.c_fallback = {
            v: _OBS.counter(
                "sentinel_shard_fallback_total",
                _FALLBACK_HELP,
                labels={"shard": name, "verdict": v},
            )
            for v in ("pass", "block")
        }
        self.c_enter = _OBS.counter(
            "sentinel_shard_degrade_transitions_total",
            _TRANSITION_HELP,
            labels={"shard": name, "transition": "enter"},
        )
        self.c_exit = _OBS.counter(
            "sentinel_shard_degrade_transitions_total",
            _TRANSITION_HELP,
            labels={"shard": name, "transition": "exit"},
        )
        self.g_degraded = _OBS.gauge(
            "sentinel_shard_degraded", _DEGRADED_HELP, labels=labels
        )
        self.c_lease_tokens = _OBS.counter(
            "sentinel_shard_lease_tokens_total", _LEASE_HELP, labels=labels
        )
        self.c_local_admits = _OBS.counter(
            "sentinel_lease_local_admits_total", _LOCAL_ADMIT_HELP, labels=labels
        )
        # the shared degrade-hysteresis primitive (adaptive/degrade.py),
        # scoped to THIS shard: same journal kinds ("shard.degrade.*"),
        # counters and gauge as the hand-rolled state it replaced.  The
        # cooldown is re-armed per enter() by the owning client (it owns
        # retry_interval_s).
        self.hy = Hysteresis(
            "shard.degrade",
            cooldown_s=5.0,
            attrs={"shard": name},
            counter_enter=self.c_enter,
            counter_exit=self.c_exit,
            gauge=self.g_degraded,
        )

    # attribute-compatible views (tests and the chaos harness poke these)
    @property
    def degraded_active(self) -> bool:
        return self.hy.active

    @degraded_active.setter
    def degraded_active(self, v: bool) -> None:
        self.hy.active = bool(v)

    @property
    def degraded_until(self) -> float:
        return self.hy.until

    @degraded_until.setter
    def degraded_until(self, v: float) -> None:
        self.hy.until = float(v)


class ShardedTokenClient(TokenService):
    """Hash-ring fan-out over N ``ClusterTokenClient`` connections.

    ``members`` maps shard name → ``(host, port)``.  Shard names are the
    ring members, so placement depends only on the NAMES — restarting a
    shard on a new port moves no keys.

    ``lease_slack`` sizes the per-flow standing lease as a fraction of
    the flow's threshold (0 disables leasing: a dead shard's flows then
    fail closed immediately).  Rule thresholds are learned via
    ``register_flow_rule`` — the ``ShardFleet``/RLS loaders call it; a
    client wired by hand must feed it the same rules its servers hold,
    or fallback (correctly) fails closed for unknown flows.

    Lease-first admission (protocol v2): with ``lease_slack > 0`` the
    standing lease is not just failover slack — it is the PRIMARY
    admission path.  A healthy flow admits locally by debiting the
    lease (zero RPCs) and tops the lease up in the background once the
    spendable remainder dips under ``lease_refresh_frac`` of the grant
    (or the TTL nears expiry).  Expiry still fails closed exactly as
    before: an expired or spent lease routes the request remotely.
    ``lease_refresh_async=False`` (or an armed chaos plan — see
    ``_refresh_lease_soon``) runs the top-up inline on the admitting
    thread, keeping failpoint hit counts a pure function of the seed.
    """

    def __init__(
        self,
        members: Dict[str, Tuple[str, int]],
        namespace: str = C.DEFAULT_NAMESPACE,
        timeout_ms: int = C.DEFAULT_REQUEST_TIMEOUT_MS,
        vnodes: int = DEFAULT_VNODES,
        retry_interval_s: float = 5.0,
        lease_slack: float = 0.25,
        reconnect_interval_s: float = 2.0,
        clients: Optional[Dict[str, ClusterTokenClient]] = None,
        lease_refresh_frac: float = 0.5,
        lease_refresh_async: bool = True,
    ):
        if not members:
            raise ValueError("sharded client needs at least one member")
        self.namespace = namespace
        self.retry_interval_s = retry_interval_s
        self.lease_slack = float(lease_slack)
        self.lease_refresh_frac = float(lease_refresh_frac)
        self.lease_refresh_async = bool(lease_refresh_async)
        self._refresher = _LeaseRefresher(self)
        self.ring = HashRing(sorted(members), vnodes=vnodes)
        self._order = sorted(members)  # index ↔ name, for composite token ids
        self._shards: Dict[str, _ShardState] = {}
        for name in self._order:
            host, port = members[name]
            cli = (clients or {}).get(name) or ClusterTokenClient(
                host,
                port,
                namespace=namespace,
                timeout_ms=timeout_ms,
                reconnect_interval_s=reconnect_interval_s,
            )
            self._shards[name] = _ShardState(name, cli)
        self._rule_counts: Dict[int, float] = {}
        self._rules_lock = threading.Lock()
        #: ClusterFlowRuleManager-quacking loader.  The default facade
        #: only LEARNS thresholds (lease sizing — pushing the rules to
        #: the shard servers is whoever runs them); ShardFleet replaces
        #: it with _FleetFlowRules, which also partitions rules onto the
        #: owners, so the RLS rule manager can project onto either shape
        self.flow_rules = _ClientFlowRules(self)
        _FLEET_REGISTRY.add(self)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for st in self._shards.values():
            st.client.start()

    def close(self) -> None:
        # deregister FIRST: a closed client must drop out of the
        # GET /api/shards topology even while callers still hold a ref
        _FLEET_REGISTRY.discard(self)
        self._refresher.close()
        for st in self._shards.values():
            st.client.close()

    @property
    def connected(self) -> bool:
        return any(st.client.connected for st in self._shards.values())

    # -- topology ------------------------------------------------------------

    def owner_of(self, flow_id: int) -> str:
        return self.ring.owner_of_flow(flow_id)

    def register_flow_rule(self, flow_id: int, count: float) -> None:
        """Teach the client a flow's threshold (lease sizing + fallback
        legality).  ``count <= 0`` forgets the flow — and its standing
        leases: a dropped rule must not keep admitting fallback traffic
        until the lease TTL runs out (this is also the only eviction
        ``st.leases`` has, so churning flow ids don't grow it forever)."""
        fid = int(flow_id)
        with self._rules_lock:
            if count > 0:
                self._rule_counts[fid] = float(count)
            else:
                self._rule_counts.pop(fid, None)
        if count <= 0:
            for st in self._shards.values():
                with st.lock:
                    st.leases.pop(fid, None)

    def shard_degraded(self, name: str) -> bool:
        return self._shards[name].degraded_active

    def describe(self) -> dict:
        now = mono_s()
        with self._rules_lock:
            # snapshot under the lock: a concurrent rule push mutating
            # the dict mid-iteration would fail the /api/shards request
            flow_ids = sorted(self._rule_counts)
        return {
            "namespace": self.namespace,
            "vnodes": self.ring.vnodes,
            "lease_slack": self.lease_slack,
            "flows_registered": len(flow_ids),
            "ring_spread": self.ring.spread(
                [flow_key(fid) for fid in flow_ids]
            ),
            "shards": [
                {
                    "name": st.name,
                    "addr": f"{st.client.host}:{st.client.port}",
                    "connected": st.client.connected,
                    "degraded": st.degraded_active,
                    "cooldown_remaining_s": round(
                        max(st.degraded_until - now, 0.0), 3
                    )
                    if st.degraded_active
                    else 0.0,
                    "leases": len(st.leases),
                }
                for st in self._shards.values()
            ],
        }

    # -- failover hysteresis (per shard) ------------------------------------

    def _enter_degraded(self, st: _ShardState) -> None:
        # transition mechanics (cooldown, counters, gauge, journal) live
        # in the shared adaptive.degrade.Hysteresis — scoped to ONE shard
        st.hy.enter(cooldown_s=self.retry_interval_s)

    def _exit_degraded(self, st: _ShardState) -> None:
        st.hy.exit()

    # -- routing core --------------------------------------------------------

    def _call(
        self,
        flow_id: int,
        remote: Callable[[ClusterTokenClient], TokenResult],
        fallback: Callable[[_ShardState], TokenResult],
    ) -> TokenResult:
        """Route one request to the owning shard with the failover
        protocol: degraded-and-cooling serves the fallback, an expired
        cooldown probes the shard (success exits degraded, failure
        re-arms the cooldown), and any transport-level failure —
        exception or ``STATUS_FAIL`` — enters degraded for THIS shard
        only."""
        st = self._shards[self.ring.owner_of_flow(flow_id)]
        st.c_requests.inc()
        degraded = st.degraded_active
        if degraded:
            if mono_s() < st.degraded_until:
                return fallback(st)
            # cooldown expired: single-flight the probe, or every thread
            # in flight pays timeout_ms against the dead shard at once
            if not st.probe_lock.acquire(blocking=False):
                return fallback(st)
        try:
            if degraded:
                FP.hit(_FP_PROBE)
            FP.hit(_FP_ROUTE)
            r = remote(st.client)
        except Exception:  # stlint: disable=fail-open — degrade to the shard-local lease fallback (fail-closed when no lease), never PASS
            self._enter_degraded(st)
            return fallback(st)
        finally:
            if degraded:
                st.probe_lock.release()
        if r.status == C.STATUS_FAIL:
            self._enter_degraded(st)
            return fallback(st)
        # BAD_REQUEST is synthesized client-side (oversized frame): it
        # proves nothing about shard health, so it must not exit degraded
        if degraded and r.status != C.STATUS_BAD_REQUEST:
            self._exit_degraded(st)
        return r

    # -- leases --------------------------------------------------------------

    def _lease_units(self, flow_id: int) -> int:
        count = self._rule_counts.get(int(flow_id), 0.0)
        if count <= 0 or self.lease_slack <= 0:
            return 0
        return min(
            max(int(math.ceil(count * self.lease_slack)), 1), C.MAX_LEASE_UNITS
        )

    def _maybe_refresh_lease(self, flow_id: int) -> None:
        """Bootstrap/expiry refresh on the request path: at most one
        blocking LEASE round-trip per validity window per flow, exactly
        the pre-lease-first contract.  In the v2 steady state the
        ahead-of-exhaustion top-up (``_refresh_lease_soon``) keeps the
        lease from ever expiring, so this fires only for a flow's FIRST
        request (or after an owner outage).  Failures are ignored — a
        missing lease just means the fallback fails closed, which is
        the safe direction."""
        if self._lease_units(flow_id) <= 0:
            return
        st = self._shards[self.ring.owner_of_flow(flow_id)]
        if st.degraded_active:
            # never refresh against a degraded shard — not even once the
            # cooldown expires (fallback-served requests would stampede
            # timeout_ms LEASE RPCs past the single-flight route probe);
            # the probe that heals the shard clears degraded_active, and
            # the same request then refreshes right below
            return
        now = wall_ms_now()
        with st.lock:
            lease = st.leases.get(flow_id)
            if lease is not None and now < lease.expires_ms:
                return
            if flow_id in st.lease_inflight:
                return
            st.lease_inflight.add(flow_id)
        self._refresh_lease_now(st, flow_id)

    def _lease_admit(self, flow_id: int, count: int) -> Optional[TokenResult]:
        """Lease-first fast path: admit locally against the standing
        bounded-slack lease while the owner is HEALTHY — zero RPCs on
        the request.  Returns ``None`` whenever the fast path does not
        apply (leasing disabled, shard degraded, lease missing, spent,
        or expired) and the caller routes remotely exactly as before —
        expiry fails closed into the remote path, never a local pass.
        Every grant here was debited from the global budget when the
        lease was acquired, so local admits conserve tokens."""
        if self.lease_slack <= 0 or count <= 0:
            return None
        st = self._shards[self.ring.owner_of_flow(flow_id)]
        if st.degraded_active:
            return None  # degraded flows use the metered fallback path
        now = wall_ms_now()
        refresh = False
        with st.lock:
            lease = st.leases.get(flow_id)
            if (
                lease is None
                or lease.granted <= 0
                or now >= lease.expires_ms
                or lease.used + count > lease.granted
            ):
                return None
            lease.used += count
            remaining = lease.granted - lease.used
            st.c_local_admits.inc()
            # top up ahead of exhaustion: once the spendable remainder
            # dips under refresh_frac of the grant — or the TTL enters
            # its last quarter — schedule a background refresh so the
            # NEXT admission window never pays a blocking RPC
            low = remaining <= lease.granted * self.lease_refresh_frac
            near = (lease.expires_ms - now) <= st.lease_ttl_hint_ms * 0.25
            if (low or near) and now >= lease.retry_at_ms:
                refresh = True
        if refresh:
            self._refresh_lease_soon(st, flow_id)
        return TokenResult(C.STATUS_OK, remaining=remaining)

    def _refresh_lease_soon(self, st: _ShardState, flow_id: int) -> None:
        """Ahead-of-exhaustion top-up dispatch: claim the single-flight
        marker and hand the RPC to the background refresher so the
        admitting request never pays transport latency.  While a chaos
        plan is armed — or ``lease_refresh_async=False`` — the hop runs
        INLINE instead: a background worker would make the LEASE
        failpoints fire at a nondeterministic point, breaking the chaos
        plane's injected-counts-are-a-pure-function-of-the-seed
        contract."""
        with st.lock:
            if flow_id in st.lease_inflight:
                return
            st.lease_inflight.add(flow_id)
        if self.lease_refresh_async and not FP.is_armed():
            self._refresher.enqueue(st, flow_id)
            return
        try:
            FP.hit(_FP_LEASE_ASYNC)
        except Exception:  # stlint: disable=fail-open — an injected dispatch fault skips ONE top-up; the lease keeps draining and fails closed at exhaustion
            with st.lock:
                st.lease_inflight.discard(flow_id)
            return
        self._refresh_lease_now(st, flow_id)

    def flush_lease_refresh(self, timeout_s: float = 5.0) -> bool:
        """Block until every queued ahead-of-exhaustion top-up has
        drained (tests and the bench use this to sequence assertions
        against the background refresher)."""
        return self._refresher.flush(timeout_s)

    def _lease_ask(self, st: _ShardState, flow_id: int) -> Tuple[int, int]:
        """``(ask, units_total)`` for a top-up: the lease target minus
        the still-spendable carry of the current lease."""
        units_total = self._lease_units(flow_id)
        if units_total <= 0:
            return 0, 0
        now = wall_ms_now()
        with st.lock:
            lease = st.leases.get(flow_id)
            carry = 0
            if lease is not None and now < lease.expires_ms:
                carry = max(lease.granted - lease.used, 0)
        return units_total - carry, units_total

    def _refresh_lease_now(self, st: _ShardState, flow_id: int) -> None:
        """Blocking lease top-up; the caller must already hold the
        in-flight marker for this flow (single-flight)."""
        ask, units_total = self._lease_ask(st, flow_id)
        if ask <= 0:
            with st.lock:
                st.lease_inflight.discard(flow_id)
            return
        try:
            FP.hit(_FP_LEASE)
            r = st.client.request_lease(flow_id, ask)
        except Exception:  # stlint: disable=fail-open — no lease acquired: the fallback path fails CLOSED for this flow
            with st.lock:
                st.lease_inflight.discard(flow_id)
            return
        if r.status == C.STATUS_FAIL:
            # transport-shaped failure, NOT an admission denial: caching
            # it would pin a zero-unit lease for a whole TTL window and
            # silently disable the failover slack.  Leave it uncached —
            # a genuinely sick shard degrades via the route path, which
            # then skips refresh entirely.
            with st.lock:
                st.lease_inflight.discard(flow_id)
            return
        self._store_lease_result(st, flow_id, r, units_total)

    def _store_lease_result(
        self, st: _ShardState, flow_id: int, r: TokenResult, units_total: int
    ) -> None:
        """Fold one grant/denial into the standing lease, in the SAME
        critical section that clears the in-flight marker:
        discard-then-store would let another thread slip in between and
        double-debit the budget."""
        if r.status == C.STATUS_OK and r.remaining > 0:
            st.c_lease_tokens.inc(r.remaining)
        now = wall_ms_now()
        with st.lock:
            st.lease_inflight.discard(flow_id)
            if int(flow_id) not in self._rule_counts:
                # the rule was dropped while the RPC was in flight —
                # storing the grant would resurrect a deleted rule's
                # standing lease past register_flow_rule's eviction
                return
            lease = st.leases.get(flow_id)
            carry = 0
            if lease is not None and now < lease.expires_ms:
                # recompute the carry NOW — local admits kept debiting
                # while the RPC was in flight, so the grant folds onto
                # whatever is genuinely left (bounded by units_total:
                # a shrunken carry only under-fills, never over)
                carry = max(lease.granted - lease.used, 0)
            if r.status == C.STATUS_OK and r.remaining > 0:
                st.lease_ttl_hint_ms = max(r.wait_ms, 1)
                st.leases[flow_id] = _Lease(
                    min(carry + r.remaining, units_total),
                    now + max(r.wait_ms, 1),
                )
            elif carry > 0:
                # top-up DENIED but the standing lease still has carry:
                # keep draining it and just back off further asks until
                # the denial horizon — replacing it with a zero-lease
                # would throw away slack the budget already paid for
                lease.retry_at_ms = now + max(r.wait_ms, st.lease_ttl_hint_ms)
            else:
                # cache the DENIAL too: a saturated flow otherwise
                # retries a blocking LEASE round-trip on every request
                # for the rest of the window, breaking the ≤1
                # RPC/TTL-window/flow contract.  A zero-unit lease
                # behaves exactly like no lease in the fallback (fails
                # closed) while suppressing the retries.
                st.leases[flow_id] = _Lease(
                    0, now + max(r.wait_ms, st.lease_ttl_hint_ms)
                )

    def _refresh_leases_batch(self, st: _ShardState, flow_ids: List[int]) -> None:
        """Background top-up for several of one shard's flows at once:
        a v2 peer answers them as ONE batched LEASE frame (one
        round-trip for the whole group), a v1 peer gets pipelined
        individual requests.  The caller (the refresher thread) already
        holds every flow's in-flight marker."""
        if st.degraded_active:
            with st.lock:
                for fid in flow_ids:
                    st.lease_inflight.discard(fid)
            return
        live: List[Tuple[int, int]] = []  # (flow_id, units_total)
        entries: List[Tuple[int, int, int]] = []
        for fid in flow_ids:
            ask, units_total = self._lease_ask(st, fid)
            if ask <= 0:
                with st.lock:
                    st.lease_inflight.discard(fid)
                continue
            live.append((fid, units_total))
            entries.append((C.BATCH_KIND_LEASE, fid, ask))
        if not live:
            return
        try:
            FP.hit(_FP_LEASE)
            results = st.client.request_batch(entries)
        except Exception:  # stlint: disable=fail-open — no lease acquired: the fallback fails CLOSED for these flows
            with st.lock:
                for fid, _ in live:
                    st.lease_inflight.discard(fid)
            return
        for (fid, units_total), r in zip(live, results):
            if r.status == C.STATUS_FAIL:
                # transport-shaped — leave uncached (see _refresh_lease_now)
                with st.lock:
                    st.lease_inflight.discard(fid)
                continue
            self._store_lease_result(st, fid, r, units_total)

    def _fallback_flow(self, st: _ShardState, flow_id: int, count: int) -> TokenResult:
        """Shard-local decision while the owner is unreachable: debit the
        standing lease, fail CLOSED when it is missing, spent, or expired
        — an unknown budget never passes."""
        now = wall_ms_now()
        with st.lock:
            lease = st.leases.get(flow_id)
            if (
                lease is not None
                and now < lease.expires_ms
                and lease.used + count <= lease.granted
            ):
                lease.used += count
                st.c_fallback["pass"].inc()
                return TokenResult(
                    C.STATUS_OK, remaining=lease.granted - lease.used
                )
        st.c_fallback["block"].inc()
        return TokenResult(C.STATUS_BLOCKED)

    def _fallback_block(self, st: _ShardState) -> TokenResult:
        st.c_fallback["block"].inc()
        return TokenResult(C.STATUS_BLOCKED)

    # -- TokenService --------------------------------------------------------

    def request_token(
        self, flow_id: int, count: int = 1, prioritized: bool = False
    ) -> TokenResult:
        r = self._lease_admit(flow_id, count)
        if r is not None:
            return r
        r = self._call(
            flow_id,
            lambda c: c.request_token(flow_id, count, prioritized),
            lambda st: self._fallback_flow(st, flow_id, count),
        )
        if r.status in (C.STATUS_OK, C.STATUS_SHOULD_WAIT, C.STATUS_BLOCKED):
            self._maybe_refresh_lease(flow_id)
        return r

    def request_token_batch(self, flow_id: int, units: int) -> TokenResult:
        r = self._lease_admit(flow_id, units)
        if r is not None:
            return TokenResult(C.STATUS_OK, remaining=units)

        def _fb(st: _ShardState) -> TokenResult:
            r = self._fallback_flow(st, flow_id, units)
            if r.status == C.STATUS_OK:
                return TokenResult(C.STATUS_OK, remaining=units)
            return TokenResult(C.STATUS_BLOCKED, remaining=0)

        r = self._call(
            flow_id, lambda c: c.request_token_batch(flow_id, units), _fb
        )
        if r.status in (C.STATUS_OK, C.STATUS_SHOULD_WAIT, C.STATUS_BLOCKED):
            self._maybe_refresh_lease(flow_id)
        return r

    def request_token_many(
        self, requests: Sequence[Tuple[int, int]]
    ) -> List[TokenResult]:
        """Admit many ``(flow_id, count)`` asks in one pass: lease-local
        admits cost nothing, and whatever must route remotely is grouped
        per owning shard into ONE protocol-v2 batch frame each (a v1
        peer gets a pipelined burst over the same multiplexed socket).
        The RLS front door drives multi-descriptor requests through
        this instead of one blocking round-trip per descriptor."""
        out: List[Optional[TokenResult]] = [None] * len(requests)
        per: Dict[str, List[int]] = {}
        for i, (fid, cnt) in enumerate(requests):
            r = self._lease_admit(fid, cnt)
            if r is not None:
                out[i] = r
                continue
            per.setdefault(self.ring.owner_of_flow(fid), []).append(i)
        for name, idxs in per.items():
            st = self._shards[name]
            st.c_requests.inc(len(idxs))
            entries = [
                (C.BATCH_KIND_FLOW, requests[i][0], requests[i][1]) for i in idxs
            ]
            rs = self._call_batch(st, entries)
            if rs is None:
                for i in idxs:
                    out[i] = self._fallback_flow(st, requests[i][0], requests[i][1])
                continue
            for i, r in zip(idxs, rs):
                out[i] = r
            for i in idxs:
                if out[i].status in (
                    C.STATUS_OK,
                    C.STATUS_SHOULD_WAIT,
                    C.STATUS_BLOCKED,
                ):
                    self._maybe_refresh_lease(requests[i][0])
        return [r if r is not None else TokenResult(C.STATUS_FAIL) for r in out]

    def _call_batch(
        self, st: _ShardState, entries: List[Tuple[int, int, int]]
    ) -> Optional[List[TokenResult]]:
        """One shard's slice of a many-flow request, under the same
        failover protocol as ``_call``.  Returns ``None`` when the
        exchange failed at the transport level (the caller serves every
        entry from the lease fallback)."""
        degraded = st.degraded_active
        if degraded:
            if mono_s() < st.degraded_until:
                return None
            if not st.probe_lock.acquire(blocking=False):
                return None
        try:
            if degraded:
                FP.hit(_FP_PROBE)
            FP.hit(_FP_ROUTE)
            rs = st.client.request_batch(entries)  # stlint: disable=blocking-under-lock — single-flight probe: probe_lock is only taken with blocking=False, so contenders serve the lease fallback instantly instead of queuing behind this round-trip
        except Exception:  # stlint: disable=fail-open — degrade to the shard-local lease fallback (fail-closed when no lease), never PASS
            self._enter_degraded(st)
            return None
        finally:
            if degraded:
                st.probe_lock.release()
        if rs and all(r.status == C.STATUS_FAIL for r in rs):
            # request_batch fails closed as a UNIT on transport trouble
            # (whole-frame FAIL, timeout, dead socket), so all-FAIL is
            # the batched shape of a single STATUS_FAIL round-trip
            self._enter_degraded(st)
            return None
        if degraded:
            self._exit_degraded(st)
        return rs

    def request_param_token(
        self, flow_id: int, count: int, params: List
    ) -> TokenResult:
        # no lease covers hot-param budgets (per-value state lives only
        # on the owner) → degraded param flows fail closed
        return self._call(
            flow_id,
            lambda c: c.request_param_token(flow_id, count, params),
            self._fallback_block,
        )

    def request_lease(self, flow_id: int, units: int) -> TokenResult:
        # a lease minted by anyone but the owner would double the budget
        return self._call(
            flow_id,
            lambda c: c.request_lease(flow_id, units),
            lambda st: TokenResult(C.STATUS_FAIL),
        )

    # concurrent tokens: the grantor must also see the release, so the
    # sharded token id carries the shard index in its high bits — ids
    # stay opaque int64s on the wire and release routes without a map
    _SHARD_BITS = 48

    def request_concurrent_token(self, flow_id: int, count: int = 1) -> TokenResult:
        name = self.ring.owner_of_flow(flow_id)
        idx = self._order.index(name)
        r = self._call(
            flow_id,
            lambda c: c.request_concurrent_token(flow_id, count),
            self._fallback_block,
        )
        if r.status == C.STATUS_OK and r.token_id:
            r = TokenResult(
                r.status, token_id=(idx << self._SHARD_BITS) | r.token_id
            )
        return r

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        idx, raw = token_id >> self._SHARD_BITS, token_id & ((1 << self._SHARD_BITS) - 1)
        if not (0 <= idx < len(self._order)):
            return TokenResult(C.STATUS_BAD_REQUEST)
        st = self._shards[self._order[idx]]
        if st.degraded_active and mono_s() < st.degraded_until:
            # don't stall timeout_ms against a shard already known dead —
            # the server-side TTL sweep expires the lost release
            return TokenResult(C.STATUS_FAIL)
        try:
            return st.client.release_concurrent_token(raw)
        except Exception:  # stlint: disable=fail-open — a lost release expires via the server-side TTL sweep; never PASSes anything
            return TokenResult(C.STATUS_FAIL)


class _LeaseRefresher:
    """Background lease top-up worker for one ``ShardedTokenClient``:
    the admitting thread only enqueues ``(shard, flow)``; this thread
    drains the queue and groups everything bound for the same shard
    into one batched LEASE exchange (``_refresh_leases_batch``).  The
    thread starts lazily on the first enqueue, so clients that never
    trigger an async top-up (slack 0, chaos runs, ``lease_refresh_async
    =False``) cost nothing.  Every queued flow's single-flight marker
    is already held by the enqueuer; whatever drops out of the queue —
    including at ``close()`` — must release it."""

    def __init__(self, client: "ShardedTokenClient"):
        # weakref: the refresher thread must not pin a dropped client
        # (close() also stops it explicitly, but tests that leak
        # clients still shouldn't leak fleets through the daemon)
        self._client = weakref.ref(client)
        self._cv = threading.Condition()
        self._q: List[Tuple[_ShardState, int]] = []
        self._busy = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    def enqueue(self, st: _ShardState, flow_id: int) -> None:
        with self._cv:
            if not self._closed:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, name="sentinel-lease-refresh", daemon=True
                    )
                    self._thread.start()
                self._q.append((st, flow_id))
                self._cv.notify()
                return
        with st.lock:
            st.lease_inflight.discard(flow_id)

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Wait until the queue is empty AND no drain is in progress."""
        deadline = mono_s() + timeout_s
        with self._cv:
            while self._q or self._busy:
                left = deadline - mono_s()
                if left <= 0:
                    return False
                self._cv.wait(timeout=left)
        return True

    def close(self) -> None:
        with self._cv:
            self._closed = True
            pending, self._q = self._q, []
            self._cv.notify_all()
        for st, fid in pending:
            with st.lock:
                st.lease_inflight.discard(fid)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    # bounded: the predicate loop makes the timeout free
                    # (spurious wakeups just re-check), and a notify lost
                    # to a future refactor degrades to a 1 s idle poll
                    # instead of wedging this thread and close() forever
                    self._cv.wait(timeout=1.0)
                if self._closed:
                    return
                batch, self._q = self._q, []
                self._busy = True
            try:
                per: Dict[str, List[int]] = {}
                states: Dict[str, _ShardState] = {}
                for st, fid in batch:
                    states[st.name] = st
                    per.setdefault(st.name, []).append(fid)
                client = self._client()
                if client is None:
                    for st, fid in batch:
                        with st.lock:
                            st.lease_inflight.discard(fid)
                elif len(per) == 1:
                    ((name, fids),) = per.items()
                    client._refresh_leases_batch(states[name], fids)
                else:
                    # one blocking exchange PER OWNING SHARD — issued
                    # concurrently, not in a serial loop: each shard's
                    # connection is independently multiplexed, and a
                    # serial sweep would charge one drain cycle the SUM
                    # of every shard's round-trip (the fleet's lease
                    # capacity would then shrink as shards are added)
                    hops = [
                        threading.Thread(
                            target=client._refresh_leases_batch,
                            args=(states[name], fids),
                            daemon=True,
                        )
                        for name, fids in per.items()
                    ]
                    for h in hops:
                        h.start()
                    for h in hops:
                        h.join()
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()


class _ClientFlowRules:
    """Threshold-learning ``ClusterFlowRuleManager`` facade for a
    hand-built ``ShardedTokenClient`` (no fleet): ``load`` teaches the
    client each flow's count so lease sizing works and the RLS rule
    manager can project onto it without crashing.  It does NOT push the
    rules to the shard servers — whoever operates them must load the
    same rules there, or decisions return NO_RULE (and fallback fails
    closed).  ``ShardFleet`` replaces this with ``_FleetFlowRules``,
    which does both."""

    def __init__(self, client: "ShardedTokenClient"):
        self._client = client
        self._by_ns: Dict[str, list] = {}

    def load(self, namespace: str, rules: list) -> None:
        old_fids = {r.cluster_flow_id for r in self._by_ns.get(namespace, [])}
        self._by_ns[namespace] = list(rules)
        for r in rules:
            self._client.register_flow_rule(r.cluster_flow_id, r.count)
        for fid in old_fids - {r.cluster_flow_id for r in rules}:
            self._client.register_flow_rule(fid, 0)

    def get(self, namespace: str) -> list:
        return list(self._by_ns.get(namespace, []))


class _FleetFlowRules:
    """``ClusterFlowRuleManager``-shaped facade over a fleet: ``load``
    partitions a namespace's rules onto their ring owners (every shard
    sees a load, so rules leaving a shard are cleared there) and teaches
    the sharded client the thresholds for lease sizing."""

    def __init__(self, fleet: "ShardFleet"):
        self._fleet = fleet
        # the learn/forget-thresholds half is exactly the bare-client
        # facade's job — delegate, don't duplicate
        self._learn = _ClientFlowRules(fleet.client)

    def load(self, namespace: str, rules: list) -> None:
        fleet = self._fleet
        self._learn.load(namespace, rules)
        parts: Dict[str, list] = {name: [] for name in fleet.names}
        for r in rules:
            parts[fleet.client.ring.owner_of_flow(r.cluster_flow_id)].append(r)
        for name in fleet.names:
            fleet.services[name].flow_rules.load(namespace, parts[name])

    def get(self, namespace: str) -> list:
        return self._learn.get(namespace)


class ShardFleet:
    """In-process N-shard token fleet (tests / chaos / bench / demos).

    Each shard is a full ``DefaultTokenService`` on its own decision
    engine client behind its own TCP ``ClusterTokenServer``;
    ``client_factory`` builds the decision engines (tests pass their
    fixture factory — identical configs share the XLA compile cache, so
    N shards cost one compile).  ``kill``/``rejoin`` stop and restart a
    shard's server on its original port, the fleet-level fault the chaos
    ``shard_failover`` scenario and the bench failover-blip measurement
    drive."""

    def __init__(
        self,
        client_factory: Callable[[], object],
        n_shards: int = 2,
        names: Optional[Sequence[str]] = None,
        host: str = "127.0.0.1",
        lease_ttl_ms: int = C.DEFAULT_LEASE_TTL_MS,
        warm: bool = True,
        **sharded_kw,
    ):
        from sentinel_tpu.cluster.server import ClusterTokenServer
        from sentinel_tpu.cluster.token_service import DefaultTokenService

        self.names: List[str] = list(names or (f"shard-{i}" for i in range(n_shards)))
        self.services: Dict[str, DefaultTokenService] = {}
        self.servers: Dict[str, Optional[ClusterTokenServer]] = {}
        members: Dict[str, Tuple[str, int]] = {}
        try:
            for name in self.names:
                decision = client_factory()
                if warm:
                    # pay the decision engine's first-tick XLA compile NOW,
                    # on a throwaway resource — otherwise the fleet's first
                    # token request times out against a compiling shard and
                    # flips it straight into failover (the chaos harness
                    # learned this the hard way; identical configs share
                    # the jit cache, so only the first shard compiles)
                    decision.registry.resource_id(f"shard/warm/{name}")
                    f = decision.submit_acquire(f"shard/warm/{name}")
                    if f is not None:
                        f.result(timeout=120.0)
                svc = DefaultTokenService(decision, lease_ttl_ms=lease_ttl_ms)
                server = ClusterTokenServer(svc, host=host, port=0)
                server.start()
                self.services[name] = svc
                self.servers[name] = server
                members[name] = (host, server.port)
            self._host = host
            self._ports = {name: members[name][1] for name in self.names}
            self.client = ShardedTokenClient(members, **sharded_kw)
            self.client.flow_rules = _FleetFlowRules(self)
            self.client.start()
        except BaseException:
            # a failed 3rd-of-4 shard must not strand the first two's
            # live TCP servers with no fleet object to stop() (decision
            # engines stay caller-owned — client_factory's maker stops
            # them, exactly as fleet.stop() leaves them running too)
            client = getattr(self, "client", None)
            if client is not None:
                client.close()
            for server in self.servers.values():
                if server is not None:
                    server.stop()
            raise

    # -- rules ---------------------------------------------------------------

    def load_flow_rules(self, namespace: str, rules: list) -> None:
        self.client.flow_rules.load(namespace, rules)

    # -- fleet-level faults --------------------------------------------------

    def kill(self, name: str) -> None:
        """Stop one shard's server (its decision engine stays up, so
        ``rejoin`` restores service without a recompile)."""
        server = self.servers[name]
        if server is not None:
            server.stop()
            self.servers[name] = None

    def rejoin(self, name: str) -> None:
        """Restart a killed shard on its ORIGINAL port — ring placement
        keys on the shard NAME, so no flows move."""
        from sentinel_tpu.cluster.server import ClusterTokenServer

        if self.servers[name] is not None:
            return
        server = ClusterTokenServer(
            self.services[name], host=self._host, port=self._ports[name]
        )
        server.start()
        self.servers[name] = server

    def stop(self) -> None:
        self.client.close()
        for name, server in self.servers.items():
            if server is not None:
                server.stop()
                self.servers[name] = None

    def describe(self) -> dict:
        return self.client.describe()
