"""Cluster token decision service.

The reference's token server answers requestToken(flowId, count, priority)
with a verdict from a per-rule ClusterMetric sliding window
(DefaultTokenService.java:34-44 → ClusterFlowChecker.acquireClusterToken:55-88).

TPU inversion: each cluster flowId is interned as a resource
(``$cluster/flow/<id>``) on a dedicated decision ``SentinelClient``, so token
verdicts ride the same batched device engine as local rules — concurrent
requests from many connections coalesce into one micro-batch tick.  The
global threshold
``count × (1 if thresholdType==GLOBAL else connectedCount) × exceedCount``
(ClusterFlowChecker.java:38,68) is recomputed and pushed to the engine
whenever rules or the connection census change.

Host-side pieces (naturally request-scoped, not tensor-shaped):
  * GlobalRequestLimiter — per-namespace QPS guard
    (GlobalRequestLimiter.java:28, RequestLimiter.java:29-39)
  * ConcurrentTokenManager — cluster-wide concurrency tokens with TTL expiry
    (ConcurrentClusterFlowChecker.java:34-81, CurrentConcurrencyManager,
    TokenCacheNodeManager, RegularExpireStrategy)
"""

from __future__ import annotations

import itertools
import threading
import time as _time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster.rules import (
    ClusterFlowRuleManager,
    ClusterParamFlowRuleManager,
    ClusterServerConfigManager,
    flow_resource,
    param_resource,
)
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core import rules as R
from sentinel_tpu.obs import profile as PROF
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.host_window import HostWindow

_H_DECISION = _OBS.histogram(
    "sentinel_token_decision_ms",
    "engine-backed token decision latency (request to verdict)",
)
_C_DECISIONS = _OBS.counter(
    "sentinel_token_decisions_total", "token verdicts served by this process"
)
_C_SHED = _OBS.counter(
    "sentinel_token_shed_total",
    "token requests shed before the engine (namespace guard or backpressure)",
)
_C_BATCHED = _OBS.counter(
    "sentinel_cluster_batched_decisions_total",
    "token entries decided by the device column kernel (ops/token_col.py)",
)

#: chaos failpoint on the decision path: a raise here exercises every
#: caller's STATUS_FAIL conversion (request_token's catch, the TCP
#: server's _flow_and_reply/_process catches) — degrade, never PASS
_FP_DECIDE = FP.register(
    "cluster.token.decide", "token service decision entry", FP.HIT_ACTIONS
)


#: engine stages the cluster token decision path exercises: flow checks
#: (with occupy-ahead for prioritized SHOULD_WAIT grants) and hot-param
#: token checks.  The decision client's resources are interned flowIds —
#: no ctx/origin node fan-out, no circuit breakers, no authority/system
#: rules ever bind to them, so a dedicated decision engine compiled with
#: exactly this set serves token verdicts with the minimal tick.  The
#: jaxpr analyzer (sentinel_tpu/analysis/jaxpr) traces `ops.engine.tick`
#: under this feature set as its `tick/cluster-token` entry point, so
#: CI pins the compiled token-decision program alongside the local ones.
DECISION_FEATURES = frozenset({"flow", "occupy", "param"})


@dataclass
class TokenResult:
    status: int
    remaining: int = 0
    wait_ms: int = 0
    token_id: int = 0
    # deny provenance (protocol v3 _T_PROV, obs/explain.py): populated on
    # STATUS_BLOCKED by services that know WHY — verdict kind, blamed rule
    # (flow id), observed usage at decision time, and the limit it hit.
    # None on OK results, on pre-v3 peers, and on transport failures, so
    # every consumer must treat provenance as best-effort.
    prov_kind: Optional[int] = None
    prov_rule: Optional[int] = None
    prov_observed: Optional[float] = None
    prov_limit: Optional[float] = None

    @property
    def ok(self) -> bool:
        return self.status == C.STATUS_OK

    @property
    def blocked(self) -> bool:
        return self.status == C.STATUS_BLOCKED


class TokenService:
    """Abstract token service (cluster/TokenService.java:26-62)."""

    #: lease validity window granted to holders; implementations with a
    #: configured TTL (``DefaultTokenService``) shadow this per instance
    lease_ttl_ms: int = C.DEFAULT_LEASE_TTL_MS

    def request_token(self, flow_id: int, count: int = 1, prioritized: bool = False) -> TokenResult:
        raise NotImplementedError

    def request_token_batch(self, flow_id: int, units: int) -> TokenResult:
        """Partial-grant acquire: ask for ``units`` single tokens, receive
        granted k in ``remaining`` (0..units).  Default maps onto the
        all-or-nothing request_token for foreign implementations."""
        r = self.request_token(flow_id, units, False)
        if r.status == C.STATUS_OK:
            return TokenResult(C.STATUS_OK, remaining=units, wait_ms=r.wait_ms)
        if r.status == C.STATUS_BLOCKED:
            return TokenResult(C.STATUS_BLOCKED, remaining=0)
        return r

    def request_param_token(self, flow_id: int, count: int, params: List[Any]) -> TokenResult:
        raise NotImplementedError

    def request_concurrent_token(self, flow_id: int, count: int = 1) -> TokenResult:
        raise NotImplementedError

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        raise NotImplementedError

    def request_lease(self, flow_id: int, units: int) -> TokenResult:
        """Bounded-slack budget lease (cluster/shard.py): grant up to
        ``units`` tokens spendable by the holder for one validity window
        (``remaining`` = granted k, ``wait_ms`` = window ms).  The grant
        rides the partial-grant batch acquire — debited from the SAME
        global budget as ordinary tokens, which is what makes the
        holder's offline spending conserve it — so any TokenService can
        serve as a lease source.  Units clamp to ``MAX_LEASE_UNITS``
        here, for EVERY implementation: a hostile/miscalibrated request
        must not stall the decision backend."""
        r = self.request_token_batch(flow_id, min(units, C.MAX_LEASE_UNITS))
        if r.status == C.STATUS_OK:
            return TokenResult(
                C.STATUS_OK, remaining=r.remaining, wait_ms=self.lease_ttl_ms
            )
        return r


class GlobalRequestLimiter:
    """Per-namespace request-QPS guard in front of the decision engine."""

    def __init__(self, config: ClusterServerConfigManager):
        self._config = config
        self._windows: Dict[str, HostWindow] = {}
        self._lock = threading.Lock()

    def _window(self, namespace: str, cfg) -> HostWindow:
        # a pushed config is unvalidated: round interval up to a multiple of
        # sample_count instead of letting HostWindow's divisibility assert
        # fire on the request hot path
        sample_count = max(int(cfg.sample_count), 1)
        interval_ms = max(int(cfg.interval_ms), sample_count)
        interval_ms = ((interval_ms + sample_count - 1) // sample_count) * sample_count
        w = self._windows.get(namespace)
        if w is None or (w.sample_count, w.interval_ms) != (sample_count, interval_ms):
            # (re)build to the configured shape; a config push that reshapes
            # the window restarts its accounting, like the reference's
            # per-namespace RequestLimiter re-creation
            with self._lock:
                w = self._windows.get(namespace)
                if w is None or (w.sample_count, w.interval_ms) != (
                    sample_count,
                    interval_ms,
                ):
                    w = HostWindow(sample_count, interval_ms)
                    self._windows[namespace] = w
        return w

    def try_pass(self, namespace: str, now_ms: int) -> bool:
        cfg = self._config.flow_config(namespace)
        return self._window(namespace, cfg).try_pass(now_ms, cfg.max_allowed_qps)

    def current_qps(self, namespace: str, now_ms: int) -> float:
        w = self._windows.get(namespace)
        return w.qps(now_ms) if w else 0.0


class ConcurrentTokenManager:
    """Cluster-wide concurrency tokens with TTL expiry."""

    def __init__(self, ttl_ms: int = 5000):
        self.ttl_ms = ttl_ms
        self._lock = threading.Lock()
        self._current: Dict[int, int] = {}  # flowId -> concurrency in flight
        self._tokens: Dict[int, tuple] = {}  # tokenId -> (flowId, count, deadline)
        self._ids = itertools.count(1)

    def acquire(self, flow_id: int, count: int, limit: float, now_ms: int) -> Optional[int]:
        with self._lock:
            cur = self._current.get(flow_id, 0)
            if cur + count > limit:
                return None
            self._current[flow_id] = cur + count
            tid = next(self._ids)
            self._tokens[tid] = (flow_id, count, now_ms + self.ttl_ms)
            return tid

    def release(self, token_id: int) -> bool:
        with self._lock:
            node = self._tokens.pop(token_id, None)
            if node is None:
                return False
            fid, count, _ = node
            self._current[fid] = max(self._current.get(fid, 0) - count, 0)
            return True

    def current(self, flow_id: int) -> int:
        return self._current.get(flow_id, 0)

    def expire(self, now_ms: int) -> int:
        """Drop expired tokens (RegularExpireStrategy sweep). Returns count."""
        with self._lock:
            dead = [tid for tid, (_, _, dl) in self._tokens.items() if dl <= now_ms]
            for tid in dead:
                fid, count, _ = self._tokens.pop(tid)
                self._current[fid] = max(self._current.get(fid, 0) - count, 0)
            return len(dead)


class TokenColumnBatcher:
    """Coalesces token decisions into one jitted device column call.

    Every decision entry path — the blocking API, the thread-free TCP
    FLOW path, and whole protocol-v2 BATCH frames from many connections
    — submits ``(flow_id, units, partial)`` entries here; a worker
    thread drains the queue and answers a whole chunk with ONE
    ``ops/token_col.decide_batch`` call.  All paths therefore debit the
    SAME device-resident budget ledger (the per-slot sliding window IS
    the ledger), so coalescing can never double-admit against a separate
    engine-side account.

    Entries are presorted by slot host-side (native batch_sort3, stable)
    and rebased prefix sums inside the kernel make one coalesced batch
    admit exactly what sequential requests would have.

    Slot assignment is stable across rule pushes: retained flows keep
    their row (the standing ledger survives a reprojection, matching the
    engine tier where windows persist across rule reloads); dropped
    flows release their row with its ledger zeroed before reuse.
    """

    #: entries per device call — one compiled shape per slot capacity;
    #: bigger drains chunk sequentially (same-slot carry is exact: the
    #: window is updated between chunks)
    CAPACITY = 256

    def __init__(self, service: "DefaultTokenService"):
        # lazy heavyweight imports: the cluster codec/client modules must
        # stay importable without dragging jax in
        from sentinel_tpu.native import ring as NR
        from sentinel_tpu.obs import timeline as TLM
        from sentinel_tpu.ops import token_col as TC

        self._TC = TC
        self._NR = NR
        self._TLM = TLM
        self.svc = service
        # per-window cumulative [TL_COLS] rows fed to the decision
        # client's TimelineRecorder: the col path answers off-engine, so
        # it must land the same per-second `$cluster/flow/<id>` rows the
        # engine's device top-K matrix used to produce (worker-thread
        # only — no lock needed beyond the recorder's own)
        self._tl_wid = -1
        self._tl_acc: Dict[int, np.ndarray] = {}
        self._tl_rids: Dict[int, int] = {}
        self._q_lock = threading.Lock()
        self._cv = threading.Condition(self._q_lock)
        self._pending: List[tuple] = []  # (flow_id, units, partial, Future)
        self._s_lock = threading.Lock()  # slots + device state
        self._slots: Dict[int, int] = {}
        self._free: List[int] = []
        self._next_slot = 0
        # flow id -> projected global threshold, for deny provenance
        # (replaced wholesale in project(); dict swap is GIL-atomic so
        # the worker thread reads it lock-free)
        self._limits_by_fid: Dict[int, float] = {}
        self._cap = 8
        self._state = TC.init_state(self._cap)
        # memory ledger (obs/profile.py): token-column device state under
        # a per-batcher owner so close() releases exactly this claim
        self._ledger_name = f"tokencol:{id(self):x}"
        with PROF.ledger_owner(self._ledger_name):
            PROF.LEDGER.track("tokens", "token_col.state", self._state)
        self._decide = TC.jitted_decide()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="sentinel-token-col", daemon=True
        )
        self._worker.start()

    def pending_entries(self) -> int:
        return len(self._pending)

    def submit(
        self, flow_id: int, units: int, partial: bool, forced: bool = False
    ) -> "Future":
        """Enqueue one decision entry; resolves to ``(granted, observed,
        limit)`` — granted units plus the window usage and threshold the
        entry was decided against (deny provenance, obs/explain.py).  A
        flow whose rule dropped between guard and decide grants 0 — fail
        closed, like every ambiguity on this path.  ``forced`` charges
        unconditionally (the occupy-ahead emulation)."""
        f: Future = Future()
        with self._cv:
            if self._closed:
                f.set_exception(RuntimeError("token column batcher closed"))
                return f
            self._pending.append((flow_id, units, partial, forced, f))
            self._cv.notify()
        return f

    def ms_to_next_bucket(self, now_ms: int) -> int:
        return self._TC.ms_to_next_bucket(int(now_ms))

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        PROF.LEDGER.drop_owner(self._ledger_name)

    def warm(self) -> None:
        """Pay the XLA compile for the current capacity off the request
        path — a cold first decision would outlive entry timeouts and
        read as a dead shard (the ShardFleet warm lesson)."""
        with self._s_lock:
            self._warm_locked()

    def _warm_locked(self) -> None:
        TC = self._TC
        now = np.int32(int(self.svc.client.time.now_ms()))
        slots = np.zeros(self.CAPACITY, np.int32)
        units = np.zeros(self.CAPACITY, np.int32)
        heads = np.arange(self.CAPACITY, dtype=np.int32)
        partial = np.zeros(self.CAPACITY, bool)
        forced = np.zeros(self.CAPACITY, bool)
        g, _obs, self._state = self._decide(
            self._state, now, slots, units, heads, partial, forced
        )
        np.asarray(g)  # block until the executable is built

    def project(self, thresholds: Dict[int, float]) -> None:
        """Rebuild slot map + per-slot limits from a rule/census push.
        Retained flows keep their slot AND their standing window ledger;
        recycled and grown rows start zeroed."""
        import jax.numpy as jnp

        TC = self._TC
        W = TC.W
        with self._s_lock:
            zero_rows: List[int] = []
            for fid in [f for f in self._slots if f not in thresholds]:
                s = self._slots.pop(fid)
                self._free.append(s)
            for fid in thresholds:
                if fid not in self._slots:
                    if self._free:
                        s = self._free.pop()
                        zero_rows.append(s)  # no inherited ledger
                    else:
                        s = self._next_slot
                        self._next_slot += 1
                    self._slots[fid] = s
            cap = self._cap
            while cap < self._next_slot:
                cap *= 2
            if zero_rows or cap != self._cap:
                counts = np.zeros(
                    (cap,) + tuple(self._state.win.counts.shape[1:]), np.int32
                )
                rt_sum = np.zeros((cap,) + tuple(self._state.win.rt_sum.shape[1:]), np.float32)
                rt_min = np.full(
                    (cap,) + tuple(self._state.win.rt_min.shape[1:]),
                    W.RT_MIN_INIT,
                    np.float32,
                )
                run = np.zeros((cap, W.NUM_EVENTS), np.int32)
                run_rt = np.zeros((cap,), np.float32)
                run_rt_min = np.full((cap,), W.RT_MIN_INIT, np.float32)
                old = self._cap
                counts[:old] = np.asarray(self._state.win.counts)
                rt_sum[:old] = np.asarray(self._state.win.rt_sum)
                rt_min[:old] = np.asarray(self._state.win.rt_min)
                run[:old] = np.asarray(self._state.win.run)
                run_rt[:old] = np.asarray(self._state.win.run_rt)
                run_rt_min[:old] = np.asarray(self._state.win.run_rt_min)
                if zero_rows:
                    counts[zero_rows] = 0
                    rt_sum[zero_rows] = 0.0
                    rt_min[zero_rows] = W.RT_MIN_INIT
                    run[zero_rows] = 0
                    run_rt[zero_rows] = 0.0
                    run_rt_min[zero_rows] = W.RT_MIN_INIT
                win = W.WindowState(
                    counts=jnp.asarray(counts),
                    rt_sum=jnp.asarray(rt_sum),
                    rt_min=jnp.asarray(rt_min),
                    epochs=self._state.win.epochs,
                    run=jnp.asarray(run),
                    run_rt=jnp.asarray(run_rt),
                    run_rt_min=jnp.asarray(run_rt_min),
                    rot_wid=self._state.win.rot_wid,
                )
                grew = cap != self._cap
                self._state = TC.TokenColState(win=win, limits=self._state.limits)
                self._cap = cap
                with PROF.ledger_owner(self._ledger_name):
                    PROF.LEDGER.track("tokens", "token_col.state", self._state)
            else:
                grew = False
            limits = np.zeros(cap, np.float32)
            for fid, thr in thresholds.items():
                limits[self._slots[fid]] = thr
            self._limits_by_fid = dict(thresholds)
            self._state = TC.set_limits(self._state, jnp.asarray(limits))
            if grew:
                # rule pushes pay the new shape's compile, requests don't
                self._warm_locked()

    # -- worker -------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    # bounded: the predicate loop makes the timeout free
                    # (spurious wakeups just re-check), and a notify lost
                    # to a future refactor degrades to a 1 s idle poll
                    # instead of wedging this thread and close() forever
                    self._cv.wait(timeout=1.0)
                if not self._pending and self._closed:
                    return
                batch, self._pending = self._pending, []
            try:
                now = int(self.svc.client.time.now_ms())
                with self._s_lock:
                    for i in range(0, len(batch), self.CAPACITY):
                        self._decide_chunk(batch[i : i + self.CAPACITY], now)
            except Exception as e:  # stlint: disable=fail-open — a failed future is STATUS_FAIL at every caller: degrade, never PASS
                for *_, f in batch:
                    if not f.done():
                        f.set_exception(e)

    def _decide_chunk(self, chunk: List[tuple], now: int) -> None:
        n = len(chunk)
        raw_slots = np.zeros(n, np.int32)
        raw_units = np.zeros(n, np.int32)
        raw_partial = np.zeros(n, bool)
        raw_forced = np.zeros(n, bool)
        for i, (fid, u, p, fo, _f) in enumerate(chunk):
            s = self._slots.get(fid, -1)
            if s >= 0 and u > 0:
                raw_slots[i] = s
                raw_units[i] = u  # unknown/dropped flows keep units 0 → granted 0
            raw_partial[i] = bool(p)
            raw_forced[i] = bool(fo)
        z = np.zeros(n, np.int32)
        order, _ = self._NR.batch_sort3(raw_slots, z, z, want_inv=False)
        s_sorted = raw_slots[order]
        u_sorted = raw_units[order]
        slots = np.zeros(self.CAPACITY, np.int32)
        units = np.zeros(self.CAPACITY, np.int32)
        partial = np.zeros(self.CAPACITY, bool)
        forced = np.zeros(self.CAPACITY, bool)
        heads = np.arange(self.CAPACITY, dtype=np.int32)
        slots[:n], units[:n] = s_sorted, u_sorted
        partial[:n], forced[:n] = raw_partial[order], raw_forced[order]
        if n:
            newseg = np.ones(n, bool)
            newseg[1:] = s_sorted[1:] != s_sorted[:-1]
            heads[:n] = np.maximum.accumulate(
                np.where(newseg, np.arange(n), 0)
            ).astype(np.int32)
        g, obs, self._state = self._decide(
            self._state, np.int32(now), slots, units, heads, partial, forced
        )
        granted = np.empty(n, np.int32)
        granted[order] = np.asarray(g)[:n]
        observed = np.empty(n, np.float32)
        observed[order] = np.asarray(obs)[:n]
        _C_BATCHED.inc(n)
        self._note_timeline(chunk, granted, now)
        lims = self._limits_by_fid
        for i, (fid, _u, _p, _fo, f) in enumerate(chunk):
            if not f.done():
                f.set_result(
                    (int(granted[i]), float(observed[i]), lims.get(fid, 0.0))
                )

    def _note_timeline(self, chunk: List[tuple], granted: np.ndarray, now: int) -> None:
        """Land this chunk's verdicts in the decision client's timeline.

        The recorder keeps the LATEST cumulative row per (window,
        resource), so this accumulates per-window pass/block counts and
        re-emits the whole current window each call — byte-for-byte the
        contract of the engine's device top-K matrix, minus the stages
        (rt/concurrency) a token verdict doesn't have."""
        TLM = self._TLM
        tl = self.svc.client.timeline
        if tl is None:
            return
        wid = int(now) // tl.window_ms
        if wid != self._tl_wid:
            # the recorder already holds the previous window's final
            # cumulative rows; only the open window needs an accumulator
            self._tl_wid = wid
            self._tl_acc.clear()
        for i, (fid, u, p, fo, _f) in enumerate(chunk):
            rid = self._tl_rids.get(fid)
            if rid is None:
                rid = self.svc.client.registry.resource_id(flow_resource(fid))
                if rid is None:
                    continue  # registry exhausted: stats degrade, verdicts don't
                self._tl_rids[fid] = rid
            row = self._tl_acc.get(rid)
            if row is None:
                row = np.zeros(8, np.float32)  # ops/engine TL_COLS layout
                row[TLM.TL_RID] = rid
                row[TLM.TL_RT_MIN] = TLM._RT_MIN_INIT
                self._tl_acc[rid] = row
            g = int(granted[i])
            ok = fo or g >= u or (p and g > 0)
            row[TLM.TL_PASS if ok else TLM.TL_BLOCK] += 1.0
        if self._tl_acc:
            tl.note_tick(
                np.stack(list(self._tl_acc.values())),
                now,
                int(self.svc.client.time.wall_ms(now)) - int(now),
            )


class DefaultTokenService(TokenService):
    """Engine-backed token service.

    ``decision_client`` is a dedicated SentinelClient whose resources are the
    cluster flowIds.  ``connected_count_fn(namespace) -> int`` feeds the
    AVG_LOCAL threshold scaling; the server wires it to its ConnectionManager
    (ConnectionGroup.getConnectedCount), standalone/embedded default is 1.

    Prioritized requests that exceed the current bucket borrow from the next
    one (engine occupy-ahead, DefaultController.tryOccupyNext) and surface as
    STATUS_SHOULD_WAIT with the wait until that bucket starts — the client
    sleeps and enters, matching TokenResultStatus.SHOULD_WAIT semantics.
    """

    def __init__(
        self,
        decision_client,
        config: Optional[ClusterServerConfigManager] = None,
        connected_count_fn: Optional[Callable[[str], int]] = None,
        concurrent_ttl_ms: int = 5000,
        lease_ttl_ms: int = C.DEFAULT_LEASE_TTL_MS,
        use_token_column: bool = True,
    ):
        self.client = decision_client
        self.lease_ttl_ms = lease_ttl_ms
        self.config = config or ClusterServerConfigManager()
        self.connected_count_fn = connected_count_fn or (lambda ns: 1)
        # device column batcher first: _reproject (fired by every rule
        # push below) projects thresholds into it
        self.col = TokenColumnBatcher(self) if use_token_column else None
        self.flow_rules = ClusterFlowRuleManager(on_change=self._reproject)
        self.param_rules = ClusterParamFlowRuleManager(on_change=self._reproject)
        self.limiter = GlobalRequestLimiter(self.config)
        self.concurrent = ConcurrentTokenManager(ttl_ms=concurrent_ttl_ms)
        self.config.add_listener(self._reproject)
        self._lock = threading.Lock()
        if self.col is not None:
            self.col.warm()

    def warm(self) -> None:
        """Compile the device decision path off the request clock (a cold
        first decision outlives entry timeouts and reads as a dead shard)."""
        if self.col is not None:
            self.col.warm()

    def close(self) -> None:
        if self.col is not None:
            self.col.close()

    # -- projection onto the engine ----------------------------------------

    def _global_threshold(self, rule: R.FlowRule, namespace: str) -> float:
        cfg = self.config.flow_config(namespace)
        n = (
            1
            if rule.cluster_threshold_type == C.FLOW_THRESHOLD_GLOBAL
            else max(self.connected_count_fn(namespace), 1)
        )
        return rule.count * n * cfg.exceed_count

    def _reproject(self) -> None:
        """Rebuild the decision client's engine rules from cluster rules."""
        with self._lock:
            flow = []
            thresholds: Dict[int, float] = {}
            for fid in self.flow_rules.all_ids():
                rule = self.flow_rules.get_by_id(fid)
                if rule is None:
                    continue  # unloaded between snapshot and lookup
                ns = self.flow_rules.namespace_of(fid) or C.DEFAULT_NAMESPACE
                thr = self._global_threshold(rule, ns)
                thresholds[fid] = thr
                flow.append(
                    R.FlowRule(
                        resource=flow_resource(fid),
                        count=thr,
                        grade=R.GRADE_QPS,
                    )
                )
            param = []
            for fid in self.param_rules.all_ids():
                rule = self.param_rules.get_by_id(fid)
                if rule is None:
                    continue
                param.append(
                    R.ParamFlowRule(
                        resource=param_resource(fid),
                        count=rule.count,
                        grade=rule.grade,
                        param_idx=0,  # client sends extracted values
                        duration_in_sec=rule.duration_in_sec,
                        param_flow_item_list=rule.param_flow_item_list,
                    )
                )
            self.client.flow_rules.load(flow)
            self.client.param_flow_rules.load(param)
            if self.col is not None:
                self.col.project(thresholds)

    def refresh_connected_count(self) -> None:
        """Call when the connection census changes.  Only AVG_LOCAL rules
        scale with the census — with purely GLOBAL rules this is a no-op,
        so a churning client fleet doesn't trigger recompiles."""
        has_avg_local = any(
            r is not None and r.cluster_threshold_type != C.FLOW_THRESHOLD_GLOBAL
            for r in (
                self.flow_rules.get_by_id(fid) for fid in self.flow_rules.all_ids()
            )
        )
        if has_avg_local:
            self._reproject()

    # -- TokenService --------------------------------------------------------

    def request_token(self, flow_id: int, count: int = 1, prioritized: bool = False) -> TokenResult:
        """Blocking token grant — delegates to the async path so the guards
        and verdict mapping live in exactly one place."""
        try:
            return self.request_token_async(flow_id, count, prioritized).result(
                timeout=self.client.entry_timeout_s
            )
        except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
            return TokenResult(C.STATUS_FAIL)

    def request_token_async(self, flow_id: int, count: int = 1, prioritized: bool = False):
        """Non-blocking request_token: returns a concurrent Future of
        TokenResult (or a completed result for no-rule / namespace-guard
        outcomes).  Lets the TCP server keep thousands of token requests
        in flight with no thread per request — they coalesce into the
        decision engine's micro-batches (the TPU-native shape)."""
        from concurrent.futures import Future as _F

        FP.hit(_FP_DECIDE)
        done = _F()
        rule = self.flow_rules.get_by_id(flow_id)
        if rule is None:
            done.set_result(TokenResult(C.STATUS_NO_RULE))
            return done
        ns = self.flow_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        if not self.limiter.try_pass(ns, self.client.time.now_ms()):
            _C_SHED.inc()
            done.set_result(TokenResult(C.STATUS_TOO_MANY_REQUEST))
            return done
        if self.col is not None:
            if self.col.pending_entries() > 4 * TokenColumnBatcher.CAPACITY:
                _C_SHED.inc()
                done.set_result(TokenResult(C.STATUS_TOO_MANY_REQUEST))
                return done
            if count <= 0:  # zero-unit ask: nothing to debit
                _C_DECISIONS.inc()
                done.set_result(TokenResult(C.STATUS_OK))
                return done
            _span = OT.TRACER.begin("token.decision", flow_id=flow_id)
            cf = self.col.submit(flow_id, count, partial=False)

            def _chain_col(fut):
                _C_DECISIONS.inc()
                if _span is not None:
                    OT.stage_ns(
                        "token.decision",
                        _span.t0_ns,
                        OT.now_ns() - _span.t0_ns,
                        _H_DECISION,
                        trace=_span.trace,
                        attrs=_span.attrs,
                    )
                try:
                    granted, observed, limit = fut.result()
                except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
                    done.set_result(TokenResult(C.STATUS_FAIL))
                    return
                if granted >= count:
                    done.set_result(TokenResult(C.STATUS_OK))
                    return
                if not prioritized:
                    done.set_result(
                        TokenResult(
                            C.STATUS_BLOCKED,
                            prov_kind=ERR.BLOCK_FLOW,
                            prov_rule=flow_id,
                            prov_observed=observed,
                            prov_limit=limit,
                        )
                    )
                    return
                # occupy-ahead emulation: charge the ask unconditionally
                # (debits the CURRENT bucket — one earlier than the
                # engine's tryOccupyNext, the conservative direction) and
                # tell the caller to sleep into the next bucket
                f2 = self.col.submit(flow_id, count, partial=False, forced=True)

                def _chain_occ(fut2):
                    try:
                        fut2.result()
                    except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
                        done.set_result(TokenResult(C.STATUS_FAIL))
                        return
                    wait = self.col.ms_to_next_bucket(
                        int(self.client.time.now_ms())
                    )
                    done.set_result(
                        TokenResult(C.STATUS_SHOULD_WAIT, wait_ms=wait)
                    )

                f2.add_done_callback(_chain_occ)

            cf.add_done_callback(_chain_col)
            return done
        # backpressure: with the thread-free TCP path nothing else bounds
        # in-flight requests, so shed load once the acquire queue exceeds a
        # few engine batches (the reference's namespace guard plays this
        # role only when configured tightly)
        if self.client.pending_acquires() > 4 * self.client.cfg.batch_size:
            _C_SHED.inc()
            done.set_result(TokenResult(C.STATUS_TOO_MANY_REQUEST))
            return done
        f = self.client.submit_acquire(
            flow_resource(flow_id), count=count, prioritized=prioritized
        )
        if f is None:
            _C_DECISIONS.inc()  # fast-path verdict is still a served decision
            done.set_result(TokenResult(C.STATUS_OK))
            return done
        # cross-thread span: begun here (adopting the wire trace context
        # the TCP server installed, if any), ended on the resolver/tick
        # thread that fires the engine future — the handle carries the
        # trace id and the caller's span id (attrs["parent"]) across
        _span = OT.TRACER.begin("token.decision", flow_id=flow_id)

        def _chain(fut):
            _C_DECISIONS.inc()
            if _span is not None:
                OT.stage_ns(
                    "token.decision",
                    _span.t0_ns,
                    OT.now_ns() - _span.t0_ns,
                    _H_DECISION,
                    trace=_span.trace,
                    attrs=_span.attrs,
                )
            try:
                verdict, wait_ms = fut.result()
            except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
                done.set_result(TokenResult(C.STATUS_FAIL))
                return
            if verdict == ERR.PASS:
                done.set_result(TokenResult(C.STATUS_OK))
            elif verdict == ERR.PASS_WAIT:
                done.set_result(TokenResult(C.STATUS_SHOULD_WAIT, wait_ms=wait_ms))
            else:
                # engine path: the verdict code names the kind; observed/
                # limit stay unknown (the tick already consumed them)
                done.set_result(
                    TokenResult(
                        C.STATUS_BLOCKED,
                        prov_kind=int(verdict),
                        prov_rule=flow_id,
                    )
                )

        f.add_done_callback(_chain)
        return done

    def request_token_batch(self, flow_id: int, units: int) -> TokenResult:
        """Partial grant: `units` unit-acquires coalesce into one engine
        micro-batch; granted = how many passed (within-tick prefix-sum
        admission makes this bit-exact with sequential acquisition)."""
        FP.hit(_FP_DECIDE)
        rule = self.flow_rules.get_by_id(flow_id)
        if rule is None:
            return TokenResult(C.STATUS_NO_RULE)
        if units <= 0:
            return TokenResult(C.STATUS_BAD_REQUEST)
        ns = self.flow_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        if not self.limiter.try_pass(ns, self.client.time.now_ms()):
            _C_SHED.inc()
            return TokenResult(C.STATUS_TOO_MANY_REQUEST)
        if self.col is not None:
            with OT.TRACER.span("token.decision_batch", flow_id=flow_id, units=units):
                try:
                    granted, observed, limit = self.col.submit(
                        flow_id, units, partial=True
                    ).result(timeout=self.client.entry_timeout_s)
                    granted = int(granted)
                except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
                    return TokenResult(C.STATUS_FAIL)
            _C_DECISIONS.inc(units)
            if granted == 0:
                return TokenResult(
                    C.STATUS_BLOCKED,
                    remaining=0,
                    prov_kind=ERR.BLOCK_FLOW,
                    prov_rule=flow_id,
                    prov_observed=observed,
                    prov_limit=limit,
                )
            return TokenResult(C.STATUS_OK, remaining=granted)
        with OT.TRACER.span("token.decision_batch", flow_id=flow_id, units=units):
            results = self.client.check_batch([flow_resource(flow_id)] * units)
        _C_DECISIONS.inc(units)
        granted = sum(1 for v, _ in results if v in (ERR.PASS, ERR.PASS_WAIT))
        wait = max((w for v, w in results if v == ERR.PASS_WAIT), default=0)
        if granted == 0:
            return TokenResult(
                C.STATUS_BLOCKED,
                remaining=0,
                prov_kind=ERR.BLOCK_FLOW,
                prov_rule=flow_id,
            )
        return TokenResult(C.STATUS_OK, remaining=granted, wait_ms=wait)

    def request_param_token(self, flow_id: int, count: int, params: List[Any]) -> TokenResult:
        FP.hit(_FP_DECIDE)
        rule = self.param_rules.get_by_id(flow_id)
        if rule is None:
            return TokenResult(C.STATUS_NO_RULE)
        if not params:
            return TokenResult(C.STATUS_BAD_REQUEST)
        ns = self.param_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        if not self.limiter.try_pass(ns, self.client.time.now_ms()):
            _C_SHED.inc()
            return TokenResult(C.STATUS_TOO_MANY_REQUEST)
        name = param_resource(flow_id)
        with OT.TRACER.span("token.decision_param", flow_id=flow_id):
            results = self.client.check_batch(
                [name] * len(params),
                counts=[count] * len(params),
                params=list(params),
            )
        _C_DECISIONS.inc(len(params))
        if all(v == ERR.PASS for v, _ in results):
            return TokenResult(C.STATUS_OK)
        return TokenResult(
            C.STATUS_BLOCKED, prov_kind=ERR.BLOCK_PARAM, prov_rule=flow_id
        )

    # request_lease: the TokenService base implementation already rides
    # request_token_batch with the MAX_LEASE_UNITS clamp and honors this
    # instance's lease_ttl_ms — no override needed

    def request_concurrent_token(self, flow_id: int, count: int = 1) -> TokenResult:
        rule = self.flow_rules.get_by_id(flow_id)
        if rule is None:
            return TokenResult(C.STATUS_NO_RULE)
        ns = self.flow_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        limit = self._global_threshold(rule, ns)
        tid = self.concurrent.acquire(
            flow_id, count, limit, self.client.time.now_ms()
        )
        if tid is None:
            return TokenResult(
                C.STATUS_BLOCKED,
                prov_kind=ERR.BLOCK_FLOW,
                prov_rule=flow_id,
                prov_limit=limit,
            )
        return TokenResult(C.STATUS_OK, token_id=tid)

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        ok = self.concurrent.release(token_id)
        return TokenResult(C.STATUS_RELEASE_OK if ok else C.STATUS_ALREADY_RELEASE)

    # -- protocol v2 BATCH frames -------------------------------------------

    def decide_frame(
        self, kinds, ids, counts, flags
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, list]:
        """Answer one protocol-v2 BATCH frame's entry columns.

        Host-side guards (rule lookup, namespace limiter, validation) run
        per entry; every surviving entry joins ONE column submission, so a
        frame carrying a hundred flows costs one device decision.  Entry
        kinds map onto the existing verdict surface:

          BATCH_KIND_FLOW        all-or-nothing → OK / BLOCKED
          BATCH_KIND_FLOW_BATCH  partial grant  → OK(remaining=granted) / BLOCKED
          BATCH_KIND_LEASE       MAX_LEASE_UNITS-clamped partial grant;
                                 wait_ms carries the lease TTL

        The prioritized flag has no occupy-ahead on the column path: an
        over-limit prioritized entry is BLOCKED (fail closed), never
        SHOULD_WAIT.  Returns (statuses i8, remainings i32, waits i32,
        token_ids i64, prov) aligned with the request entries; ``prov[i]``
        is ``(kind, rule, observed|None, limit|None)`` on BLOCKED entries
        whose cause is known, else None — the server ships it back only
        when the client set BATCH_FLAG_EXPLAIN (protocol v3 _T_PROV).
        """
        n = len(kinds)
        # seed FAIL, not OK: any entry a bug leaves untouched must read as
        # a failure the client degrades on, never as a grant
        statuses = np.full(n, C.STATUS_FAIL, np.int8)
        remainings = np.zeros(n, np.int32)
        waits = np.zeros(n, np.int32)
        token_ids = np.zeros(n, np.int64)
        prov: List[Optional[Tuple[int, int, Optional[float], Optional[float]]]] = [
            None
        ] * n
        if self.col is None:
            for i in range(n):
                kind, fid, cnt = int(kinds[i]), int(ids[i]), int(counts[i])
                prio = bool(int(flags[i]) & C.BATCH_FLAG_PRIORITIZED)
                if kind == C.BATCH_KIND_FLOW:
                    r = self.request_token(fid, cnt, prio)
                elif kind == C.BATCH_KIND_FLOW_BATCH:
                    r = self.request_token_batch(fid, cnt)
                elif kind == C.BATCH_KIND_LEASE:
                    r = self.request_lease(fid, cnt)
                else:
                    r = TokenResult(C.STATUS_BAD_REQUEST)
                statuses[i] = r.status
                remainings[i] = r.remaining
                waits[i] = r.wait_ms
                token_ids[i] = r.token_id
                if r.prov_kind is not None:
                    prov[i] = (
                        r.prov_kind,
                        r.prov_rule if r.prov_rule is not None else fid,
                        r.prov_observed,
                        r.prov_limit,
                    )
            return statuses, remainings, waits, token_ids, prov
        now = self.client.time.now_ms()
        futs: List[Future] = []
        meta: List[Tuple[int, int, int]] = []
        for i in range(n):
            FP.hit(_FP_DECIDE)
            kind, fid, cnt = int(kinds[i]), int(ids[i]), int(counts[i])
            if kind not in (
                C.BATCH_KIND_FLOW,
                C.BATCH_KIND_FLOW_BATCH,
                C.BATCH_KIND_LEASE,
            ):
                statuses[i] = C.STATUS_BAD_REQUEST
                continue
            rule = self.flow_rules.get_by_id(fid)
            if rule is None:
                statuses[i] = C.STATUS_NO_RULE
                continue
            if cnt <= 0:
                # a zero-unit all-or-nothing ask requests nothing and
                # passes; a zero/negative batch or lease ask is malformed
                statuses[i] = (
                    C.STATUS_OK
                    if kind == C.BATCH_KIND_FLOW and cnt == 0
                    else C.STATUS_BAD_REQUEST
                )
                continue
            ns = self.flow_rules.namespace_of(fid) or C.DEFAULT_NAMESPACE
            if not self.limiter.try_pass(ns, now):
                _C_SHED.inc()
                statuses[i] = C.STATUS_TOO_MANY_REQUEST
                continue
            units = min(cnt, C.MAX_LEASE_UNITS) if kind == C.BATCH_KIND_LEASE else cnt
            futs.append(
                self.col.submit(fid, units, partial=kind != C.BATCH_KIND_FLOW)
            )
            meta.append((i, kind, units, fid))
        timeout = self.client.entry_timeout_s
        for f, (i, kind, units, fid) in zip(futs, meta):
            try:
                granted, observed, limit = f.result(timeout=timeout)
                granted = int(granted)
            except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
                statuses[i] = C.STATUS_FAIL
                continue
            _C_DECISIONS.inc(1 if kind == C.BATCH_KIND_FLOW else units)
            blocked = (
                granted < units if kind == C.BATCH_KIND_FLOW else granted == 0
            )
            if blocked:
                statuses[i] = C.STATUS_BLOCKED
                prov[i] = (ERR.BLOCK_FLOW, fid, observed, limit)
            else:
                statuses[i] = C.STATUS_OK
                if kind != C.BATCH_KIND_FLOW:
                    remainings[i] = granted
                    if kind == C.BATCH_KIND_LEASE:
                        waits[i] = self.lease_ttl_ms
        return statuses, remainings, waits, token_ids, prov
