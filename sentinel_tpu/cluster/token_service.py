"""Cluster token decision service.

The reference's token server answers requestToken(flowId, count, priority)
with a verdict from a per-rule ClusterMetric sliding window
(DefaultTokenService.java:34-44 → ClusterFlowChecker.acquireClusterToken:55-88).

TPU inversion: each cluster flowId is interned as a resource
(``$cluster/flow/<id>``) on a dedicated decision ``SentinelClient``, so token
verdicts ride the same batched device engine as local rules — concurrent
requests from many connections coalesce into one micro-batch tick.  The
global threshold
``count × (1 if thresholdType==GLOBAL else connectedCount) × exceedCount``
(ClusterFlowChecker.java:38,68) is recomputed and pushed to the engine
whenever rules or the connection census change.

Host-side pieces (naturally request-scoped, not tensor-shaped):
  * GlobalRequestLimiter — per-namespace QPS guard
    (GlobalRequestLimiter.java:28, RequestLimiter.java:29-39)
  * ConcurrentTokenManager — cluster-wide concurrency tokens with TTL expiry
    (ConcurrentClusterFlowChecker.java:34-81, CurrentConcurrencyManager,
    TokenCacheNodeManager, RegularExpireStrategy)
"""

from __future__ import annotations

import itertools
import threading
import time as _time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster.rules import (
    ClusterFlowRuleManager,
    ClusterParamFlowRuleManager,
    ClusterServerConfigManager,
    flow_resource,
    param_resource,
)
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core import rules as R
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.host_window import HostWindow

_H_DECISION = _OBS.histogram(
    "sentinel_token_decision_ms",
    "engine-backed token decision latency (request to verdict)",
)
_C_DECISIONS = _OBS.counter(
    "sentinel_token_decisions_total", "token verdicts served by this process"
)
_C_SHED = _OBS.counter(
    "sentinel_token_shed_total",
    "token requests shed before the engine (namespace guard or backpressure)",
)

#: chaos failpoint on the decision path: a raise here exercises every
#: caller's STATUS_FAIL conversion (request_token's catch, the TCP
#: server's _flow_and_reply/_process catches) — degrade, never PASS
_FP_DECIDE = FP.register(
    "cluster.token.decide", "token service decision entry", FP.HIT_ACTIONS
)


#: engine stages the cluster token decision path exercises: flow checks
#: (with occupy-ahead for prioritized SHOULD_WAIT grants) and hot-param
#: token checks.  The decision client's resources are interned flowIds —
#: no ctx/origin node fan-out, no circuit breakers, no authority/system
#: rules ever bind to them, so a dedicated decision engine compiled with
#: exactly this set serves token verdicts with the minimal tick.  The
#: jaxpr analyzer (sentinel_tpu/analysis/jaxpr) traces `ops.engine.tick`
#: under this feature set as its `tick/cluster-token` entry point, so
#: CI pins the compiled token-decision program alongside the local ones.
DECISION_FEATURES = frozenset({"flow", "occupy", "param"})


@dataclass
class TokenResult:
    status: int
    remaining: int = 0
    wait_ms: int = 0
    token_id: int = 0

    @property
    def ok(self) -> bool:
        return self.status == C.STATUS_OK

    @property
    def blocked(self) -> bool:
        return self.status == C.STATUS_BLOCKED


class TokenService:
    """Abstract token service (cluster/TokenService.java:26-62)."""

    #: lease validity window granted to holders; implementations with a
    #: configured TTL (``DefaultTokenService``) shadow this per instance
    lease_ttl_ms: int = C.DEFAULT_LEASE_TTL_MS

    def request_token(self, flow_id: int, count: int = 1, prioritized: bool = False) -> TokenResult:
        raise NotImplementedError

    def request_token_batch(self, flow_id: int, units: int) -> TokenResult:
        """Partial-grant acquire: ask for ``units`` single tokens, receive
        granted k in ``remaining`` (0..units).  Default maps onto the
        all-or-nothing request_token for foreign implementations."""
        r = self.request_token(flow_id, units, False)
        if r.status == C.STATUS_OK:
            return TokenResult(C.STATUS_OK, remaining=units, wait_ms=r.wait_ms)
        if r.status == C.STATUS_BLOCKED:
            return TokenResult(C.STATUS_BLOCKED, remaining=0)
        return r

    def request_param_token(self, flow_id: int, count: int, params: List[Any]) -> TokenResult:
        raise NotImplementedError

    def request_concurrent_token(self, flow_id: int, count: int = 1) -> TokenResult:
        raise NotImplementedError

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        raise NotImplementedError

    def request_lease(self, flow_id: int, units: int) -> TokenResult:
        """Bounded-slack budget lease (cluster/shard.py): grant up to
        ``units`` tokens spendable by the holder for one validity window
        (``remaining`` = granted k, ``wait_ms`` = window ms).  The grant
        rides the partial-grant batch acquire — debited from the SAME
        global budget as ordinary tokens, which is what makes the
        holder's offline spending conserve it — so any TokenService can
        serve as a lease source.  Units clamp to ``MAX_LEASE_UNITS``
        here, for EVERY implementation: a hostile/miscalibrated request
        must not stall the decision backend."""
        r = self.request_token_batch(flow_id, min(units, C.MAX_LEASE_UNITS))
        if r.status == C.STATUS_OK:
            return TokenResult(
                C.STATUS_OK, remaining=r.remaining, wait_ms=self.lease_ttl_ms
            )
        return r


class GlobalRequestLimiter:
    """Per-namespace request-QPS guard in front of the decision engine."""

    def __init__(self, config: ClusterServerConfigManager):
        self._config = config
        self._windows: Dict[str, HostWindow] = {}
        self._lock = threading.Lock()

    def _window(self, namespace: str, cfg) -> HostWindow:
        # a pushed config is unvalidated: round interval up to a multiple of
        # sample_count instead of letting HostWindow's divisibility assert
        # fire on the request hot path
        sample_count = max(int(cfg.sample_count), 1)
        interval_ms = max(int(cfg.interval_ms), sample_count)
        interval_ms = ((interval_ms + sample_count - 1) // sample_count) * sample_count
        w = self._windows.get(namespace)
        if w is None or (w.sample_count, w.interval_ms) != (sample_count, interval_ms):
            # (re)build to the configured shape; a config push that reshapes
            # the window restarts its accounting, like the reference's
            # per-namespace RequestLimiter re-creation
            with self._lock:
                w = self._windows.get(namespace)
                if w is None or (w.sample_count, w.interval_ms) != (
                    sample_count,
                    interval_ms,
                ):
                    w = HostWindow(sample_count, interval_ms)
                    self._windows[namespace] = w
        return w

    def try_pass(self, namespace: str, now_ms: int) -> bool:
        cfg = self._config.flow_config(namespace)
        return self._window(namespace, cfg).try_pass(now_ms, cfg.max_allowed_qps)

    def current_qps(self, namespace: str, now_ms: int) -> float:
        w = self._windows.get(namespace)
        return w.qps(now_ms) if w else 0.0


class ConcurrentTokenManager:
    """Cluster-wide concurrency tokens with TTL expiry."""

    def __init__(self, ttl_ms: int = 5000):
        self.ttl_ms = ttl_ms
        self._lock = threading.Lock()
        self._current: Dict[int, int] = {}  # flowId -> concurrency in flight
        self._tokens: Dict[int, tuple] = {}  # tokenId -> (flowId, count, deadline)
        self._ids = itertools.count(1)

    def acquire(self, flow_id: int, count: int, limit: float, now_ms: int) -> Optional[int]:
        with self._lock:
            cur = self._current.get(flow_id, 0)
            if cur + count > limit:
                return None
            self._current[flow_id] = cur + count
            tid = next(self._ids)
            self._tokens[tid] = (flow_id, count, now_ms + self.ttl_ms)
            return tid

    def release(self, token_id: int) -> bool:
        with self._lock:
            node = self._tokens.pop(token_id, None)
            if node is None:
                return False
            fid, count, _ = node
            self._current[fid] = max(self._current.get(fid, 0) - count, 0)
            return True

    def current(self, flow_id: int) -> int:
        return self._current.get(flow_id, 0)

    def expire(self, now_ms: int) -> int:
        """Drop expired tokens (RegularExpireStrategy sweep). Returns count."""
        with self._lock:
            dead = [tid for tid, (_, _, dl) in self._tokens.items() if dl <= now_ms]
            for tid in dead:
                fid, count, _ = self._tokens.pop(tid)
                self._current[fid] = max(self._current.get(fid, 0) - count, 0)
            return len(dead)


class DefaultTokenService(TokenService):
    """Engine-backed token service.

    ``decision_client`` is a dedicated SentinelClient whose resources are the
    cluster flowIds.  ``connected_count_fn(namespace) -> int`` feeds the
    AVG_LOCAL threshold scaling; the server wires it to its ConnectionManager
    (ConnectionGroup.getConnectedCount), standalone/embedded default is 1.

    Prioritized requests that exceed the current bucket borrow from the next
    one (engine occupy-ahead, DefaultController.tryOccupyNext) and surface as
    STATUS_SHOULD_WAIT with the wait until that bucket starts — the client
    sleeps and enters, matching TokenResultStatus.SHOULD_WAIT semantics.
    """

    def __init__(
        self,
        decision_client,
        config: Optional[ClusterServerConfigManager] = None,
        connected_count_fn: Optional[Callable[[str], int]] = None,
        concurrent_ttl_ms: int = 5000,
        lease_ttl_ms: int = C.DEFAULT_LEASE_TTL_MS,
    ):
        self.client = decision_client
        self.lease_ttl_ms = lease_ttl_ms
        self.config = config or ClusterServerConfigManager()
        self.connected_count_fn = connected_count_fn or (lambda ns: 1)
        self.flow_rules = ClusterFlowRuleManager(on_change=self._reproject)
        self.param_rules = ClusterParamFlowRuleManager(on_change=self._reproject)
        self.limiter = GlobalRequestLimiter(self.config)
        self.concurrent = ConcurrentTokenManager(ttl_ms=concurrent_ttl_ms)
        self.config.add_listener(self._reproject)
        self._lock = threading.Lock()

    # -- projection onto the engine ----------------------------------------

    def _global_threshold(self, rule: R.FlowRule, namespace: str) -> float:
        cfg = self.config.flow_config(namespace)
        n = (
            1
            if rule.cluster_threshold_type == C.FLOW_THRESHOLD_GLOBAL
            else max(self.connected_count_fn(namespace), 1)
        )
        return rule.count * n * cfg.exceed_count

    def _reproject(self) -> None:
        """Rebuild the decision client's engine rules from cluster rules."""
        with self._lock:
            flow = []
            for fid in self.flow_rules.all_ids():
                rule = self.flow_rules.get_by_id(fid)
                if rule is None:
                    continue  # unloaded between snapshot and lookup
                ns = self.flow_rules.namespace_of(fid) or C.DEFAULT_NAMESPACE
                flow.append(
                    R.FlowRule(
                        resource=flow_resource(fid),
                        count=self._global_threshold(rule, ns),
                        grade=R.GRADE_QPS,
                    )
                )
            param = []
            for fid in self.param_rules.all_ids():
                rule = self.param_rules.get_by_id(fid)
                if rule is None:
                    continue
                param.append(
                    R.ParamFlowRule(
                        resource=param_resource(fid),
                        count=rule.count,
                        grade=rule.grade,
                        param_idx=0,  # client sends extracted values
                        duration_in_sec=rule.duration_in_sec,
                        param_flow_item_list=rule.param_flow_item_list,
                    )
                )
            self.client.flow_rules.load(flow)
            self.client.param_flow_rules.load(param)

    def refresh_connected_count(self) -> None:
        """Call when the connection census changes.  Only AVG_LOCAL rules
        scale with the census — with purely GLOBAL rules this is a no-op,
        so a churning client fleet doesn't trigger recompiles."""
        has_avg_local = any(
            r is not None and r.cluster_threshold_type != C.FLOW_THRESHOLD_GLOBAL
            for r in (
                self.flow_rules.get_by_id(fid) for fid in self.flow_rules.all_ids()
            )
        )
        if has_avg_local:
            self._reproject()

    # -- TokenService --------------------------------------------------------

    def request_token(self, flow_id: int, count: int = 1, prioritized: bool = False) -> TokenResult:
        """Blocking token grant — delegates to the async path so the guards
        and verdict mapping live in exactly one place."""
        try:
            return self.request_token_async(flow_id, count, prioritized).result(
                timeout=self.client.entry_timeout_s
            )
        except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
            return TokenResult(C.STATUS_FAIL)

    def request_token_async(self, flow_id: int, count: int = 1, prioritized: bool = False):
        """Non-blocking request_token: returns a concurrent Future of
        TokenResult (or a completed result for no-rule / namespace-guard
        outcomes).  Lets the TCP server keep thousands of token requests
        in flight with no thread per request — they coalesce into the
        decision engine's micro-batches (the TPU-native shape)."""
        from concurrent.futures import Future as _F

        FP.hit(_FP_DECIDE)
        done = _F()
        rule = self.flow_rules.get_by_id(flow_id)
        if rule is None:
            done.set_result(TokenResult(C.STATUS_NO_RULE))
            return done
        ns = self.flow_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        if not self.limiter.try_pass(ns, self.client.time.now_ms()):
            _C_SHED.inc()
            done.set_result(TokenResult(C.STATUS_TOO_MANY_REQUEST))
            return done
        # backpressure: with the thread-free TCP path nothing else bounds
        # in-flight requests, so shed load once the acquire queue exceeds a
        # few engine batches (the reference's namespace guard plays this
        # role only when configured tightly)
        if self.client.pending_acquires() > 4 * self.client.cfg.batch_size:
            _C_SHED.inc()
            done.set_result(TokenResult(C.STATUS_TOO_MANY_REQUEST))
            return done
        f = self.client.submit_acquire(
            flow_resource(flow_id), count=count, prioritized=prioritized
        )
        if f is None:
            _C_DECISIONS.inc()  # fast-path verdict is still a served decision
            done.set_result(TokenResult(C.STATUS_OK))
            return done
        # cross-thread span: begun here (adopting the wire trace context
        # the TCP server installed, if any), ended on the resolver/tick
        # thread that fires the engine future — the handle carries the
        # trace id and the caller's span id (attrs["parent"]) across
        _span = OT.TRACER.begin("token.decision", flow_id=flow_id)

        def _chain(fut):
            _C_DECISIONS.inc()
            if _span is not None:
                OT.stage_ns(
                    "token.decision",
                    _span.t0_ns,
                    OT.now_ns() - _span.t0_ns,
                    _H_DECISION,
                    trace=_span.trace,
                    attrs=_span.attrs,
                )
            try:
                verdict, wait_ms = fut.result()
            except Exception:  # stlint: disable=fail-open — STATUS_FAIL makes the caller degrade to local enforcement, never PASS
                done.set_result(TokenResult(C.STATUS_FAIL))
                return
            if verdict == ERR.PASS:
                done.set_result(TokenResult(C.STATUS_OK))
            elif verdict == ERR.PASS_WAIT:
                done.set_result(TokenResult(C.STATUS_SHOULD_WAIT, wait_ms=wait_ms))
            else:
                done.set_result(TokenResult(C.STATUS_BLOCKED))

        f.add_done_callback(_chain)
        return done

    def request_token_batch(self, flow_id: int, units: int) -> TokenResult:
        """Partial grant: `units` unit-acquires coalesce into one engine
        micro-batch; granted = how many passed (within-tick prefix-sum
        admission makes this bit-exact with sequential acquisition)."""
        FP.hit(_FP_DECIDE)
        rule = self.flow_rules.get_by_id(flow_id)
        if rule is None:
            return TokenResult(C.STATUS_NO_RULE)
        if units <= 0:
            return TokenResult(C.STATUS_BAD_REQUEST)
        ns = self.flow_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        if not self.limiter.try_pass(ns, self.client.time.now_ms()):
            _C_SHED.inc()
            return TokenResult(C.STATUS_TOO_MANY_REQUEST)
        with OT.TRACER.span("token.decision_batch", flow_id=flow_id, units=units):
            results = self.client.check_batch([flow_resource(flow_id)] * units)
        _C_DECISIONS.inc(units)
        granted = sum(1 for v, _ in results if v in (ERR.PASS, ERR.PASS_WAIT))
        wait = max((w for v, w in results if v == ERR.PASS_WAIT), default=0)
        if granted == 0:
            return TokenResult(C.STATUS_BLOCKED, remaining=0)
        return TokenResult(C.STATUS_OK, remaining=granted, wait_ms=wait)

    def request_param_token(self, flow_id: int, count: int, params: List[Any]) -> TokenResult:
        FP.hit(_FP_DECIDE)
        rule = self.param_rules.get_by_id(flow_id)
        if rule is None:
            return TokenResult(C.STATUS_NO_RULE)
        if not params:
            return TokenResult(C.STATUS_BAD_REQUEST)
        ns = self.param_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        if not self.limiter.try_pass(ns, self.client.time.now_ms()):
            _C_SHED.inc()
            return TokenResult(C.STATUS_TOO_MANY_REQUEST)
        name = param_resource(flow_id)
        with OT.TRACER.span("token.decision_param", flow_id=flow_id):
            results = self.client.check_batch(
                [name] * len(params),
                counts=[count] * len(params),
                params=list(params),
            )
        _C_DECISIONS.inc(len(params))
        if all(v == ERR.PASS for v, _ in results):
            return TokenResult(C.STATUS_OK)
        return TokenResult(C.STATUS_BLOCKED)

    # request_lease: the TokenService base implementation already rides
    # request_token_batch with the MAX_LEASE_UNITS clamp and honors this
    # instance's lease_ttl_ms — no override needed

    def request_concurrent_token(self, flow_id: int, count: int = 1) -> TokenResult:
        rule = self.flow_rules.get_by_id(flow_id)
        if rule is None:
            return TokenResult(C.STATUS_NO_RULE)
        ns = self.flow_rules.namespace_of(flow_id) or C.DEFAULT_NAMESPACE
        limit = self._global_threshold(rule, ns)
        tid = self.concurrent.acquire(
            flow_id, count, limit, self.client.time.now_ms()
        )
        if tid is None:
            return TokenResult(C.STATUS_BLOCKED)
        return TokenResult(C.STATUS_OK, token_id=tid)

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        ok = self.concurrent.release(token_id)
        return TokenResult(C.STATUS_RELEASE_OK if ok else C.STATUS_ALREADY_RELEASE)
