"""Cluster rule + config managers.

The reference keys cluster rules by a **global flowId** across namespaces
(ClusterFlowRuleManager.java:63-76, getFlowRuleById:202); the token server
loads per-namespace rule sets and answers requestToken(flowId, …).  Here the
managers also *project* cluster rules onto the decision engine: every
flowId becomes an interned resource name on the token-server's
SentinelClient, with an engine FlowRule/ParamFlowRule whose threshold is the
computed global threshold.

Config managers mirror ServerFlowConfig / ClusterServerConfigManager /
ClusterClientConfigManager (server namespaces + transport knobs; client
server-address assignment + request timeout), all push-updatable via
SentinelProperty (SURVEY.md §3.4).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.core import rules as R


def flow_resource(flow_id: int) -> str:
    """Engine resource name backing a cluster flow rule."""
    return f"$cluster/flow/{flow_id}"


def param_resource(flow_id: int) -> str:
    return f"$cluster/param/{flow_id}"


@dataclass
class ServerFlowConfig:
    """Per-namespace server-side flow config (ServerFlowConfig.java:26-40)."""

    exceed_count: float = C.DEFAULT_EXCEED_COUNT
    max_occupy_ratio: float = C.DEFAULT_MAX_OCCUPY_RATIO
    interval_ms: int = C.DEFAULT_INTERVAL_MS
    sample_count: int = C.DEFAULT_SAMPLE_COUNT
    max_allowed_qps: float = C.DEFAULT_MAX_ALLOWED_QPS


@dataclass
class ServerTransportConfig:
    """ClusterServerConfigManager's transport slice."""

    port: int = C.DEFAULT_PORT
    idle_seconds: int = C.DEFAULT_IDLE_SECONDS


class ClusterServerConfigManager:
    def __init__(self):
        self._lock = threading.Lock()
        self.transport = ServerTransportConfig()
        self._namespaces: set = {C.DEFAULT_NAMESPACE}
        self._flow_configs: Dict[str, ServerFlowConfig] = {}
        self._listeners: List[Callable[[], None]] = []

    def namespaces(self) -> List[str]:
        return sorted(self._namespaces)

    def set_namespaces(self, namespaces) -> None:
        with self._lock:
            self._namespaces = set(namespaces) or {C.DEFAULT_NAMESPACE}
        self._notify()

    def flow_config(self, namespace: str) -> ServerFlowConfig:
        return self._flow_configs.get(namespace) or self._flow_configs.setdefault(
            "__global__", ServerFlowConfig()
        )

    def set_flow_config(self, namespace: str, cfg: ServerFlowConfig) -> None:
        with self._lock:
            self._flow_configs[namespace] = cfg
        self._notify()

    def add_listener(self, fn: Callable[[], None]) -> None:
        self._listeners.append(fn)

    def _notify(self) -> None:
        for fn in list(self._listeners):
            fn()


@dataclass
class ClusterClientAssignConfig:
    """Token-server address assignment (ClusterClientAssignConfig.java)."""

    host: str = ""
    port: int = C.DEFAULT_PORT


class ClusterClientConfigManager:
    def __init__(self):
        self.assign = ClusterClientAssignConfig()
        self.request_timeout_ms: int = C.DEFAULT_REQUEST_TIMEOUT_MS
        self._listeners: List[Callable[[], None]] = []

    def apply_assign(self, host: str, port: int) -> None:
        self.assign = ClusterClientAssignConfig(host=host, port=port)
        for fn in list(self._listeners):
            fn()

    def add_listener(self, fn: Callable[[], None]) -> None:
        self._listeners.append(fn)


class ClusterFlowRuleManager:
    """flowId → FlowRule, grouped by namespace.

    ``load(namespace, rules)`` replaces a namespace's rule set
    (registerPropertyIfAbsent/applyClusterFlowRule analog); rules must carry
    ``cluster_flow_id`` and have ``cluster_mode=True``.
    """

    def __init__(self, on_change: Optional[Callable[[], None]] = None):
        self._lock = threading.Lock()
        self._by_ns: Dict[str, List[R.FlowRule]] = {}
        self._by_id: Dict[int, R.FlowRule] = {}
        self._ns_by_id: Dict[int, str] = {}
        self._on_change = on_change
        self._listeners: List[Callable[[], None]] = []

    def add_listener(self, fn: Callable[[], None]) -> None:
        """Fires after every load, AFTER the primary on_change (so engine
        rule projection runs first and listeners see compiled state)."""
        self._listeners.append(fn)

    def load(self, namespace: str, rules: List[R.FlowRule]) -> None:
        rules = [r for r in rules if r.cluster_mode and r.cluster_flow_id > 0]
        with self._lock:
            old = self._by_ns.get(namespace, [])
            for r in old:
                # only drop ids this namespace still owns — a flow id that
                # was re-registered by another namespace stays live
                if self._ns_by_id.get(r.cluster_flow_id) == namespace:
                    self._by_id.pop(r.cluster_flow_id, None)
                    self._ns_by_id.pop(r.cluster_flow_id, None)
            self._by_ns[namespace] = rules
            for r in rules:
                self._by_id[r.cluster_flow_id] = r
                self._ns_by_id[r.cluster_flow_id] = namespace
        if self._on_change:
            self._on_change()
        for fn in list(self._listeners):
            fn()

    def get_by_id(self, flow_id: int) -> Optional[R.FlowRule]:
        return self._by_id.get(flow_id)

    def namespace_of(self, flow_id: int) -> Optional[str]:
        return self._ns_by_id.get(flow_id)

    def all_ids(self) -> List[int]:
        return list(self._by_id.keys())

    def rules_of(self, namespace: str) -> List[R.FlowRule]:
        return list(self._by_ns.get(namespace, []))


class ClusterParamFlowRuleManager:
    """flowId → ParamFlowRule, grouped by namespace."""

    def __init__(self, on_change: Optional[Callable[[], None]] = None):
        self._lock = threading.Lock()
        self._by_ns: Dict[str, List[R.ParamFlowRule]] = {}
        self._by_id: Dict[int, R.ParamFlowRule] = {}
        self._ns_by_id: Dict[int, str] = {}
        self._on_change = on_change
        self._listeners: List[Callable[[], None]] = []

    def add_listener(self, fn: Callable[[], None]) -> None:
        """Fires after every load, AFTER the primary on_change (so engine
        recompilation precedes dependents like the front door's id map)."""
        self._listeners.append(fn)

    def load(self, namespace: str, rules: List[R.ParamFlowRule]) -> None:
        rules = [r for r in rules if r.cluster_mode and r.cluster_flow_id > 0]
        with self._lock:
            old = self._by_ns.get(namespace, [])
            for r in old:
                if self._ns_by_id.get(r.cluster_flow_id) == namespace:
                    self._by_id.pop(r.cluster_flow_id, None)
                    self._ns_by_id.pop(r.cluster_flow_id, None)
            self._by_ns[namespace] = rules
            for r in rules:
                self._by_id[r.cluster_flow_id] = r
                self._ns_by_id[r.cluster_flow_id] = namespace
        if self._on_change:
            self._on_change()
        for fn in list(self._listeners):
            fn()

    def get_by_id(self, flow_id: int) -> Optional[R.ParamFlowRule]:
        return self._by_id.get(flow_id)

    def namespace_of(self, flow_id: int) -> Optional[str]:
        return self._ns_by_id.get(flow_id)

    def all_ids(self) -> List[int]:
        return list(self._by_id.keys())
