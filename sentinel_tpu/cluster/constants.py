"""Cluster wire constants.

Message-type and status values mirror the reference's public wire contract
(`sentinel-core/.../cluster/ClusterConstants.java` and
`TokenResultStatus.java`) so a reference client could in principle talk to
this token server after swapping the transport framing for ours.
"""

# -- message types (ClusterConstants.MSG_TYPE_*) -----------------------------
MSG_TYPE_PING = 0
MSG_TYPE_FLOW = 1
MSG_TYPE_PARAM_FLOW = 2
MSG_TYPE_CONCURRENT_ACQUIRE = 3
MSG_TYPE_CONCURRENT_RELEASE = 4
# extension beyond the reference protocol: partial-grant batch acquire —
# request n units, response carries granted k (0..n) in `remaining`.  The
# TPU server answers it with n unit-acquires in ONE engine tick.
MSG_TYPE_FLOW_BATCH = 10
# extension: host-shard RESOURCE batch check (parallel/remote_shard.py) —
# a mixed batch of 5-tuples (name, count, prioritized, origin, typed-param:
# "i:<n>"/"s:<text>"/"") answered with per-item (verdict, wait_ms); lets a
# ShardRouter treat a remote host as a shard over the same framing/codec
# as token requests
MSG_TYPE_RES_CHECK = 12
# extension: bounded-slack budget LEASE (cluster/shard.py) — request n
# units against a flow's budget; the owning shard grants k (0..n) in
# `remaining` and a validity window in `wait_ms`.  The holder may spend
# the granted units locally while the shard is unreachable (failover
# fallback), so global overshoot is bounded by the outstanding leases —
# the slack-window reconciliation idea (arXiv 1703.01166)
MSG_TYPE_LEASE = 13
# protocol v2 extension: BATCH — one frame carries many flows' token
# requests as fixed-width column entries (see protocol.py "v2 BATCH
# frame layout").  The server coalesces BATCH frames across connections
# into one device decision batch (ops/token_col.py), so the shard
# answers at engine speed instead of socket speed.  Version-negotiated
# via HELLO: a v1 peer never sees a BATCH frame.
MSG_TYPE_BATCH = 14
# protocol v2 extension: HELLO — version negotiation.  A v2 client sends
# HELLO (its version in `count`) after connect; a v2 server answers
# STATUS_OK with its own version in `remaining`.  A v1 server drops the
# unknown frame on the floor, the HELLO times out, and the client keeps
# speaking v1 — legacy frames stay byte-identical either way.
MSG_TYPE_HELLO = 15

# -- token result status (TokenResultStatus.java) ----------------------------
STATUS_BAD_REQUEST = -4
STATUS_TOO_MANY_REQUEST = -2  # namespace guard tripped
STATUS_FAIL = -1  # transport / unexpected failure
STATUS_OK = 0
STATUS_BLOCKED = 2
STATUS_SHOULD_WAIT = 4
STATUS_NO_RULE = 5
STATUS_NO_REF_RULE = 6
STATUS_NOT_AVAILABLE = 7
STATUS_RELEASE_OK = 8
STATUS_ALREADY_RELEASE = 9

# -- defaults (ServerFlowConfig.java:26-40, ClusterConstants) ----------------
DEFAULT_PORT = 18730
DEFAULT_IDLE_SECONDS = 600
DEFAULT_MAX_ALLOWED_QPS = 30_000.0  # per-namespace guard
DEFAULT_EXCEED_COUNT = 1.0
DEFAULT_MAX_OCCUPY_RATIO = 1.0
DEFAULT_SAMPLE_COUNT = 10
DEFAULT_INTERVAL_MS = 1000
DEFAULT_NAMESPACE = "default"
DEFAULT_REQUEST_TIMEOUT_MS = 200
# lease validity window: one flow-rule accounting interval — granted
# units are spendable for at most this long, so a dead shard's budget
# stops leaking exactly one window after its last grant
DEFAULT_LEASE_TTL_MS = 1000
# hard ceiling on units per LEASE grant, enforced on BOTH sides of the
# wire: the server answers a lease with `units` unit-acquires in engine
# micro-batches, so an uncapped request against a huge-threshold rule
# (slack × 1e9) would stall the decision engine for everyone.  Large
# budgets just re-lease more often; slack stays bounded either way.
MAX_LEASE_UNITS = 1024

# cluster threshold types (ClusterRuleConstant)
FLOW_THRESHOLD_AVG_LOCAL = 0
FLOW_THRESHOLD_GLOBAL = 1

# -- protocol v2 (BATCH frames) ----------------------------------------------
# v3 adds deny provenance: a client that sets BATCH_FLAG_EXPLAIN on an
# entry asks the server to append a _T_PROV block to the batch response —
# (kind, rule, observed, limit) for each BLOCKED entry whose cause is
# known — so a remote block explains itself like a local one
# (obs/explain.py).  Negotiated via the same HELLO exchange; a v2 peer
# never sees the flag or the block, and frames without it are
# byte-identical to v2.
PROTOCOL_VERSION = 3
# per-entry kinds inside a BATCH frame (NOT wire message types — the
# frame's type byte is MSG_TYPE_BATCH; these select the per-entry
# decision semantics)
BATCH_KIND_FLOW = 1  # all-or-nothing acquire of `count` units
BATCH_KIND_FLOW_BATCH = 2  # partial-grant acquire (granted k in remaining)
BATCH_KIND_LEASE = 3  # bounded-slack lease top-up (granted k + TTL)
# per-entry flag bits
BATCH_FLAG_PRIORITIZED = 0x01
# v3: request deny provenance for this entry (set only after HELLO
# negotiated version >= 3; a v2 server treats unknown flag bits as
# garbage, so the client gates it on the negotiated version)
BATCH_FLAG_EXPLAIN = 0x02
# hard ceiling on entries per BATCH frame: 14 B/entry keeps the frame
# comfortably under MAX_FRAME (65535) and bounds one coalesced device
# decision batch
MAX_BATCH_ENTRIES = 2048
