"""Binary frame codec for the cluster token protocol.

Framing: every frame is ``[len:uint16 BE][body]`` — the shape the
reference's Netty pipeline decodes with
``LengthFieldBasedFrameDecoder(1024, 0, 2, 0, 2)`` + ``LengthFieldPrepender(2)``
(NettyTransportServer.java:88-93).

Request body:  ``[xid:int32][type:uint8][payload]``
Response body: ``[xid:int32][type:uint8][status:int8][payload]``

Payloads (big-endian, mirroring the reference entity writers):
  PING               → [namespace:utf8]               (registers the connection)
  FLOW               → [flowId:int64][count:int32][priority:uint8]
                       (FlowRequestData.java:24-26)
  PARAM_FLOW         → [flowId:int64][count:int32][params…] with each param
                       type-tagged (ParamFlowRequestDataWriter semantics:
                       only primitives/strings serialize; others dropped)
  CONCURRENT_ACQUIRE → [flowId:int64][count:int32][prioritized:uint8]
  CONCURRENT_RELEASE → [tokenId:int64]
  LEASE              → [flowId:int64][units:int32][reserved:uint8]
                       (bounded-slack budget lease, cluster/shard.py;
                       response: granted k in `remaining`, validity
                       window ms in `waitMs`)

  flow/param response       → [remaining:int32][waitMs:int32]
  concurrent acquire resp   → [tokenId:int64]
  others                    → empty
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.obs.registry import REGISTRY as _OBS

MAX_FRAME = 65535  # 2-byte length prefix ceiling; RES_CHECK batches chunk
# client-side (parallel/remote_shard.py) so ordinary frames stay small

#: wire byte accounting at THE codec choke point (every cluster frame —
#: client and server, requests and responses — passes through exactly one
#: encode and one decode), frame-length-prefix included.  Same metric name
#: as the host<->device accounting in runtime/client.py; the path label
#: separates them.
_WIRE_HELP = "bytes moved, by path (device|cluster) and direction (tx|rx)"
_C_WIRE_TX = _OBS.counter(
    "sentinel_wire_bytes_total", _WIRE_HELP,
    labels={"path": "cluster", "direction": "tx"},
)
_C_WIRE_RX = _OBS.counter(
    "sentinel_wire_bytes_total", _WIRE_HELP,
    labels={"path": "cluster", "direction": "rx"},
)

# param type tags
_T_INT = 0
_T_LONG = 1
_T_DOUBLE = 2
_T_STRING = 3
_T_BOOL = 4
#: trace-context tag: a 17-byte ``[0x07][trace_id:u64][span_id:u64]``
#: block.  In variable-payload frames (PARAM_FLOW / RES_CHECK) it rides
#: the param stream as a final tagged element; in fixed-payload frames
#: it is an optional tail after the known payload size.  Version
#: tolerance: frames WITHOUT the block are byte-identical to the pre-
#: trace format (tracing-off peers interoperate bit-exactly with any
#: version), an old fixed-offset reader skips the tail of a traced
#: frame, and a reader that has never seen tag 7 rejects only traced
#: variable frames — which the transport already treats as a dropped
#: malformed frame (caller times out and degrades, never crashes).
_T_TRACE = 7
_TRACE_BLOCK = struct.Struct(">BQQ")


@dataclass
class ClusterRequest:
    xid: int
    type: int
    flow_id: int = 0
    count: int = 1
    priority: bool = False
    token_id: int = 0
    namespace: str = ""
    params: List[Any] = field(default_factory=list)
    # distributed-trace context (0 = absent; see _T_TRACE above)
    trace_id: int = 0
    span_id: int = 0


@dataclass
class ClusterResponse:
    xid: int
    type: int
    status: int
    remaining: int = 0
    wait_ms: int = 0
    token_id: int = 0
    items: List[tuple] = field(default_factory=list)  # RES_CHECK verdicts
    # trace context echoed from the request (0 = absent)
    trace_id: int = 0
    span_id: int = 0


def _trace_tail(trace_id: int, span_id: int) -> bytes:
    """Optional 17-byte trace block; empty when no context is attached —
    untraced frames stay byte-identical to the legacy format."""
    if not trace_id:
        return b""
    return _TRACE_BLOCK.pack(_T_TRACE, trace_id & 2**64 - 1, span_id & 2**64 - 1)


def _read_trace_tail(p: bytes, off: int) -> Tuple[int, int]:
    """Trace block at ``off`` if present, else ``(0, 0)`` (legacy frame)."""
    if len(p) >= off + _TRACE_BLOCK.size and p[off] == _T_TRACE:
        _tag, tid, sid = _TRACE_BLOCK.unpack_from(p, off)
        return tid, sid
    return 0, 0


def _pack_params(params: List[Any]) -> bytes:
    out = bytearray()
    for p in params:
        # bool before int: bool is an int subclass in Python
        if isinstance(p, bool):
            out += struct.pack(">BB", _T_BOOL, 1 if p else 0)
        elif isinstance(p, int):
            if -(2**31) <= p < 2**31:
                out += struct.pack(">Bi", _T_INT, p)
            else:
                out += struct.pack(">Bq", _T_LONG, p)
        elif isinstance(p, float):
            out += struct.pack(">Bd", _T_DOUBLE, p)
        elif isinstance(p, str):
            b = p.encode("utf-8")
            out += struct.pack(">BH", _T_STRING, len(b)) + b
        # unsupported types are silently dropped (ParamFlowRequestDataWriter)
    return bytes(out)


def _unpack_params(buf: bytes) -> Tuple[List[Any], int, int]:
    """Decode a tagged param stream; returns ``(params, trace_id,
    span_id)`` — the trace block (tag 7) is surfaced out-of-band, never
    as a param value."""
    out: List[Any] = []
    trace_id = span_id = 0
    i = 0
    while i < len(buf):
        tag = buf[i]
        if tag == _T_TRACE:
            tid, sid = _read_trace_tail(buf, i)
            if tid:
                trace_id, span_id = tid, sid
                i += _TRACE_BLOCK.size
                continue
        i += 1
        if tag == _T_INT:
            out.append(struct.unpack_from(">i", buf, i)[0])
            i += 4
        elif tag == _T_LONG:
            out.append(struct.unpack_from(">q", buf, i)[0])
            i += 8
        elif tag == _T_DOUBLE:
            out.append(struct.unpack_from(">d", buf, i)[0])
            i += 8
        elif tag == _T_STRING:
            (n,) = struct.unpack_from(">H", buf, i)
            i += 2
            out.append(buf[i : i + n].decode("utf-8"))
            i += n
        elif tag == _T_BOOL:
            out.append(buf[i] != 0)
            i += 1
        else:
            raise ValueError(f"bad param tag {tag}")
    return out, trace_id, span_id


def encode_request(req: ClusterRequest) -> bytes:
    head = struct.pack(">iB", req.xid, req.type)
    t = req.type
    tail = _trace_tail(req.trace_id, req.span_id)
    if t == C.MSG_TYPE_PING:
        # PING's payload is the raw namespace string (whole remainder) —
        # no room for a skippable tail, and registration needs no trace
        payload = req.namespace.encode("utf-8")
    elif t in (C.MSG_TYPE_FLOW, C.MSG_TYPE_FLOW_BATCH, C.MSG_TYPE_LEASE):
        payload = struct.pack(">qiB", req.flow_id, req.count, 1 if req.priority else 0) + tail
    elif t == C.MSG_TYPE_PARAM_FLOW:
        payload = struct.pack(">qi", req.flow_id, req.count) + _pack_params(req.params) + tail
    elif t == C.MSG_TYPE_CONCURRENT_ACQUIRE:
        payload = struct.pack(">qiB", req.flow_id, req.count, 1 if req.priority else 0) + tail
    elif t == C.MSG_TYPE_CONCURRENT_RELEASE:
        payload = struct.pack(">q", req.token_id) + tail
    elif t == C.MSG_TYPE_RES_CHECK:
        # params = flat 5-tuples (name, count, prio, origin, typed-param)
        payload = _pack_params(req.params) + tail
    else:
        raise ValueError(f"bad request type {t}")
    body = head + payload
    if len(body) > MAX_FRAME:
        raise ValueError("frame too large")
    _C_WIRE_TX.inc(len(body) + 2)
    return struct.pack(">H", len(body)) + body


def decode_request(body: bytes) -> ClusterRequest:
    _C_WIRE_RX.inc(len(body) + 2)  # +2: the stripped length prefix
    xid, t = struct.unpack_from(">iB", body, 0)
    p = body[5:]
    req = ClusterRequest(xid=xid, type=t)
    if t == C.MSG_TYPE_PING:
        req.namespace = p.decode("utf-8") if p else C.DEFAULT_NAMESPACE
    elif t in (
        C.MSG_TYPE_FLOW,
        C.MSG_TYPE_FLOW_BATCH,
        C.MSG_TYPE_CONCURRENT_ACQUIRE,
        C.MSG_TYPE_LEASE,
    ):
        req.flow_id, req.count, prio = struct.unpack_from(">qiB", p, 0)
        req.priority = prio != 0
        req.trace_id, req.span_id = _read_trace_tail(p, 13)
    elif t == C.MSG_TYPE_PARAM_FLOW:
        req.flow_id, req.count = struct.unpack_from(">qi", p, 0)
        req.params, req.trace_id, req.span_id = _unpack_params(p[12:])
    elif t == C.MSG_TYPE_CONCURRENT_RELEASE:
        (req.token_id,) = struct.unpack_from(">q", p, 0)
        req.trace_id, req.span_id = _read_trace_tail(p, 8)
    elif t == C.MSG_TYPE_RES_CHECK:
        req.params, req.trace_id, req.span_id = _unpack_params(p)
    else:
        raise ValueError(f"bad request type {t}")
    return req


def encode_response(rsp: ClusterResponse) -> bytes:
    head = struct.pack(">iBb", rsp.xid, rsp.type, rsp.status)
    if rsp.type in (
        C.MSG_TYPE_FLOW,
        C.MSG_TYPE_PARAM_FLOW,
        C.MSG_TYPE_FLOW_BATCH,
        C.MSG_TYPE_LEASE,
    ):
        payload = struct.pack(">ii", rsp.remaining, rsp.wait_ms)
    elif rsp.type == C.MSG_TYPE_CONCURRENT_ACQUIRE:
        payload = struct.pack(">q", rsp.token_id)
    elif rsp.type == C.MSG_TYPE_RES_CHECK:
        payload = struct.pack(">i", len(rsp.items)) + b"".join(
            struct.pack(">bi", v, w) for v, w in rsp.items
        )
    else:
        payload = b""
    # every response payload is either fixed-size or count-bounded, so an
    # appended trace tail is skipped cleanly even by a legacy reader
    body = head + payload + _trace_tail(rsp.trace_id, rsp.span_id)
    _C_WIRE_TX.inc(len(body) + 2)
    return struct.pack(">H", len(body)) + body


def decode_response(body: bytes) -> ClusterResponse:
    _C_WIRE_RX.inc(len(body) + 2)  # +2: the stripped length prefix
    xid, t, status = struct.unpack_from(">iBb", body, 0)
    p = body[6:]
    rsp = ClusterResponse(xid=xid, type=t, status=status)
    tail_off = 0
    if (
        t
        in (
            C.MSG_TYPE_FLOW,
            C.MSG_TYPE_PARAM_FLOW,
            C.MSG_TYPE_FLOW_BATCH,
            C.MSG_TYPE_LEASE,
        )
        and len(p) >= 8
    ):
        rsp.remaining, rsp.wait_ms = struct.unpack_from(">ii", p, 0)
        tail_off = 8
    elif t == C.MSG_TYPE_CONCURRENT_ACQUIRE and len(p) >= 8:
        (rsp.token_id,) = struct.unpack_from(">q", p, 0)
        tail_off = 8
    elif t == C.MSG_TYPE_RES_CHECK and len(p) >= 4:
        (n,) = struct.unpack_from(">i", p, 0)
        off = 4
        # bounds-checked: a truncated/hostile frame yields a SHORT item
        # list (the caller length-checks and degrades), not struct.error
        for _ in range(max(n, 0)):
            if off + 5 > len(p):
                break
            v, w = struct.unpack_from(">bi", p, off)
            off += 5
            rsp.items.append((v, w))
        tail_off = off
    rsp.trace_id, rsp.span_id = _read_trace_tail(p, tail_off)
    return rsp


class FrameReader:
    """Incremental 2-byte-length-prefixed frame splitter."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        frames = []
        while True:
            if len(self._buf) < 2:
                break
            (n,) = struct.unpack_from(">H", self._buf, 0)
            if len(self._buf) < 2 + n:
                break
            frames.append(bytes(self._buf[2 : 2 + n]))
            del self._buf[: 2 + n]
        return frames
