"""Binary frame codec for the cluster token protocol.

Framing: every frame is ``[len:uint16 BE][body]`` — the shape the
reference's Netty pipeline decodes with
``LengthFieldBasedFrameDecoder(1024, 0, 2, 0, 2)`` + ``LengthFieldPrepender(2)``
(NettyTransportServer.java:88-93).

Request body:  ``[xid:int32][type:uint8][payload]``
Response body: ``[xid:int32][type:uint8][status:int8][payload]``

Payloads (big-endian, mirroring the reference entity writers):
  PING               → [namespace:utf8]               (registers the connection)
  FLOW               → [flowId:int64][count:int32][priority:uint8]
                       (FlowRequestData.java:24-26)
  PARAM_FLOW         → [flowId:int64][count:int32][params…] with each param
                       type-tagged (ParamFlowRequestDataWriter semantics:
                       only primitives/strings serialize; others dropped)
  CONCURRENT_ACQUIRE → [flowId:int64][count:int32][prioritized:uint8]
  CONCURRENT_RELEASE → [tokenId:int64]
  LEASE              → [flowId:int64][units:int32][reserved:uint8]
                       (bounded-slack budget lease, cluster/shard.py;
                       response: granted k in `remaining`, validity
                       window ms in `waitMs`)

  flow/param response       → [remaining:int32][waitMs:int32]
  concurrent acquire resp   → [tokenId:int64]
  others                    → empty

v2 BATCH frame layout (MSG_TYPE_BATCH, version-negotiated via HELLO —
a v1 peer never receives one; all v1 frames above stay byte-identical):

  request  → [xid:int32][type=14:uint8][n:uint16]
             n × [kind:uint8][id:int64][count:int32][flags:uint8]   (14 B)
             [optional 17-byte trace tail]
  response → [xid:int32][type=14:uint8][status:int8][n:uint16]
             n × [status:int8][remaining:int32][waitMs:int32][tokenId:int64]  (17 B)
             [optional v3 _T_PROV deny-provenance block — see _T_PROV]
             [optional 17-byte trace tail]

Entry columns are fixed-width big-endian, so pack/unpack is a single
zero-copy reinterpret (native sx_frame_* or a numpy structured-dtype
fallback — byte-identical by construction).  Decoding validates the
EXACT frame length (header + n×entry + optional tail): a corrupt or
short-read frame raises and the WHOLE frame fails closed — partial
answers are never applied.

  HELLO    → request  [version:uint8];  response carries the server's
             version in `remaining`.  A v1 server drops the unknown
             frame (client's HELLO times out → keeps speaking v1).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.native import ring as _NR
from sentinel_tpu.obs.explain import fx_decode, fx_encode
from sentinel_tpu.obs.registry import REGISTRY as _OBS

MAX_FRAME = 65535  # 2-byte length prefix ceiling; RES_CHECK batches chunk
# client-side (parallel/remote_shard.py) so ordinary frames stay small

#: wire byte accounting at THE codec choke point (every cluster frame —
#: client and server, requests and responses — passes through exactly one
#: encode and one decode), frame-length-prefix included.  Same metric name
#: as the host<->device accounting in runtime/client.py; the path label
#: separates them.
_WIRE_HELP = "bytes moved, by path (device|cluster) and direction (tx|rx)"
_C_WIRE_TX = _OBS.counter(
    "sentinel_wire_bytes_total", _WIRE_HELP,
    labels={"path": "cluster", "direction": "tx"},
)
_C_WIRE_RX = _OBS.counter(
    "sentinel_wire_bytes_total", _WIRE_HELP,
    labels={"path": "cluster", "direction": "rx"},
)

#: v2 BATCH frames through the codec — the RPC-coalescing win is visible
#: as this counter rising while per-decision RPC counts fall
_BATCH_HELP = "protocol v2 BATCH frames encoded/decoded, by direction"
_C_BATCH_TX = _OBS.counter(
    "sentinel_cluster_batch_frames_total", _BATCH_HELP, labels={"direction": "tx"}
)
_C_BATCH_RX = _OBS.counter(
    "sentinel_cluster_batch_frames_total", _BATCH_HELP, labels={"direction": "rx"}
)

# param type tags
_T_INT = 0
_T_LONG = 1
_T_DOUBLE = 2
_T_STRING = 3
_T_BOOL = 4
#: trace-context tag: a 17-byte ``[0x07][trace_id:u64][span_id:u64]``
#: block.  In variable-payload frames (PARAM_FLOW / RES_CHECK) it rides
#: the param stream as a final tagged element; in fixed-payload frames
#: it is an optional tail after the known payload size.  Version
#: tolerance: frames WITHOUT the block are byte-identical to the pre-
#: trace format (tracing-off peers interoperate bit-exactly with any
#: version), an old fixed-offset reader skips the tail of a traced
#: frame, and a reader that has never seen tag 7 rejects only traced
#: variable frames — which the transport already treats as a dropped
#: malformed frame (caller times out and degrades, never crashes).
_T_TRACE = 7
_TRACE_BLOCK = struct.Struct(">BQQ")
#: deny-provenance tag (protocol v3): an optional block in BATCH
#: responses — ``[0x08][count:u16]`` then ``count`` records of
#: ``[entry_idx:u16][kind:u8][rule:u64][observed:u32][limit:u32]``,
#: one per BLOCKED entry whose cause the server knows.  observed/limit
#: use the obs/explain.py fixed-point encoding (×256, 0xFFFFFFFF =
#: unknown) — the same words the device explain records carry, so a
#: remote block folds into the provenance plane exactly like a local
#: one.  Placement: after the result slab, BEFORE the trace tail.  Sent
#: only when the client requested it (BATCH_FLAG_EXPLAIN, v3+ peers);
#: frames without it are byte-identical to v2.
_T_PROV = 8
_PROV_HEAD = struct.Struct(">BH")
_PROV_ENTRY = struct.Struct(">HBQII")


@dataclass
class ClusterRequest:
    xid: int
    type: int
    flow_id: int = 0
    count: int = 1
    priority: bool = False
    token_id: int = 0
    namespace: str = ""
    params: List[Any] = field(default_factory=list)
    # distributed-trace context (0 = absent; see _T_TRACE above)
    trace_id: int = 0
    span_id: int = 0


@dataclass
class ClusterResponse:
    xid: int
    type: int
    status: int
    remaining: int = 0
    wait_ms: int = 0
    token_id: int = 0
    items: List[tuple] = field(default_factory=list)  # RES_CHECK verdicts
    # trace context echoed from the request (0 = absent)
    trace_id: int = 0
    span_id: int = 0


def _trace_tail(trace_id: int, span_id: int) -> bytes:
    """Optional 17-byte trace block; empty when no context is attached —
    untraced frames stay byte-identical to the legacy format."""
    if not trace_id:
        return b""
    return _TRACE_BLOCK.pack(_T_TRACE, trace_id & 2**64 - 1, span_id & 2**64 - 1)


def _read_trace_tail(p: bytes, off: int) -> Tuple[int, int]:
    """Trace block at ``off`` if present, else ``(0, 0)`` (legacy frame)."""
    if len(p) >= off + _TRACE_BLOCK.size and p[off] == _T_TRACE:
        _tag, tid, sid = _TRACE_BLOCK.unpack_from(p, off)
        return tid, sid
    return 0, 0


def _pack_params(params: List[Any]) -> bytes:
    out = bytearray()
    for p in params:
        # bool before int: bool is an int subclass in Python
        if isinstance(p, bool):
            out += struct.pack(">BB", _T_BOOL, 1 if p else 0)
        elif isinstance(p, int):
            if -(2**31) <= p < 2**31:
                out += struct.pack(">Bi", _T_INT, p)
            else:
                out += struct.pack(">Bq", _T_LONG, p)
        elif isinstance(p, float):
            out += struct.pack(">Bd", _T_DOUBLE, p)
        elif isinstance(p, str):
            b = p.encode("utf-8")
            out += struct.pack(">BH", _T_STRING, len(b)) + b
        # unsupported types are silently dropped (ParamFlowRequestDataWriter)
    return bytes(out)


def _unpack_params(buf: bytes) -> Tuple[List[Any], int, int]:
    """Decode a tagged param stream; returns ``(params, trace_id,
    span_id)`` — the trace block (tag 7) is surfaced out-of-band, never
    as a param value."""
    out: List[Any] = []
    trace_id = span_id = 0
    i = 0
    while i < len(buf):
        tag = buf[i]
        if tag == _T_TRACE:
            tid, sid = _read_trace_tail(buf, i)
            if tid:
                trace_id, span_id = tid, sid
                i += _TRACE_BLOCK.size
                continue
        i += 1
        if tag == _T_INT:
            out.append(struct.unpack_from(">i", buf, i)[0])
            i += 4
        elif tag == _T_LONG:
            out.append(struct.unpack_from(">q", buf, i)[0])
            i += 8
        elif tag == _T_DOUBLE:
            out.append(struct.unpack_from(">d", buf, i)[0])
            i += 8
        elif tag == _T_STRING:
            (n,) = struct.unpack_from(">H", buf, i)
            i += 2
            out.append(buf[i : i + n].decode("utf-8"))
            i += n
        elif tag == _T_BOOL:
            out.append(buf[i] != 0)
            i += 1
        else:
            raise ValueError(f"bad param tag {tag}")
    return out, trace_id, span_id


def encode_request(req: ClusterRequest) -> bytes:
    head = struct.pack(">iB", req.xid, req.type)
    t = req.type
    tail = _trace_tail(req.trace_id, req.span_id)
    if t == C.MSG_TYPE_PING:
        # PING's payload is the raw namespace string (whole remainder) —
        # no room for a skippable tail, and registration needs no trace
        payload = req.namespace.encode("utf-8")
    elif t in (C.MSG_TYPE_FLOW, C.MSG_TYPE_FLOW_BATCH, C.MSG_TYPE_LEASE):
        payload = struct.pack(">qiB", req.flow_id, req.count, 1 if req.priority else 0) + tail
    elif t == C.MSG_TYPE_PARAM_FLOW:
        payload = struct.pack(">qi", req.flow_id, req.count) + _pack_params(req.params) + tail
    elif t == C.MSG_TYPE_CONCURRENT_ACQUIRE:
        payload = struct.pack(">qiB", req.flow_id, req.count, 1 if req.priority else 0) + tail
    elif t == C.MSG_TYPE_CONCURRENT_RELEASE:
        payload = struct.pack(">q", req.token_id) + tail
    elif t == C.MSG_TYPE_RES_CHECK:
        # params = flat 5-tuples (name, count, prio, origin, typed-param)
        payload = _pack_params(req.params) + tail
    elif t == C.MSG_TYPE_HELLO:
        # version negotiation: the speaker's protocol version in `count`
        payload = struct.pack(">B", req.count & 0xFF) + tail
    else:
        raise ValueError(f"bad request type {t}")
    body = head + payload
    if len(body) > MAX_FRAME:
        raise ValueError("frame too large")
    _C_WIRE_TX.inc(len(body) + 2)
    return struct.pack(">H", len(body)) + body


def decode_request(body: bytes) -> ClusterRequest:
    _C_WIRE_RX.inc(len(body) + 2)  # +2: the stripped length prefix
    xid, t = struct.unpack_from(">iB", body, 0)
    p = body[5:]
    req = ClusterRequest(xid=xid, type=t)
    if t == C.MSG_TYPE_PING:
        req.namespace = p.decode("utf-8") if p else C.DEFAULT_NAMESPACE
    elif t in (
        C.MSG_TYPE_FLOW,
        C.MSG_TYPE_FLOW_BATCH,
        C.MSG_TYPE_CONCURRENT_ACQUIRE,
        C.MSG_TYPE_LEASE,
    ):
        req.flow_id, req.count, prio = struct.unpack_from(">qiB", p, 0)
        req.priority = prio != 0
        req.trace_id, req.span_id = _read_trace_tail(p, 13)
    elif t == C.MSG_TYPE_PARAM_FLOW:
        req.flow_id, req.count = struct.unpack_from(">qi", p, 0)
        req.params, req.trace_id, req.span_id = _unpack_params(p[12:])
    elif t == C.MSG_TYPE_CONCURRENT_RELEASE:
        (req.token_id,) = struct.unpack_from(">q", p, 0)
        req.trace_id, req.span_id = _read_trace_tail(p, 8)
    elif t == C.MSG_TYPE_RES_CHECK:
        req.params, req.trace_id, req.span_id = _unpack_params(p)
    elif t == C.MSG_TYPE_HELLO:
        req.count = p[0] if p else 1
        req.trace_id, req.span_id = _read_trace_tail(p, 1)
    else:
        raise ValueError(f"bad request type {t}")
    return req


def encode_response(rsp: ClusterResponse) -> bytes:
    head = struct.pack(">iBb", rsp.xid, rsp.type, rsp.status)
    if rsp.type in (
        C.MSG_TYPE_FLOW,
        C.MSG_TYPE_PARAM_FLOW,
        C.MSG_TYPE_FLOW_BATCH,
        C.MSG_TYPE_LEASE,
        C.MSG_TYPE_HELLO,  # v2 extension: server version in `remaining`
    ):
        payload = struct.pack(">ii", rsp.remaining, rsp.wait_ms)
    elif rsp.type == C.MSG_TYPE_CONCURRENT_ACQUIRE:
        payload = struct.pack(">q", rsp.token_id)
    elif rsp.type == C.MSG_TYPE_RES_CHECK:
        payload = struct.pack(">i", len(rsp.items)) + b"".join(
            struct.pack(">bi", v, w) for v, w in rsp.items
        )
    else:
        payload = b""
    # every response payload is either fixed-size or count-bounded, so an
    # appended trace tail is skipped cleanly even by a legacy reader
    body = head + payload + _trace_tail(rsp.trace_id, rsp.span_id)
    _C_WIRE_TX.inc(len(body) + 2)
    return struct.pack(">H", len(body)) + body


def decode_response(body: bytes) -> ClusterResponse:
    _C_WIRE_RX.inc(len(body) + 2)  # +2: the stripped length prefix
    xid, t, status = struct.unpack_from(">iBb", body, 0)
    p = body[6:]
    rsp = ClusterResponse(xid=xid, type=t, status=status)
    tail_off = 0
    if (
        t
        in (
            C.MSG_TYPE_FLOW,
            C.MSG_TYPE_PARAM_FLOW,
            C.MSG_TYPE_FLOW_BATCH,
            C.MSG_TYPE_LEASE,
            C.MSG_TYPE_HELLO,  # v2 extension: peer version in `remaining`
        )
        and len(p) >= 8
    ):
        rsp.remaining, rsp.wait_ms = struct.unpack_from(">ii", p, 0)
        tail_off = 8
    elif t == C.MSG_TYPE_CONCURRENT_ACQUIRE and len(p) >= 8:
        (rsp.token_id,) = struct.unpack_from(">q", p, 0)
        tail_off = 8
    elif t == C.MSG_TYPE_RES_CHECK and len(p) >= 4:
        (n,) = struct.unpack_from(">i", p, 0)
        off = 4
        # bounds-checked: a truncated/hostile frame yields a SHORT item
        # list (the caller length-checks and degrades), not struct.error
        for _ in range(max(n, 0)):
            if off + 5 > len(p):
                break
            v, w = struct.unpack_from(">bi", p, off)
            off += 5
            rsp.items.append((v, w))
        tail_off = off
    rsp.trace_id, rsp.span_id = _read_trace_tail(p, tail_off)
    return rsp


# ---------------------------------------------------------------------------
# protocol v2: BATCH frames (column entries, zero-copy pack/unpack)
# ---------------------------------------------------------------------------

_BATCH_REQ_HEAD = struct.Struct(">iBH")  # xid, type, n
_BATCH_RSP_HEAD = struct.Struct(">iBbH")  # xid, type, frame status, n


@dataclass
class ClusterBatchRequest:
    """One v2 frame carrying many flows' token requests as columns."""

    xid: int
    kinds: np.ndarray  # uint8[n] — C.BATCH_KIND_*
    ids: np.ndarray  # int64[n] — flow ids
    counts: np.ndarray  # int32[n] — units requested
    flags: np.ndarray  # uint8[n] — C.BATCH_FLAG_*
    trace_id: int = 0
    span_id: int = 0

    def __len__(self) -> int:
        return len(self.kinds)


@dataclass
class ClusterBatchResponse:
    """Per-entry verdict columns; ``status`` is the WHOLE-frame status
    (non-OK ⇒ no entry was applied — fail closed, never partially)."""

    xid: int
    status: int
    statuses: np.ndarray  # int8[n] — C.STATUS_* per entry
    remainings: np.ndarray  # int32[n] — granted units / remaining
    waits: np.ndarray  # int32[n] — wait/TTL ms per entry
    token_ids: np.ndarray  # int64[n] — concurrent token ids (0 otherwise)
    trace_id: int = 0
    span_id: int = 0
    # v3 deny provenance, entry-aligned: ``prov[i]`` is ``(kind, rule,
    # observed|None, limit|None)`` for a BLOCKED entry whose cause the
    # server knows, else None; the whole field is None on v2 frames
    prov: Optional[List[Optional[Tuple[int, int, Optional[float], Optional[float]]]]] = None

    def __len__(self) -> int:
        return len(self.statuses)


def encode_batch_request(req: ClusterBatchRequest) -> bytes:
    n = len(req.kinds)
    if not 0 < n <= C.MAX_BATCH_ENTRIES:
        raise ValueError(f"bad batch size {n}")
    body = (
        _BATCH_REQ_HEAD.pack(req.xid, C.MSG_TYPE_BATCH, n)
        + _NR.pack_batch_entries(req.kinds, req.ids, req.counts, req.flags)
        + _trace_tail(req.trace_id, req.span_id)
    )
    if len(body) > MAX_FRAME:
        raise ValueError("frame too large")
    _C_WIRE_TX.inc(len(body) + 2)
    _C_BATCH_TX.inc()
    return struct.pack(">H", len(body)) + body


def _prov_tail(prov) -> bytes:
    """Optional v3 deny-provenance block (entry-aligned list as stored on
    ClusterBatchResponse.prov); empty when no entry has provenance — the
    frame stays byte-identical to v2."""
    if not prov:
        return b""
    recs = [(i, pv) for i, pv in enumerate(prov) if pv is not None]
    if not recs:
        return b""
    out = bytearray(_PROV_HEAD.pack(_T_PROV, len(recs)))
    for i, (kind, rule, observed, limit) in recs:
        out += _PROV_ENTRY.pack(
            i,
            int(kind) & 0xFF,
            int(rule) & 2**64 - 1,
            fx_encode(observed),
            fx_encode(limit),
        )
    return bytes(out)


def _batch_payload(
    p: bytes, n: int, entry_size: int
) -> Tuple[bytes, int, int, Optional[list]]:
    """Strict-length entry slab + optional blocks.  The remainder after
    the count header must be EXACTLY ``n`` entries, optionally followed
    by a well-formed _T_PROV block (v3) and/or trace block — anything
    else (bit-flipped count byte, short read, trailing garbage) raises,
    and the caller rejects the whole frame: a corrupted BATCH frame
    never yields partial answers."""
    want = n * entry_size
    if len(p) < want:
        raise ValueError(f"bad batch frame length {len(p)} for {n} entries")
    off = want
    prov: Optional[list] = None
    if off < len(p) and p[off] == _T_PROV:
        if off + _PROV_HEAD.size > len(p):
            raise ValueError("truncated prov block")
        _tag, k = _PROV_HEAD.unpack_from(p, off)
        off += _PROV_HEAD.size
        if k > n or off + k * _PROV_ENTRY.size > len(p):
            raise ValueError(f"bad prov block count {k} for {n} entries")
        prov = [None] * n
        for _ in range(k):
            idx, kind, rule, obs_w, lim_w = _PROV_ENTRY.unpack_from(p, off)
            off += _PROV_ENTRY.size
            if idx >= n:
                raise ValueError(f"prov entry index {idx} out of range")
            prov[idx] = (kind, rule, fx_decode(obs_w), fx_decode(lim_w))
    tid = sid = 0
    if off < len(p):
        if len(p) == off + _TRACE_BLOCK.size and p[off] == _T_TRACE:
            tid, sid = _read_trace_tail(p, off)
        else:
            raise ValueError(f"bad batch frame length {len(p)} for {n} entries")
    return p[:want], tid, sid, prov


def decode_batch_request(body: bytes) -> ClusterBatchRequest:
    _C_WIRE_RX.inc(len(body) + 2)
    _C_BATCH_RX.inc()
    xid, t, n = _BATCH_REQ_HEAD.unpack_from(body, 0)
    if t != C.MSG_TYPE_BATCH:
        raise ValueError(f"not a batch frame (type {t})")
    if not 0 < n <= C.MAX_BATCH_ENTRIES:
        raise ValueError(f"bad batch size {n}")
    slab, tid, sid, _ = _batch_payload(body[_BATCH_REQ_HEAD.size :], n, _NR.BATCH_ENTRY_SIZE)
    kinds, ids, counts, flags = _NR.unpack_batch_entries(slab)
    return ClusterBatchRequest(
        xid=xid, kinds=kinds, ids=ids, counts=counts, flags=flags,
        trace_id=tid, span_id=sid,
    )


def encode_batch_response(rsp: ClusterBatchResponse) -> bytes:
    n = len(rsp.statuses)
    body = _BATCH_RSP_HEAD.pack(rsp.xid, C.MSG_TYPE_BATCH, rsp.status, n)
    if n:
        body += _NR.pack_batch_results(
            rsp.statuses, rsp.remainings, rsp.waits, rsp.token_ids
        )
        body += _prov_tail(rsp.prov)
    body += _trace_tail(rsp.trace_id, rsp.span_id)
    if len(body) > MAX_FRAME:
        raise ValueError("frame too large")
    _C_WIRE_TX.inc(len(body) + 2)
    _C_BATCH_TX.inc()
    return struct.pack(">H", len(body)) + body


def decode_batch_response(body: bytes) -> ClusterBatchResponse:
    _C_WIRE_RX.inc(len(body) + 2)
    _C_BATCH_RX.inc()
    xid, t, status, n = _BATCH_RSP_HEAD.unpack_from(body, 0)
    if t != C.MSG_TYPE_BATCH:
        raise ValueError(f"not a batch frame (type {t})")
    if not 0 <= n <= C.MAX_BATCH_ENTRIES:
        raise ValueError(f"bad batch size {n}")
    slab, tid, sid, prov = _batch_payload(body[_BATCH_RSP_HEAD.size :], n, _NR.BATCH_RESULT_SIZE)
    statuses, remainings, waits, tokens = _NR.unpack_batch_results(slab)
    return ClusterBatchResponse(
        xid=xid, status=status, statuses=statuses, remainings=remainings,
        waits=waits, token_ids=tokens, trace_id=tid, span_id=sid, prov=prov,
    )


def peek_type(body: bytes) -> int:
    """Frame type byte without a full decode (offset 4 in both request
    and response bodies) — lets transport loops route BATCH frames to
    the column codec and everything else to the legacy one."""
    return body[4] if len(body) >= 5 else -1


class FrameReader:
    """Incremental 2-byte-length-prefixed frame splitter."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        frames = []
        while True:
            if len(self._buf) < 2:
                break
            (n,) = struct.unpack_from(">H", self._buf, 0)
            if len(self._buf) < 2 + n:
                break
            frames.append(bytes(self._buf[2 : 2 + n]))
            del self._buf[: 2 + n]
        return frames
