"""Cluster flow control: the distributed token backend (SURVEY.md §2.5).

A TPU-native re-design of the reference's `sentinel-cluster` modules:
the token *decisions* run on the same batched device engine as local rules
(flowIds interned as resources on a dedicated decision client), while the
host provides the wire protocol, connection bookkeeping, namespace guard,
and concurrent-token TTL cache.

Modules:
  constants      — wire message types / status codes (ClusterConstants.java)
  protocol       — length-prefixed binary frame codec (default transport)
  rules          — ClusterFlowRuleManager / ClusterParamFlowRuleManager /
                   server+client config managers
  token_service  — TokenService interface + DefaultTokenService on the engine
  server         — asyncio TCP token server + ConnectionManager
  client         — ClusterTokenClient (xid-correlated, auto-reconnect)
  state          — ClusterStateManager (NOT_STARTED / CLIENT / SERVER flips)
  ring           — consistent-hash ring with virtual nodes (placement law)
  shard          — ShardedTokenClient + ShardFleet: N-shard fleet with
                   per-shard failover and bounded-slack budget leases
"""

from sentinel_tpu.cluster.constants import (  # noqa: F401
    MSG_TYPE_PING,
    MSG_TYPE_FLOW,
    MSG_TYPE_PARAM_FLOW,
    MSG_TYPE_CONCURRENT_ACQUIRE,
    MSG_TYPE_CONCURRENT_RELEASE,
    STATUS_OK,
    STATUS_BLOCKED,
    STATUS_SHOULD_WAIT,
    STATUS_FAIL,
    STATUS_NO_RULE,
    STATUS_TOO_MANY_REQUEST,
    STATUS_BAD_REQUEST,
    STATUS_RELEASE_OK,
    STATUS_ALREADY_RELEASE,
)
from sentinel_tpu.cluster.token_service import (  # noqa: F401
    TokenResult,
    TokenService,
    DefaultTokenService,
)
from sentinel_tpu.cluster.state import ClusterStateManager  # noqa: F401
from sentinel_tpu.cluster.ring import HashRing, flow_key  # noqa: F401
from sentinel_tpu.cluster.shard import (  # noqa: F401
    ShardFleet,
    ShardedTokenClient,
)
