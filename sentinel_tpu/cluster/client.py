"""Cluster token client: xid-correlated requests with auto-reconnect.

The reference pairs a Netty channel with a xid→promise map
(DefaultClusterTokenClient.java:45, TokenClientPromiseHolder); here a plain
socket plus a daemon reader thread resolves per-request Futures.  Failures
degrade, never break: a dead server yields STATUS_FAIL results and the
runtime falls back to local rule checking
(FlowRuleChecker.fallbackToLocalOrPass:166 — see runtime/client.py wiring).
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
from concurrent.futures import CancelledError, Future
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.obs import flight as FL
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.time_source import mono_s

_H_RPC = _OBS.histogram(
    "sentinel_cluster_rpc_ms",
    "token-server request/response round-trip (successful responses only; "
    "failures count in sentinel_cluster_rpc_failures_total)",
)
# degraded round-trips, labeled by failure KIND so chaos scenarios (and
# operators) can assert WHICH fault fired instead of reading one lump:
#   connect   — could not (re)establish the server connection
#   send      — the request write failed mid-frame
#   timeout   — no response within timeout_ms (includes server-side drops
#               of malformed/corrupted frames, whose xid never resolves)
#   conn_lost — the connection died while the request was in flight
#   decode    — a response frame arrived but failed to parse (the caller
#               still times out, counted separately under `timeout`)
_RPC_FAIL_HELP = (
    "token-server round-trips that degraded, by failure kind "
    "(connect|send|timeout|conn_lost|decode)"
)
_C_RPC_FAIL = {
    k: _OBS.counter(
        "sentinel_cluster_rpc_failures_total", _RPC_FAIL_HELP, labels={"kind": k}
    )
    for k in ("connect", "send", "timeout", "conn_lost", "decode")
}

#: frames currently awaiting a response across all cluster client
#: connections (multiplexing depth) — mirrors the xid→Future map exactly
_G_INFLIGHT = _OBS.gauge(
    "sentinel_cluster_inflight_frames",
    "request frames awaiting responses across all cluster client connections",
)

#: chaos failpoints (chaos/failpoints.py) on the round-trip path — the
#: exact points a real transport fault strikes, one flag check disarmed
_FP_CONNECT = FP.register(
    "cluster.rpc.connect", "token-server TCP connect", FP.HIT_ACTIONS
)
_FP_SEND = FP.register(
    "cluster.rpc.send",
    "token-server request frame write (per round-trip)",
    FP.PIPE_ACTIONS,
)
_FP_RECV = FP.register(
    "cluster.rpc.recv",
    "token-server response bytes (reader thread)",
    FP.PIPE_ACTIONS,
)

#: sentinel returned by _roundtrip for requests that can never be encoded
#: (oversized params) — a client-side problem, NOT a server failure, so it
#: must not flip the runtime into degraded mode
_BAD_REQUEST = P.ClusterResponse(xid=-1, type=0, status=C.STATUS_BAD_REQUEST)


class ClusterTokenClient(TokenService):
    def __init__(
        self,
        host: str,
        port: int,
        namespace: str = C.DEFAULT_NAMESPACE,
        timeout_ms: int = C.DEFAULT_REQUEST_TIMEOUT_MS,
        reconnect_interval_s: float = 2.0,
        reconnect_backoff_cap_s: float = 30.0,
    ):
        self.host = host
        self.port = port
        self.namespace = namespace
        self.timeout_ms = timeout_ms
        self.reconnect_interval_s = reconnect_interval_s
        # exponential backoff with FULL jitter between reconnect attempts
        # (adaptive/degrade.py): a fixed retry interval let N clients that
        # lost the same shard stampede it in lockstep the moment it came
        # back.  ``reconnect_interval_s`` is the base (attempt 0 ceiling)
        # and stays live-tunable — tests zero it for no-throttle mode.
        from sentinel_tpu.adaptive.degrade import Backoff

        self._backoff = Backoff(
            reconnect_interval_s, cap_s=reconnect_backoff_cap_s
        )
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # serializes sendall: concurrent partial writes from two threads
        # would interleave mid-frame and desync the server's FrameReader
        self._send_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._xid_counter = itertools.count(0)
        self._reader: Optional[threading.Thread] = None
        self._closed = False
        # negotiated protocol version for the CURRENT connection: starts
        # at 1, bumped to 2 when the server answers our HELLO, reset on
        # every teardown (a failover target may be an older build)
        self._peer_version = 1

    def _next_xid(self) -> int:
        # xid is an int32 on the wire; wrap within the positive range
        return next(self._xid_counter) % 0x7FFFFFFF + 1

    def _pend_add(self, xid: int, f: Future) -> None:
        self._pending[xid] = f
        _G_INFLIGHT.inc()

    def _pend_pop(self, xid: int) -> Optional[Future]:
        f = self._pending.pop(xid, None)
        if f is not None:
            _G_INFLIGHT.dec()
        return f

    # -- connection management ----------------------------------------------

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def start(self) -> None:
        self._ensure_connected()

    def close(self) -> None:
        self._closed = True
        self._teardown(kind="close")

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        if self._closed:
            return False
        # single-flight the connect: create_connection stalls up to its
        # 2 s timeout against a dead shard, and admission threads used to
        # QUEUE on this lock behind the connecting thread for that whole
        # window.  A busy lock now means someone else is already paying
        # the connect (or a teardown is mid-swap) — report unconnected
        # immediately and let the caller take its degraded fallback.
        if not self._lock.acquire(blocking=False):
            return False
        try:
            if self._sock is not None:
                return True
            # base stays live-tunable (tests zero reconnect_interval_s on
            # a built client); cap ramp-up via the jittered backoff
            self._backoff.base_s = self.reconnect_interval_s
            if not self._backoff.ready():
                return False
            try:
                FP.hit(_FP_CONNECT)
                s = socket.create_connection((self.host, self.port), timeout=2.0)  # stlint: disable=blocking-under-lock — single-flight: _lock is only ever taken with blocking=False here, so no admission thread waits out this connect; the sole blocking acquirer is _teardown, off the admission path
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # the CONNECT timeout must not linger as a read deadline:
                # create_connection leaves it on the socket, and a server
                # quiet for 2 s (first-tick XLA compile, idle lulls) would
                # time out the reader thread's recv and tear down a
                # HEALTHY connection (found by the chaos harness, scenario
                # cluster_partition).  Response waits are bounded by the
                # per-request future timeout, not the socket.
                s.settimeout(None)
            except OSError:
                self._backoff.failure()
                return False
            self._sock = s
            self._reader = threading.Thread(
                target=self._read_loop, args=(s,), name="sentinel-token-client", daemon=True
            )
            self._reader.start()
        finally:
            self._lock.release()
        # announce namespace so the server's census counts us (PING)
        try:
            self._send_nowait(
                P.ClusterRequest(self._next_xid(), C.MSG_TYPE_PING, namespace=self.namespace)
            )
        except OSError:
            # the socket accepted the connect but died on the first write:
            # as unhealthy as a refused connect — keep the backoff ramping
            # so a flapping server isn't hammered at line rate
            self._backoff.failure()
            self._teardown(kind="send_fail")
            return False
        # protocol negotiation rides behind the PING, off the request
        # path: a v2 server answers with its version; a v1 server's
        # decoder rejects the unknown type and drops the frame, so the
        # future never resolves and a reaper timer pins this connection
        # to v1 framing.  Either way no request ever waits on it.
        try:
            hx = self._next_xid()
            hf: Future = Future()

            def _hello_done(fut: Future) -> None:
                try:
                    rsp = fut.result(timeout=0)
                except Exception:  # stlint: disable=fail-open — HELLO is a best-effort probe: any failure leaves the peer on v1 legacy framing, the conservative direction
                    return
                if (
                    rsp is not None
                    and rsp.status == C.STATUS_OK
                    and rsp.remaining >= 2
                ):
                    # speak the highest version BOTH sides know
                    self._peer_version = min(
                        C.PROTOCOL_VERSION, int(rsp.remaining)
                    )

            hf.add_done_callback(_hello_done)
            self._pend_add(hx, hf)

            def _hello_reap() -> None:
                f2 = self._pend_pop(hx)
                if f2 is not None and not f2.done():
                    f2.set_result(None)  # v1 peer: HELLO went unanswered

            self._send_nowait(
                P.ClusterRequest(hx, C.MSG_TYPE_HELLO, count=C.PROTOCOL_VERSION)
            )
            t = threading.Timer(self.timeout_ms / 1000.0, _hello_reap)
            t.daemon = True
            t.start()
        except OSError:
            self._pend_pop(hx)
            # PING already proved the socket once; a HELLO write failure
            # just leaves the connection on v1 until the next reconnect
        # NO backoff reset here: a connect (or even a buffered write)
        # proves nothing about server health — an accept-then-die flapper
        # would hold the backoff at attempt 0 forever and the fleet would
        # hammer it at line rate.  The reset lives in _read_loop, on the
        # first DECODED response (a real healthy exchange).
        return True

    def _teardown(self, kind: str = "conn_lost") -> None:
        with self._lock:
            s, self._sock = self._sock, None
            pending, self._pending = self._pending, {}
            self._peer_version = 1  # renegotiate on the next connection
        if pending:
            _G_INFLIGHT.dec(len(pending))
        if s is not None:
            # black-box journal: WHY a live connection went away (close /
            # send_fail / conn_lost) with how many requests it stranded
            FL.note(
                "cluster.conn.teardown",
                kind=kind,
                peer=f"{self.host}:{self.port}",
                in_flight=len(pending),
            )
            try:
                s.close()
            except OSError:
                pass
        for f in pending.values():
            if not f.done():
                f.set_result(None)

    def _read_loop(self, s: socket.socket) -> None:
        frames = P.FrameReader()
        try:
            while True:
                data = s.recv(4096)
                if not data:
                    break
                # chaos: drop => treated as peer-close, corrupt/short-read
                # => decode failures / frame desync below
                data = FP.pipe(_FP_RECV, data)
                if not data:
                    break
                for body in frames.feed(data):
                    try:
                        # BATCH responses carry column slabs the legacy
                        # decoder would misparse — route on the type byte
                        if P.peek_type(body) == C.MSG_TYPE_BATCH:
                            rsp = P.decode_batch_response(body)
                        else:
                            rsp = P.decode_response(body)
                    except (ValueError, struct.error):
                        _C_RPC_FAIL["decode"].inc()
                        continue  # malformed frame; xid never resolves -> caller times out to STATUS_FAIL
                    if self._backoff.attempt:
                        # first decoded response = the healthy exchange
                        # that resets the reconnect backoff ramp
                        self._backoff.success()
                    f = self._pend_pop(rsp.xid)
                    if f is not None and not f.done():
                        f.set_result(rsp)
        except OSError:
            pass
        finally:
            if self._sock is s:
                self._teardown()

    def _send_nowait(self, req: P.ClusterRequest) -> None:
        raw = P.encode_request(req)
        s = self._sock
        if s is None:
            raise OSError("not connected")
        with self._send_lock:
            s.sendall(raw)  # stlint: disable=blocking-under-lock — _send_lock IS the socket-write framing lock: serializing sendall is its entire purpose; replies arrive via the mux reader thread, never under it

    def _roundtrip(self, req: P.ClusterRequest) -> Optional[P.ClusterResponse]:
        if not self._ensure_connected():
            _C_RPC_FAIL["connect"].inc()
            return None
        _t = OT.t0()
        _attrs = None
        if _t:
            # distributed trace context: adopt the caller's ambient trace
            # (or start a fresh wire trace), mint this round-trip's span
            # id, and ride both on the frame's optional trace tail — the
            # server's decision spans re-install them (obs.trace.maybe_ctx)
            # so `--merge` can join the two processes' dumps with flow
            # events.  All of it is behind the one t0() flag check.
            tid, parent = OT.current_ctx()
            if not tid:
                tid = OT.new_trace_id()
            req.trace_id = tid
            req.span_id = OT.new_span_id()
            _attrs = {"type": req.type, "span_id": req.span_id}
            if parent:
                _attrs["parent"] = parent
        try:
            raw = P.encode_request(req)
        except (ValueError, struct.error):
            return _BAD_REQUEST  # unencodable request; connection is fine
        f: Future = Future()
        self._pend_add(req.xid, f)
        try:
            s = self._sock
            if s is None:
                raise OSError("not connected")
            # chaos: raise => this send path's degrade; drop/corrupt =>
            # the server never answers this xid => timeout kind
            raw = FP.pipe(_FP_SEND, raw)
            with self._send_lock:
                s.sendall(raw)  # stlint: disable=blocking-under-lock — _send_lock IS the socket-write framing lock: serializing sendall is its entire purpose; replies arrive via the mux reader thread, never under it
        except OSError:
            self._pend_pop(req.xid)
            self._teardown(kind="send_fail")
            _C_RPC_FAIL["send"].inc()
            if _t:
                # failures skip the latency histogram (a timeout-ceiling
                # sample would corrupt the success-path percentiles; the
                # failure RATE lives in _C_RPC_FAIL) — the span keeps the
                # duration for trace-level diagnosis
                OT.stage(
                    "cluster.rpc", _t, trace=req.trace_id,
                    attrs=dict(_attrs, ok=False),
                )
            return None
        try:
            rsp = f.result(timeout=self.timeout_ms / 1000.0)
        except (_FutTimeout, CancelledError):
            self._pend_pop(req.xid)
            _C_RPC_FAIL["timeout"].inc()
            if _t:
                OT.stage(
                    "cluster.rpc", _t, trace=req.trace_id,
                    attrs=dict(_attrs, ok=False),
                )
            return None  # -> STATUS_FAIL at the TokenService surface (degrade, never PASS)
        if rsp is None:
            _C_RPC_FAIL["conn_lost"].inc()  # connection died mid-wait (_teardown resolved us)
        if _t:
            OT.stage(
                "cluster.rpc", _t, _H_RPC if rsp is not None else None,
                trace=req.trace_id,
                attrs=dict(_attrs, ok=rsp is not None),
            )
        return rsp

    # -- TokenService --------------------------------------------------------

    def request_token(self, flow_id: int, count: int = 1, prioritized: bool = False) -> TokenResult:
        rsp = self._roundtrip(
            P.ClusterRequest(
                self._next_xid(), C.MSG_TYPE_FLOW, flow_id=flow_id, count=count, priority=prioritized
            )
        )
        if rsp is None:
            return TokenResult(C.STATUS_FAIL)
        return TokenResult(rsp.status, remaining=rsp.remaining, wait_ms=rsp.wait_ms)

    def request_token_batch(self, flow_id: int, units: int) -> TokenResult:
        if self._peer_version >= 3:
            # v3 peers answer over a BATCH frame so a deny carries its
            # provenance (_T_PROV); one entry is still one round trip
            return self.request_batch([(C.BATCH_KIND_FLOW_BATCH, flow_id, units)])[0]
        rsp = self._roundtrip(
            P.ClusterRequest(
                self._next_xid(), C.MSG_TYPE_FLOW_BATCH, flow_id=flow_id, count=units
            )
        )
        if rsp is None:
            return TokenResult(C.STATUS_FAIL)
        return TokenResult(rsp.status, remaining=rsp.remaining, wait_ms=rsp.wait_ms)

    @property
    def peer_version(self) -> int:
        return self._peer_version

    def request_batch(
        self, entries: Sequence[Tuple[int, ...]]
    ) -> List[TokenResult]:
        """Many token requests in ONE wire exchange.

        ``entries`` is a sequence of ``(kind, flow_id, count)`` or
        ``(kind, flow_id, count, flags)`` tuples (kind is a
        C.BATCH_KIND_* constant).  Against a v2 peer the whole list rides
        one BATCH frame; against a v1 peer the entries are pipelined as
        individual frames on the same connection — all sends first, then
        one collection pass — so wall clock is one round-trip either
        way.  Transport failure fails every entry CLOSED (STATUS_FAIL):
        partial answers from a corrupted frame are never applied."""
        n = len(entries)
        if n == 0:
            return []
        if not self._ensure_connected():
            _C_RPC_FAIL["connect"].inc()
            return [TokenResult(C.STATUS_FAIL)] * n
        if self._peer_version >= 2 and n <= C.MAX_BATCH_ENTRIES:
            return self._request_batch_v2(entries)
        return self._request_batch_v1(entries)

    def _request_batch_v2(self, entries) -> List[TokenResult]:
        n = len(entries)
        flags = np.array([e[3] if len(e) > 3 else 0 for e in entries], np.uint8)
        if self._peer_version >= 3:
            # ask a v3 server to explain its denies (_T_PROV block); a v2
            # server never sees the flag, so its frames stay byte-identical
            flags |= np.uint8(C.BATCH_FLAG_EXPLAIN)
        req = P.ClusterBatchRequest(
            xid=self._next_xid(),
            kinds=np.array([e[0] for e in entries], np.uint8),
            ids=np.array([e[1] for e in entries], np.int64),
            counts=np.array([e[2] for e in entries], np.int32),
            flags=flags,
        )
        _t = OT.t0()
        _attrs = None
        if _t:
            tid, parent = OT.current_ctx()
            if not tid:
                tid = OT.new_trace_id()
            req.trace_id = tid
            req.span_id = OT.new_span_id()
            _attrs = {"type": C.MSG_TYPE_BATCH, "n": n, "span_id": req.span_id}
            if parent:
                _attrs["parent"] = parent
        try:
            raw = P.encode_batch_request(req)
        except (ValueError, struct.error):
            return [TokenResult(C.STATUS_BAD_REQUEST)] * n
        f: Future = Future()
        self._pend_add(req.xid, f)
        try:
            s = self._sock
            if s is None:
                raise OSError("not connected")
            raw = FP.pipe(_FP_SEND, raw)
            with self._send_lock:
                s.sendall(raw)  # stlint: disable=blocking-under-lock — _send_lock IS the socket-write framing lock: serializing sendall is its entire purpose; replies arrive via the mux reader thread, never under it
        except OSError:
            self._pend_pop(req.xid)
            self._teardown(kind="send_fail")
            _C_RPC_FAIL["send"].inc()
            if _t:
                OT.stage(
                    "cluster.rpc", _t, trace=req.trace_id,
                    attrs=dict(_attrs, ok=False),
                )
            return [TokenResult(C.STATUS_FAIL)] * n
        try:
            rsp = f.result(timeout=self.timeout_ms / 1000.0)
        except (_FutTimeout, CancelledError):
            self._pend_pop(req.xid)
            _C_RPC_FAIL["timeout"].inc()
            rsp = None
        if rsp is None and not self.connected:
            _C_RPC_FAIL["conn_lost"].inc()
        if _t:
            OT.stage(
                "cluster.rpc", _t, _H_RPC if rsp is not None else None,
                trace=req.trace_id, attrs=dict(_attrs, ok=rsp is not None),
            )
        # whole-frame fail-closed: a non-OK frame status or an entry-count
        # mismatch means NO entry verdict can be trusted
        if (
            rsp is None
            or not isinstance(rsp, P.ClusterBatchResponse)
            or rsp.status != C.STATUS_OK
            or len(rsp) != n
        ):
            return [TokenResult(C.STATUS_FAIL)] * n
        out = []
        for i in range(n):
            pv = rsp.prov[i] if rsp.prov is not None else None
            out.append(
                TokenResult(
                    int(rsp.statuses[i]),
                    remaining=int(rsp.remainings[i]),
                    wait_ms=int(rsp.waits[i]),
                    token_id=int(rsp.token_ids[i]),
                    prov_kind=pv[0] if pv else None,
                    prov_rule=pv[1] if pv else None,
                    prov_observed=pv[2] if pv else None,
                    prov_limit=pv[3] if pv else None,
                )
            )
        return out

    _BATCH_KIND_TO_MSG = {
        C.BATCH_KIND_FLOW: C.MSG_TYPE_FLOW,
        C.BATCH_KIND_FLOW_BATCH: C.MSG_TYPE_FLOW_BATCH,
        C.BATCH_KIND_LEASE: C.MSG_TYPE_LEASE,
    }

    def _request_batch_v1(self, entries) -> List[TokenResult]:
        n = len(entries)
        out: List[Optional[TokenResult]] = [None] * n
        waiters: List[Tuple[int, int, Future]] = []
        for i, e in enumerate(entries):
            mt = self._BATCH_KIND_TO_MSG.get(int(e[0]))
            if mt is None:
                out[i] = TokenResult(C.STATUS_BAD_REQUEST)
                continue
            prio = bool((e[3] if len(e) > 3 else 0) & C.BATCH_FLAG_PRIORITIZED)
            req = P.ClusterRequest(
                self._next_xid(), mt, flow_id=int(e[1]), count=int(e[2]),
                priority=prio,
            )
            f: Future = Future()
            self._pend_add(req.xid, f)
            try:
                raw = FP.pipe(_FP_SEND, P.encode_request(req))
                s = self._sock
                if s is None:
                    raise OSError("not connected")
                with self._send_lock:
                    s.sendall(raw)  # stlint: disable=blocking-under-lock — _send_lock IS the socket-write framing lock: serializing sendall is its entire purpose; replies arrive via the mux reader thread, never under it
            except (ValueError, struct.error):
                self._pend_pop(req.xid)
                out[i] = TokenResult(C.STATUS_BAD_REQUEST)
                continue
            except OSError:
                self._pend_pop(req.xid)
                self._teardown(kind="send_fail")
                _C_RPC_FAIL["send"].inc()
                out[i] = TokenResult(C.STATUS_FAIL)
                continue
            waiters.append((i, req.xid, f))
        # one shared deadline for the whole pipeline: the responses were
        # all in flight before the first wait started
        end = mono_s() + self.timeout_ms / 1000.0
        for i, xid, f in waiters:
            try:
                rsp = f.result(timeout=max(0.0, end - mono_s()))
            except (_FutTimeout, CancelledError):
                self._pend_pop(xid)
                _C_RPC_FAIL["timeout"].inc()
                rsp = None
            if rsp is None:
                out[i] = TokenResult(C.STATUS_FAIL)
            else:
                out[i] = TokenResult(
                    rsp.status, remaining=rsp.remaining,
                    wait_ms=rsp.wait_ms, token_id=rsp.token_id,
                )
        return [r if r is not None else TokenResult(C.STATUS_FAIL) for r in out]

    def request_param_token(self, flow_id: int, count: int, params: List[Any]) -> TokenResult:
        rsp = self._roundtrip(
            P.ClusterRequest(
                self._next_xid(), C.MSG_TYPE_PARAM_FLOW, flow_id=flow_id, count=count, params=params
            )
        )
        if rsp is None:
            return TokenResult(C.STATUS_FAIL)
        return TokenResult(rsp.status, remaining=rsp.remaining, wait_ms=rsp.wait_ms)

    def request_lease(self, flow_id: int, units: int) -> TokenResult:
        rsp = self._roundtrip(
            P.ClusterRequest(
                self._next_xid(), C.MSG_TYPE_LEASE, flow_id=flow_id, count=units
            )
        )
        if rsp is None:
            return TokenResult(C.STATUS_FAIL)
        return TokenResult(rsp.status, remaining=rsp.remaining, wait_ms=rsp.wait_ms)

    def request_concurrent_token(self, flow_id: int, count: int = 1) -> TokenResult:
        rsp = self._roundtrip(
            P.ClusterRequest(
                self._next_xid(), C.MSG_TYPE_CONCURRENT_ACQUIRE, flow_id=flow_id, count=count
            )
        )
        if rsp is None:
            return TokenResult(C.STATUS_FAIL)
        return TokenResult(rsp.status, token_id=rsp.token_id)

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        rsp = self._roundtrip(
            P.ClusterRequest(self._next_xid(), C.MSG_TYPE_CONCURRENT_RELEASE, token_id=token_id)
        )
        if rsp is None:
            return TokenResult(C.STATUS_FAIL)
        return TokenResult(rsp.status)
