"""Cluster role management (ClusterStateManager.java:38-137).

A process is NOT_STARTED, a token CLIENT (remote server), or a token SERVER
(embedded: serves the network *and* its own in-process traffic).  Roles flip
at runtime; the manager owns the lifecycle of the underlying client/server
objects and exposes the TokenService the local runtime should consult for
cluster-mode rules.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.rules import ClusterClientConfigManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService, TokenService

CLUSTER_NOT_STARTED = -1
CLUSTER_CLIENT = 0
CLUSTER_SERVER = 1


class ClusterStateManager:
    def __init__(self, client_config: Optional[ClusterClientConfigManager] = None):
        self.mode = CLUSTER_NOT_STARTED
        self.client_config = client_config or ClusterClientConfigManager()
        self._lock = threading.Lock()
        self._token_client: Optional[ClusterTokenClient] = None
        self._server: Optional[ClusterTokenServer] = None
        self._embedded: Optional[DefaultTokenService] = None

    # -- queries -------------------------------------------------------------

    def token_service(self) -> Optional[TokenService]:
        """The TokenService local cluster-mode rules should consult."""
        if self.mode == CLUSTER_CLIENT:
            return self._token_client
        if self.mode == CLUSTER_SERVER:
            return self._embedded
        return None

    def is_available(self) -> bool:
        svc = self.token_service()
        if svc is None:
            return False
        if isinstance(svc, ClusterTokenClient):
            return svc.connected or svc._ensure_connected()
        return True

    # -- transitions ---------------------------------------------------------

    def set_to_client(
        self,
        host: Optional[str] = None,
        port: Optional[int] = None,
        namespace: str = C.DEFAULT_NAMESPACE,
    ) -> None:
        with self._lock:
            # keep the token service so a later set_to_server (dashboard
            # re-assignment) can revive this machine as a server
            if self._embedded is not None:
                self._last_service = self._embedded
            self._stop_server_locked()
            if host is not None:
                self.client_config.apply_assign(host, port or C.DEFAULT_PORT)
            a = self.client_config.assign
            if self._token_client is not None:
                self._token_client.close()
            self._token_client = ClusterTokenClient(
                a.host,
                a.port,
                namespace=namespace,
                timeout_ms=self.client_config.request_timeout_ms,
            )
            self._token_client.start()
            self.mode = CLUSTER_CLIENT

    def set_to_sharded_client(
        self,
        members,
        namespace: str = C.DEFAULT_NAMESPACE,
        **sharded_kw,
    ) -> None:
        """Become a client of an N-shard token FLEET (cluster/shard.py):
        cluster-mode rules consult a ``ShardedTokenClient`` that routes
        each flow to its ring owner.  Failover lives INSIDE the sharded
        client: a dead shard's flows serve from its bounded-slack lease
        and then fail CLOSED — always STATUS_BLOCKED, never STATUS_FAIL
        — so the runtime's cluster-degrade hysteresis and rules'
        ``cluster_fallback_to_local`` do NOT engage behind a fleet.  A
        total-fleet outage blocks cluster-ruled traffic rather than
        reverting to unmetered local enforcement (token conservation
        over availability, the fleet's fail-closed-on-ambiguity law).

        Lease sizing needs the flow thresholds: feed the same rules the
        shard servers hold through ``token_service().flow_rules.load``
        (the client's built-in threshold-learning facade) — without them
        every flow's lease is zero and shard failover fails closed
        immediately."""
        from sentinel_tpu.cluster.shard import ShardedTokenClient

        with self._lock:
            if self._embedded is not None:
                self._last_service = self._embedded
            self._stop_server_locked()
            if self._token_client is not None:
                self._token_client.close()
            sharded_kw.setdefault(
                "timeout_ms", self.client_config.request_timeout_ms
            )
            self._token_client = ShardedTokenClient(
                dict(members),
                namespace=namespace,
                **sharded_kw,
            )
            self._token_client.start()
            self.mode = CLUSTER_CLIENT

    def set_to_server(
        self,
        token_service: DefaultTokenService,
        port: Optional[int] = None,
        serve_network: bool = True,
    ) -> None:
        """Become an (embedded) token server: local traffic consults the
        in-process service directly (DefaultEmbeddedTokenServer)."""
        with self._lock:
            if self._token_client is not None:
                self._token_client.close()
                self._token_client = None
            # idempotent: a machine already in server mode (dashboard
            # re-assign) must not double-bind its port
            self._stop_server_locked()
            self._embedded = token_service
            self._last_service = token_service
            if serve_network:
                self._server = ClusterTokenServer(token_service, port=port)
                self._server.start()
            self.mode = CLUSTER_SERVER

    def stop(self) -> None:
        with self._lock:
            if self._token_client is not None:
                self._token_client.close()
                self._token_client = None
            self._stop_server_locked()
            self._embedded = None
            self.mode = CLUSTER_NOT_STARTED

    def _stop_server_locked(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None

    @property
    def server(self) -> Optional[ClusterTokenServer]:
        return self._server
