"""Cluster token server: TCP front door over the decision engine.

The reference's Netty server (NettyTransportServer.java:88-93 pipeline →
TokenServerHandler.java:61-75 dispatch) becomes an asyncio TCP server in a
daemon thread: frames decode on the event loop, token decisions execute in a
small thread pool (the decision client's check_batch blocks on the engine
tick, which must not stall the loop).

Connection bookkeeping mirrors ConnectionManager/ConnectionGroup: a client's
first PING carries its namespace; the per-namespace connected count scales
AVG_LOCAL thresholds (DefaultTokenService.refresh_connected_count).  Idle
connections are reaped on a timer (ScanIdleConnectionTask).
"""

from __future__ import annotations

import asyncio
import struct
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

import numpy as np

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.cluster import constants as C
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.utils.time_source import mono_s
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.token_service import DefaultTokenService, TokenResult
from sentinel_tpu.utils.record_log import record_log

#: chaos failpoint covering server-side request processing (worker-pool
#: types incl. RES_CHECK shard chunks); a raise converts to STATUS_FAIL.
#: Its HIT COUNT doubles as the chaos harness's server-side "chunks
#: processed" probe — the no-replay invariant reads it.
_FP_PROCESS = FP.register(
    "cluster.server.process", "token server request processing", FP.HIT_ACTIONS
)

#: chaos failpoint on the protocol-v2 BATCH frame transport: corrupt /
#: short_read mangle the frame bytes before decode, which must fail the
#: WHOLE frame closed — partial answers are never applied
_FP_BATCH = FP.register(
    "cluster.batch.frame", "protocol-v2 batch frame transport", FP.PIPE_ACTIONS
)


class ConnectionManager:
    """namespace → live connection census (ConnectionManager/ConnectionGroup)."""

    def __init__(self, on_change=None):
        self._lock = threading.Lock()
        self._groups: Dict[str, set] = {}
        self._conn_ns: Dict[int, str] = {}
        self._on_change = on_change

    def register(self, conn_id: int, namespace: str) -> None:
        with self._lock:
            old = self._conn_ns.get(conn_id)
            if old is not None:
                self._groups.get(old, set()).discard(conn_id)
            self._conn_ns[conn_id] = namespace
            self._groups.setdefault(namespace, set()).add(conn_id)
        if self._on_change:
            self._on_change()

    def remove(self, conn_id: int) -> None:
        with self._lock:
            ns = self._conn_ns.pop(conn_id, None)
            if ns is not None:
                self._groups.get(ns, set()).discard(conn_id)
        if ns is not None and self._on_change:
            self._on_change()

    def connected_count(self, namespace: str) -> int:
        return len(self._groups.get(namespace, ()))


class ClusterTokenServer:
    """Standalone token server (SentinelDefaultTokenServer analog).

    ``start()`` spins the asyncio loop in a daemon thread and returns once
    the socket is listening; ``port`` may be 0 to bind an ephemeral port
    (tests) — the bound port is then available as ``.port``.
    """

    def __init__(
        self,
        token_service: DefaultTokenService,
        host: str = "0.0.0.0",
        port: Optional[int] = None,
        idle_seconds: Optional[int] = None,
        workers: int = 8,
    ):
        self.service = token_service
        self.host = host
        cfg = token_service.config.transport
        self.port = cfg.port if port is None else port
        self.idle_seconds = cfg.idle_seconds if idle_seconds is None else idle_seconds
        self._pool = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="tok")
        # census changes fire on the event loop (PING / disconnect); the
        # reprojection they may trigger recompiles engine rules, so run it
        # on the worker pool instead of stalling the loop
        def _census_changed():
            try:
                self._pool.submit(token_service.refresh_connected_count)
            except RuntimeError:
                pass  # pool already shut down (server stopping)

        self.connections = ConnectionManager(on_change=_census_changed)
        self.service.connected_count_fn = self.connections.connected_count
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._conn_seq = 0
        self._last_active: Dict[int, float] = {}
        self._writers: Dict[int, asyncio.StreamWriter] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run_loop, name="sentinel-token-server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("token server failed to start")

    def stop(self) -> None:
        loop = self._loop
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._pool.shutdown(wait=False)

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def _boot():
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self.port
            )
            self.port = self._server.sockets[0].getsockname()[1]
            loop.create_task(self._idle_scan())
            loop.create_task(self._expire_scan())
            self._started.set()

        loop.run_until_complete(_boot())
        try:
            loop.run_forever()
        finally:
            if self._server is not None:
                self._server.close()
            pending = asyncio.all_tasks(loop)
            for t in pending:
                t.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            loop.close()

    # -- periodic tasks ------------------------------------------------------

    async def _idle_scan(self) -> None:
        # close idle sockets (ScanIdleConnectionTask): the census entry is
        # removed by the handler's finally-block, and a still-alive client
        # reconnects + re-PINGs, so connectedCount stays truthful
        while True:
            await asyncio.sleep(min(self.idle_seconds, 30))
            cutoff = mono_s() - self.idle_seconds
            for cid, last in list(self._last_active.items()):
                if last < cutoff:
                    w = self._writers.get(cid)
                    if w is not None:
                        try:
                            w.close()
                        except Exception:
                            pass

    async def _expire_scan(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            self.service.concurrent.expire(self.service.client.time.now_ms())

    # -- per-connection protocol --------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._conn_seq += 1
        cid = self._conn_seq
        frames = P.FrameReader()
        self._last_active[cid] = mono_s()
        self._writers[cid] = writer
        loop = asyncio.get_running_loop()
        try:
            while True:
                data = await reader.read(4096)
                if not data:
                    break
                self._last_active[cid] = mono_s()
                for body in frames.feed(data):
                    if P.peek_type(body) == C.MSG_TYPE_BATCH:
                        loop.create_task(self._batch_and_reply(body, writer))
                        continue
                    try:
                        req = P.decode_request(body)
                    except (ValueError, struct.error, IndexError):
                        # malformed frame — drop it, server stays up
                        # (IndexError: _unpack_params indexing a truncated
                        # param buffer; must not escape to the connection
                        # handler and kill every pipelined request)
                        continue
                    if req.type == C.MSG_TYPE_PING:
                        self.connections.register(cid, req.namespace or C.DEFAULT_NAMESPACE)
                        writer.write(
                            P.encode_response(
                                P.ClusterResponse(req.xid, req.type, C.STATUS_OK)
                            )
                        )
                        continue
                    if req.type == C.MSG_TYPE_HELLO:
                        # version negotiation: answer our protocol version
                        # inline.  A v1 server never gets here — its
                        # decoder rejects type HELLO, the frame is dropped
                        # above, and the client's HELLO times out, pinning
                        # the connection to v1 framing.
                        writer.write(
                            P.encode_response(
                                P.ClusterResponse(
                                    req.xid, req.type, C.STATUS_OK,
                                    remaining=C.PROTOCOL_VERSION,
                                    trace_id=req.trace_id, span_id=req.span_id,
                                )
                            )
                        )
                        continue
                    # one task per request: pipelined requests on a single
                    # connection run concurrently so they coalesce into
                    # engine micro-batches (xid correlation makes
                    # out-of-order replies safe); awaiting inline would
                    # serialize a connection at one tick per request.
                    # FLOW requests take the fully-async path (a queued
                    # future, no worker thread) so in-flight count is
                    # unbounded; other types go through the worker pool.
                    if req.type == C.MSG_TYPE_FLOW:
                        loop.create_task(self._flow_and_reply(req, writer))
                    else:
                        loop.create_task(self._process_and_reply(req, writer))
                await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        except Exception:  # stlint: disable=fail-open — connection dies (finally cleans census), peer times out to STATUS_FAIL and degrades
            record_log().exception("token server connection error")
        finally:
            self._last_active.pop(cid, None)
            self._writers.pop(cid, None)
            self.connections.remove(cid)
            try:
                writer.close()
            except Exception:
                pass

    async def _process_and_reply(
        self, req: P.ClusterRequest, writer: asyncio.StreamWriter
    ) -> None:
        loop = asyncio.get_running_loop()
        rsp = await loop.run_in_executor(self._pool, self._process, req)
        try:
            writer.write(P.encode_response(rsp))
            await writer.drain()
        except (ConnectionResetError, OSError):
            pass  # peer vanished mid-reply

    async def _flow_and_reply(
        self, req: P.ClusterRequest, writer: asyncio.StreamWriter
    ) -> None:
        """Thread-free token grant: request_token_async queues the acquire
        into the decision engine's next micro-batch and the reply writes
        when its future resolves — no per-request worker, so the in-flight
        ceiling is the engine batch size, not the pool size."""
        try:
            # adopt the frame's trace context for the synchronous part of
            # the decision (the token.decision span begins in here), so
            # the server-side span carries the client's trace id + parent
            with OT.maybe_ctx(req.trace_id, req.span_id):
                fut = self.service.request_token_async(
                    req.flow_id, req.count, req.priority
                )
            # bounded wait: a wedged engine must produce STATUS_FAIL, not a
            # silently hung connection (the worker-pool path got this from
            # check_batch's entry timeout)
            r = await asyncio.wait_for(
                asyncio.wrap_future(fut),
                timeout=self.service.client.entry_timeout_s + 1.0,
            )
            rsp = P.ClusterResponse(
                req.xid, req.type, r.status, remaining=r.remaining,
                wait_ms=r.wait_ms, trace_id=req.trace_id, span_id=req.span_id,
            )
        except Exception:  # stlint: disable=fail-open — converted to STATUS_FAIL: an explicit degrade signal, never a PASS
            record_log().exception("token request failed")
            rsp = P.ClusterResponse(
                req.xid, req.type, C.STATUS_FAIL,
                trace_id=req.trace_id, span_id=req.span_id,
            )
        try:
            writer.write(P.encode_response(rsp))
            await writer.drain()
        except (ConnectionResetError, OSError):
            pass  # peer vanished mid-reply

    async def _batch_and_reply(self, body: bytes, writer: asyncio.StreamWriter) -> None:
        """Protocol-v2 BATCH frame: chaos pipe → strict decode → ONE
        worker-pool decision over the whole frame.

        Any transport mangling fails the WHOLE frame CLOSED: if the xid
        is still readable the client gets a single frame-level
        STATUS_FAIL covering every entry; otherwise the frame is dropped
        and the client times out.  Partial answers are never applied."""
        loop = asyncio.get_running_loop()
        try:
            breq = P.decode_batch_request(FP.pipe(_FP_BATCH, body))
        except Exception:  # stlint: disable=fail-open — this handler IS the fail-closed path: the whole frame is answered STATUS_FAIL (or dropped), partial answers never applied
            xid = None
            if len(body) >= 4:
                try:
                    xid = struct.unpack_from(">i", body, 0)[0]
                except struct.error:
                    xid = None
            if xid is not None:
                rsp = P.ClusterBatchResponse(
                    xid, C.STATUS_FAIL,
                    np.zeros(0, np.int8), np.zeros(0, np.int32),
                    np.zeros(0, np.int32), np.zeros(0, np.int64),
                )
                try:
                    writer.write(P.encode_batch_response(rsp))
                    await writer.drain()
                except (ConnectionResetError, OSError):
                    pass
            return
        rsp = await loop.run_in_executor(self._pool, self._process_batch, breq)
        try:
            writer.write(P.encode_batch_response(rsp))
            await writer.drain()
        except (ConnectionResetError, OSError):
            pass  # peer vanished mid-reply

    def _process_batch(self, breq: P.ClusterBatchRequest) -> P.ClusterBatchResponse:
        n = len(breq)
        # the frame's trace context rides this worker thread, so the
        # column decision spans adopt the caller's trace id
        with OT.maybe_ctx(breq.trace_id, breq.span_id):
            try:
                FP.hit(_FP_PROCESS)
                statuses, remainings, waits, token_ids, prov = self.service.decide_frame(
                    breq.kinds, breq.ids, breq.counts, breq.flags
                )
                status = C.STATUS_OK
                # v3 deny provenance: attach only for entries that asked
                # (BATCH_FLAG_EXPLAIN) — a pre-v3 client never set the
                # flag, so its response stays byte-identical to v2
                prov = [
                    pv if int(breq.flags[i]) & C.BATCH_FLAG_EXPLAIN else None
                    for i, pv in enumerate(prov)
                ]
                if not any(pv is not None for pv in prov):
                    prov = None
            except Exception:  # stlint: disable=fail-open — whole-frame STATUS_FAIL: every entry degrades, none passes
                record_log().exception("batch frame processing failed")
                statuses = np.full(n, C.STATUS_FAIL, np.int8)
                remainings = np.zeros(n, np.int32)
                waits = np.zeros(n, np.int32)
                token_ids = np.zeros(n, np.int64)
                status = C.STATUS_FAIL
                prov = None
        return P.ClusterBatchResponse(
            breq.xid, status, statuses, remainings, waits, token_ids,
            trace_id=breq.trace_id, span_id=breq.span_id, prov=prov,
        )

    def _process(self, req: P.ClusterRequest) -> P.ClusterResponse:
        # install the frame's trace context on this worker thread so every
        # decision span recorded below (token.decision*, server.res_check)
        # adopts the caller's trace id and parents to its RPC span
        with OT.maybe_ctx(req.trace_id, req.span_id):
            rsp = self._process_inner(req)
        rsp.trace_id, rsp.span_id = req.trace_id, req.span_id
        return rsp

    def _process_inner(self, req: P.ClusterRequest) -> P.ClusterResponse:
        try:
            FP.hit(_FP_PROCESS)
            t = req.type
            if t == C.MSG_TYPE_FLOW:
                r = self.service.request_token(req.flow_id, req.count, req.priority)
            elif t == C.MSG_TYPE_FLOW_BATCH:
                r = self.service.request_token_batch(req.flow_id, req.count)
            elif t == C.MSG_TYPE_PARAM_FLOW:
                r = self.service.request_param_token(req.flow_id, req.count, req.params)
            elif t == C.MSG_TYPE_CONCURRENT_ACQUIRE:
                r = self.service.request_concurrent_token(req.flow_id, req.count)
            elif t == C.MSG_TYPE_CONCURRENT_RELEASE:
                r = self.service.release_concurrent_token(req.token_id)
            elif t == C.MSG_TYPE_LEASE:
                r = self.service.request_lease(req.flow_id, req.count)
            elif t == C.MSG_TYPE_RES_CHECK:
                # host-shard resource batch (parallel/remote_shard.py):
                # params = flat (name, count, prio, origin, param) 5-tuples
                names = [str(x) for x in req.params[0::5]]
                counts = [int(x) for x in req.params[1::5]]
                prios = [bool(x) for x in req.params[2::5]]
                origins = [str(x) for x in req.params[3::5]]
                pvals = []
                for x in req.params[4::5]:
                    xs = str(x)
                    if not xs:
                        pvals.append(None)
                    elif xs.startswith("i:"):
                        try:
                            pvals.append(int(xs[2:]))
                        except ValueError:
                            pvals.append(xs[2:])
                    elif xs.startswith("s:"):
                        pvals.append(xs[2:])
                    else:  # legacy/bare value
                        pvals.append(xs)
                # server-side chunk span: adopts the ambient trace ctx
                # installed by _process, so the shard client's per-chunk
                # span and this one share a trace id across the wire
                with OT.TRACER.span("server.res_check", items=len(names)):
                    res = self.service.client.check_batch(
                        names,
                        counts=counts,
                        prioritized=prios,
                        origins=origins if any(origins) else None,
                        params=pvals if any(p is not None for p in pvals) else None,
                    )
                return P.ClusterResponse(
                    req.xid, t, C.STATUS_OK, items=[(int(v), int(w)) for v, w in res]
                )
            else:
                r = TokenResult(C.STATUS_BAD_REQUEST)
        except Exception:  # stlint: disable=fail-open — converted to STATUS_FAIL: an explicit degrade signal, never a PASS
            record_log().exception("token request processing failed")
            r = TokenResult(C.STATUS_FAIL)
        return P.ClusterResponse(
            xid=req.xid,
            type=req.type,
            status=r.status,
            remaining=r.remaining,
            wait_ms=r.wait_ms,
            token_id=r.token_id,
        )
