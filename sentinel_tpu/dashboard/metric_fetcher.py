"""Metric fetcher — polls every healthy machine's ``metric`` command.

The analog of MetricFetcher.java:70-88: a loop wakes ~every second, asks
each healthy machine for metric-log lines since the machine's last fetched
second (with a catch-up window capped at ``max_catchup_ms`` — reference 15 s
:74,263-282), and saves parsed nodes into the repository keyed by app.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from sentinel_tpu.dashboard.api_client import SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository
from sentinel_tpu.obs.registry import REGISTRY as _OBS
from sentinel_tpu.utils.time_source import wall_ms_now

DEFAULT_INTERVAL_S = 1.0
DEFAULT_MAX_CATCHUP_MS = 15_000

# dashboard self-observability: a silently failing fetch loop used to be
# invisible — the repository just stopped filling.  Now every machine
# pull (metric-log line fetch or /metrics scrape) counts by outcome, and
# the last-success gauge gives alerting a freshness signal.
_FETCH_HELP = "dashboard machine pulls (metric fetch + prometheus scrape) by outcome"
_C_FETCH_OK = _OBS.counter(
    "sentinel_dashboard_fetch_total", _FETCH_HELP, labels={"result": "ok"}
)
_C_FETCH_ERR = _OBS.counter(
    "sentinel_dashboard_fetch_total", _FETCH_HELP, labels={"result": "error"}
)
_G_LAST_SUCCESS = _OBS.gauge(
    "sentinel_dashboard_last_success_ms",
    "wall-clock ms of the dashboard's last successful machine pull",
)


class MetricFetcher:
    def __init__(
        self,
        discovery: AppManagement,
        repository: InMemoryMetricsRepository,
        api: Optional[SentinelApiClient] = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        max_catchup_ms: int = DEFAULT_MAX_CATCHUP_MS,
    ):
        self.discovery = discovery
        self.repository = repository
        self.api = api or SentinelApiClient(timeout_s=2.0)
        self.interval_s = interval_s
        self.max_catchup_ms = max_catchup_ms
        self._last_fetched_ms: Dict[str, int] = {}  # machine key → last second pulled
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fetch_ok = 0
        self.fetch_fail = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="sentinel-tpu-metric-fetcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def fetch_once(self, now_ms: Optional[int] = None) -> int:
        """One sweep over all healthy machines; returns #nodes saved."""
        now_ms = wall_ms_now() if now_ms is None else now_ms
        saved = 0
        for app in self.discovery.apps():
            for m in self.discovery.machines(app, only_healthy=True):
                # fetch up to the PREVIOUS full second — the current second
                # is still being written on the machine
                end = (now_ms // 1000) * 1000 - 1000
                # first fetch looks back the whole catch-up window so a
                # dashboard restart doesn't lose the recent history
                start = self._last_fetched_ms.get(m.key, end - self.max_catchup_ms)
                start = max(start, end - self.max_catchup_ms)
                if start > end:
                    continue
                try:
                    nodes = self.api.fetch_metric(m.ip, m.port, start, end)
                    self.fetch_ok += 1
                    _C_FETCH_OK.inc()
                    _G_LAST_SUCCESS.set(wall_ms_now())
                except OSError:
                    self.fetch_fail += 1
                    _C_FETCH_ERR.inc()
                    continue
                if nodes:
                    self.repository.save_all(app, nodes)
                    saved += len(nodes)
                    self._last_fetched_ms[m.key] = max(n.timestamp for n in nodes) + 1000
                else:
                    self._last_fetched_ms[m.key] = end
        return saved

    def fetch_timelines(
        self,
        resource: Optional[str] = None,
        start_ms: int = 0,
        end_ms: Optional[int] = None,
        app: Optional[str] = None,
    ) -> int:
        """One sweep of every healthy machine's ``GET /api/metric``
        (obs/timeline.py rows), saved into the repository PER MACHINE —
        ``repository.query_timeline`` then merges machines on second
        boundaries with per-machine provenance.  Returns #rows saved;
        unreachable machines are counted in ``fetch_fail``."""
        saved = 0
        apps = [app] if app is not None else self.discovery.apps()
        for a in apps:
            for m in self.discovery.machines(a, only_healthy=True):
                try:
                    rows = self.api.fetch_timeline(
                        m.ip, m.port, resource, start_ms, end_ms
                    )
                    self.fetch_ok += 1
                    _C_FETCH_OK.inc()
                    _G_LAST_SUCCESS.set(wall_ms_now())
                except OSError:
                    self.fetch_fail += 1
                    _C_FETCH_ERR.inc()
                    continue
                if rows:
                    self.repository.save_timeline(a, m.key, rows)
                    saved += len(rows)
        return saved

    def scrape_prometheus(self, app: Optional[str] = None) -> Dict[str, str]:
        """One sweep of every healthy machine's ``GET /metrics`` — the
        obs-plane exposition (tick-stage histograms, pipeline occupancy,
        degrade state) keyed by machine, alongside the metric-log poll.
        Unreachable machines are skipped (counted in ``fetch_fail``)."""
        out: Dict[str, str] = {}
        apps = [app] if app is not None else self.discovery.apps()
        for a in apps:
            for m in self.discovery.machines(a, only_healthy=True):
                try:
                    out[m.key] = self.api.fetch_prometheus(m.ip, m.port)
                    self.fetch_ok += 1
                    _C_FETCH_OK.inc()
                    _G_LAST_SUCCESS.set(wall_ms_now())
                except OSError:
                    self.fetch_fail += 1
                    _C_FETCH_ERR.inc()
        return out

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.fetch_once()
            except Exception:  # noqa: BLE001 — the poll loop must survive anything
                from sentinel_tpu.utils.record_log import record_log

                record_log().exception("metric fetch sweep failed")
