"""Dashboard-side client for each instance's command plane.

The analog of SentinelApiClient.java:93-121: every dashboard operation on a
machine (fetch/modify rules, pull metrics, read the node tree, flip cluster
mode) is an HTTP call to that machine's command center (§2.4 handlers).
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Any, List, Optional

from sentinel_tpu.core import rules as R
from sentinel_tpu.metrics.node import MetricNode

DEFAULT_TIMEOUT_S = 3.0
#: rule pushes are control-plane ops that BLOCK until enforcement is live
#: on the machine — a reload that changes the compiled feature set (e.g.
#: the first authority rule) swaps in a freshly XLA-compiled tick, which
#: takes tens of seconds on TPU.  The publish honestly waits for it (a
#: fast ACK would report rules "live" during an unenforced window), so
#: its timeout is its own, much larger than telemetry's.
RULE_PUSH_TIMEOUT_S = 180.0


class SentinelApiClient:
    def __init__(
        self, timeout_s: float = DEFAULT_TIMEOUT_S, auth_token: Optional[str] = None
    ):
        # auth_token is the MACHINE-side command-plane bearer token — sent
        # on every request so machines running SimpleHttpCommandCenter with
        # auth enabled still accept dashboard pulls and rule pushes
        self.timeout_s = timeout_s
        self.auth_token = auth_token

    # -- raw --------------------------------------------------------------

    def _headers(self) -> dict:
        from sentinel_tpu.utils.authn import bearer_header

        return bearer_header(self.auth_token)

    def _get(self, ip: str, port: int, command: str, **params) -> str:
        qs = urllib.parse.urlencode({k: v for k, v in params.items() if v is not None})
        url = f"http://{ip}:{port}/{command}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, headers=self._headers())
        with urllib.request.urlopen(req, timeout=self.timeout_s) as rsp:
            return rsp.read().decode("utf-8")

    def _post(
        self, ip: str, port: int, command: str, timeout_s: Optional[float] = None,
        **params,
    ) -> str:
        url = f"http://{ip}:{port}/{command}"
        body = urllib.parse.urlencode(
            {k: v for k, v in params.items() if v is not None}
        ).encode("ascii")
        req = urllib.request.Request(
            url, data=body, method="POST", headers=self._headers()
        )
        with urllib.request.urlopen(
            req, timeout=timeout_s or self.timeout_s
        ) as rsp:
            return rsp.read().decode("utf-8")

    # -- rules ------------------------------------------------------------

    def fetch_rules(self, ip: str, port: int, type_: str) -> List[Any]:
        kind = {"paramFlow": "param-flow"}.get(type_, type_)
        raw = json.loads(self._get(ip, port, "getRules", type=type_))
        return R.rules_from_json_list(kind, raw)

    def set_rules(self, ip: str, port: int, type_: str, rules: List[Any]) -> bool:
        data = json.dumps(R.rules_to_json_list(rules))
        return (
            self._post(
                ip, port, "setRules", timeout_s=RULE_PUSH_TIMEOUT_S,
                type=type_, data=data,
            )
            == "success"
        )

    # -- telemetry ---------------------------------------------------------

    def fetch_metric(
        self, ip: str, port: int, start_ms: int, end_ms: Optional[int] = None
    ) -> List[MetricNode]:
        raw = self._get(ip, port, "metric", startTime=start_ms, endTime=end_ms)
        out = []
        for line in raw.split("\n"):
            if not line.strip():
                continue
            try:
                out.append(MetricNode.from_line(line))
            except ValueError:
                continue
        return out

    def fetch_timeline(
        self,
        ip: str,
        port: int,
        resource: Optional[str] = None,
        start_ms: int = 0,
        end_ms: Optional[int] = None,
    ) -> List[dict]:
        """``GET /api/metric`` — the machine's per-resource per-second
        timeline rows (obs/timeline.py; dicts with ts/resource/pass/
        block/success/exception/rt_sum/rt_min/concurrency).  The
        device-batched successor of ``fetch_metric``'s text lines."""
        return json.loads(
            self._get(
                ip, port, "api/metric",
                resource=resource, start=start_ms, end=end_ms,
            )
        )

    def fetch_prometheus(self, ip: str, port: int) -> str:
        """``GET /metrics`` — the machine's obs-registry exposition
        (Prometheus text format); raw text so the dashboard can re-serve
        or parse it."""
        return self._get(ip, port, "metrics")

    def fetch_traces(self, ip: str, port: int) -> dict:
        """``GET /api/traces`` — the machine's span ring as Chrome-trace
        JSON (Perfetto-loadable; ``obs.load_spans`` parses it)."""
        return json.loads(self._get(ip, port, "api/traces"))

    def fetch_flight(self, ip: str, port: int, stored: Optional[int] = None):
        """``GET /api/flight`` — the machine's black-box flight recorder:
        a fresh on-demand bundle, or with ``stored=N`` the last N
        automatically-triggered ones (``obs.flight`` docs the contents;
        ``python -m sentinel_tpu.obs --postmortem`` analyzes a bundle)."""
        return json.loads(
            self._get(ip, port, "api/flight", stored=stored)
        )

    def fetch_explain(
        self,
        ip: str,
        port: int,
        resource: Optional[str] = None,
        top: Optional[int] = None,
    ) -> dict:
        """``GET /api/explain`` — the machine's verdict provenance plane:
        coverage, the top block-cause leaderboard, and the newest
        device-packed block explanations (obs/explain.py)."""
        return json.loads(
            self._get(ip, port, "api/explain", resource=resource, top=top)
        )

    def fetch_json_tree(self, ip: str, port: int) -> dict:
        return json.loads(self._get(ip, port, "jsonTree"))

    def fetch_cluster_node(self, ip: str, port: int) -> list:
        return json.loads(self._get(ip, port, "clusterNode"))

    def fetch_basic_info(self, ip: str, port: int) -> dict:
        return json.loads(self._get(ip, port, "basicInfo"))

    # -- cluster ----------------------------------------------------------

    def get_cluster_mode(self, ip: str, port: int) -> dict:
        return json.loads(self._get(ip, port, "getClusterMode"))

    def set_cluster_mode(
        self, ip: str, port: int, mode: int, host: str = None, token_port: int = None
    ) -> bool:
        return (
            self._post(
                ip, port, "setClusterMode", mode=mode, host=host,
                tokenPort=token_port,
            )
            == "success"
        )

    def get_cluster_server_info(self, ip: str, port: int) -> dict:
        return json.loads(self._get(ip, port, "clusterServerInfo"))
