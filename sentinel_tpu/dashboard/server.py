"""Dashboard REST server — the control plane (L7).

A stdlib-HTTP re-design of sentinel-dashboard's Spring controllers (the
AngularJS webapp is out of scope; this is the JSON API it talks to):

    POST /registry/machine                  heartbeat receiver
    GET  /apps                              app → machines listing
    GET  /metric?app&identity&startTime&endTime      chart data (repository)
    GET  /metric/top?app&limit              top-N resources by volume
    GET  /resources?app                     known resources of an app
    GET  /rules?app&ip&port&type            rule CRUD — fetches live from the
    POST /rules?app&ip&port&type  (body: JSON rules)   machine's command plane
    GET  /cluster/mode?ip&port              cluster role of a machine
    POST /cluster/mode?ip&port&mode         flip cluster role
    GET  /tree?ip&port                      live invocation tree

Rule pushes go through DynamicRuleProvider/Publisher when configured
(dashboard/rule/DynamicRuleProvider.java:22 — e.g. a config-store backend);
the default round-trips via the machine API, like the reference.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from sentinel_tpu.core import rules as R
from sentinel_tpu.dashboard.api_client import SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.metric_fetcher import MetricFetcher
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository


class DynamicRuleProvider:
    """Fetch rules for an app from an external store (SPI; default: live
    machine API)."""

    def fetch(self, app: str, type_: str):  # pragma: no cover - interface
        raise NotImplementedError


class DynamicRulePublisher:
    """Publish rules for an app to an external store (SPI)."""

    def publish(self, app: str, type_: str, rules: list):  # pragma: no cover
        raise NotImplementedError


class DashboardServer:
    def __init__(
        self,
        host: Optional[str] = None,
        port: int = 8080,
        fetch_metrics: bool = True,
        rule_provider: Optional[DynamicRuleProvider] = None,
        rule_publisher: Optional[DynamicRulePublisher] = None,
        auth_token: Optional[str] = None,
        machine_token: Optional[str] = None,
    ):
        from sentinel_tpu.utils.authn import default_bind_host, normalize_token

        # auth_token gates every route — including /registry/machine — with
        # a bearer token (the AuthController/login-filter analog).  The
        # reference leaves registry open, but an open registry feeds the
        # proxy-target allowlist and the metric fetcher, so when auth is on,
        # heartbeats must carry the token too (HeartbeatSender auth_token=).
        # machine_token is what THIS server sends to each machine's command
        # plane (SimpleHttpCommandCenter auth_token=) on proxy/metric calls.
        self.auth_token = normalize_token(auth_token)
        self.discovery = AppManagement()
        self.repository = InMemoryMetricsRepository()
        self.api = SentinelApiClient(auth_token=machine_token)
        self.fetcher = MetricFetcher(self.discovery, self.repository, self.api)
        self.rule_provider = rule_provider
        self.rule_publisher = rule_publisher
        # default bind is loopback; a wider bind is explicit opt-in
        self.host = default_bind_host(host)
        self.requested_port = port
        self.port: Optional[int] = None
        self._fetch_metrics = fetch_metrics
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._server is not None:
            return
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                from sentinel_tpu.utils.record_log import command_center_log

                command_center_log().info("dashboard %s", fmt % args)

            def do_GET(self):
                outer._route(self, "GET")

            def do_POST(self):
                outer._route(self, "POST")

        last_err = None
        for probe in range(50):
            try:
                self._server = ThreadingHTTPServer(
                    (self.host, self.requested_port + probe), Handler
                )
                break
            except OSError as e:
                last_err = e
        if self._server is None:
            raise OSError(f"no free dashboard port near {self.requested_port}: {last_err}")
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sentinel-tpu-dashboard", daemon=True
        )
        self._thread.start()
        if self._fetch_metrics:
            self.fetcher.start()

    def stop(self) -> None:
        self.fetcher.stop()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.port = None

    # -- routing -----------------------------------------------------------

    def _route(self, handler: BaseHTTPRequestHandler, method: str) -> None:
        parsed = urllib.parse.urlparse(handler.path)
        params = {k: v[-1] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        length = int(handler.headers.get("Content-Length") or 0)
        body = handler.rfile.read(length).decode("utf-8") if length else ""
        if body and not body.lstrip().startswith(("[", "{")):
            for k, v in urllib.parse.parse_qs(body).items():
                params.setdefault(k, v[-1])
            body = ""
        route = (method, parsed.path.rstrip("/") or "/")
        if route == ("GET", "/"):
            # the static UI page (dashboard/ui.py) — no data inside, so it
            # is served without auth; its fetches carry the bearer token
            from sentinel_tpu.dashboard.ui import PAGE

            payload = PAGE.encode("utf-8")
            handler.send_response(200)
            handler.send_header("Content-Type", "text/html; charset=utf-8")
            handler.send_header("Content-Length", str(len(payload)))
            handler.end_headers()
            handler.wfile.write(payload)
            return
        fn = self._routes().get(route)
        try:
            from sentinel_tpu.utils.authn import check_bearer

            if not check_bearer(
                handler.headers.get("Authorization"), self.auth_token
            ):
                code, result = 401, {"error": "unauthorized"}
            elif route == ("POST", "/registry/machine") and not handler.headers.get(
                "X-Sentinel-Heartbeat"
            ):
                # custom-header requirement = CSRF guard: registrations feed
                # the proxy allowlist and the metric fetcher, and a cross-
                # site form POST (which can reach a loopback bind from the
                # operator's browser) cannot carry a custom header
                code, result = 403, {"error": "missing X-Sentinel-Heartbeat"}
            elif fn is None:
                code, result = 404, {"error": f"no route {route[0]} {route[1]}"}
            else:
                code, result = fn(params, body)
        except ValueError as e:
            # parameter validation (missing/unknown machine, bad values) is
            # a client error, not a server fault
            code, result = 400, {"error": str(e)}
        except (OSError, KeyError) as e:
            code, result = 500, {"error": f"{type(e).__name__}: {e}"}
        payload = json.dumps(result).encode("utf-8")
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json; charset=utf-8")
        handler.send_header("Content-Length", str(len(payload)))
        handler.end_headers()
        handler.wfile.write(payload)

    def _routes(self) -> Dict[Tuple[str, str], Callable]:
        return {
            ("POST", "/registry/machine"): self._register_machine,
            ("GET", "/apps"): self._apps,
            ("GET", "/metric"): self._metric,
            ("GET", "/metric/top"): self._metric_top,
            ("GET", "/resources"): self._resources,
            ("GET", "/rules"): self._get_rules,
            ("POST", "/rules"): self._set_rules,
            ("GET", "/cluster/mode"): self._get_cluster_mode,
            ("POST", "/cluster/mode"): self._set_cluster_mode,
            ("POST", "/cluster/assign"): self._cluster_assign,
            ("GET", "/tree"): self._tree,
            ("GET", "/explain"): self._explain,
        }

    # -- handlers ----------------------------------------------------------

    def _register_machine(self, params, body):
        app = params.get("app")
        ip = params.get("ip")
        port = params.get("port")
        if not (app and ip and port):
            return 400, {"error": "app, ip, port are required"}
        self.discovery.register(
            MachineInfo(
                app=app,
                ip=ip,
                port=int(port),
                hostname=params.get("hostname", ""),
                pid=int(params.get("pid", "0")),
                version=params.get("version", ""),
            )
        )
        return 200, {"code": 0, "msg": "success"}

    def _apps(self, params, body):
        return 200, {
            app: [m.to_json() for m in self.discovery.machines(app)]
            for app in self.discovery.apps()
        }

    def _metric(self, params, body):
        app = params.get("app")
        identity = params.get("identity")
        if not (app and identity):
            return 400, {"error": "app and identity are required"}
        start = int(params.get("startTime", "0"))
        end = int(params.get("endTime", str(2**62)))
        nodes = self.repository.query(app, identity, start, end)
        return 200, [vars(n) for n in nodes]

    def _metric_top(self, params, body):
        app = params.get("app")
        if not app:
            return 400, {"error": "app is required"}
        start = int(params.get("startTime", "0"))
        end = int(params.get("endTime", str(2**62)))
        limit = int(params.get("limit", "30"))
        return 200, self.repository.top_resources(app, start, end, limit)

    def _resources(self, params, body):
        app = params.get("app")
        if not app:
            return 400, {"error": "app is required"}
        return 200, self.repository.resources_of(app)

    def _machine_of(self, params):
        ip, port = params.get("ip"), params.get("port")
        if not (ip and port):
            raise ValueError("ip and port are required")
        port = int(port)
        # proxy routes (/rules, /tree, /cluster/mode) cause server-side HTTP
        # requests to ip:port — only allow targets that actually registered
        # via heartbeat, so the dashboard can't be used as an SSRF relay
        known = {
            (m.ip, m.port)
            for app in self.discovery.apps()
            for m in self.discovery.machines(app)
        }
        if (ip, port) not in known:
            raise ValueError(f"unknown machine {ip}:{port} (not in discovery)")
        return ip, port

    def _get_rules(self, params, body):
        type_ = params.get("type", "flow")
        app = params.get("app", "")
        if self.rule_provider is not None:
            rules = self.rule_provider.fetch(app, type_)
            return 200, R.rules_to_json_list(rules)
        ip, port = self._machine_of(params)
        rules = self.api.fetch_rules(ip, port, type_)
        return 200, R.rules_to_json_list(rules)

    def _set_rules(self, params, body):
        type_ = params.get("type", "flow")
        app = params.get("app", "")
        kind = {"paramFlow": "param-flow"}.get(type_, type_)
        data = body or params.get("data", "[]")
        rules = R.rules_from_json_list(kind, json.loads(data))
        if self.rule_publisher is not None:
            self.rule_publisher.publish(app, type_, rules)
            return 200, {"code": 0, "msg": "published"}
        # default: push straight to every healthy machine of the app, or to
        # the one machine given by ip/port (reference round-trip semantics)
        targets = []
        if params.get("ip") and params.get("port"):
            targets = [self._machine_of(params)]
        elif app:
            targets = [(m.ip, m.port) for m in self.discovery.machines(app, only_healthy=True)]
        if not targets:
            return 400, {"error": "no target machines"}
        pushed = sum(1 for ip, port in targets if self.api.set_rules(ip, port, type_, rules))
        return 200, {"code": 0, "pushed": pushed, "targets": len(targets)}

    def _cluster_assign(self, params, body):
        """One-shot token-server/client assignment across machines
        (ClusterAssignServiceImpl.java analog): body JSON names the server
        machine and the client machines; the dashboard flips the server
        first, reads its bound token port, then points every client at it.

            {"server": {"ip": ..., "port": ...},      # command-plane addr
             "clients": [{"ip": ..., "port": ...}, ...],
             "tokenPort": optional fixed port}

        Every machine must be heartbeat-registered (same SSRF guard as the
        proxy routes).  Partial failures report per-machine results so the
        operator can retry the stragglers."""
        try:
            spec = json.loads(body or "{}")
        except ValueError:
            return 400, {"error": "invalid JSON body"}
        srv = spec.get("server") or {}
        try:
            sip, sport = self._machine_of(srv)
        except ValueError as e:
            return 400, {"error": f"server: {e}"}
        results = {"server": None, "clients": []}
        from sentinel_tpu.cluster import state as CS

        tok_port = spec.get("tokenPort")
        ok = self.api.set_cluster_mode(
            sip, sport, CS.CLUSTER_SERVER, token_port=tok_port
        )
        if not ok:
            return 502, {"error": f"server flip failed on {sip}:{sport}"}
        try:
            info = self.api.get_cluster_server_info(sip, sport)
            token_port = int(info.get("tokenPort", -1))
        except Exception:
            token_port = -1
        if token_port <= 0:
            return 502, {"error": "server reports no token port"}
        results["server"] = {"ip": sip, "tokenPort": token_port}
        for cm in spec.get("clients") or []:
            try:
                cip, cport = self._machine_of(cm)
                ok = self.api.set_cluster_mode(
                    cip, cport, CS.CLUSTER_CLIENT, host=sip, token_port=token_port
                )
                results["clients"].append(
                    {"ip": cip, "port": cport, "ok": bool(ok)}
                )
            except Exception as e:
                results["clients"].append(
                    {"ip": cm.get("ip"), "port": cm.get("port"), "ok": False,
                     "error": str(e)}
                )
        return 200, results

    def _get_cluster_mode(self, params, body):
        ip, port = self._machine_of(params)
        return 200, self.api.get_cluster_mode(ip, port)

    def _set_cluster_mode(self, params, body):
        ip, port = self._machine_of(params)
        ok = self.api.set_cluster_mode(ip, port, int(params.get("mode", "-99")))
        return (200, {"code": 0}) if ok else (500, {"error": "set mode failed"})

    def _tree(self, params, body):
        ip, port = self._machine_of(params)
        return 200, self.api.fetch_json_tree(ip, port)

    def _explain(self, params, body):
        """Proxy to the machine's ``GET /api/explain`` — the "top block
        causes" panel's data source (same SSRF allowlist as the other
        proxy routes)."""
        ip, port = self._machine_of(params)
        top = params.get("top")
        return 200, self.api.fetch_explain(
            ip, port,
            resource=params.get("resource"),
            top=int(top) if top else None,
        )
