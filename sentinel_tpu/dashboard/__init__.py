"""Dashboard-lite (SURVEY §2.6): machine discovery via heartbeats, metric
pull + in-memory repository, rule CRUD proxied to each machine's command
plane, cluster role assignment — the control plane, minus the AngularJS UI."""

from sentinel_tpu.dashboard.api_client import SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.metric_fetcher import MetricFetcher
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository
from sentinel_tpu.dashboard.server import (
    DashboardServer,
    DynamicRuleProvider,
    DynamicRulePublisher,
)

__all__ = [
    "SentinelApiClient",
    "AppManagement",
    "MachineInfo",
    "MetricFetcher",
    "InMemoryMetricsRepository",
    "DashboardServer",
    "DynamicRuleProvider",
    "DynamicRulePublisher",
]
