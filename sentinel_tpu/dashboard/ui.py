"""Minimal dashboard web UI — a single static page over the JSON API.

The reference ships an AngularJS 1.x SPA with ECharts; this is the same
idea at minimum viable scale with zero dependencies (vanilla JS + canvas):
machine discovery table, per-app top resources, live QPS chart polling
/metric once a second, a "top block causes" verdict-provenance panel
(GET /explain — which rule blocked, observed vs threshold, sketch-tier /
possibly-false flags), and a rule MANAGER (list/add/edit/delete for
flow / degrade / paramFlow / system / authority rules — the
flow_v1.html / degrade.html / param_flow.html / system.html /
authority.html pages of the reference SPA) publishing the full per-type
list through the same POST /rules machine round-trip the REST API exposes.
Served by DashboardServer at GET /.
"""

PAGE = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>sentinel-tpu dashboard</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 1.5rem; color: #222; }
  h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.5rem; }
  table { border-collapse: collapse; margin: .5rem 0; }
  td, th { border: 1px solid #ccc; padding: .25rem .6rem; font-size: .85rem; }
  th { background: #f3f3f3; text-align: left; }
  .muted { color: #888; } .ok { color: #0a0 ; } .bad { color: #c00; }
  canvas { border: 1px solid #ddd; margin-top: .5rem; }
  select, input, button { font-size: .9rem; margin-right: .5rem; }
  #err { color: #c00; font-size: .85rem; }
  .tab { background: #eee; border: 1px solid #bbb; padding: .2rem .7rem; }
  #rules input, #rules select { margin: 0; }
</style>
</head>
<body>
<h1>sentinel-tpu dashboard</h1>
<div>
  <label>app <select id="app"></select></label>
  <label>resource <select id="res"></select></label>
  <input id="token" placeholder="auth token (if set)" size="18">
  <span id="err"></span>
</div>

<h2>machines</h2>
<table id="machines"><tr><th>app</th><th>ip:port</th><th>hostname</th><th>pid</th><th>health</th></tr></table>

<h2>qps <span class="muted" id="resname"></span></h2>
<canvas id="chart" width="860" height="220"></canvas>
<div class="muted">green: pass/s &nbsp; red: block/s &nbsp; blue (right axis): avg rt ms &nbsp; (trailing 5 min, 1 s points)</div>

<h2>top resources <span class="muted">(last second)</span></h2>
<table id="top"><tr><th>resource</th><th>pass/s</th><th>block/s</th><th>avg rt</th><th>threads</th></tr></table>

<h2>top block causes <span class="muted" id="explcov"></span></h2>
<div class="muted">verdict provenance (GET /explain via the selected rule
machine): which rule blocked, what it observed vs its threshold; ~ marks
sketch-tier estimates, ! marks possibly-false blocks (margin within the
audit eps bound)</div>
<table id="explain"><tr><th>count</th><th>kind</th><th>rule</th><th>origin</th><th>resource</th><th>last observed/threshold</th></tr></table>

<h2>rules</h2>
<div>
  <label>machine <select id="rmach"></select></label>
  <button class="tab" id="tab-flow">flow</button>
  <button class="tab" id="tab-degrade">degrade</button>
  <button class="tab" id="tab-paramFlow">paramFlow</button>
  <button class="tab" id="tab-system">system</button>
  <button class="tab" id="tab-authority">authority</button>
  <button id="rload">reload</button>
  <span class="muted">edits publish the FULL list for the selected type
  (reference rule-manager semantics)</span>
</div>
<table id="rules"></table>
<div>
  <button id="radd">add rule</button>
  <button id="rsave">save</button>
  <span id="rout" class="muted"></span>
</div>

<h2>cluster assignment</h2>
<div class="muted">pick one machine as token server; every other healthy
machine of the app becomes its client (POST /cluster/assign)</div>
<div>
  <label>server <select id="srv"></select></label>
  <button id="assign">assign</button>
  <span id="assignout" class="muted"></span>
</div>

<script>
const $ = id => document.getElementById(id);
// every server-sourced string goes through esc(): machine fields arrive via
// the UNAUTHENTICATED heartbeat endpoint and must never reach innerHTML raw
const esc = s => String(s).replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const hdrs = () => $("token").value ? {"Authorization": "Bearer " + $("token").value} : {};
async function j(url) {
  const r = await fetch(url, {headers: hdrs()});
  if (!r.ok) throw new Error(url + " -> " + r.status);
  return r.json();
}
let apps = {}, series = [];

async function refreshApps() {
  apps = await j("/apps");
  const sel = $("app"), cur = sel.value;
  sel.innerHTML = "";
  Object.keys(apps).forEach(a => sel.add(new Option(a, a)));
  if (cur && apps[cur] !== undefined) sel.value = cur;
  const t = $("machines");
  t.innerHTML = "<tr><th>app</th><th>ip:port</th><th>hostname</th><th>pid</th><th>health</th></tr>";
  for (const [app, ms] of Object.entries(apps)) for (const m of ms) {
    const row = t.insertRow();
    row.innerHTML = `<td>${esc(app)}</td><td>${esc(m.ip)}:${esc(m.port)}</td>` +
      `<td>${esc(m.hostname)}</td><td>${esc(m.pid)}</td>` +
      `<td class="${m.healthy ? "ok" : "bad"}">${m.healthy ? "healthy" : "stale"}</td>`;
  }
}

async function refreshResources() {
  const app = $("app").value;
  if (!app) return [];
  const top = await j(`/metric/top?app=${encodeURIComponent(app)}&limit=12`);
  const sel = $("res"), cur = sel.value;
  sel.innerHTML = "";
  top.forEach(r => sel.add(new Option(r, r)));
  if (cur && top.includes(cur)) sel.value = cur;
  return top;
}

async function refreshChart() {
  const app = $("app").value, res = $("res").value;
  if (!app || !res) return;
  const since = Date.now() - 5 * 60 * 1000;
  series = await j(`/metric?app=${encodeURIComponent(app)}&identity=${encodeURIComponent(res)}&startTime=${since}`);
  $("resname").textContent = res;
  const c = $("chart"), ctx = c.getContext("2d");
  ctx.clearRect(0, 0, c.width, c.height);
  if (!series.length) return;
  const t0 = since, t1 = Date.now();
  const ymax = Math.max(5, ...series.map(p => Math.max(p.pass_qps, p.block_qps))) * 1.15;
  const X = ts => (ts - t0) / (t1 - t0) * (c.width - 40) + 35;
  const Y = v  => c.height - 18 - v / ymax * (c.height - 30);
  ctx.strokeStyle = "#ddd"; ctx.fillStyle = "#888"; ctx.font = "11px sans-serif";
  for (let i = 0; i <= 4; i++) {
    const v = ymax / 4 * i, y = Y(v);
    ctx.beginPath(); ctx.moveTo(35, y); ctx.lineTo(c.width - 5, y); ctx.stroke();
    ctx.fillText(v.toFixed(0), 2, y + 4);
  }
  const line = (key, color, yf) => {
    ctx.strokeStyle = color; ctx.lineWidth = 1.5; ctx.beginPath();
    series.forEach((p, i) => i ? ctx.lineTo(X(p.timestamp), yf(p[key]))
                               : ctx.moveTo(X(p.timestamp), yf(p[key])));
    ctx.stroke();
  };
  line("pass_qps", "#2a2", Y);
  line("block_qps", "#c33", Y);
  // avg RT on its own right-hand scale (the reference chart's second axis)
  const rmax = Math.max(1, ...series.map(p => p.rt)) * 1.15;
  const Yr = v => c.height - 18 - v / rmax * (c.height - 30);
  ctx.fillStyle = "#36c";
  ctx.fillText(rmax.toFixed(0) + "ms", c.width - 38, 12);
  line("rt", "#36c", Yr);
}

async function refreshTop(names) {
  const app = $("app").value;
  if (!app || !names) return;
  const since = Date.now() - 3000;
  // parallel fetches: 12 serial awaits would overrun the 1 s tick
  const rows = await Promise.all(names.map(async name => {
    const pts = await j(`/metric?app=${encodeURIComponent(app)}&identity=${encodeURIComponent(name)}&startTime=${since}`);
    return [name, pts.length ? pts[pts.length - 1] : null];
  }));
  const t = $("top");
  t.innerHTML = "<tr><th>resource</th><th>pass/s</th><th>block/s</th><th>avg rt</th><th>threads</th></tr>";
  for (const [name, p] of rows) {
    const row = t.insertRow();
    row.innerHTML = `<td>${esc(name)}</td><td>${p ? esc(p.pass_qps) : "-"}</td>` +
      `<td>${p ? esc(p.block_qps) : "-"}</td><td>${p ? esc(p.rt.toFixed(1)) : "-"}</td>` +
      `<td>${p ? esc(p.concurrency) : "-"}</td>`;
  }
}

// ---- rule manager (flow_v1.html / degrade.html / param_flow.html) ------
// column spec per rule type: [json field, label, kind]; kind: "s" text,
// "n" number, or [value, label] pairs for a select
const RCOLS = {
  flow: [
    ["resource", "resource", "s"],
    ["grade", "grade", [[1, "QPS"], [0, "THREAD"]]],
    ["count", "count", "n"],
    ["strategy", "strategy", [[0, "DIRECT"], [1, "RELATE"], [2, "CHAIN"]]],
    ["refResource", "refResource", "s"],
    ["controlBehavior", "behavior",
     [[0, "default"], [1, "warmUp"], [2, "rateLimiter"], [3, "warmUp+RL"]]],
    ["maxQueueingTimeMs", "maxQueueMs", "n"],
    ["limitApp", "limitApp", "s"],
  ],
  degrade: [
    ["resource", "resource", "s"],
    ["grade", "grade",
     [[0, "slowRatio"], [1, "errorRatio"], [2, "errorCount"]]],
    ["count", "count", "n"],
    ["slowRatioThreshold", "slowRatio", "n"],
    ["timeWindow", "windowSec", "n"],
    ["minRequestAmount", "minRequests", "n"],
    ["statIntervalMs", "statMs", "n"],
  ],
  paramFlow: [
    ["resource", "resource", "s"],
    ["paramIdx", "paramIdx", "n"],
    ["grade", "grade", [[1, "QPS"], [0, "THREAD"]]],
    ["count", "count", "n"],
    ["durationInSec", "durationSec", "n"],
    ["burstCount", "burst", "n"],
  ],
  // system rules are GLOBAL (no resource column; -1 disables a threshold)
  // — views/system.html of the reference SPA
  system: [
    ["highestSystemLoad", "load", "n"],
    ["highestCpuUsage", "cpuUsage", "n"],
    ["qps", "qps", "n"],
    ["avgRt", "avgRt", "n"],
    ["maxThread", "maxThread", "n"],
  ],
  // views/authority.html: origin allow/deny per resource; limitApp is a
  // comma-separated origin list
  authority: [
    ["resource", "resource", "s"],
    ["limitApp", "origins (comma-sep)", "s"],
    ["strategy", "strategy", [[0, "WHITE (allow)"], [1, "BLACK (deny)"]]],
  ],
};
const RDEFAULTS = {
  flow: {resource: "", grade: 1, count: 10, strategy: 0, refResource: "",
         controlBehavior: 0, maxQueueingTimeMs: 500, limitApp: "default"},
  degrade: {resource: "", grade: 0, count: 100, slowRatioThreshold: 1.0,
            timeWindow: 10, minRequestAmount: 5, statIntervalMs: 1000},
  paramFlow: {resource: "", paramIdx: 0, grade: 1, count: 10,
              durationInSec: 1, burstCount: 0},
  system: {highestSystemLoad: -1, highestCpuUsage: -1, qps: -1,
           avgRt: -1, maxThread: -1},
  authority: {resource: "", limitApp: "", strategy: 0},
};
let rtype = "flow", rrules = [];  // the editable full list for rtype
let rloadedFrom = "";  // machine rrules was fetched from (save guard)

function rmachine() {
  const pick = $("rmach").value;
  if (!pick) return null;
  const [ip, port] = pick.split(":");
  return {ip, port: +port};
}

function renderRules() {
  const cols = RCOLS[rtype], t = $("rules");
  document.querySelectorAll(".tab").forEach(b =>
    b.style.fontWeight = b.id === "tab-" + rtype ? "bold" : "normal");
  t.innerHTML = "<tr>" + cols.map(c => `<th>${esc(c[1])}</th>`).join("") +
    "<th></th></tr>";
  rrules.forEach((r, i) => {
    const row = t.insertRow();
    for (const [f, _label, kind] of cols) {
      const cell = row.insertCell();
      let el;
      if (Array.isArray(kind)) {
        el = document.createElement("select");
        kind.forEach(([v, lab]) => el.add(new Option(lab, v)));
        el.value = r[f] ?? kind[0][0];
        el.onchange = () => { r[f] = +el.value; };
      } else if (kind === "n") {
        el = document.createElement("input");
        el.type = "number";
        el.style.width = "5.5rem";
        el.value = r[f] ?? "";
        // NaN would serialize to null and crash from_dict server-side;
        // reject it at the field and keep the last good value
        el.onchange = () => {
          const v = parseFloat(el.value);
          if (Number.isFinite(v)) { r[f] = v; el.style.background = ""; }
          else { el.style.background = "#fdd"; el.value = r[f] ?? ""; }
        };
      } else {
        el = document.createElement("input");
        el.size = 14;
        el.value = r[f] ?? "";
        el.onchange = () => { r[f] = el.value; };
      }
      cell.appendChild(el);
    }
    const del = document.createElement("button");
    del.textContent = "delete";
    del.onclick = () => { rrules.splice(i, 1); renderRules(); };
    row.insertCell().appendChild(del);
  });
}

async function loadRules() {
  const m = rmachine();
  if (!m) { rrules = []; rloadedFrom = ""; renderRules(); return; }
  rrules = await j(`/rules?ip=${m.ip}&port=${m.port}&type=${rtype}`);
  rloadedFrom = $("rmach").value;
  renderRules();
}

function refreshRuleMachines() {
  const app = $("app").value, sel = $("rmach"), cur = sel.value;
  sel.innerHTML = "";
  (apps[app] || []).filter(m => m.healthy).forEach(m =>
    sel.add(new Option(`${m.ip}:${m.port}`, `${m.ip}:${m.port}`)));
  if (cur && [...sel.options].some(o => o.value === cur)) sel.value = cur;
}

for (const ty of ["flow", "degrade", "paramFlow", "system", "authority"])
  $("tab-" + ty).onclick = () => { rtype = ty; loadRules(); };
$("rload").onclick = loadRules;
$("rmach").onchange = loadRules;
$("radd").onclick = () => {
  rrules.push({...RDEFAULTS[rtype]});
  renderRules();
};
$("rsave").onclick = async () => {
  const m = rmachine();
  if (!m) { $("rout").textContent = "no machine"; return; }
  // publish is full-list REPLACE: saving a list loaded from machine A to
  // machine B (select silently rebuilt by tick()) would wipe B's rules
  if (rloadedFrom !== $("rmach").value) {
    $("rout").textContent =
      "machine changed since load — hit reload first (save would " +
      "overwrite this machine's rules with the other machine's list)";
    return;
  }
  // system rules are global — every other type is resource-keyed
  const bad = rtype !== "system" && rrules.find(r => !r.resource);
  if (bad) { $("rout").textContent = "every rule needs a resource"; return; }
  try {
    const r = await fetch(
      `/rules?ip=${m.ip}&port=${m.port}&type=${rtype}`, {
        method: "POST",
        headers: {...hdrs(), "Content-Type": "application/json"},
        body: JSON.stringify(rrules),
      });
    const d = await r.json();
    const pushed = d.pushed ?? 1, targets = d.targets ?? 1;
    if (r.ok && pushed > 0) {
      // textContent assignments need no esc() — the DOM treats the
      // string as text, and double-escaping would render '&amp;' literally
      $("rout").textContent =
        `published ${rrules.length} ${rtype} rules ` +
        `(${pushed}/${targets} machines)` +
        (pushed < targets ? " — SOME MACHINES REJECTED the push" : "");
    } else if (r.ok) {
      // HTTP 200 but no machine accepted: the rules are NOT live
      $("rout").textContent =
        `NOT published — 0/${targets} machines accepted the push`;
    } else {
      $("rout").textContent = `failed: ${d.error || r.status}`;
    }
    if (r.ok && pushed > 0) loadRules();  // re-read: what you see is live
  } catch (e) { $("rout").textContent = String(e); }
};

async function refreshExplain() {
  const m = rmachine();
  const t = $("explain");
  const head = "<tr><th>count</th><th>kind</th><th>rule</th><th>origin</th>" +
    "<th>resource</th><th>last observed/threshold</th></tr>";
  if (!m) { t.innerHTML = head; $("explcov").textContent = ""; return; }
  const d = await j(`/explain?ip=${m.ip}&port=${m.port}&top=8`);
  const cov = d.coverage || {};
  $("explcov").textContent = d.enabled === false
    ? "(explain plane off)"
    : `${cov.explained || 0}/${cov.blocked || 0} blocked decisions explained`;
  // newest record per (resource, kind, rule, origin) → the numbers column
  const latest = {};
  for (const r of d.recent || []) {
    const k = `${r.resource}|${r.kind}|${r.rule}|${r.origin}`;
    if (!(k in latest)) latest[k] = r;
  }
  t.innerHTML = head;
  for (const c of d.top_causes || []) {
    const r = latest[`${c.resource}|${c.kind}|${c.rule}|${c.origin}`];
    const num = r && r.observed != null && r.threshold != null
      ? `${r.observed} / ${r.threshold}` +
        (r.sketch_tier ? " ~" : "") + (r.possibly_false ? " !" : "")
      : "-";
    const row = t.insertRow();
    row.innerHTML = `<td>${esc(c.count)}</td><td>${esc(c.kind)}</td>` +
      `<td>${c.rule == null ? "-" : esc(c.rule)}</td><td>${esc(c.origin)}</td>` +
      `<td>${esc(c.name || c.resource)}</td><td>${esc(num)}</td>`;
  }
}

async function refreshAssign() {
  const app = $("app").value;
  const sel = $("srv"), cur = sel.value;
  sel.innerHTML = "";
  (apps[app] || []).filter(m => m.healthy).forEach(m =>
    sel.add(new Option(`${m.ip}:${m.port}`, `${m.ip}:${m.port}`)));
  if (cur) sel.value = cur;
}

$("assign").onclick = async () => {
  const app = $("app").value, pick = $("srv").value;
  if (!pick) return;
  const [sip, sport] = pick.split(":");
  const clients = (apps[app] || []).filter(
    m => m.healthy && `${m.ip}:${m.port}` !== pick
  ).map(m => ({ip: m.ip, port: m.port}));
  try {
    const r = await fetch("/cluster/assign", {
      method: "POST",
      headers: {...hdrs(), "Content-Type": "application/json"},
      body: JSON.stringify({server: {ip: sip, port: +sport}, clients}),
    });
    const d = await r.json();
    $("assignout").textContent = r.ok
      ? `server ${d.server.ip} token port ${d.server.tokenPort}, ` +
        `${d.clients.filter(c => c.ok).length}/${d.clients.length} clients flipped`
      : `failed: ${d.error || r.status}`;
  } catch (e) { $("assignout").textContent = String(e); }
};

let rulesLoadedOnce = false;
async function tick() {
  try {
    await refreshApps();
    const top = await refreshResources();
    await refreshChart();
    await refreshTop(top);
    // the rule EDITOR never auto-refreshes (it would wipe in-progress
    // edits); machines list stays fresh, content loads on demand
    refreshRuleMachines();
    if (!rulesLoadedOnce && $("rmach").value) {
      rulesLoadedOnce = true;
      await loadRules();
    }
    await refreshExplain();
    await refreshAssign();
    $("err").textContent = "";
  } catch (e) { $("err").textContent = String(e); }
  // self-rescheduling chain: a slow machine round-trip must not pile up
  // overlapping ticks racing each other's DOM rewrites
  setTimeout(tick, 1000);
}
tick();
</script>
</body>
</html>
"""
