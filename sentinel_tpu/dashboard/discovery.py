"""Machine discovery — who is alive, per app.

The analog of sentinel-dashboard's discovery package
(SimpleMachineDiscovery / AppManagement + MachineRegistryController):
heartbeats POSTed to /registry/machine upsert a MachineInfo; a machine is
healthy while its last heartbeat is younger than ``stale_after_s``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sentinel_tpu.utils.time_source import wall_s


@dataclass
class MachineInfo:
    app: str
    ip: str
    port: int
    hostname: str = ""
    pid: int = 0
    version: str = ""
    last_heartbeat: float = field(default_factory=wall_s)

    @property
    def key(self) -> str:
        return f"{self.ip}:{self.port}"

    def healthy(self, stale_after_s: float = 30.0) -> bool:
        return (wall_s() - self.last_heartbeat) < stale_after_s

    def to_json(self) -> dict:
        return {
            "app": self.app,
            "ip": self.ip,
            "port": self.port,
            "hostname": self.hostname,
            "pid": self.pid,
            "version": self.version,
            "lastHeartbeat": int(self.last_heartbeat * 1000),
            "healthy": self.healthy(),
        }


class AppManagement:
    def __init__(self, stale_after_s: float = 30.0):
        self._apps: Dict[str, Dict[str, MachineInfo]] = {}
        self._lock = threading.Lock()
        self.stale_after_s = stale_after_s

    def register(self, info: MachineInfo) -> None:
        with self._lock:
            machines = self._apps.setdefault(info.app, {})
            existing = machines.get(info.key)
            if existing is not None:
                existing.last_heartbeat = info.last_heartbeat
                existing.pid = info.pid
                existing.hostname = info.hostname
                existing.version = info.version
            else:
                machines[info.key] = info

    def apps(self) -> List[str]:
        return sorted(self._apps)

    def machines(self, app: str, only_healthy: bool = False) -> List[MachineInfo]:
        out = list(self._apps.get(app, {}).values())
        if only_healthy:
            out = [m for m in out if m.healthy(self.stale_after_s)]
        return sorted(out, key=lambda m: m.key)

    def get_machine(self, app: str, ip: str, port: int) -> Optional[MachineInfo]:
        return self._apps.get(app, {}).get(f"{ip}:{port}")

    def remove_stale(self, older_than_s: float = 600.0) -> int:
        """Drop machines silent for a long time; returns #removed."""
        cutoff = wall_s() - older_than_s
        removed = 0
        with self._lock:
            for machines in self._apps.values():
                for key in [k for k, m in machines.items() if m.last_heartbeat < cutoff]:
                    del machines[key]
                    removed += 1
        return removed
