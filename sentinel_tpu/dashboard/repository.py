"""In-memory metrics repository — 5-minute retention ring of MetricNodes.

The analog of InMemoryMetricsRepository: the metric fetcher saves parsed
MetricNode entries keyed (app, resource, second); queries serve the UI's
per-resource charts and the top-N resource listing.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List

from sentinel_tpu.metrics.node import MetricNode

DEFAULT_RETENTION_MS = 5 * 60 * 1000


class InMemoryMetricsRepository:
    def __init__(self, retention_ms: int = DEFAULT_RETENTION_MS):
        self.retention_ms = retention_ms
        # app -> resource -> {second_ts -> MetricNode}
        self._data: Dict[str, Dict[str, Dict[int, MetricNode]]] = defaultdict(
            lambda: defaultdict(dict)
        )
        # app -> machine -> resource -> {second_ts -> timeline row dict}
        # (the /api/metric channel, kept per machine — see save_timeline)
        self._timelines: Dict[str, Dict[str, Dict[str, Dict[int, dict]]]] = {}
        self._lock = threading.Lock()

    def save_all(self, app: str, nodes: List[MetricNode]) -> None:
        if not nodes:
            return
        with self._lock:
            per_app = self._data[app]
            for n in nodes:
                prev = per_app[n.resource].get(n.timestamp)
                if prev is not None:
                    # multiple machines of one app in the same second → sum
                    prev.pass_qps += n.pass_qps
                    prev.block_qps += n.block_qps
                    prev.success_qps += n.success_qps
                    prev.exception_qps += n.exception_qps
                    prev.occupied_pass_qps += n.occupied_pass_qps
                    prev.concurrency += n.concurrency
                    prev.rt = max(prev.rt, n.rt)
                else:
                    per_app[n.resource][n.timestamp] = n
            self._trim(per_app, max(n.timestamp for n in nodes))

    def query(self, app: str, resource: str, start_ms: int, end_ms: int) -> List[MetricNode]:
        per_res = self._data.get(app, {}).get(resource, {})
        return [per_res[t] for t in sorted(per_res) if start_ms <= t <= end_ms]

    def resources_of(self, app: str) -> List[str]:
        return sorted(self._data.get(app, {}))

    def top_resources(self, app: str, start_ms: int, end_ms: int, limit: int = 30) -> List[str]:
        """Resources ranked by total pass+block volume in the range
        (queryTopResourceMetric's ordering)."""
        totals: Dict[str, float] = {}
        for resource, per_res in self._data.get(app, {}).items():
            v = sum(
                n.pass_qps + n.block_qps
                for t, n in per_res.items()
                if start_ms <= t <= end_ms
            )
            if v > 0:
                totals[resource] = v
        ranked = sorted(totals, key=lambda r: (-totals[r], r))
        return ranked[:limit]

    def _trim(self, per_app: Dict[str, Dict[int, MetricNode]], now_ms: int) -> None:
        cutoff = now_ms - self.retention_ms
        for per_res in per_app.values():
            for t in [t for t in per_res if t < cutoff]:
                del per_res[t]

    # -- per-machine timelines (obs/timeline.py rows) ------------------------

    def save_timeline(self, app: str, machine: str, rows: List[dict]) -> None:
        """Store fetched ``/api/metric`` rows keyed (app, machine,
        resource, second) — machines stay separate so queries can merge
        with per-machine provenance (or inspect one machine)."""
        if not rows:
            return
        with self._lock:
            per_m = self._timelines.setdefault(app, {}).setdefault(machine, {})
            newest = 0
            for r in rows:
                per_m.setdefault(r["resource"], {})[int(r["ts"])] = dict(r)
                newest = max(newest, int(r["ts"]))
            cutoff = newest - self.retention_ms
            for per_res in per_m.values():
                for t in [t for t in per_res if t < cutoff]:
                    del per_res[t]

    def query_timeline(
        self, app: str, resource: str, start_ms: int, end_ms: int
    ) -> List[dict]:
        """Fleet view of one resource's timeline: machines aligned on
        second boundaries and summed (obs.fleet.merge_timelines — each
        merged row's ``sources`` maps machine → pass+block volume)."""
        from sentinel_tpu.obs.fleet import merge_timelines

        with self._lock:
            per_source = {
                machine: [
                    dict(row)
                    for t, row in sorted(per_m.get(resource, {}).items())
                    if start_ms <= t <= end_ms
                ]
                for machine, per_m in self._timelines.get(app, {}).items()
            }
        return merge_timelines(per_source)

    def timeline_machines(self, app: str) -> List[str]:
        with self._lock:
            return sorted(self._timelines.get(app, {}))
