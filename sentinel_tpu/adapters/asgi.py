"""ASGI middleware — the reactive web adapter.

The analog of sentinel-spring-webflux-adapter's SentinelWebFluxFilter:
guards async HTTP apps (Starlette/FastAPI/...). The entry handshake is a
blocking wait on the engine tick (~1 ms); it runs in a thread-pool executor
so the event loop never blocks, mirroring how the reactor adapter moves
the entry onto subscription (SentinelReactorSubscriber).
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from sentinel_tpu.adapters._common import resolve_client
from sentinel_tpu.core import errors as ERR

DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"


def default_resource_extractor(scope) -> str:
    return f"{scope.get('method', 'GET')}:{scope.get('path', '/')}"


def default_origin_parser(scope) -> str:
    for k, v in scope.get("headers", []):
        if k.lower() == b"s-user":
            return v.decode("latin-1")
    return ""


class SentinelASGIMiddleware:
    def __init__(
        self,
        app,
        client=None,
        resource_extractor: Callable = default_resource_extractor,
        origin_parser: Callable = default_origin_parser,
        block_status: int = 429,
        block_body: bytes = DEFAULT_BLOCK_BODY,
    ):
        self.app = app
        self._client = client
        self.resource_extractor = resource_extractor
        self.origin_parser = origin_parser
        self.block_status = block_status
        self.block_body = block_body

    @property
    def client(self):
        if self._client is None:
            self._client = resolve_client(None)
        return self._client

    async def __call__(self, scope, receive, send):
        if scope.get("type") != "http":
            await self.app(scope, receive, send)
            return
        resource = self.resource_extractor(scope)
        origin = self.origin_parser(scope) or ""
        loop = asyncio.get_running_loop()
        try:
            entry = await loop.run_in_executor(
                None, lambda: self.client.entry(resource, inbound=True, origin=origin)
            )
        except ERR.BlockException:
            await send(
                {
                    "type": "http.response.start",
                    "status": self.block_status,
                    "headers": [
                        (b"content-type", b"text/plain; charset=utf-8"),
                        (b"content-length", str(len(self.block_body)).encode()),
                    ],
                }
            )
            await send({"type": "http.response.body", "body": self.block_body})
            return
        try:
            await self.app(scope, receive, send)
        except Exception as e:
            entry.trace(e)
            raise
        finally:
            entry.exit()
