"""Outbound HTTP client guard — the okhttp/apache-httpclient adapter.

The analog of sentinel-okhttp-adapter / sentinel-apache-httpclient-adapter:
wrap outbound HTTP calls as outbound resources so dependencies can be
flow-limited and circuit-broken.  Two surfaces:

- ``guarded_urlopen(url, ...)`` — drop-in for urllib.request.urlopen
- ``SentinelHttpClient`` — wraps any callable transport (e.g. a
  requests.Session.request) with resource naming per (method, host, path)
"""

from __future__ import annotations

import urllib.parse
import urllib.request
from typing import Callable, Optional

from sentinel_tpu.adapters._common import resolve_client


def default_url_resource(method: str, url: str) -> str:
    """`METHOD:scheme://host/path` — query stripped, like the reference's
    default URL cleaner (avoids unbounded resource cardinality)."""
    p = urllib.parse.urlparse(url)
    return f"{method.upper()}:{p.scheme}://{p.netloc}{p.path}"


def guarded_urlopen(url, data=None, timeout=None, *, client=None, resource=None, **kw):
    c = resolve_client(client)
    if resource is None:
        target = url.full_url if hasattr(url, "full_url") else url
        method = "POST" if data is not None else "GET"
        if hasattr(url, "get_method"):
            method = url.get_method()
        resource = default_url_resource(method, target)
    # Entry.__exit__ traces the propagating exception — no manual trace here
    # or each failure would count twice
    with c.entry(resource, inbound=False):
        return urllib.request.urlopen(url, data=data, timeout=timeout, **kw)


class SentinelHttpClient:
    """Wraps a transport callable ``send(method, url, **kw)``."""

    def __init__(
        self,
        send: Callable,
        client=None,
        resource_fn: Callable[[str, str], str] = default_url_resource,
    ):
        self._send = send
        self._client = client
        self._resource_fn = resource_fn

    def request(self, method: str, url: str, **kw):
        c = resolve_client(self._client)
        with c.entry(self._resource_fn(method, url), inbound=False):
            return self._send(method, url, **kw)
