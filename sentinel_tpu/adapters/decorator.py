"""``@sentinel_resource`` — the annotation adapter.

The analog of sentinel-annotation-aspectj's @SentinelResource +
SentinelResourceAspect.java:36-42 / AbstractSentinelAspectSupport: wrap any
callable as a guarded resource with declarative block/fallback handling.

    @sentinel_resource("getUser", block_handler=on_block, fallback=on_err)
    def get_user(uid): ...

- ``block_handler(*args, block_exception=e, **kwargs)`` runs when the entry
  is rejected (BlockException); if absent, the exception propagates.
- ``fallback(*args, exception=e, **kwargs)`` runs when the function raises
  a business exception (after it is traced); if absent, it propagates.
- ``exceptions_to_ignore`` are neither traced nor sent to the fallback.
- positional args are forwarded as the entry's ``args`` so hot-param rules
  (ParamFlowRule.param_idx) see them, as the aspect forwards method args.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence, Tuple, Type

from sentinel_tpu.adapters._common import resolve_client
from sentinel_tpu.core import errors as ERR


def sentinel_resource(
    resource: Optional[str] = None,
    *,
    block_handler: Optional[Callable] = None,
    fallback: Optional[Callable] = None,
    exceptions_to_ignore: Tuple[Type[BaseException], ...] = (),
    inbound: bool = False,
    count: int = 1,
    client=None,
):
    def decorate(fn: Callable) -> Callable:
        name = resource or f"{fn.__module__}:{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            c = resolve_client(client)
            try:
                entry = c.entry(name, count=count, inbound=inbound, args=args or None)
            except ERR.BlockException as be:
                if block_handler is not None:
                    return block_handler(*args, block_exception=be, **kwargs)
                raise
            try:
                return fn(*args, **kwargs)
            except exceptions_to_ignore:
                raise  # not traced, not fell back (exceptionsToIgnore)
            except ERR.BlockException:
                raise  # nested resource blocked; not a business error here
            except Exception as e:
                entry.trace(e)
                if fallback is not None:
                    return fallback(*args, exception=e, **kwargs)
                raise
            finally:
                entry.exit()

        wrapper.__sentinel_resource__ = name
        return wrapper

    return decorate
