"""API-gateway adapter common — gateway flow rules over request attributes.

The analog of sentinel-api-gateway-adapter-common (1,914 LoC):

- ``GatewayFlowRule`` limits a *route* or a *custom API group* by QPS,
  optionally keyed by a request attribute (client IP / host / header /
  URL param / cookie) — rule/GatewayFlowRule + GatewayParamFlowItem.
- ``GatewayRuleConverter`` projects each gateway rule onto a ParamFlowRule
  with a per-rule param index (rule/GatewayRuleConverter.java); rules
  without a param item get a synthetic constant parameter so the limit
  applies per-resource.
- ``GatewayParamParser`` extracts the parameter vector for a request
  (GatewayParamParser.java:34-51); values failing the rule's match
  pattern become a NOT_MATCH sentinel that never counts toward the limit.
- ``ApiDefinitionManager`` matches request paths to custom API groups
  (api/ApiDefinition + matchers), the GatewayApiMatcherManager analog.

Engine note: each entry carries ``EngineConfig.param_dims`` hashed
argument lanes (rule_tensors.param_lanes assigns lanes per resource,
gateway rules first).  The first ``param_dims`` DISTINCT param indices on
a resource get independent enforcement; rules whose index loses the lane
assignment are not enforced and log a warning at compile.  Lane 0's value
also feeds cluster-mode token requests.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.core import rules as R

# resource modes (SentinelGatewayConstants)
RESOURCE_MODE_ROUTE_ID = 0
RESOURCE_MODE_CUSTOM_API_NAME = 1

# param parse strategies
PARAM_PARSE_STRATEGY_CLIENT_IP = 0
PARAM_PARSE_STRATEGY_HOST = 1
PARAM_PARSE_STRATEGY_HEADER = 2
PARAM_PARSE_STRATEGY_URL_PARAM = 3
PARAM_PARSE_STRATEGY_COOKIE = 4

# string match strategies (both for params and API path predicates)
PARAM_MATCH_STRATEGY_EXACT = 0
PARAM_MATCH_STRATEGY_PREFIX = 1
PARAM_MATCH_STRATEGY_REGEX = 2
PARAM_MATCH_STRATEGY_CONTAINS = 3

URL_MATCH_STRATEGY_EXACT = 0
URL_MATCH_STRATEGY_PREFIX = 1
URL_MATCH_STRATEGY_REGEX = 2

#: placeholder for "request attribute did not match the rule's pattern" —
#: a value that never equals a real attribute, so it never hits the limit
NOT_MATCH_PARAM = "$NM"
#: synthetic constant param for rules with no param item
DEFAULT_PARAM = "$D"


@dataclass
class GatewayParamFlowItem:
    parse_strategy: int = PARAM_PARSE_STRATEGY_CLIENT_IP
    field_name: str = ""  # header/param/cookie name
    pattern: str = ""
    match_strategy: int = PARAM_MATCH_STRATEGY_EXACT


@dataclass
class GatewayFlowRule:
    resource: str  # route id or API group name
    resource_mode: int = RESOURCE_MODE_ROUTE_ID
    grade: int = R.GRADE_QPS
    count: float = 0.0
    interval_sec: int = 1
    control_behavior: int = R.CONTROL_DEFAULT
    burst: int = 0
    max_queueing_timeout_ms: int = 500
    param_item: Optional[GatewayParamFlowItem] = None


@dataclass
class ApiPredicateItem:
    pattern: str = ""
    match_strategy: int = URL_MATCH_STRATEGY_EXACT


@dataclass
class ApiDefinition:
    api_name: str
    predicate_items: List[ApiPredicateItem] = field(default_factory=list)


@dataclass
class RequestAttributes:
    """Framework-neutral view of one request (the ServerWebExchange /
    HttpServletRequest of the reference parsers)."""

    path: str = "/"
    client_ip: str = ""
    host: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    url_params: Dict[str, str] = field(default_factory=dict)
    cookies: Dict[str, str] = field(default_factory=dict)


def _value_matches(value: str, pattern: str, strategy: int) -> bool:
    if strategy == PARAM_MATCH_STRATEGY_EXACT:
        return value == pattern
    if strategy == PARAM_MATCH_STRATEGY_PREFIX:
        return value.startswith(pattern)
    if strategy == PARAM_MATCH_STRATEGY_REGEX:
        try:
            return re.search(pattern, value) is not None
        except re.error:
            return False
    if strategy == PARAM_MATCH_STRATEGY_CONTAINS:
        return pattern in value
    return False


class GatewayParamParser:
    def parse_value(self, item: GatewayParamFlowItem, req: RequestAttributes) -> str:
        s = item.parse_strategy
        if s == PARAM_PARSE_STRATEGY_CLIENT_IP:
            value = req.client_ip
        elif s == PARAM_PARSE_STRATEGY_HOST:
            value = req.host
        elif s == PARAM_PARSE_STRATEGY_HEADER:
            value = req.headers.get(item.field_name, "")
        elif s == PARAM_PARSE_STRATEGY_URL_PARAM:
            value = req.url_params.get(item.field_name, "")
        elif s == PARAM_PARSE_STRATEGY_COOKIE:
            value = req.cookies.get(item.field_name, "")
        else:
            value = ""
        value = value or ""
        if item.pattern and not _value_matches(value, item.pattern, item.match_strategy):
            return NOT_MATCH_PARAM
        return value

    def parse(
        self, rules: Sequence[GatewayFlowRule], req: RequestAttributes
    ) -> List[str]:
        """Parameter vector ordered by the rules' assigned indices —
        GatewayParamParser.parseParameterFor."""
        out = []
        for rule in rules:
            if rule.param_item is None:
                out.append(DEFAULT_PARAM)
            else:
                out.append(self.parse_value(rule.param_item, req))
        return out


def convert_to_param_rule(rule: GatewayFlowRule, idx: int) -> R.ParamFlowRule:
    """GatewayRuleConverter.applyToParamRule analog."""
    return R.ParamFlowRule(
        resource=rule.resource,
        count=rule.count,
        grade=rule.grade,
        param_idx=idx,
        duration_in_sec=rule.interval_sec,
        burst_count=rule.burst,
        control_behavior=rule.control_behavior,
        max_queueing_time_ms=rule.max_queueing_timeout_ms,
        param_flow_item_list=[
            # the NOT_MATCH placeholder gets an unlimited exception slot so
            # unmatched requests are not throttled by this rule
            R.ParamFlowItem(object=NOT_MATCH_PARAM, count=1_000_000_000)
        ],
    )


class ApiDefinitionManager:
    """Custom API groups; match(path) returns every group the path joins."""

    def __init__(self):
        self._defs: List[ApiDefinition] = []

    def load(self, defs: Sequence[ApiDefinition]) -> None:
        self._defs = list(defs)

    def get(self) -> List[ApiDefinition]:
        return list(self._defs)

    def match(self, path: str) -> List[str]:
        out = []
        for d in self._defs:
            for item in d.predicate_items:
                ok = (
                    path == item.pattern
                    if item.match_strategy == URL_MATCH_STRATEGY_EXACT
                    else path.startswith(item.pattern)
                    if item.match_strategy == URL_MATCH_STRATEGY_PREFIX
                    else _safe_regex(item.pattern, path)
                )
                if ok:
                    out.append(d.api_name)
                    break
        return out


def _safe_regex(pattern: str, path: str) -> bool:
    try:
        return re.search(pattern, path) is not None
    except re.error:
        return False


class GatewayRuleManager:
    """Holds gateway rules; projects them to param-flow rules on the
    client's dedicated gateway manager (GatewayRuleManager.java +
    GatewayFlowSlot wiring)."""

    def __init__(self, client):
        self.client = client
        self._rules: List[GatewayFlowRule] = []
        self._by_resource: Dict[str, List[GatewayFlowRule]] = {}
        self.parser = GatewayParamParser()

    def load_rules(self, rules: Sequence[GatewayFlowRule]) -> None:
        self._rules = list(rules)
        by_res: Dict[str, List[GatewayFlowRule]] = {}
        for r in self._rules:
            by_res.setdefault(r.resource, []).append(r)
        self._by_resource = by_res
        converted = []
        for res, group in by_res.items():
            for idx, r in enumerate(group):
                converted.append(convert_to_param_rule(r, idx))
        self.client.gateway_param_rules.load(converted)

    def get_rules(self) -> List[GatewayFlowRule]:
        return list(self._rules)

    def params_for(self, resource: str, req: RequestAttributes) -> Optional[List[str]]:
        group = self._by_resource.get(resource)
        if not group:
            return None
        return self.parser.parse(group, req)


class GatewayAdapter:
    """Request-level entry helper shared by the route adapters
    (spring-cloud-gateway / zuul analog): enters the route resource AND
    every matching custom API group, with parsed params."""

    def __init__(
        self,
        client,
        rules: GatewayRuleManager = None,
        apis: ApiDefinitionManager = None,
        origin_fn: Optional[Callable[[RequestAttributes], str]] = None,
    ):
        self.client = client
        self.rules = rules or GatewayRuleManager(client)
        self.apis = apis or ApiDefinitionManager()
        # origin is OPT-IN: client IPs are unbounded-cardinality, so using
        # them as origins would churn through the interned-origin budget;
        # pass origin_fn explicitly when callers are a bounded set
        self.origin_fn = origin_fn

    def entries_for(self, route_id: str, req: RequestAttributes):
        """Yield entries (route first, then API groups); raises
        BlockException after exiting already-acquired entries."""
        resources = [route_id] + self.apis.match(req.path)
        origin = self.origin_fn(req) if self.origin_fn is not None else ""
        entries = []
        try:
            for res in resources:
                args = self.rules.params_for(res, req)
                entries.append(
                    self.client.entry(res, inbound=True, args=args, origin=origin)
                )
        except Exception:
            for e in reversed(entries):
                e.exit()
            raise
        return entries
