"""Generic RPC adapter — the chained-resource provider/consumer pattern.

The reference's Dubbo adapters (sentinel-apache-dubbo-adapter,
SentinelDubboProviderFilter.java / SentinelDubboConsumerFilter.java)
guard every RPC with a RESOURCE CHAIN rather than a single entry:

  provider side:  ContextUtil.enter(interfaceResource, remoteApplication)
                  -> SphU.entry(interfaceResource)   (EntryType.IN)
                  -> SphU.entry(methodResource)
  consumer side:  SphU.entry(interfaceResource)      (EntryType.OUT)
                  -> SphU.entry(methodResource)

so operators can limit per-interface AND per-method, and the invocation
tree shows method nodes under interface nodes with the caller app as
origin.  This module is the framework-agnostic form of that pattern: any
RPC server/client integration calls ``provider_call``/``consumer_call``
(or uses the context managers) around its handler invocation.

Resource naming follows the reference (interface, then
``interface:method(argTypes...)`` is up to the caller — pass any string).
Block exceptions propagate; business exceptions feed Tracer semantics on
BOTH entries, and exits run method-first (LIFO), matching the filter's
finally-block order.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from sentinel_tpu.adapters._common import resolve_client
from sentinel_tpu.runtime import context as CTX


@contextmanager
def provider_entry(
    interface: str,
    method: str,
    origin: str = "",
    client=None,
):
    """Provider-side chained entries under a context carrying the caller
    app as origin (SentinelDubboProviderFilter.java:46-70)."""
    c = resolve_client(client)
    token = CTX.enter(interface, origin or "")
    iface_entry = None
    method_entry = None
    try:
        iface_entry = c.entry(interface, inbound=True, origin=origin or None)
        method_entry = c.entry(method, inbound=True, origin=origin or None)
        try:
            yield
        except BaseException as exc:
            method_entry.trace(exc)
            iface_entry.trace(exc)
            raise
    finally:
        if method_entry is not None:
            method_entry.exit()
        if iface_entry is not None:
            iface_entry.exit()
        CTX.exit_ctx(token)


@contextmanager
def consumer_entry(interface: str, method: str, client=None):
    """Consumer-side chained entries in the CURRENT context (outbound —
    SentinelDubboConsumerFilter.java:45-63)."""
    c = resolve_client(client)
    iface_entry = None
    method_entry = None
    try:
        iface_entry = c.entry(interface, inbound=False)
        method_entry = c.entry(method, inbound=False)
        try:
            yield
        except BaseException as exc:
            method_entry.trace(exc)
            iface_entry.trace(exc)
            raise
    finally:
        if method_entry is not None:
            method_entry.exit()
        if iface_entry is not None:
            iface_entry.exit()


def provider_call(interface: str, method: str, fn, *args, origin: str = "", client=None, **kw):
    """Invoke ``fn`` guarded by the provider chain; returns its result."""
    with provider_entry(interface, method, origin=origin, client=client):
        return fn(*args, **kw)


def consumer_call(interface: str, method: str, fn, *args, client=None, **kw):
    """Invoke ``fn`` guarded by the consumer chain; returns its result."""
    with consumer_entry(interface, method, client=client):
        return fn(*args, **kw)
