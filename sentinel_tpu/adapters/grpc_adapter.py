"""gRPC interceptors — the RPC adapter.

The analog of sentinel-grpc-adapter's SentinelGrpcServerInterceptor /
SentinelGrpcClientInterceptor (251 LoC): the server side guards inbound
RPCs by full method name and aborts blocked calls with RESOURCE_EXHAUSTED;
the client side guards outbound calls (outbound entry, no origin).
"""

from __future__ import annotations

from typing import Optional

import grpc

from sentinel_tpu.adapters._common import resolve_client
from sentinel_tpu.core import errors as ERR

ORIGIN_METADATA_KEY = "s-user"


class SentinelServerInterceptor(grpc.ServerInterceptor):
    def __init__(self, client=None):
        self._client = client

    def intercept_service(self, continuation, handler_call_details):
        client = resolve_client(self._client)
        resource = handler_call_details.method  # "/pkg.Service/Method"
        origin = ""
        for k, v in handler_call_details.invocation_metadata or ():
            if k == ORIGIN_METADATA_KEY:
                origin = v
                break
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        # wrap the unary-unary behavior (streaming variants pass through the
        # same pattern; reference guards unary calls)
        if not handler.unary_unary:
            return handler

        inner = handler.unary_unary

        def guarded(request, context):
            try:
                entry = client.entry(resource, inbound=True, origin=origin)
            except ERR.BlockException as e:
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED, f"Blocked by Sentinel: {e}"
                )
                return None  # pragma: no cover — abort raises
            try:
                return inner(request, context)
            except Exception as ex:
                entry.trace(ex)
                raise
            finally:
                entry.exit()

        return grpc.unary_unary_rpc_method_handler(
            guarded,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


class SentinelClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    def __init__(self, client=None):
        self._client = client

    def intercept_unary_unary(self, continuation, client_call_details, request):
        client = resolve_client(self._client)
        resource = client_call_details.method
        if isinstance(resource, bytes):
            resource = resource.decode("ascii")
        entry = client.entry(resource, inbound=False)  # raises BlockException
        try:
            call = continuation(client_call_details, request)
        except Exception as e:
            entry.trace(e)
            entry.exit()
            raise
        # exit when the RPC completes so RT covers the wire round-trip
        call.add_done_callback(lambda c: _finish(entry, c))
        return call


def _finish(entry, call) -> None:
    try:
        if call.code() is not None and call.code() != grpc.StatusCode.OK:
            entry.trace(RuntimeError(f"grpc status {call.code()}"))
    except Exception:  # noqa: BLE001
        pass
    entry.exit()
