"""WSGI middleware — the servlet-filter adapter.

The analog of sentinel-web-servlet's CommonFilter + the WebMVC
interceptor's lifecycle (AbstractSentinelInterceptor.java:88-137): every
request enters a resource named ``METHOD:path`` (customizable), with the
origin parsed from the request (S-user header by default); blocked requests
get a 429 response; the entry exits when the response body is fully
consumed, so RT covers streaming responses.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from sentinel_tpu.adapters._common import resolve_client
from sentinel_tpu.core import errors as ERR

DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"
ORIGIN_HEADER = "HTTP_S_USER"  # S-user: the reference's default origin header


def default_resource_extractor(environ) -> str:
    return f"{environ.get('REQUEST_METHOD', 'GET')}:{environ.get('PATH_INFO', '/')}"


def default_origin_parser(environ) -> str:
    return environ.get(ORIGIN_HEADER, "")


class _EntryClosingIterator:
    """Wraps the app's response iterable; exits the entry on close so RT
    spans the full response, and traces errors raised mid-stream."""

    def __init__(self, iterable: Iterable[bytes], entry):
        self._it = iter(iterable)
        self._iterable = iterable
        self._entry = entry

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            raise
        except Exception as e:
            self._entry.trace(e)
            raise

    def close(self):
        try:
            close = getattr(self._iterable, "close", None)
            if close is not None:
                close()
        finally:
            self._entry.exit()


class SentinelWSGIMiddleware:
    def __init__(
        self,
        app,
        client=None,
        resource_extractor: Callable = default_resource_extractor,
        origin_parser: Callable = default_origin_parser,
        block_status: str = "429 Too Many Requests",
        block_body: bytes = DEFAULT_BLOCK_BODY,
        context_name: Optional[str] = None,
    ):
        self.app = app
        self._client = client
        self.resource_extractor = resource_extractor
        self.origin_parser = origin_parser
        self.block_status = block_status
        self.block_body = block_body
        self.context_name = context_name

    @property
    def client(self):
        if self._client is None:
            self._client = resolve_client(None)
        return self._client

    def __call__(self, environ, start_response):
        resource = self.resource_extractor(environ)
        if not resource:
            return self.app(environ, start_response)
        origin = self.origin_parser(environ) or ""
        try:
            entry = self.client.entry(resource, inbound=True, origin=origin)
        except ERR.BlockException:
            start_response(
                self.block_status,
                [
                    ("Content-Type", "text/plain; charset=utf-8"),
                    ("Content-Length", str(len(self.block_body))),
                ],
            )
            return [self.block_body]
        try:
            result = self.app(environ, start_response)
        except Exception as e:
            entry.trace(e)
            entry.exit()
            raise
        return _EntryClosingIterator(result, entry)
