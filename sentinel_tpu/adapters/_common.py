"""Shared adapter plumbing."""

from __future__ import annotations


def resolve_client(client):
    """The adapter-wide 'explicit client or the process-wide singleton'
    resolution (Env.sph analog), in one place."""
    if client is not None:
        return client
    from sentinel_tpu.core.api import get_client

    return get_client()
