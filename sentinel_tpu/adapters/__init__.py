"""Adapters (SURVEY §2.7): entry points that bridge user traffic into the
engine — decorator, WSGI/ASGI middleware, gRPC interceptors, outbound HTTP
client guards, the chained-resource RPC provider/consumer pattern, the
async-streaming wrapper, and the API-gateway rule/param bridge."""

from sentinel_tpu.adapters.decorator import sentinel_resource
from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware
from sentinel_tpu.adapters.asgi import SentinelASGIMiddleware
from sentinel_tpu.adapters.http_client import (
    SentinelHttpClient,
    guarded_urlopen,
    default_url_resource,
)
from sentinel_tpu.adapters.rpc import (
    consumer_call,
    consumer_entry,
    provider_call,
    provider_entry,
)
from sentinel_tpu.adapters.streaming import (
    guard_aiter,
    guard_awaitable,
    guard_stream,
)
from sentinel_tpu.adapters.gateway import (
    ApiDefinition,
    ApiDefinitionManager,
    ApiPredicateItem,
    GatewayAdapter,
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayParamParser,
    GatewayRuleManager,
    RequestAttributes,
    convert_to_param_rule,
)

__all__ = [
    "sentinel_resource",
    "SentinelWSGIMiddleware",
    "SentinelASGIMiddleware",
    "SentinelHttpClient",
    "consumer_call",
    "consumer_entry",
    "provider_call",
    "provider_entry",
    "guard_aiter",
    "guard_awaitable",
    "guard_stream",
    "guarded_urlopen",
    "default_url_resource",
    "ApiDefinition",
    "ApiDefinitionManager",
    "ApiPredicateItem",
    "GatewayAdapter",
    "GatewayFlowRule",
    "GatewayParamFlowItem",
    "GatewayParamParser",
    "GatewayRuleManager",
    "RequestAttributes",
    "convert_to_param_rule",
]
