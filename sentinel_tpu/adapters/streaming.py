"""Async-streaming adapter — entry on subscribe, exit on complete/error.

The reference's reactor adapter (sentinel-reactor-adapter,
SentinelReactorSubscriber.java) lifts flow control onto reactive
streams: the entry happens when the stream is SUBSCRIBED (not when the
pipeline is assembled), the whole stream holds one concurrency slot
while it runs, a BlockException surfaces through the stream's error
channel, and the entry exits on complete OR error with the stream's
full lifetime as RT; cancel() releases without error accounting.

Python's reactive analog is the async iterator / async generator:

    async for item in guard_stream("res", upstream()): ...

``guard_stream`` returns an async GENERATOR wrapping ``upstream`` —
generator semantics give the subscriber lifecycle for free:

- lazy: nothing is acquired until the first ``__anext__`` (subscription);
- early ``break``: the generator's ``aclose()`` runs the ``finally``
  (CPython refcounting makes this immediate), releasing the entry
  without error accounting — the cancel() path;
- ``asyncio`` cancellation / ``GeneratorExit``: released, NOT traced as a
  business exception (routine cancellation must not trip error-ratio
  circuit breakers);
- upstream exception: traced on the entry, then re-raised.

``guard_aiter`` is the decorator form; ``guard_awaitable`` guards a
single awaitable the same way — the Mono analog.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterable, Awaitable, Optional

from sentinel_tpu.adapters._common import resolve_client


async def guard_stream(
    resource: str,
    source: AsyncIterable,
    client=None,
    inbound: bool = False,
    origin: Optional[str] = None,
    args: Optional[tuple] = None,
):
    """Async generator wrapping ``source`` with stream-scoped flow control
    (one entry spanning the whole stream; see module docstring)."""
    c = resolve_client(client)
    entry = await c.entry_async(
        resource,
        inbound=inbound,
        origin=origin,
        args=list(args) if args else None,
    )
    try:
        async for item in source:
            yield item
    except (asyncio.CancelledError, GeneratorExit):
        raise  # cancel(): release (finally) without error accounting
    except BaseException as exc:
        entry.trace(exc)
        raise
    finally:
        entry.exit()
        closer = getattr(source, "aclose", None)
        if closer is not None:
            try:
                await closer()
            except RuntimeError:
                pass  # already closing / closed


def guard_aiter(resource: str, client=None, **kw):
    """Decorator form for async-generator functions:

        @guard_aiter("stream-res")
        async def numbers():
            yield 1
    """

    def wrap(fn):
        def inner(*a, **k):
            return guard_stream(resource, fn(*a, **k), client=client, **kw)

        return inner

    return wrap


async def guard_awaitable(
    resource: str,
    aw: Awaitable,
    client=None,
    inbound: bool = False,
    origin: Optional[str] = None,
):
    """Guard a single awaitable (the Mono analog): entry before awaiting,
    trace on exception (not on cancellation), exit when it resolves."""
    c = resolve_client(client)
    entry = await c.entry_async(resource, inbound=inbound, origin=origin)
    try:
        result = await aw
    except asyncio.CancelledError:
        entry.exit()
        raise
    except BaseException as exc:
        entry.trace(exc)
        entry.exit()
        raise
    entry.exit()
    return result
