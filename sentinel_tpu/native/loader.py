"""Build + load the native host library.

Compiled lazily with g++ into the package directory (falls back to a
temp dir when the package is read-only); the artifact name embeds a hash
of the source, so a stale or foreign binary is never loaded — only a
.so produced from the exact sentinel_host.cpp present on disk.  Binaries
are never committed to version control.  When no toolchain is available,
``load_native()`` returns None and callers use the pure-Python fallbacks.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "sentinel_host.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _src_digest() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def _so_path() -> str:
    name = f"_sentinel_host-{_src_digest()}.so"
    base = os.path.dirname(__file__)
    if os.access(base, os.W_OK):
        return os.path.join(base, name)
    # never a shared world-writable path: a pre-planted .so there would be
    # loaded into this process — use a per-user 0700 cache dir and refuse
    # anything not owned by us
    d = os.path.join(
        os.path.expanduser("~"), ".cache", "sentinel_tpu", "native"
    )
    os.makedirs(d, mode=0o700, exist_ok=True)
    st = os.stat(d)
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        d = tempfile.mkdtemp(prefix="sentinel_tpu_native_")
    return os.path.join(d, name)


def _build(so: str) -> bool:
    # compile to a temp name, rename into place: a g++ killed mid-write
    # must never leave a truncated artifact at the final (hash-named,
    # existence-is-freshness) path
    tmp = f"{so}.tmp.{os.getpid()}"
    try:
        r = subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC],
            capture_output=True,
            text=True,
            timeout=120,
        )
        if r.returncode != 0:
            from sentinel_tpu.utils.record_log import record_log

            record_log().warning("native build failed: %s", r.stderr[-2000:])
            return False
        os.replace(tmp, so)
        # reap binaries from superseded source revisions (and the legacy
        # unhashed name from pre-hash checkouts)
        d = os.path.dirname(so)
        for name in os.listdir(d):
            stale = name == "_sentinel_host.so" or (
                name.startswith("_sentinel_host-")
                and name.endswith(".so")
                and os.path.join(d, name) != so
            )
            if stale:
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    i32, i64, u32, u64, f32 = c.c_int32, c.c_int64, c.c_uint32, c.c_uint64, c.c_float
    p = c.c_void_p
    lib.sx_ring_new.restype = p
    lib.sx_ring_new.argtypes = [u64]
    lib.sx_ring_free.argtypes = [p]
    lib.sx_ring_push.restype = i32
    lib.sx_ring_push.argtypes = [
        p, i32, i32, i32, i32, i32, f32, i32, i32, i32, i32, i32, i32
    ]
    lib.sx_ring_drain.restype = i64
    lib.sx_ring_drain.argtypes = [p, i64] + [p] * 12
    lib.sx_ring_size.restype = i64
    lib.sx_ring_size.argtypes = [p]
    lib.sx_intern_new.restype = p
    lib.sx_intern_new.argtypes = [u64, i32, i32]
    lib.sx_intern_free.argtypes = [p]
    lib.sx_intern_get.restype = i32
    lib.sx_intern_get.argtypes = [p, c.c_char_p, u32]
    lib.sx_intern_count.restype = i32
    lib.sx_intern_count.argtypes = [p, i32]
    # native front door (epoll token-protocol server)
    lib.sx_front_new.restype = p
    lib.sx_front_new.argtypes = [i32, u64, u64, u64, i32]
    lib.sx_front_free.argtypes = [p]
    lib.sx_front_port.restype = i32
    lib.sx_front_port.argtypes = [p]
    lib.sx_front_start.restype = i32
    lib.sx_front_start.argtypes = [p]
    lib.sx_front_stop.argtypes = [p]
    lib.sx_front_map_flow.restype = i32
    lib.sx_front_map_flow.argtypes = [p, i64, i32]
    lib.sx_front_map_param.restype = i32
    lib.sx_front_map_param.argtypes = [p, i64, i32, i32]
    lib.sx_front_set_guard.argtypes = [p, i64]
    lib.sx_front_clear_flows.argtypes = [p]
    lib.sx_front_acq_backlog.restype = i64
    lib.sx_front_acq_backlog.argtypes = [p]
    lib.sx_front_drain_acquires.restype = i64
    lib.sx_front_drain_acquires.argtypes = [p, i64] + [p] * 4
    lib.sx_front_drain_acquires2.restype = i64
    lib.sx_front_drain_acquires2.argtypes = [p, i64] + [p] * 7
    lib.sx_front_respond.restype = i32
    lib.sx_front_respond.argtypes = [p, i64] + [p] * 3
    lib.sx_front_respond_ex.restype = i32
    lib.sx_front_respond_ex.argtypes = [p, i64] + [p] * 5
    # batch-build presort (stable multi-key argsort + inverse permutation)
    lib.sx_batch_sort5.restype = i64
    lib.sx_batch_sort5.argtypes = [i64] + [p] * 7
    lib.sx_batch_sort3.restype = i64
    lib.sx_batch_sort3.argtypes = [i64] + [p] * 5
    # protocol v2 BATCH framing (big-endian column entries <-> int columns)
    lib.sx_frame_pack_entries.restype = i64
    lib.sx_frame_pack_entries.argtypes = [i64] + [p] * 5
    lib.sx_frame_unpack_entries.restype = i64
    lib.sx_frame_unpack_entries.argtypes = [i64] + [p] * 5
    lib.sx_frame_pack_results.restype = i64
    lib.sx_frame_pack_results.argtypes = [i64] + [p] * 5
    lib.sx_frame_unpack_results.restype = i64
    lib.sx_frame_unpack_results.argtypes = [i64] + [p] * 5
    return lib


def load_native() -> Optional[ctypes.CDLL]:
    """The bound CDLL, building it on first use; None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        so = _so_path()
        # the hash in the filename ties the binary to this exact source —
        # existence is sufficient freshness
        if not os.path.exists(so) and not _build(so):
            return None
        try:
            _LIB = _bind(ctypes.CDLL(so))
        except OSError:
            _LIB = None
        return _LIB


def native_available() -> bool:
    return load_native() is not None
