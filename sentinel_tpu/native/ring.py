"""Python wrappers over the native ring/interner, with pure fallbacks.

EventRing drains straight into numpy arrays (the exact layout the engine's
AcquireBatch/CompleteBatch want), so the tick thread's batch assembly is a
single C call instead of a Python loop over event objects.
"""

from __future__ import annotations

import ctypes
import threading
from collections import deque
from typing import Optional, Tuple

import numpy as np

from sentinel_tpu.native.loader import load_native

FLAG_INBOUND = 1
FLAG_PRIORITIZED = 2
FLAG_COMPLETION = 4


class EventRing:
    """Bounded MPMC event ring; native when possible, deque fallback."""

    def __init__(self, capacity_pow2: int = 1 << 16):
        assert capacity_pow2 & (capacity_pow2 - 1) == 0
        self.capacity = capacity_pow2
        self._lib = load_native()
        if self._lib is not None:
            self._ring = self._lib.sx_ring_new(capacity_pow2)
            if not self._ring:  # allocation failed → fallback
                self._lib = None
        if self._lib is None:
            self._dq: deque = deque()
            self._dq_lock = threading.Lock()

    @property
    def native(self) -> bool:
        return self._lib is not None

    def push(
        self,
        res: int,
        count: int = 1,
        origin_id: int = -1,
        param_hash: int = 0,
        flags: int = 0,
        rt_ms: float = 0.0,
        error: int = 0,
        user_tag: int = 0,
        aux0: int = 0,
        aux1: int = 0,
        aux2: int = 0,
        aux3: int = 0,
    ) -> bool:
        if self._lib is not None:
            return (
                self._lib.sx_ring_push(
                    self._ring, res, count, origin_id, param_hash, flags,
                    rt_ms, error, user_tag, aux0, aux1, aux2, aux3,
                )
                == 0
            )
        with self._dq_lock:
            if len(self._dq) >= self.capacity:
                return False
            self._dq.append((res, count, origin_id, param_hash, flags, rt_ms,
                             error, user_tag, aux0, aux1, aux2, aux3))
            return True

    def drain(self, max_n: int) -> Tuple[np.ndarray, ...]:
        """(res, count, origin_id, param_hash, flags, rt_ms, error,
        user_tag, aux0, aux1, aux2, aux3) arrays of length n <= max_n."""
        res = np.empty(max_n, np.int32)
        count = np.empty(max_n, np.int32)
        origin = np.empty(max_n, np.int32)
        ph = np.empty(max_n, np.int32)
        flags = np.empty(max_n, np.int32)
        rt = np.empty(max_n, np.float32)
        err = np.empty(max_n, np.int32)
        tag = np.empty(max_n, np.int32)
        aux0 = np.empty(max_n, np.int32)
        aux1 = np.empty(max_n, np.int32)
        aux2 = np.empty(max_n, np.int32)
        aux3 = np.empty(max_n, np.int32)
        if self._lib is not None:
            cp = lambda a: a.ctypes.data_as(ctypes.c_void_p)
            n = self._lib.sx_ring_drain(
                self._ring, max_n, cp(res), cp(count), cp(origin), cp(ph),
                cp(flags), cp(rt), cp(err), cp(tag), cp(aux0), cp(aux1),
                cp(aux2), cp(aux3),
            )
        else:
            n = 0
            with self._dq_lock:
                while n < max_n and self._dq:
                    row = self._dq.popleft()
                    (res[n], count[n], origin[n], ph[n], flags[n], rt[n],
                     err[n], tag[n], aux0[n], aux1[n], aux2[n], aux3[n]) = row
                    n += 1
        return tuple(a[:n] for a in (res, count, origin, ph, flags, rt, err,
                                     tag, aux0, aux1, aux2, aux3))

    def __len__(self) -> int:
        if self._lib is not None:
            return int(self._lib.sx_ring_size(self._ring))
        return len(self._dq)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_ring", None):
            lib.sx_ring_free(self._ring)
            self._ring = None


class NativeInterner:
    """String -> dense id with lock-free reads.

    Not wired into the Python Registry: crossing ctypes per lookup costs
    more than a dict hit, so from Python the dict wins.  This exists for
    native-side ingestion (a C command/RLS front door resolving resource
    names without entering Python — SURVEY §2.9's host boundary), where
    the same id space must be shared with the device engine."""

    def __init__(self, capacity_pow2: int = 1 << 20, first_id: int = 1, max_ids: int = 1 << 20):
        self._lib = load_native()
        self.first_id = first_id
        if self._lib is not None:
            self._tbl = self._lib.sx_intern_new(capacity_pow2, first_id, max_ids)
            if not self._tbl:
                self._lib = None
        if self._lib is None:
            self._py: dict = {}
            self._lock = threading.Lock()
            self._next = first_id
            self._max = max_ids

    @property
    def native(self) -> bool:
        return self._lib is not None

    def get(self, name: str) -> int:
        """Dense id for name; -1 when capacity is exhausted."""
        if self._lib is not None:
            b = name.encode("utf-8")
            return int(self._lib.sx_intern_get(self._tbl, b, len(b)))
        rid = self._py.get(name)
        if rid is not None:
            return rid
        with self._lock:
            rid = self._py.get(name)
            if rid is not None:
                return rid
            if self._next >= self._max:
                return -1
            rid = self._next
            self._next += 1
            self._py[name] = rid
            return rid

    def count(self) -> int:
        if self._lib is not None:
            return int(self._lib.sx_intern_count(self._tbl, self.first_id))
        return len(self._py)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_tbl", None):
            lib.sx_intern_free(self._tbl)
            self._tbl = None


def _as_i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def batch_sort5(k0, k1, k2, k3, k4, want_inv: bool = True):
    """Stable argsort by (k0, k1, k2, k3, k4), k0 most significant.

    Equivalent to ``np.lexsort((k4, k3, k2, k1, k0))`` — both the native
    and the fallback path are stable sorts, so tie order is identical.
    Returns ``(order, inv)`` int32 arrays (``inv`` None when not wanted);
    ``inv[order] == arange(n)``.
    """
    k0, k1, k2, k3, k4 = map(_as_i32, (k0, k1, k2, k3, k4))
    n = k0.shape[0]
    lib = load_native()
    if lib is not None:
        order = np.empty(n, np.int32)
        inv = np.empty(n, np.int32) if want_inv else None
        cp = lambda a: a.ctypes.data_as(ctypes.c_void_p) if a is not None else None
        lib.sx_batch_sort5(n, cp(k0), cp(k1), cp(k2), cp(k3), cp(k4),
                           cp(order), cp(inv))
        return order, inv
    order = np.lexsort((k4, k3, k2, k1, k0)).astype(np.int32)
    inv = None
    if want_inv:
        inv = np.empty(n, np.int32)
        inv[order] = np.arange(n, dtype=np.int32)
    return order, inv


# ---------------------------------------------------------------------------
# protocol v2 BATCH framing (cluster/protocol.py)
# ---------------------------------------------------------------------------
#
# Fixed-width big-endian column entries; the native pack/unpack loop and
# the numpy structured-dtype fallback produce IDENTICAL bytes (pinned by
# tests/test_native.py parity tests), so peers built with and without a
# toolchain interoperate bit-exactly.

BATCH_ENTRY_SIZE = 14  # [kind:u8][id:i64][count:i32][flags:u8]
BATCH_RESULT_SIZE = 17  # [status:i8][remaining:i32][wait:i32][token:i64]

_ENTRY_DT = np.dtype(
    [("kind", "u1"), ("id", ">i8"), ("count", ">i4"), ("flags", "u1")]
)
_RESULT_DT = np.dtype(
    [("status", "i1"), ("remaining", ">i4"), ("wait", ">i4"), ("token", ">i8")]
)
assert _ENTRY_DT.itemsize == BATCH_ENTRY_SIZE
assert _RESULT_DT.itemsize == BATCH_RESULT_SIZE

_cp = lambda a: a.ctypes.data_as(ctypes.c_void_p)


def pack_batch_entries(kinds, ids, counts, flags) -> bytes:
    """Request entry columns → packed wire bytes (n × 14 B)."""
    kinds = np.ascontiguousarray(kinds, np.uint8)
    ids = np.ascontiguousarray(ids, np.int64)
    counts = np.ascontiguousarray(counts, np.int32)
    flags = np.ascontiguousarray(flags, np.uint8)
    n = kinds.shape[0]
    lib = load_native()
    if lib is not None:
        out = np.empty(n * BATCH_ENTRY_SIZE, np.uint8)
        lib.sx_frame_pack_entries(n, _cp(kinds), _cp(ids), _cp(counts),
                                  _cp(flags), _cp(out))
        return out.tobytes()
    rec = np.empty(n, _ENTRY_DT)
    rec["kind"], rec["id"], rec["count"], rec["flags"] = kinds, ids, counts, flags
    return rec.tobytes()


def unpack_batch_entries(buf: bytes) -> Tuple[np.ndarray, ...]:
    """Packed wire bytes → ``(kinds, ids, counts, flags)`` native-endian
    columns; raises on a length that is not a whole number of entries."""
    n, rem = divmod(len(buf), BATCH_ENTRY_SIZE)
    if rem:
        raise ValueError(f"truncated batch entries ({len(buf)} bytes)")
    lib = load_native()
    if lib is not None:
        raw = np.frombuffer(buf, np.uint8)
        kinds = np.empty(n, np.uint8)
        ids = np.empty(n, np.int64)
        counts = np.empty(n, np.int32)
        flags = np.empty(n, np.uint8)
        lib.sx_frame_unpack_entries(n, _cp(raw), _cp(kinds), _cp(ids),
                                    _cp(counts), _cp(flags))
        return kinds, ids, counts, flags
    rec = np.frombuffer(buf, _ENTRY_DT)
    return (
        rec["kind"].astype(np.uint8),
        rec["id"].astype(np.int64),
        rec["count"].astype(np.int32),
        rec["flags"].astype(np.uint8),
    )


def pack_batch_results(statuses, remainings, waits, tokens) -> bytes:
    """Response entry columns → packed wire bytes (n × 17 B)."""
    statuses = np.ascontiguousarray(statuses, np.int8)
    remainings = np.ascontiguousarray(remainings, np.int32)
    waits = np.ascontiguousarray(waits, np.int32)
    tokens = np.ascontiguousarray(tokens, np.int64)
    n = statuses.shape[0]
    lib = load_native()
    if lib is not None:
        out = np.empty(n * BATCH_RESULT_SIZE, np.uint8)
        lib.sx_frame_pack_results(n, _cp(statuses), _cp(remainings),
                                  _cp(waits), _cp(tokens), _cp(out))
        return out.tobytes()
    rec = np.empty(n, _RESULT_DT)
    rec["status"], rec["remaining"] = statuses, remainings
    rec["wait"], rec["token"] = waits, tokens
    return rec.tobytes()


def unpack_batch_results(buf: bytes) -> Tuple[np.ndarray, ...]:
    """Packed wire bytes → ``(statuses, remainings, waits, tokens)``."""
    n, rem = divmod(len(buf), BATCH_RESULT_SIZE)
    if rem:
        raise ValueError(f"truncated batch results ({len(buf)} bytes)")
    lib = load_native()
    if lib is not None:
        raw = np.frombuffer(buf, np.uint8)
        statuses = np.empty(n, np.int8)
        remainings = np.empty(n, np.int32)
        waits = np.empty(n, np.int32)
        tokens = np.empty(n, np.int64)
        lib.sx_frame_unpack_results(n, _cp(raw), _cp(statuses),
                                    _cp(remainings), _cp(waits), _cp(tokens))
        return statuses, remainings, waits, tokens
    rec = np.frombuffer(buf, _RESULT_DT)
    return (
        rec["status"].astype(np.int8),
        rec["remaining"].astype(np.int32),
        rec["wait"].astype(np.int32),
        rec["token"].astype(np.int64),
    )


def batch_sort3(k0, k1, k2, want_inv: bool = False):
    """Stable argsort by (k0, k1, k2); see :func:`batch_sort5`."""
    k0, k1, k2 = map(_as_i32, (k0, k1, k2))
    n = k0.shape[0]
    lib = load_native()
    if lib is not None:
        order = np.empty(n, np.int32)
        inv = np.empty(n, np.int32) if want_inv else None
        cp = lambda a: a.ctypes.data_as(ctypes.c_void_p) if a is not None else None
        lib.sx_batch_sort3(n, cp(k0), cp(k1), cp(k2), cp(order), cp(inv))
        return order, inv
    order = np.lexsort((k2, k1, k0)).astype(np.int32)
    inv = None
    if want_inv:
        inv = np.empty(n, np.int32)
        inv[order] = np.arange(n, dtype=np.int32)
    return order, inv
