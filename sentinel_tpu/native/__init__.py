"""Native host runtime (C++): MPMC event ring + string interner.

The compute path is JAX/XLA on device; the runtime AROUND it — request
threads feeding micro-batches, string->id interning on the ingest hot
path — is native C++ bound via ctypes (see sentinel_host.cpp).  Pure-
Python fallbacks keep everything working when a compiler is unavailable.
"""

from sentinel_tpu.native.loader import native_available, load_native
from sentinel_tpu.native.ring import EventRing, NativeInterner

__all__ = ["native_available", "load_native", "EventRing", "NativeInterner"]
