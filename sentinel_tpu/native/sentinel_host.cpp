// Native host-runtime primitives for the TPU flow-control engine.
//
// The device engine consumes fixed-shape micro-batches; the host hot path
// is "many request threads append events, one tick thread drains a batch".
// In the reference this role is played by lock-free Java structures
// (LongAdder queues, COW maps — SURVEY §5 "race detection").  Here:
//
//  - sx_ring:    a bounded MPMC ring buffer of acquire/complete events
//                (atomic ticket acquisition, per-slot sequence numbers —
//                 the classic Vyukov bounded queue), drained in batch
//                 order directly into caller-provided arrays so Python
//                 receives ready-to-use int32/float32 buffers.
//  - sx_intern:  an open-addressing FNV-1a string -> dense id table with
//                a single writer lock and lock-free readers (the analog
//                of CtSph's copy-on-write chainMap, CtSph.java:207-211).
//
// Built as a plain C ABI shared library; Python binds via ctypes
// (pybind11 is not available in this image).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>

extern "C" {

// ---------------------------------------------------------------------------
// event ring
// ---------------------------------------------------------------------------

struct sx_event {
    int32_t res;
    int32_t count;
    int32_t origin_id;
    int32_t param_hash;
    int32_t flags;    // bit0 inbound, bit1 prioritized, bit2 completion
    float   rt_ms;    // completions
    int32_t error;    // completions
    int32_t user_tag; // round-trips to the drainer (e.g. future index)
    int32_t aux0;     // completions: hot-param release lane 0
    int32_t aux1;     // completions: hot-param release lane 1
};

struct sx_slot {
    std::atomic<uint64_t> seq;
    sx_event ev;
};

struct sx_ring {
    uint64_t mask;
    sx_slot* slots;
    alignas(64) std::atomic<uint64_t> head; // next write ticket
    alignas(64) std::atomic<uint64_t> tail; // next read ticket
};

sx_ring* sx_ring_new(uint64_t capacity_pow2) {
    if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1)) != 0)
        return nullptr;
    auto* r = new (std::nothrow) sx_ring();
    if (!r) return nullptr;
    r->slots = new (std::nothrow) sx_slot[capacity_pow2];
    if (!r->slots) { delete r; return nullptr; }
    r->mask = capacity_pow2 - 1;
    for (uint64_t i = 0; i <= r->mask; ++i)
        r->slots[i].seq.store(i, std::memory_order_relaxed);
    r->head.store(0, std::memory_order_relaxed);
    r->tail.store(0, std::memory_order_relaxed);
    return r;
}

void sx_ring_free(sx_ring* r) {
    if (!r) return;
    delete[] r->slots;
    delete r;
}

// push one event; returns 0 on success, -1 if the ring is full
int32_t sx_ring_push(sx_ring* r, int32_t res, int32_t count, int32_t origin_id,
                     int32_t param_hash, int32_t flags, float rt_ms,
                     int32_t error, int32_t user_tag, int32_t aux0,
                     int32_t aux1) {
    uint64_t pos = r->head.load(std::memory_order_relaxed);
    for (;;) {
        sx_slot& s = r->slots[pos & r->mask];
        uint64_t seq = s.seq.load(std::memory_order_acquire);
        int64_t diff = (int64_t)seq - (int64_t)pos;
        if (diff == 0) {
            if (r->head.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed))
            {
                s.ev = {res, count, origin_id, param_hash, flags, rt_ms,
                        error, user_tag, aux0, aux1};
                s.seq.store(pos + 1, std::memory_order_release);
                return 0;
            }
        } else if (diff < 0) {
            return -1; // full
        } else {
            pos = r->head.load(std::memory_order_relaxed);
        }
    }
}

// drain up to max_n events into parallel arrays; returns count drained.
// Single-consumer use is expected (the tick thread), but the ticket
// scheme stays correct with several.
int64_t sx_ring_drain(sx_ring* r, int64_t max_n, int32_t* res, int32_t* count,
                      int32_t* origin_id, int32_t* param_hash, int32_t* flags,
                      float* rt_ms, int32_t* error, int32_t* user_tag,
                      int32_t* aux0, int32_t* aux1) {
    int64_t n = 0;
    while (n < max_n) {
        uint64_t pos = r->tail.load(std::memory_order_relaxed);
        sx_slot& s = r->slots[pos & r->mask];
        uint64_t seq = s.seq.load(std::memory_order_acquire);
        int64_t diff = (int64_t)seq - (int64_t)(pos + 1);
        if (diff == 0) {
            if (!r->tail.compare_exchange_weak(pos, pos + 1,
                                               std::memory_order_relaxed))
                continue;
            const sx_event& e = s.ev;
            res[n] = e.res; count[n] = e.count; origin_id[n] = e.origin_id;
            param_hash[n] = e.param_hash; flags[n] = e.flags;
            rt_ms[n] = e.rt_ms; error[n] = e.error; user_tag[n] = e.user_tag;
            aux0[n] = e.aux0; aux1[n] = e.aux1;
            s.seq.store(pos + r->mask + 1, std::memory_order_release);
            ++n;
        } else {
            break; // empty (or producer mid-write: next drain gets it)
        }
    }
    return n;
}

int64_t sx_ring_size(sx_ring* r) {
    return (int64_t)(r->head.load(std::memory_order_relaxed) -
                     r->tail.load(std::memory_order_relaxed));
}

// ---------------------------------------------------------------------------
// string interner
// ---------------------------------------------------------------------------

struct sx_intern_entry {
    std::atomic<uint64_t> hash; // 0 = empty
    std::atomic<int32_t> id;    // valid once hash is published
    char* key;
    uint32_t len;
};

struct sx_intern {
    uint64_t mask;
    sx_intern_entry* entries;
    std::atomic<int32_t> next_id;
    int32_t max_ids;
    std::mutex write_lock;
};

static uint64_t fnv1a(const char* p, uint64_t n) {
    uint64_t h = 1469598103934665603ull;
    for (uint64_t i = 0; i < n; ++i) {
        h ^= (unsigned char)p[i];
        h *= 1099511628211ull;
    }
    return h ? h : 1; // 0 is the empty marker
}

sx_intern* sx_intern_new(uint64_t capacity_pow2, int32_t first_id,
                         int32_t max_ids) {
    if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1)) != 0)
        return nullptr;
    auto* t = new (std::nothrow) sx_intern();
    if (!t) return nullptr;
    t->entries = new (std::nothrow) sx_intern_entry[capacity_pow2]();
    if (!t->entries) { delete t; return nullptr; }
    t->mask = capacity_pow2 - 1;
    t->next_id.store(first_id, std::memory_order_relaxed);
    t->max_ids = max_ids;
    return t;
}

void sx_intern_free(sx_intern* t) {
    if (!t) return;
    for (uint64_t i = 0; i <= t->mask; ++i) delete[] t->entries[i].key;
    delete[] t->entries;
    delete t;
}

// lookup-or-insert; returns the dense id, or -1 when id space / table full.
// Readers are lock-free (acquire loads); inserts take the writer lock.
int32_t sx_intern_get(sx_intern* t, const char* key, uint32_t len) {
    uint64_t h = fnv1a(key, len);
    uint64_t idx = h & t->mask;
    // fast path: lock-free probe
    for (uint64_t probes = 0; probes <= t->mask; ++probes) {
        uint64_t eh = t->entries[idx].hash.load(std::memory_order_acquire);
        if (eh == 0) break;
        if (eh == h) {
            const sx_intern_entry& e = t->entries[idx];
            if (e.len == len && std::memcmp(e.key, key, len) == 0)
                return e.id.load(std::memory_order_acquire);
        }
        idx = (idx + 1) & t->mask;
    }
    // slow path: insert under lock (re-probe: someone may have raced us)
    std::lock_guard<std::mutex> g(t->write_lock);
    idx = h & t->mask;
    for (uint64_t probes = 0; probes <= t->mask; ++probes) {
        sx_intern_entry& e = t->entries[idx];
        uint64_t eh = e.hash.load(std::memory_order_acquire);
        if (eh == h && e.len == len && std::memcmp(e.key, key, len) == 0)
            return e.id.load(std::memory_order_acquire);
        if (eh == 0) {
            int32_t id = t->next_id.load(std::memory_order_relaxed);
            if (id >= t->max_ids) return -1;
            char* copy = new (std::nothrow) char[len];
            if (!copy) return -1;
            std::memcpy(copy, key, len);
            e.key = copy;
            e.len = len;
            e.id.store(id, std::memory_order_release);
            e.hash.store(h, std::memory_order_release); // publish last
            t->next_id.store(id + 1, std::memory_order_relaxed);
            return id;
        }
        idx = (idx + 1) & t->mask;
    }
    return -1; // table full
}

int32_t sx_intern_count(sx_intern* t, int32_t first_id) {
    return t->next_id.load(std::memory_order_relaxed) - first_id;
}

}  // extern "C"
